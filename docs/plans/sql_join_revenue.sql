-- TPC-H-Q3-ish: join + filter + grouped revenue + top-k
SELECT l.okey, SUM(l.price * l.qty) AS revenue, COUNT(*) AS n
FROM lineitem l
JOIN orders o ON l.okey = o.okey
WHERE o.flag = 1
GROUP BY l.okey
ORDER BY revenue DESC
LIMIT 10
