-- single-table scan shape: predicate pushover + global sort
SELECT okey, price, qty
FROM lineitem
WHERE qty > 2 AND tag != 'void'
ORDER BY price DESC
LIMIT 100
