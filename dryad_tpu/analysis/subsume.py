"""Subsumption: can one query's scan+filter prefix serve another?

The second half of the plan-equivalence analyzer (with
analysis/canon.py): given two plans over the same catalog, decide
whether work may be SHARED and emit an info-grade DTA5xx verdict:

* **DTA501 exact-equivalent** — canonical semantic fingerprints match:
  the cached plan, its compiled stages, and its results are shareable
  verbatim (the service's semantic plan-cache hit).
* **DTA502 subsumed-prefix** — query A's scan+filter prefix *contains*
  query B's: B's predicate implies A's (proved over the cost
  analyzer's :class:`~dryad_tpu.analysis.domain.Interval` bounds), B
  projects a subset of A's columns, and both read the same source
  content (``sql.catalog.table_fingerprint`` equality).  B could read
  A's Tee'd prefix output instead of paying a second cold scan.
* **DTA503 unsound-to-share** — the plans overlap (same source, or
  structurally equal shapes) but sharing is REFUSED, with the reason:
  a nondeterministic UDF in the shared prefix (per
  analysis/udf_lint — a replayed/shared evaluation would observe
  different values), differing source content behind one table name,
  or a standing query's side-effecting registration.

No verdict (``None``) means the plans are simply unrelated — nothing
to share, nothing unsound.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

from dryad_tpu.analysis.canon import (scan_prefix, semantic_fingerprint)
from dryad_tpu.analysis.diagnostics import Diagnostic
from dryad_tpu.analysis.domain import Interval

__all__ = ["Verdict", "compare", "implies", "bounds_of",
           "dataset_share_verdict", "prefix_nondet_findings"]


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One share/refuse decision; ``code`` is DTA501/502/503."""

    code: str
    message: str
    # direction for DTA502: which side's prefix contains the other's
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def diagnostic(self) -> Diagnostic:
        return Diagnostic(self.code, "info", self.message, node="reuse")

    def render(self) -> str:
        return f"{self.code}: {self.message}"


# -- predicate implication over Interval bounds -------------------------


@dataclasses.dataclass(frozen=True)
class _Bounds:
    """Per-column constraint: the numeric hull as an
    :class:`~dryad_tpu.analysis.domain.Interval` (``lo=-inf`` /
    ``hi=None`` for unbounded sides) plus open/closed flags."""

    iv: Interval
    lo_strict: bool = False
    hi_strict: bool = False

    def intersect(self, other: "_Bounds") -> "_Bounds":
        lo, los = max((self.iv.lo, self.lo_strict),
                      (other.iv.lo, other.lo_strict))
        if self.iv.hi is None:
            hi, his = other.iv.hi, other.hi_strict
        elif other.iv.hi is None:
            hi, his = self.iv.hi, self.hi_strict
        else:
            hi, his = min((self.iv.hi, not self.hi_strict),
                          (other.iv.hi, not other.hi_strict))
            his = not his
        return _Bounds(Interval(lo, hi), los, his)

    def contained_in(self, outer: "_Bounds") -> bool:
        """Every value satisfying ``self`` satisfies ``outer``."""
        if outer.iv.lo > self.iv.lo or (
                outer.iv.lo == self.iv.lo
                and outer.lo_strict and not self.lo_strict):
            return False
        if outer.iv.hi is not None:
            if self.iv.hi is None or self.iv.hi > outer.iv.hi or (
                    self.iv.hi == outer.iv.hi
                    and outer.hi_strict and not self.hi_strict):
                return False
        return True


_FREE = _Bounds(Interval(-math.inf, None))


def _bound_of_conjunct(c: List) -> Optional[Tuple[str, _Bounds]]:
    """(column, bounds) for an interval-shaped canonical conjunct
    (col-vs-literal comparison), else None (residual)."""
    if c[0] != "bin":
        return None
    op, a, b = c[1], c[2], c[3]
    if a[0] == "col" and b[0] == "lit" \
            and isinstance(b[1], (int, float)) \
            and not isinstance(b[1], bool):
        col, v = a[1], float(b[1])
        if op == "=":
            return col, _Bounds(Interval(v, v))
        if op == "<":
            return col, _Bounds(Interval(-math.inf, v), hi_strict=True)
        if op == "<=":
            return col, _Bounds(Interval(-math.inf, v))
    elif a[0] == "lit" and b[0] == "col" \
            and isinstance(a[1], (int, float)) \
            and not isinstance(a[1], bool):
        col, v = b[1], float(a[1])
        if op == "=":                  # canon sorts col first for "=",
            return col, _Bounds(Interval(v, v))   # but stay defensive
        if op == "<":
            return col, _Bounds(Interval(v, None), lo_strict=True)
        if op == "<=":
            return col, _Bounds(Interval(v, None))
    return None


def bounds_of(conjuncts: List[List]
              ) -> Tuple[Dict[str, _Bounds], List[str]]:
    """({column: intersected bounds}, residual conjunct keys).
    Residuals are conjuncts the Interval domain cannot shape
    (disjunctions, col-vs-col comparisons, !=, string equality) —
    implication requires them verbatim."""
    import json
    bounds: Dict[str, _Bounds] = {}
    residual: List[str] = []
    for c in conjuncts:
        hit = _bound_of_conjunct(c)
        if hit is None:
            residual.append(json.dumps(c, sort_keys=True, default=str))
        else:
            col, b = hit
            bounds[col] = bounds.get(col, _FREE).intersect(b)
    return bounds, residual


def implies(p: List[List], q: List[List]) -> bool:
    """Does predicate ``p`` (conjunct list) imply predicate ``q``?
    Sound, not complete: every interval constraint of q must contain
    p's interval for that column, and every residual conjunct of q
    must appear verbatim among p's conjuncts.  ``[]`` is TRUE (implied
    by anything)."""
    pb, pr = bounds_of(p)
    qb, qr = bounds_of(q)
    for col, outer in qb.items():
        if not pb.get(col, _FREE).contained_in(outer):
            return False
    return set(qr) <= set(pr)


# -- bound SQL statement comparison -------------------------------------


def compare(catalog, bound_a, bound_b) -> Optional[Verdict]:
    """Share verdict for two bound SQL statements over one catalog:
    DTA501 / DTA502 / DTA503 / None (unrelated).  ``bound_a`` plays
    the cached/running side, ``bound_b`` the new submission."""
    fa = semantic_fingerprint(catalog, bound_a)
    fb = semantic_fingerprint(catalog, bound_b)
    if fa == fb:
        if bound_b.emit_every is not None:
            return Verdict(
                "DTA503",
                f"plans are semantically equivalent ({fa}) but the "
                f"submission is a standing query (EMIT EVERY) — its "
                f"registration is stateful, one-shot results are not "
                f"shareable", {"fingerprint": fa})
        return Verdict(
            "DTA501",
            f"semantically equivalent to cached plan {fa} — plan, "
            f"compiled stages, and results shareable verbatim, zero "
            f"compile", {"fingerprint": fa})
    pa, pb = scan_prefix(catalog, bound_a), scan_prefix(catalog,
                                                       bound_b)
    if pa is None or pb is None or pa["table"] != pb["table"]:
        return None
    if pa["content"] != pb["content"]:
        return Verdict(
            "DTA503",
            f"both plans scan table {pa['table']!r} but the source "
            f"content fingerprints differ ({pa['content']} vs "
            f"{pb['content']}) — a shared scan would serve stale "
            f"rows", {"table": pa["table"]})
    if set(pb["columns"]) <= set(pa["columns"]) \
            and implies(pb["filter"], pa["filter"]):
        return Verdict(
            "DTA502",
            f"scan+filter prefix of the cached plan subsumes this "
            f"query over {pa['table']!r}: predicate implied over "
            f"Interval bounds, projection a subset — the Tee'd cached "
            f"scan serves both", {"table": pa["table"],
                                  "direction": "cached-covers-new"})
    if set(pa["columns"]) <= set(pb["columns"]) \
            and implies(pa["filter"], pb["filter"]):
        return Verdict(
            "DTA502",
            f"this query's scan+filter prefix subsumes the cached "
            f"plan over {pa['table']!r} — sharing is possible in the "
            f"other direction", {"table": pa["table"],
                                 "direction": "new-covers-cached"})
    return None


# -- api.Dataset DAG sharing --------------------------------------------


def _prefix_nodes(root) -> List[Any]:
    """The scan prefix of a Dataset DAG: every Source plus the
    single-parent Map/Filter chain above each (the segment a shared
    Tee'd edge would serve)."""
    from dryad_tpu.plan import expr as E
    nodes = list(E.walk(root))
    prefix: List[Any] = []
    for n in nodes:
        if isinstance(n, E.Source):
            prefix.append(n)
            cur = n
            while True:
                nxt = [m for m in nodes
                       if cur in m.parents
                       and isinstance(m, (E.Map, E.Filter))
                       and len(m.parents) == 1]
                if len(nxt) != 1:
                    break
                cur = nxt[0]
                prefix.append(cur)
    return prefix


def prefix_nondet_findings(root) -> List[Diagnostic]:
    """udf_lint findings (DTA101/102/103) for every callable in a
    DAG's scan prefix — the evidence a DTA503 refusal cites."""
    import dataclasses as _dc

    from dryad_tpu.analysis.udf_lint import lint_udf
    out: List[Diagnostic] = []
    for n in _prefix_nodes(root):
        for f in _dc.fields(n):
            v = getattr(n, f.name)
            if callable(v) and not hasattr(v, "__ship_payload__"):
                out.extend(d for d in lint_udf(v, role=f.name)
                           if d.code in ("DTA101", "DTA102", "DTA103"))
    return out


def dataset_share_verdict(root_a, root_b) -> Optional[Verdict]:
    """Share verdict for two api.Dataset DAGs (their root plan nodes):
    DTA503 when a nondeterministic UDF sits in either scan prefix
    (sharing one evaluation is unsound even for structurally equal
    DAGs — each run legitimately observes different values), DTA501
    when the canonical DAG fingerprints match, else None."""
    from dryad_tpu.analysis.canon import node_fingerprint
    nondet = prefix_nondet_findings(root_a) \
        + prefix_nondet_findings(root_b)
    if nondet:
        why = "; ".join(sorted({d.message for d in nondet}))
        return Verdict(
            "DTA503",
            f"nondeterministic UDF in the scan prefix — sharing one "
            f"evaluation is unsound ({why})",
            {"findings": [d.code for d in nondet]})
    fa, fb = node_fingerprint(root_a), node_fingerprint(root_b)
    if fa == fb:
        return Verdict(
            "DTA501",
            f"semantically equivalent DAGs (canonical fingerprint "
            f"{fa}) — compiled stages and cached results shareable",
            {"fingerprint": fa})
    return None
