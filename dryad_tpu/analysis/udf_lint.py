"""AST-based determinism + shippability lint for user UDFs.

Replay-based fault tolerance (ARCHITECTURE.md "Determinism") is only sound
when UDFs are deterministic: a replayed stage must recompute byte-identical
output, and the reference's whole recovery model (re-run the vertex from
its inputs, DrVertex replay) carries the same silent assumption.  Nothing
enforced it until now — this module walks the UDF's AST and flags the
constructs that break replay:

* wall-clock / RNG / uuid / os.urandom calls without a fixed seed
  (DTA101) — import aliases resolve before matching, so
  ``import time as t; t.time()`` and ``from datetime import datetime;
  datetime.now()`` are caught under their real dotted names
* ``id()`` and builtin ``hash()`` — interpreter/object-identity dependent
  (``hash`` of str/bytes is salted per process) (DTA102)
* iteration over sets — order varies across processes (DTA103)
* mutation of captured (closure/global) state — replays observe
  different values (DTA104)
* capture of a device array / large ndarray constant — the bytes ship
  with EVERY task envelope that references the UDF, and a captured
  device buffer pins a specific process's device memory (DTA105)

Shippability (the reference's serializable-expression constraint,
QueryParser.cs:100 `assembly!class.method` entries) is checked by
``shippability_of``: the same importability test runtime/shiplan.py applies
at submit, surfaced pre-submit with the UDF's definition site.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Callable, List, Optional, Tuple

from dryad_tpu.analysis.diagnostics import Diagnostic, Span

__all__ = ["lint_udf", "fn_def_site", "shippability_of"]

# dotted-call prefixes that are nondeterministic across replays
# (jax.random is NOT here: it is functionally pure — explicit keys)
_NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "secrets.", "uuid.")
# exact dotted calls that are nondeterministic
_NONDET_CALLS = {"os.urandom", "os.getpid", "datetime.datetime.now",
                 "datetime.datetime.utcnow", "datetime.date.today"}
# seeded-constructor suffixes: a constant argument fixes the stream, so
# the call IS deterministic (np.random.RandomState(0), random.Random(7),
# jax.random.PRNGKey(0), np.random.default_rng(3))
_SEEDED_CTORS = (".RandomState", ".default_rng", ".Random", ".PRNGKey",
                 ".key", ".seed")
# methods that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "sort",
             "reverse"}
# a captured HOST ndarray at or above this many bytes is "large" for
# DTA105 (it re-serializes into every task envelope); device arrays are
# flagged at ANY size — a captured device buffer additionally pins the
# driver process's device memory into the program
DTA105_NDARRAY_BYTES = 64 << 10


def _captured_payload(v) -> Optional[str]:
    """Why a captured value is heavyweight for shipping, or None.
    Duck-typed so jax need not be importable: a jax.Array exposes
    ``.device`` / ``.sharding``; a numpy ndarray exposes ``.nbytes``
    without either."""
    if v is None or isinstance(v, (int, float, str, bytes, bool)):
        return None
    nbytes = getattr(v, "nbytes", None)
    if nbytes is None:
        return None
    if hasattr(v, "sharding") or hasattr(v, "device_buffer"):
        return (f"a device array ({int(nbytes)} bytes) — the buffer "
                f"transfers to host and re-ships with every task "
                f"envelope; pass it through the query as a dataset "
                f"(broadcast()/cross_apply) instead")
    if int(nbytes) >= DTA105_NDARRAY_BYTES:
        return (f"a {int(nbytes)}-byte ndarray constant — it "
                f"re-serializes into every task envelope; load it "
                f"worker-side or pass it as a broadcast dataset")
    return None


def fn_def_site(fn: Callable) -> Optional[Span]:
    """Definition site (file:line) of a Python callable, if knowable."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    return Span(code.co_filename, code.co_firstlineno,
                getattr(fn, "__qualname__", ""))


def shippability_of(fn: Callable) -> Optional[str]:
    """None if ``fn`` ships to a cluster (importable as module:qualname,
    or a shippable VALUE serializing as data — plan/serialize.ship_ref_of,
    e.g. SQL row-expression programs), else a human explanation
    mirroring runtime/shiplan's rejection."""
    from dryad_tpu.plan.serialize import ship_ref_of
    from dryad_tpu.runtime.shiplan import _import_ref
    if _import_ref(fn) is not None or ship_ref_of(fn) is not None:
        return None
    qual = getattr(fn, "__qualname__", repr(fn))
    kind = "lambda" if "<lambda>" in str(qual) else \
        "closure/non-importable callable"
    return (f"{kind} {qual!r} cannot ship to workers — move it to module "
            f"level, or register it by name via register_fn_table "
            f"(runtime/shiplan.py) / Context(fn_table=...)")


def _alias_ref(v) -> Optional[str]:
    """Real dotted name behind a bound value: module objects resolve to
    ``module.__name__`` (``import time as t`` -> ``time``),
    from-imported classes/functions to ``module.qualname``
    (``from datetime import datetime`` -> ``datetime.datetime``)."""
    import types
    if isinstance(v, types.ModuleType):
        return v.__name__
    mod = getattr(v, "__module__", None)
    qual = getattr(v, "__qualname__", None)
    if isinstance(mod, str) and isinstance(qual, str) \
            and "." not in qual:
        return f"{mod}.{qual}"
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """a.b.c attribute chain as a dotted string (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _fn_source(fn: Callable) -> Optional[Tuple[ast.AST, str, int]]:
    """(parsed AST, filename, first source line) or None when the source
    is unavailable (builtins, C extensions, exec'd code)."""
    try:
        lines, start = inspect.getsourcelines(fn)
    except (OSError, TypeError):
        return None
    src = textwrap.dedent("".join(lines))
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # inline lambdas may yield an unparsable statement fragment; the
        # shippability check still covers them via the code object
        return None
    fname = getattr(fn, "__code__", None)
    return tree, (fname.co_filename if fname else "<unknown>"), start


class _UdfVisitor(ast.NodeVisitor):
    def __init__(self, fn: Callable):
        self.findings: List[Tuple[str, str, int]] = []  # (code, msg, line)
        code = getattr(fn, "__code__", None)
        self.freevars = set(code.co_freevars) if code else set()
        # params + locally-assigned names compile to LOAD_FAST — a local
        # shadowing a module-level array captures nothing
        self.local_names = set(code.co_varnames) if code else set()
        # captured globals that are MUTABLE containers: mutating them in a
        # UDF leaks state across replays/partitions
        self.mutable_globals = {
            name for name, v in getattr(fn, "__globals__", {}).items()
            if isinstance(v, (list, dict, set, bytearray))}
        # concrete captured VALUES for the payload lint (DTA105): closure
        # cells by freevar name; referenced globals resolve lazily
        self._globals = getattr(fn, "__globals__", {})
        self.captured_values = {}
        clo = getattr(fn, "__closure__", None) or ()
        if code is not None:
            for name, cell in zip(code.co_freevars, clo):
                try:
                    self.captured_values[name] = cell.cell_contents
                except ValueError:   # not yet filled (recursive def)
                    pass
        self._payload_flagged: set = set()
        # import-alias resolution: real dotted name behind each bound
        # name, so `import time as t; t.time()` matches "time." and
        # `from datetime import datetime; datetime.now()` matches
        # "datetime.datetime.now".  Seeded from captured values +
        # globals; inline import statements add entries during the walk.
        self.alias_map: dict = {}
        for name, v in list(self._globals.items()) \
                + list(self.captured_values.items()):
            ref = _alias_ref(v)
            if ref is not None:
                self.alias_map[name] = ref
        # names bound by import statements INSIDE the function body —
        # they are locals too, but the import tells us exactly what
        # they are, so they resolve despite the local-shadow rule
        self._inline_imports: set = set()

    # -- heavyweight captures (DTA105) ------------------------------------

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) \
                and node.id not in self._payload_flagged \
                and node.id not in self.local_names:
            if node.id in self.captured_values:
                v = self.captured_values[node.id]
            elif node.id in self.freevars:
                v = None
            else:
                v = self._globals.get(node.id)
            why = _captured_payload(v)
            if why is not None:
                self._payload_flagged.add(node.id)
                self._flag("DTA105",
                           f"closes over {node.id!r}: {why}", node)
        self.generic_visit(node)

    def _flag(self, code: str, msg: str, node: ast.AST) -> None:
        self.findings.append((code, msg, getattr(node, "lineno", 1)))

    # -- import-alias resolution ------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.asname:
                self.alias_map[a.asname] = a.name
                self._inline_imports.add(a.asname)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and not node.level:
            for a in node.names:
                self.alias_map[a.asname or a.name] = \
                    f"{node.module}.{a.name}"
                self._inline_imports.add(a.asname or a.name)
        self.generic_visit(node)

    def _canon_dotted(self, dotted: str) -> str:
        """Resolve the head of a dotted call through import aliases.
        Plain locals shadow the surrounding module's aliases, but a
        name bound by an import statement in the function body (also
        a local) resolves — the import says exactly what it is."""
        head, dot, rest = dotted.partition(".")
        if head in self.local_names \
                and head not in self._inline_imports:
            return dotted
        ref = self.alias_map.get(head)
        if ref is None:
            return dotted
        return f"{ref}{dot}{rest}" if rest else ref

    # -- nondeterministic calls -------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        if dotted is not None:
            dotted = self._canon_dotted(dotted)
            if dotted == "id":
                self._flag("DTA102",
                           "id() depends on interpreter object placement "
                           "— never stable across replays", node)
            elif dotted == "hash":
                self._flag("DTA102",
                           "builtin hash() is salted per process for "
                           "str/bytes — use ops.hashing for stable keys",
                           node)
            elif dotted in ("set", "frozenset"):
                pass  # construction is fine; iteration is flagged below
            elif self._is_nondet(dotted, node):
                self._flag("DTA101",
                           f"call to {dotted}() is nondeterministic "
                           f"across replays — seed it explicitly or hoist "
                           f"it out of the query", node)
        # mutating a captured container: captured.append(...), including
        # subscripted receivers like state["k"].append(...) (whose dotted
        # form is None) — outside the dotted guard on purpose
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            root = node.func.value
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name) and (
                    root.id in self.freevars
                    or root.id in self.mutable_globals):
                self._flag("DTA104",
                           f"mutates captured state "
                           f"{root.id!r}.{node.func.attr}() — UDFs "
                           f"must be pure for replay soundness", node)
        self.generic_visit(node)

    def _is_nondet(self, dotted: str, node: ast.Call) -> bool:
        if dotted in _NONDET_CALLS:
            return True
        if not (dotted + ".").startswith(_NONDET_PREFIXES):
            return False
        # seeded constructors with a literal argument (positional or
        # keyword: default_rng(seed=42)) are deterministic
        if dotted.endswith(_SEEDED_CTORS) and any(
                isinstance(a, ast.Constant)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]):
            return False
        return True

    # -- set iteration order ----------------------------------------------

    def _iter_is_set(self, it: ast.AST) -> bool:
        if isinstance(it, ast.Set):
            return True
        if isinstance(it, ast.Call):
            d = _dotted(it.func)
            return d in ("set", "frozenset")
        return False

    def visit_For(self, node: ast.For) -> None:
        if self._iter_is_set(node.iter):
            self._flag("DTA103",
                       "iteration over a set — element order varies by "
                       "process (hash salting); sort first", node)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        if self._iter_is_set(node.iter):
            self._flag("DTA103",
                       "comprehension over a set — element order varies "
                       "by process; sort first", node.iter)
        self.generic_visit(node)

    # -- captured-state mutation ------------------------------------------

    def visit_Global(self, node: ast.Global) -> None:
        self._flag("DTA104",
                   f"rebinds global(s) {', '.join(node.names)} — UDFs "
                   f"must be pure for replay soundness", node)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self._flag("DTA104",
                   f"rebinds closure variable(s) {', '.join(node.names)} "
                   f"— UDFs must be pure for replay soundness", node)

    def _check_store_target(self, tgt: ast.AST, node: ast.AST) -> None:
        if isinstance(tgt, ast.Subscript):
            root = tgt.value
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name) and (
                    root.id in self.freevars
                    or root.id in self.mutable_globals):
                self._flag("DTA104",
                           f"assigns into captured state {root.id!r}[...] "
                           f"— UDFs must be pure for replay soundness",
                           node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_store_target(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_target(node.target, node)
        self.generic_visit(node)


def lint_udf(fn: Callable, role: str = "udf") -> List[Diagnostic]:
    """Determinism findings for one callable (empty when the source is
    unavailable — builtins / C extensions are framework-owned)."""
    mod = getattr(fn, "__module__", "") or ""
    if mod.split(".")[0] in ("jax", "jaxlib", "numpy", "builtins"):
        return []
    parsed = _fn_source(fn)
    if parsed is None:
        return []
    tree, fname, first_line = parsed
    v = _UdfVisitor(fn)
    v.visit(tree)
    qual = getattr(fn, "__qualname__", role)
    out = []
    for code, msg, lineno in v.findings:
        out.append(Diagnostic(
            code, "warn", f"{role} {qual!r}: {msg}",
            Span(fname, first_line + lineno - 1, str(qual)), node=role))
    return out
