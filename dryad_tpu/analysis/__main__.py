"""CLI: lint serialized plans offline + the analyzer's own selfcheck.

``python -m dryad_tpu.analysis plan.json`` — run the structural subset of
the plan verifier over a plan JSON artifact (plan/serialize.graph_to_json
output, the artifact ``runtime/shiplan.serialize_for_cluster`` ships to
workers).  Exit code 1 when error-severity findings exist, so CI can gate
committed plan artifacts.

``--cost`` appends the offline capacity/row cost table
(analysis/cost.estimate_plan_json: callables and sources are gone from a
serialized plan, so byte predictions are unavailable — but every
capacity is structural, so the per-stage capacity/row-bound table still
computes; size it with ``--nparts``).

``python -m dryad_tpu.analysis --selfcheck`` — one fast gate over the
analyzer itself: ruff (when installed) / the shared unused-import scan
(analysis/selflint.py), the generated-docs drift check
(docs/diagnostics.md vs diagnostics.render_code_table), and an analyzer
smoke over the committed example plans (docs/plans/*.json).  Wired as a
tier-1 pytest so analyzer rot is caught the day it lands.
"""

from __future__ import annotations

import argparse
import pathlib
import shutil
import subprocess
import sys

from dryad_tpu.analysis import check_plan_json

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent


def _selfcheck() -> int:
    from dryad_tpu.analysis.cost import estimate_plan_json
    from dryad_tpu.analysis.diagnostics import render_code_table
    from dryad_tpu.analysis.selflint import scan_package
    failures = []

    ruff = shutil.which("ruff")
    if ruff is not None:
        proc = subprocess.run(
            [ruff, "check", "--no-cache", "dryad_tpu"], cwd=str(_REPO),
            capture_output=True, text=True)
        if proc.returncode != 0:
            failures.append(f"ruff:\n{proc.stdout}{proc.stderr}")
        else:
            print("ruff: clean")
    else:
        print("ruff: not installed — AST fallback only")
    findings = scan_package()
    if findings:
        failures.append("unused imports:\n" + "\n".join(findings))
    else:
        print("selflint (unused imports): clean")

    docs = _REPO / "docs" / "diagnostics.md"
    if not docs.exists():
        failures.append(f"{docs}: missing (regenerate with "
                        f"--selfcheck --write-docs)")
    elif docs.read_text() != render_code_table():
        failures.append(
            f"{docs}: stale vs diagnostics.CODES — regenerate with "
            f"`python -m dryad_tpu.analysis --selfcheck --write-docs`")
    else:
        print("docs/diagnostics.md: in sync with diagnostics.CODES")

    # every diagnostic code must belong to a documented family — a new
    # code series (e.g. DTA5xx) that skips _CODE_FAMILIES would render
    # into docs/diagnostics.md without a family heading
    from dryad_tpu.analysis.diagnostics import _CODE_FAMILIES, CODES
    orphans = [c for c in CODES
               if not any(c.startswith(p) for p, _ in _CODE_FAMILIES)]
    if orphans:
        failures.append(f"diagnostics codes with no _CODE_FAMILIES "
                        f"entry: {', '.join(sorted(orphans))}")
    else:
        print("diagnostics families: every code covered")

    failures.extend(_sql_golden_check())
    failures.extend(_canon_golden_check())
    failures.extend(_obs_docs_check())

    import json as _json
    plans = [p for p in sorted((_REPO / "docs" / "plans").glob("*.json"))
             if "stages" in _json.loads(p.read_text())]
    plan_failures = []
    if not plans:
        plan_failures.append(f"{_REPO / 'docs' / 'plans'}: no committed "
                             f"example plans to smoke the analyzer over")
    for p in plans:
        js = p.read_text()
        rep = check_plan_json(js)
        if rep.errors:
            plan_failures.append(f"{p.name}: unexpected error "
                                 f"findings:\n" + rep.render())
        cost = estimate_plan_json(js, nparts=8)
        if not cost.stages or not any(s.capacity for s in cost.stages):
            plan_failures.append(f"{p.name}: offline cost pass produced "
                                 f"no capacity table")
    if plans and not plan_failures:
        print(f"analyzer smoke: {len(plans)} committed plan(s) ok")
    failures.extend(plan_failures)

    for f in failures:
        print(f"SELFCHECK FAILURE: {f}", file=sys.stderr)
    return 1 if failures else 0


def _obs_docs_check() -> list:
    """docs/observability.md drift gate: the consolidated observability
    guide must cover every ``python -m dryad_tpu.obs`` subcommand
    (obs/__main__.OBS_COMMANDS is the source of truth) and the live
    service-observability surfaces — an added/renamed tool or endpoint
    that skips the doc fails the selfcheck the day it lands."""
    doc = _REPO / "docs" / "observability.md"
    if not doc.exists():
        return [f"{doc}: missing (the consolidated observability "
                f"guide — ISSUE 13)"]
    text = doc.read_text()
    from dryad_tpu.obs.__main__ import OBS_COMMANDS
    missing = [f"obs subcommand {c!r}" for c in OBS_COMMANDS
               if c not in text]
    missing += [f"surface {s!r}" for s in
                ("/events/", "/slo", "/latency", "EXPLAIN ANALYZE",
                 "regression_suspect", "slo_breach",
                 "latency_waterfall", "DRYAD_LOGGING_LEVEL")
                if s not in text]
    if missing:
        return [f"{doc}: stale — not mentioned: {', '.join(missing)}"]
    print("docs/observability.md: covers every obs subcommand + live "
          "service surfaces")
    return []


def _sql_golden_check() -> list:
    """SQL golden-plan drift gate: every committed ``docs/plans/*.sql``
    recompiles (offline, schema-only catalog, nparts=8) to EXACTLY its
    committed ``<name>.json`` lowering, and that JSON round-trips
    through graph_from_json -> graph_to_json byte-identically (the
    shippable-value protocol's self-decode contract).  A planner or
    sql/ change that alters lowered plans must regenerate the goldens
    deliberately (tests/test_sql.py shows the one-liner)."""
    failures = []
    plans_dir = _REPO / "docs" / "plans"
    sqls = sorted(plans_dir.glob("*.sql"))
    cat_path = plans_dir / "sql_catalog.json"
    if not sqls:
        return [f"{plans_dir}: no committed .sql golden queries"]
    if not cat_path.exists():
        return [f"{cat_path}: missing (the catalog the committed .sql "
                f"goldens compile against)"]
    from dryad_tpu.sql import Catalog, offline_plan_json
    catalog = Catalog.load(str(cat_path))
    for sp in sqls:
        golden = sp.with_suffix(".json")
        if not golden.exists():
            failures.append(f"{sp.name}: no committed golden "
                            f"{golden.name}")
            continue
        js = offline_plan_json(catalog, sp.read_text(), nparts=8,
                               origin=sp.name)
        if js != golden.read_text():
            failures.append(
                f"{golden.name}: stale vs the lowering of {sp.name} — "
                f"regenerate via sql.offline_plan_json(catalog, query, "
                f"nparts=8, origin={sp.name!r})")
            continue
        # round trip: rebuild (row-expressions self-decode as data,
        # zero fn_table) and re-serialize byte-identically
        import json as _json

        from dryad_tpu.plan.serialize import (graph_from_json,
                                              graph_to_json)
        d = _json.loads(js)
        slots = {f"{st['id']}:{li}": None for st in d["stages"]
                 for li, leg in enumerate(st["legs"])
                 if "source" in leg["src"]}
        try:
            graph = graph_from_json(js, fn_table={}, sources=slots)
            js2 = graph_to_json(graph)
        except Exception as e:
            failures.append(f"{golden.name}: does not round-trip "
                            f"through graph_from_json: {e!r}")
            continue
        if js2 != js:
            failures.append(f"{golden.name}: graph_from_json -> "
                            f"graph_to_json is not byte-identical")
    if not failures:
        print(f"sql goldens: {len(sqls)} committed .sql quer"
              f"{'ies' if len(sqls) != 1 else 'y'} lower to their "
              f"committed plans and round-trip")
    return failures


def _canon_golden_check() -> list:
    """Canonical-form drift gate: every committed ``docs/plans/*.sql``
    re-canonicalizes (analysis/canon.canonical_form_json, schema-only
    catalog) to EXACTLY its committed ``<name>.canon.json``.  A change
    to the canonicalization pass silently reshuffles semantic
    fingerprints — every cached plan orphans at once — so it must be
    deliberate: regenerate with ``--selfcheck --write-docs``."""
    failures = []
    plans_dir = _REPO / "docs" / "plans"
    sqls = sorted(plans_dir.glob("*.sql"))
    cat_path = plans_dir / "sql_catalog.json"
    if not sqls or not cat_path.exists():
        return []     # _sql_golden_check already reports the gap
    from dryad_tpu.analysis.canon import canonical_form_json
    from dryad_tpu.sql import Catalog, compile_query
    catalog = Catalog.load(str(cat_path))
    for sp in sqls:
        golden = sp.with_suffix(".canon.json")
        if not golden.exists():
            failures.append(f"{sp.name}: no committed canonical form "
                            f"{golden.name} (regenerate with "
                            f"--selfcheck --write-docs)")
            continue
        _mode, bound = compile_query(catalog, sp.read_text(),
                                     origin=sp.name)
        form = canonical_form_json(catalog, bound)
        if form != golden.read_text():
            failures.append(
                f"{golden.name}: stale vs the canonicalization of "
                f"{sp.name} — semantic fingerprints have moved; if "
                f"intended, regenerate with --selfcheck --write-docs")
    if not failures:
        print(f"canon goldens: {len(sqls)} committed .sql quer"
              f"{'ies' if len(sqls) != 1 else 'y'} canonicalize to "
              f"their committed forms")
    return failures


def _write_canon_goldens() -> None:
    plans_dir = _REPO / "docs" / "plans"
    cat_path = plans_dir / "sql_catalog.json"
    if not cat_path.exists():
        return
    from dryad_tpu.analysis.canon import canonical_form_json
    from dryad_tpu.sql import Catalog, compile_query
    catalog = Catalog.load(str(cat_path))
    for sp in sorted(plans_dir.glob("*.sql")):
        _mode, bound = compile_query(catalog, sp.read_text(),
                                     origin=sp.name)
        out = sp.with_suffix(".canon.json")
        out.write_text(canonical_form_json(catalog, bound))
        print(f"wrote {out}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dryad_tpu.analysis",
        description="statically lint a serialized dryad_tpu plan "
                    "(graph_to_json / shiplan output)")
    ap.add_argument("plan", nargs="?",
                    help="plan JSON path ('-' for stdin)")
    ap.add_argument("--stream", action="store_true",
                    help="the plan will execute over cluster streams "
                         "(store_stream sources): apply the streamed-"
                         "mode op rules")
    ap.add_argument("--cost", action="store_true",
                    help="append the offline per-stage capacity/row "
                         "cost table (analysis/cost.py)")
    ap.add_argument("--nparts", type=int, default=1,
                    help="partition count for --cost row bounds "
                         "(default 1)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="lint the analyzer itself: ruff/selflint, "
                         "docs drift, committed-plan smoke")
    ap.add_argument("--write-docs", action="store_true",
                    help="with --selfcheck: (re)generate "
                         "docs/diagnostics.md from diagnostics.CODES")
    args = ap.parse_args(argv)
    if args.selfcheck:
        if args.write_docs:
            from dryad_tpu.analysis.diagnostics import render_code_table
            out = _REPO / "docs" / "diagnostics.md"
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_text(render_code_table())
            print(f"wrote {out}")
            _write_canon_goldens()
        return _selfcheck()
    if args.plan is None:
        ap.error("a plan path is required (or --selfcheck)")
    if args.plan == "-":
        plan_json = sys.stdin.read()
    else:
        with open(args.plan) as f:
            plan_json = f.read()
    report = check_plan_json(plan_json, stream=args.stream)
    print(report.render())
    if args.cost:
        from dryad_tpu.analysis.cost import estimate_plan_json
        print()
        print(estimate_plan_json(plan_json,
                                 nparts=args.nparts).render())
    return 1 if report.errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
