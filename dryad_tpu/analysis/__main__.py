"""CLI: lint a serialized plan offline.

``python -m dryad_tpu.analysis plan.json`` — run the structural subset of
the plan verifier over a plan JSON artifact (plan/serialize.graph_to_json
output, the artifact ``runtime/shiplan.serialize_for_cluster`` ships to
workers).  Exit code 1 when error-severity findings exist, so CI can gate
committed plan artifacts.
"""

from __future__ import annotations

import argparse
import sys

from dryad_tpu.analysis import check_plan_json


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dryad_tpu.analysis",
        description="statically lint a serialized dryad_tpu plan "
                    "(graph_to_json / shiplan output)")
    ap.add_argument("plan", help="plan JSON path ('-' for stdin)")
    ap.add_argument("--stream", action="store_true",
                    help="the plan will execute over cluster streams "
                         "(store_stream sources): apply the streamed-"
                         "mode op rules")
    args = ap.parse_args(argv)
    if args.plan == "-":
        plan_json = sys.stdin.read()
    else:
        with open(args.plan) as f:
            plan_json = f.read()
    report = check_plan_json(plan_json, stream=args.stream)
    print(report.render())
    return 1 if report.errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
