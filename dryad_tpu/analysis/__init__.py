"""Pre-submit static analysis: plan verifier + UDF determinism lint.

The reference validates the expression tree statically in phase 1 of query
generation (DryadLinqQueryGen.cs `DryadLinqQueryGen` — serializability of
closures, operator applicability) before touching the cluster; dryad_tpu's
equivalent lives here.  Entry points:

* ``Dataset.check()`` / ``Dataset.explain(verify=True)`` — interactive
* ``JobConfig.lint = "warn" | "error"`` — pre-submit gate on every
  executor/cluster/stream submission (findings land in the EventLog and
  the viewer's Diagnostics section; "error" blocks the job)
* ``python -m dryad_tpu.analysis plan.json`` — lint serialized plans
  offline (CI over committed plan artifacts)
"""

from dryad_tpu.analysis.canon import (  # noqa: F401
    canon_prog, canonical_form_json, canonical_select, conjuncts_of,
    dag_fingerprints, node_fingerprint, scan_prefix,
    semantic_fingerprint)
from dryad_tpu.analysis.diagnostics import (  # noqa: F401
    CODES, RUNTIME_ONLY_CODES, Diagnostic, DiagnosticError,
    DiagnosticReport, LintError, Span)
from dryad_tpu.analysis.plan_rules import (  # noqa: F401
    RULES, STATIC_RULE_CODES, PlanCheck, check_plan)
from dryad_tpu.analysis.subsume import (  # noqa: F401
    Verdict, compare, dataset_share_verdict, implies)
from dryad_tpu.analysis.udf_lint import (  # noqa: F401
    fn_def_site, lint_udf, shippability_of)

__all__ = [
    "CODES", "RUNTIME_ONLY_CODES", "Diagnostic", "DiagnosticError",
    "DiagnosticReport", "LintError", "Span",
    "RULES", "STATIC_RULE_CODES", "PlanCheck", "check_plan",
    "fn_def_site", "lint_udf", "shippability_of", "check_plan_json",
    "canon_prog", "canonical_form_json", "canonical_select",
    "conjuncts_of", "dag_fingerprints", "node_fingerprint",
    "scan_prefix", "semantic_fingerprint",
    "Verdict", "compare", "dataset_share_verdict", "implies",
]


def check_plan_json(plan_json: str, stream: bool = False
                    ) -> DiagnosticReport:
    """Lint a SERIALIZED plan (plan/serialize.graph_to_json output)
    offline — no callables, no sources, no jax.  Covers the structural
    subset: stream-incompatible ops (with ``stream=True``), placeholder
    legs, and callable refs a worker could never resolve (anonymous
    ``fn_...`` names and opaque params, shiplan's DTA905 deploy
    failure).  Op spans recorded by the planner make findings point at
    the query line that created the op."""
    import json
    import re

    report = DiagnosticReport()
    d = json.loads(plan_json)

    def walk_params(v, found):
        if isinstance(v, dict):
            if "__fn__" in v:
                found.append(("fn", v["__fn__"]))
            if "__opaque__" in v:
                found.append(("opaque", v["__opaque__"]))
            for x in v.values():
                walk_params(x, found)
        elif isinstance(v, list):
            for x in v:
                walk_params(x, found)

    for st in d.get("stages", []):
        legs = st.get("legs", [])
        for leg in legs:
            if "placeholder" in leg.get("src", {}) and stream:
                report.add("DTA002", "error",
                           f"stage {st['id']}: placeholder leg in a "
                           f"streamed cluster plan", node="placeholder")
        ops = [(o, "leg") for leg in legs for o in leg.get("ops", [])] \
            + [(o, "body") for o in st.get("body", [])]
        for op, where in ops:
            span = op.get("span")
            found = []
            walk_params(op.get("params", {}), found)
            for kind, name in found:
                if kind == "fn" and ":" not in name:
                    # anonymous fn_<id> refs (graph_to_json fallback for
                    # unregistered callables) can NEVER resolve on a
                    # worker; a registered shipping name resolves when a
                    # --fn-module exports it — deploy requirement, not
                    # an error
                    anonymous = bool(re.fullmatch(r"fn_[0-9a-f]+", name))
                    report.add(
                        "DTA905", "error" if anonymous else "warn",
                        f"stage {st['id']} {where} op {op['kind']!r} "
                        f"references callable {name!r} with no "
                        f"importable module:qualname — "
                        + ("it was never registered for shipping and no "
                           "worker can resolve it" if anonymous else
                           "workers need a --fn-module exporting that "
                           "name"), span=span, node=op["kind"])
                elif kind == "opaque":
                    report.add(
                        "DTA016", "error",
                        f"stage {st['id']} {where} op {op['kind']!r} "
                        f"carries opaque param {name!r} — not "
                        f"serializable for cluster execution", span=span,
                        node=op["kind"])
    return report
