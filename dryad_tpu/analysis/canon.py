"""Canonicalization: stable SEMANTIC fingerprints for query plans.

DryadLINQ's amortization argument (PAPER.md; the LinqToDryad static
query optimizer) depends on recognizing that two expression trees mean
the same thing: equivalent queries must share plans, compiled stages,
and cached results.  Until now the service's reuse was purely syntactic
— the FileCache keyed on whitespace-normalized query TEXT, so
``SELECT a, b FROM t WHERE x > 3 AND y = 1`` and
``SELECT b, a FROM t WHERE y = 1 AND x > 3`` compiled and scanned
twice.  This module closes that gap with a canonicalization pass over

* **bound SQL plans** (:func:`canonical_select` over a
  ``sql.binder.BoundSelect``): alias-insensitive renaming (FROM-order
  positional aliases ``t0, t1, ...``), commutative/associative
  predicate and projection ordering, constant folding in rowexpr trees
  (``sql.rowexpr.fold_prog``), NNF push-down of ``NOT``, canonical
  comparison direction, and dead-column pruning of scan renames;
* **api.Dataset DAGs** (:func:`dag_fingerprints` over ``plan/expr``
  nodes): a structural bottom-up hash whose rowexpr callables
  canonicalize by content while opaque Python callables fingerprint by
  identity — unknown code never unifies, which is the sound default.

The result is a 16-hex *semantic fingerprint*: equal fingerprints mean
the plans compute the same function over the same source content
(per-table content identity rides along via
``sql.catalog.table_fingerprint``, which shares its column-order
normalization with ``Catalog.fingerprint()``).  The service keys its
SQL plan cache on this fingerprint (service/daemon.py), subsumption
verdicts build on the canonical conjuncts (analysis/subsume.py), and
committed canonical-form goldens drift-gate the pass itself
(``python -m dryad_tpu.analysis --selfcheck``).

Soundness notes: only bitwise-safe rewrites are applied.  Two-operand
commutation of ``+``/``*``/``=``/``!=`` is IEEE-exact; AND/OR chains
flatten, sort, and dedup (idempotent boolean algebra); ``NOT`` folds
through comparisons because the SQL type system has no NULLs.
Float *re-association* across operator levels is NOT performed — it is
not bit-stable, and fingerprint-equal queries must produce
bit-identical results.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional

__all__ = ["canon_prog", "canonical_select", "canonical_form_json",
           "semantic_fingerprint", "scan_prefix", "conjuncts_of",
           "dag_fingerprints", "node_fingerprint"]


# -- rowexpr program canonicalization -----------------------------------


def _key(prog: List) -> str:
    """Stable sort key for canonical operand ordering."""
    return json.dumps(prog, sort_keys=True, default=str)


def _flatten(op: str, prog: List, out: List[List]) -> None:
    if prog[0] == "bin" and prog[1] == op:
        _flatten(op, prog[2], out)
        _flatten(op, prog[3], out)
    else:
        out.append(prog)


def _not_of(x: List) -> List:
    """NOT over an already-canonical program, pushed to NNF.  Folding
    NOT through comparisons is sound here: SQL types carry no NULLs
    and numerics are totally ordered."""
    if x[0] == "lit":
        return ["lit", not x[1], "bool"]
    if x[0] == "not":
        return x[1]
    if x[0] == "bin":
        op = x[1]
        inv = {"=": "!=", "!=": "=", "<": ">=", "<=": ">"}
        if op in inv:
            return _norm(["bin", inv[op], x[2], x[3]])
        if op in ("and", "or"):
            other = "or" if op == "and" else "and"
            return _norm(["bin", other, _not_of(x[2]), _not_of(x[3])])
    return ["not", x]


def _norm(prog: List) -> List:
    head = prog[0]
    if head in ("col", "lit", "const"):
        return list(prog)
    if head == "neg":
        return ["neg", _norm(prog[1])]
    if head == "not":
        return _not_of(_norm(prog[1]))
    # head == "bin"
    op, a, b = prog[1], _norm(prog[2]), _norm(prog[3])
    if op in ("and", "or"):
        # associative + commutative + idempotent: flatten the whole
        # chain, dedup, sort, rebuild left-deep — conjunct order and
        # repetition vanish from the fingerprint
        terms: List[List] = []
        _flatten(op, ["bin", op, a, b], terms)
        uniq = {_key(t): t for t in terms}
        keys = sorted(uniq)
        out = uniq[keys[0]]
        for k in keys[1:]:
            out = ["bin", op, out, uniq[k]]
        return out
    if op in ("+", "*", "=", "!="):
        # two-operand commutation only (bitwise-exact for IEEE floats;
        # re-association across levels is not, so chains keep shape)
        if _key(b) < _key(a):
            a, b = b, a
        return ["bin", op, a, b]
    if op in (">", ">="):
        # canonical comparison direction: everything becomes < / <=
        return ["bin", "<" if op == ">" else "<=", b, a]
    return ["bin", op, a, b]


def canon_prog(prog: List) -> List:
    """Canonical form of a row-expression program: constants folded
    (``sql.rowexpr.fold_prog``), NOT pushed to NNF, AND/OR chains
    flattened + sorted + deduped, commutative operands ordered,
    comparisons directed ``< / <=``."""
    from dryad_tpu.sql.rowexpr import fold_prog
    return _norm(fold_prog(list(prog)))


def conjuncts_of(prog: Optional[List]) -> List[List]:
    """Canonical conjunct list of a (canonicalized) predicate —
    ``None`` / folded-true predicates yield ``[]``, the always-true
    filter (subsume.py's implication checks work over this)."""
    if prog is None:
        return []
    c = canon_prog(prog)
    if c == ["lit", True, "bool"]:
        return []
    out: List[List] = []
    _flatten("and", c, out)
    return out


# -- bound SQL plan canonicalization ------------------------------------


def _rename_cols(prog: List, phys_map: Dict[str, str]) -> List:
    head = prog[0]
    if head == "col":
        return ["col", phys_map.get(prog[1], prog[1])]
    if head in ("lit", "const"):
        return list(prog)
    if head in ("not", "neg"):
        return [head, _rename_cols(prog[1], phys_map)]
    return ["bin", prog[1], _rename_cols(prog[2], phys_map),
            _rename_cols(prog[3], phys_map)]


def canonical_select(catalog, bound) -> Dict[str, Any]:
    """Canonical JSON-able form of a ``BoundSelect``; see module
    docstring for the rewrite set.  ``catalog`` supplies per-table
    content fingerprints (``sql.catalog.table_fingerprint``), so the
    form identifies the *data* too — equal canonical forms compute
    the same result, not just the same function."""
    from dryad_tpu.sql.catalog import table_fingerprint
    from dryad_tpu.sql.rowexpr import prog_columns

    # alias-insensitive renaming: positional canonical aliases in FROM
    # order (join order is semantically significant — it is preserved)
    alias_map = {bound.base_alias: "t0"}
    for i, j in enumerate(bound.joins):
        alias_map[j.alias] = f"t{i + 1}"

    def canon_phys(phys: str) -> str:
        alias, _, col = phys.partition(".")
        return f"{alias_map[alias]}.{col}" if alias in alias_map \
            else phys

    all_renames = [(bound.base_alias, bound.base_renames)] \
        + [(j.alias, j.renames) for j in bound.joins]
    phys_map = {phys: canon_phys(phys)
                for _, renames in all_renames for phys in renames}

    def cp(prog: Optional[List]) -> Optional[List]:
        return None if prog is None \
            else canon_prog(_rename_cols(prog, phys_map))

    # referenced physical columns — dead-column pruning of scan renames
    referenced: set = set()
    if bound.where is not None:
        referenced |= prog_columns(bound.where)
    for j in bound.joins:
        referenced |= set(j.left_keys) | set(j.right_keys)
    if bound.grouped:
        for prog in (bound.pre_projection or {}).values():
            referenced |= prog_columns(prog)
        referenced |= set(bound.group_keys)
    else:
        for prog in bound.outputs.values():
            referenced |= prog_columns(prog)

    tables = []
    for (alias, renames), tname in zip(
            all_renames, [bound.base_table]
            + [j.table for j in bound.joins]):
        t = catalog.get(tname)
        cols = sorted(renames[p] for p in renames if p in referenced)
        tables.append({"name": tname, "alias": alias_map[alias],
                       "content": (table_fingerprint(t)
                                   if t is not None else "?"),
                       "columns": cols})

    joins = []
    for j in bound.joins:
        pairs = sorted((canon_phys(lk), canon_phys(rk))
                       for lk, rk in zip(j.left_keys, j.right_keys))
        joins.append({"how": j.how, "on": [list(p) for p in pairs]})

    form: Dict[str, Any] = {
        "tables": tables,
        "joins": joins,
        "where": cp(bound.where),
        "outputs": {name: cp(bound.outputs[name])
                    for name in sorted(bound.outputs)},
        "output_types": {name: bound.output_types[name]
                         for name in sorted(bound.output_types)},
        "distinct": bound.distinct,
        "order_by": [[name, bool(desc)] for name, desc
                     in bound.order_by],
        "limit": bound.limit,
        "emit_every": bound.emit_every,
    }
    if bound.grouped:
        # aggregates key by OUTPUT name with their canonical input
        # program inlined — the synthesized __sqlaggN numbering (a
        # SELECT-order artifact) disappears from the form
        pre = bound.pre_projection or {}
        aggs = {}
        for name in sorted(bound.aggs):
            kind, in_col = bound.aggs[name]
            aggs[name] = {"kind": kind,
                          "input": cp(pre[in_col])
                          if in_col is not None and in_col in pre
                          else None}
        form["group_keys"] = sorted(canon_phys(k)
                                    for k in bound.group_keys)
        form["aggs"] = aggs
        form["having"] = cp(bound.having)
    return form


def canonical_form_json(catalog, bound) -> str:
    """Deterministic pretty JSON of the canonical form — the committed
    golden-file format (docs/plans/*.canon.json, drift-gated by the
    analysis selfcheck)."""
    return json.dumps(canonical_select(catalog, bound), indent=1,
                      sort_keys=True) + "\n"


def semantic_fingerprint(catalog, bound) -> str:
    """16-hex semantic fingerprint of a bound statement: sha256 over
    the canonical form.  Equal fingerprints => same function over the
    same source content => shareable plans/results (the service's SQL
    plan-cache key)."""
    blob = json.dumps(canonical_select(catalog, bound), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def scan_prefix(catalog, bound) -> Optional[Dict[str, Any]]:
    """Canonical scan+filter prefix of a SINGLE-TABLE statement: the
    unit analysis/subsume.py proves containment over.  ``None`` for
    joined statements (their filters may straddle tables — prefix
    containment is only claimed where it is trivially sound).

    Keys: ``table`` / ``content`` (source identity), ``columns``
    (source column names the query reads), ``filter`` (canonical
    conjunct list over SOURCE column names; empty = always-true)."""
    from dryad_tpu.sql.catalog import table_fingerprint
    from dryad_tpu.sql.rowexpr import prog_columns
    if bound.joins:
        return None
    src_map = {phys: col for phys, col in bound.base_renames.items()}
    referenced: set = set()
    if bound.where is not None:
        referenced |= prog_columns(bound.where)
    if bound.grouped:
        for prog in (bound.pre_projection or {}).values():
            referenced |= prog_columns(prog)
        referenced |= set(bound.group_keys)
    else:
        for prog in bound.outputs.values():
            referenced |= prog_columns(prog)
    t = catalog.get(bound.base_table)
    filt = [] if bound.where is None else conjuncts_of(
        _rename_cols(bound.where, src_map))
    return {"table": bound.base_table,
            "content": table_fingerprint(t) if t is not None else "?",
            "columns": sorted(src_map[p] for p in referenced
                              if p in src_map),
            "filter": filt}


# -- api.Dataset DAG fingerprints ---------------------------------------


def _val_fp(v: Any) -> str:
    """Canonical fingerprint of one node param value.  Rowexpr
    callables canonicalize by content; registered callables by import
    ref; anything opaque by object identity (never unifies across
    distinct objects — sound by construction)."""
    from dryad_tpu.sql.rowexpr import Predicate, Projector
    if isinstance(v, Predicate):
        return "pred:" + _key(canon_prog(v.prog))
    if isinstance(v, Projector):
        return "proj:" + _key({n: canon_prog(p) for n, p in
                               sorted(v.outputs.items())})
    if hasattr(v, "__ship_payload__") \
            and hasattr(type(v), "__from_payload__"):
        return (f"ship:{type(v).__qualname__}:"
                f"{json.dumps(v.__ship_payload__(), sort_keys=True)}")
    if callable(v):
        from dryad_tpu.runtime.shiplan import _import_ref
        ref = _import_ref(v)
        return f"fn:{ref}" if ref is not None else "opaque:%x" % id(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_val_fp(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(f"{k}={_val_fp(v[k])}"
                              for k in sorted(v)) + "}"
    return repr(v)


def _source_fp(data: Any, host: Any) -> str:
    """Content identity of a Source node's data."""
    if host is not None:
        h = hashlib.sha256()
        for col in sorted(host):
            v = host[col]
            h.update(col.encode())
            try:
                import numpy as np
                if isinstance(v, (list, tuple)):
                    for x in v:
                        h.update(x if isinstance(x, bytes)
                                 else str(x).encode())
                        h.update(b"\x00")
                else:
                    h.update(np.ascontiguousarray(v).tobytes())
            except Exception:
                return "opaque:%x" % id(data)
        return "host:" + h.hexdigest()[:16]
    spec = getattr(data, "spec", None)
    if isinstance(spec, dict):
        path = spec.get("path") or spec.get("paths")
        if path is not None:
            return "spec:" + json.dumps(
                {k: spec[k] for k in sorted(spec)
                 if isinstance(spec[k], (str, int, float, bool, list,
                                         tuple))}, default=str)
    return "opaque:%x" % id(data)


def dag_fingerprints(root) -> Dict[int, str]:
    """Bottom-up semantic fingerprint per node of a ``plan/expr`` DAG
    (node id -> 16-hex fp).  Hash = node kind + canonical params +
    parent fingerprints + source content identity; spans and node ids
    are excluded (two lowerings of one query fingerprint equal)."""
    import dataclasses as _dc

    from dryad_tpu.plan import expr as E
    fps: Dict[int, str] = {}
    for node in E.walk(root):
        items = [type(node).__name__]
        items.extend(fps[p.id] for p in node.parents)
        for f in _dc.fields(node):
            if f.name in ("parents", "id", "span"):
                continue
            v = getattr(node, f.name)
            if f.name == "data":       # Source payload
                v = _source_fp(v, getattr(node, "host", None))
                items.append(f"data={v}")
            elif f.name == "host":
                continue               # folded into data
            else:
                items.append(f"{f.name}={_val_fp(v)}")
        blob = "|".join(items)
        fps[node.id] = hashlib.sha256(blob.encode()).hexdigest()[:16]
    return fps


def node_fingerprint(root) -> str:
    """Semantic fingerprint of a whole Dataset DAG (its root node)."""
    return dag_fingerprints(root)[root.id]
