"""Diagnostics engine: stable codes, severities, source provenance.

The counterpart of the reference's phase-1 static validation errors
(DryadLinqQueryGen.cs raises on non-serializable expressions / inapplicable
operators BEFORE any cluster resource is touched).  Every rule in
dryad_tpu/analysis emits ``Diagnostic`` records with a stable ``DTAxxx``
code so tooling (CI gates, the viewer, tests) can key off them; runtime
errors that mirror a static rule carry the SAME code (DiagnosticError), so
the two surfaces cannot drift apart silently — tests/test_analysis.py
asserts the mapping.

Code space:
* DTA0xx — plan verifier (structural rules over the logical Node DAG)
* DTA1xx — UDF lint (determinism / shippability of user callables)
* DTA2xx — cost & resource analyzer (analysis/cost.py: abstract
  interpretation over the lowered plan; pre-submit OOM/spill forecasts)
* DTA3xx — SQL front end (dryad_tpu/sql: lexer/parser/binder errors whose
  spans point INTO THE QUERY TEXT as line:column — the file slot of the
  Span holds the query's origin, e.g. ``<sql>`` or a ``.sql`` path)
* DTA4xx — incremental execution (dryad_tpu/inc: info-grade verdicts on
  how a standing query's refresh runs — incremental merge into persisted
  state vs full re-run — shown by EXPLAIN and carried on refresh events)
* DTA5xx — plan equivalence & cross-job reuse (analysis/canon.py +
  analysis/subsume.py: info-grade verdicts on whether two plans may
  share compiled artifacts / cached scans, and WHY sharing is refused)
* DTA9xx — runtime-only conditions (data-dependent overflows, internal
  invariants, worker-side deploy errors) that no static rule can predict
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, List, Optional

__all__ = [
    "Span", "Diagnostic", "DiagnosticReport", "DiagnosticError",
    "LintError", "SEVERITIES", "CODES", "RUNTIME_ONLY_CODES",
]

# severity rank for sorting/gating (error first)
SEVERITIES = {"error": 0, "warn": 1, "info": 2}

# every stable code with its one-line meaning — the single registry both
# the static rules and the runtime raise sites draw from
CODES = {
    # -- plan verifier (DTA0xx) -------------------------------------------
    # DTA001 (global take over cluster streams) RETIRED: the streamed
    # runner grew a real lowering (runtime/stream_plan._global_take)
    "DTA002": "placeholder (do_while loop input) in a streamed cluster "
              "plan",
    "DTA003": "operator not supported over cluster streams",
    "DTA010": "capacity hazard: fan-out op without a with_capacity bound",
    "DTA011": "redundant repartition: placement already satisfied",
    "DTA012": "fan-out (Tee) consumer without cache()",
    "DTA013": "unsound assume_* placement claim",
    "DTA014": "UDF is not cluster-shippable (lambda/closure)",
    "DTA015": "source is not cluster-shippable (non-deferred)",
    "DTA016": "op param is not serializable for cluster execution",
    "DTA017": "pinned partitioning (assume_*/explicit repartition) "
              "blocks adaptive repartitioning of an elided consumer",
    # -- UDF lint (DTA1xx) -------------------------------------------------
    "DTA101": "nondeterministic call in UDF (time/random/uuid/urandom)",
    "DTA102": "object-identity dependence in UDF (id()/salted hash())",
    "DTA103": "set-iteration-order dependence in UDF",
    "DTA104": "UDF mutates captured state",
    "DTA105": "UDF closes over a device array / large ndarray constant "
              "(ships the bytes with every task envelope)",
    # -- cost & resource analyzer (DTA2xx) ---------------------------------
    "DTA200": "cost analyzer internal failure — cost pass skipped",
    "DTA201": "predicted per-device footprint provably exceeds "
              "device_hbm_bytes",
    "DTA202": "predicted per-device footprint may exceed "
              "device_hbm_bytes (predicted spill)",
    "DTA203": "unbounded fan-out reaches an exchange (buffer sized "
              "blind)",
    "DTA204": "cache() of edge-scale data (info: lowered to the "
              "store-backed re-streaming cache tier; warn when the "
              "tier is disabled and the result pins device memory)",
    "DTA205": "per-stage predicted cost summary",
    # -- SQL front end (DTA3xx) --------------------------------------------
    "DTA301": "SQL parse error",
    "DTA302": "unknown table (not registered in the catalog)",
    "DTA303": "unknown column",
    "DTA304": "ambiguous column reference (qualify with the table "
              "alias)",
    "DTA305": "type mismatch in SQL expression",
    "DTA306": "unsupported SQL construct",
    "DTA307": "invalid standing query (EMIT EVERY misuse: non-positive "
              "interval, or a base table that cannot grow)",
    # -- incremental execution (DTA4xx, dryad_tpu/inc) ---------------------
    # info-grade verdicts of the standing-query planner: how a refresh
    # will execute, surfaced by EXPLAIN and carried on refresh events
    "DTA401": "standing query runs incrementally (decomposable "
              "aggregate suffix merges new chunks into persisted "
              "state)",
    "DTA402": "standing query falls back to full re-run (suffix not "
              "decomposable: join / DISTINCT / ORDER BY / LIMIT / "
              "HAVING over the growing table)",
    "DTA403": "cost model chose a full re-run for this refresh (the "
              "chunk delta is most of the store — state is rebuilt, "
              "not merged)",
    # -- plan equivalence & cross-job reuse (DTA5xx) -----------------------
    # info-grade verdicts of the semantic plan-equivalence analyzer
    # (analysis/canon.py canonical fingerprints + analysis/subsume.py
    # containment): surfaced by EXPLAIN and carried on service
    # admission events when a submission reuses cached work
    "DTA501": "semantically equivalent plan (canonical fingerprints "
              "match — cached plan / compiled stages / results are "
              "shareable verbatim)",
    "DTA502": "subsumed scan+filter prefix (this query's scan reads a "
              "subset of an equivalent cached prefix: predicate "
              "implied over Interval bounds, projection a subset, "
              "same source content)",
    "DTA503": "unsound to share (plans overlap textually or "
              "structurally but sharing is refused, with the reason — "
              "e.g. a nondeterministic UDF in the shared prefix, or "
              "differing source content)",
    # -- runtime-only (DTA9xx) ---------------------------------------------
    "DTA901": "internal: op kind cannot ride a wave program",
    "DTA902": "internal: unknown exchange kind in streamed plan",
    "DTA903": "bucket capacity overflow during wave exchange",
    "DTA904": "wave exchange still overflowing after capacity retries",
    "DTA905": "worker cannot resolve a plan callable (missing --fn-module)",
    # multi-tenant job service admission (dryad_tpu/service): typed,
    # code-carrying rejections raised BEFORE any work starts
    "DTA910": "job service: unknown app or malformed job spec",
    "DTA911": "job service: tenant admission queue full (backpressure — "
              "resubmit later)",
    "DTA912": "job service: tenant failure budget exhausted",
    "DTA913": "job service: daemon is draining/stopped — submission "
              "refused",
    # durable service (dryad_tpu/service/durable): raised at daemon
    # START, refusing to recover over bad durable state rather than
    # silently restoring a partial view
    "DTA914": "job service: write-ahead journal corrupt or its format "
              "version unsupported — recovery refused",
}

# codes that have NO static-analyzer rule, by design: data-dependent
# overflows, internal invariants, and worker-side deploy failures.  The
# drift test asserts every runtime raise site uses a code that is either
# carried by a static rule or listed here.
RUNTIME_ONLY_CODES = frozenset({"DTA901", "DTA902", "DTA903", "DTA904",
                                "DTA905", "DTA910", "DTA911", "DTA912",
                                "DTA913", "DTA914"})


@dataclasses.dataclass(frozen=True)
class Span:
    """Source provenance: where the user wrote the offending construct.

    For Python UDF/plan findings ``file:line`` names a source file; for
    SQL findings (DTA3xx) the ``file`` slot names the query's origin
    (``<sql>`` or a ``.sql`` path) and ``col`` carries the 1-based
    column INSIDE the query text, rendered ``origin:line:column``."""

    file: str
    line: int
    func: str = ""
    col: int = 0

    def __str__(self) -> str:
        base = f"{self.file}:{self.line}"
        return f"{base}:{self.col}" if self.col else base

    @staticmethod
    def of(v: Any) -> Optional["Span"]:
        """Coerce a (file, line[, func]) tuple / Span / None."""
        if v is None or isinstance(v, Span):
            return v
        if isinstance(v, (tuple, list)) and len(v) >= 2:
            return Span(str(v[0]), int(v[1]), str(v[2]) if len(v) > 2
                        else "")
        return None


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: stable code, severity, message, provenance."""

    code: str
    severity: str  # "error" | "warn" | "info"
    message: str
    span: Optional[Span] = None
    node: str = ""  # logical node / op the finding anchors to

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    def render(self) -> str:
        where = f"{self.span}: " if self.span else ""
        at = f" [{self.node}]" if self.node else ""
        return f"{where}{self.severity} {self.code}: {self.message}{at}"


class DiagnosticReport:
    """All findings of one check() pass, reported at once (the whole
    point: every contract violation in ONE diagnostic sweep instead of
    one runtime failure at a time)."""

    def __init__(self, diagnostics: Iterable[Diagnostic] = ()):
        self.diagnostics: List[Diagnostic] = list(diagnostics)

    def add(self, code: str, severity: str, message: str,
            span: Any = None, node: str = "") -> None:
        self.diagnostics.append(Diagnostic(code, severity, message,
                                           Span.of(span), node))

    def __iter__(self):
        return iter(self.sorted())

    def __len__(self) -> int:
        return len(self.diagnostics)

    def sorted(self) -> List[Diagnostic]:
        return sorted(self.diagnostics,
                      key=lambda d: (SEVERITIES[d.severity], d.code,
                                     str(d.span or "")))

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warn"]

    @property
    def clean(self) -> bool:
        """No error/warn findings (info notes do not dirty a plan)."""
        return not self.errors and not self.warnings

    def codes(self) -> set:
        return {d.code for d in self.diagnostics}

    def dedup(self) -> "DiagnosticReport":
        """Collapse findings that differ only by the consumer path that
        reached them: a construct consumed by N Tee'd branches (e.g. a
        pinned repartition feeding two group_bys) used to be reported
        once PER PATH — identical findings now report once, annotated
        with the path count.  The message is part of the identity: two
        DIFFERENT defects at the same span (e.g. id() and hash() on one
        UDF line) must both survive.  In place; returns self for
        chaining."""
        seen: dict = {}
        order = []
        for d in self.diagnostics:
            key = (d.code, d.severity, d.span, d.node, d.message)
            if key in seen:
                seen[key].append(d)
            else:
                seen[key] = [d]
                order.append(key)
        out: List[Diagnostic] = []
        for key in order:
            group = seen[key]
            d = group[0]
            if len(group) > 1:
                d = dataclasses.replace(
                    d, message=f"{d.message} [x{len(group)} consumer "
                               f"paths]")
            out.append(d)
        self.diagnostics = out
        return self

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def render(self) -> str:
        if not self.diagnostics:
            return "no findings"
        lines = [d.render() for d in self.sorted()]
        n_e, n_w = len(self.errors), len(self.warnings)
        n_i = len(self.diagnostics) - n_e - n_w
        lines.append(f"{n_e} error(s), {n_w} warning(s), {n_i} info")
        return "\n".join(lines)


_CODE_FAMILIES = (
    ("DTA0", "plan verifier (structural rules over the logical DAG)"),
    ("DTA1", "UDF lint (determinism / shippability / capture)"),
    ("DTA2", "cost & resource analyzer (pre-submit OOM/spill "
             "forecasts)"),
    ("DTA3", "SQL front end (parse / bind / type errors with "
             "line:column spans into the query text)"),
    ("DTA4", "incremental execution (standing-query refresh verdicts: "
             "incremental merge vs full re-run)"),
    ("DTA5", "plan equivalence & cross-job reuse (canonical-fingerprint "
             "and subsumption verdicts: what may share cached work, "
             "and why sharing is refused)"),
    ("DTA9", "runtime-only (no static rule can predict these)"),
)


def render_code_table() -> str:
    """The DTA code table as markdown, generated from :data:`CODES` —
    ``docs/diagnostics.md`` is this function's output verbatim
    (drift-tested by ``python -m dryad_tpu.analysis --selfcheck``), so
    a new code cannot ship undocumented."""
    lines = [
        "# Diagnostic codes (DTA)",
        "",
        "<!-- GENERATED from dryad_tpu/analysis/diagnostics.py::CODES"
        " by `python -m dryad_tpu.analysis --selfcheck --write-docs`;"
        " do not edit by hand — the selfcheck drift-gates this file."
        " -->",
        "",
    ]
    for prefix, family in _CODE_FAMILIES:
        lines.append(f"**{family}**")
        lines.append("")
        lines.append("| Code | Meaning |")
        lines.append("|---|---|")
        for code in sorted(c for c in CODES if c.startswith(prefix)):
            meaning = " ".join(CODES[code].split())
            lines.append(f"| `{code}` | {meaning} |")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


class DiagnosticError(RuntimeError):
    """Base for runtime errors that mirror a static diagnostic: carries
    the stable ``code`` and the offending construct's ``span`` so the
    failure message points at the user's query line, and tooling can map
    the raise back to the analyzer rule that would have caught it."""

    def __init__(self, message: str, code: Optional[str] = None,
                 span: Any = None):
        self.code = code
        self.span = Span.of(span)
        full = f"[{code}] {message}" if code else message
        if self.span is not None:
            full += f" (at {self.span})"
        super().__init__(full)


class LintError(RuntimeError):
    """Raised by the pre-submit gate (JobConfig.lint="error") when the
    static analyzer reports error-severity findings — the job never
    reaches the executor/cluster."""

    def __init__(self, report: DiagnosticReport):
        self.report = report
        super().__init__(
            "static analysis found error-severity diagnostics "
            "(JobConfig.lint='error'):\n" + report.render())
