"""Static cost & resource analyzer: abstract interpretation over the
lowered StageGraph.

DryadLINQ's static phase can say a plan is *ill-formed* (analysis/
plan_rules.py reproduces that); on a TPU engine the more valuable static
question is whether the plan *fits*: every partition lives in a fixed
HBM budget and every exchange buffer is statically sized, so per-stage
device footprints are decidable BEFORE submission.  This module walks
the physical plan with the :mod:`~dryad_tpu.analysis.domain` interval
domain:

* row counts propagate as intervals seeded from real source statistics
  (PData counts, store manifests' row/byte counts, text line counts,
  ``with_capacity`` bounds);
* column schemas propagate CONCRETELY — structured ops are re-traced
  abstractly through the SAME kernels the executor runs
  (``jax.eval_shape``: zero FLOPs, zero device memory), and user UDFs
  are eval_shape'd too, so predicted ``out_bytes`` match the executor's
  measurement to the byte unless the op is genuinely opaque (then the
  state is marked approximate and bounds widen instead of lying);
* per-op working-set multipliers (sort scratch, join build side,
  exchange send slots) model the peak per-device footprint for the
  DTA2xx OOM/spill gate.

The executor-side stage-op fusion (``exec.executor._fuse_stage_ops``)
is applied before interpretation so the model sees the ops that will
actually run (the fused wordcount tokenizer materializes a
vocab-capacity batch, not the token-capacity one).

Outputs: a machine-readable :class:`CostReport` (emitted as a
``cost_report`` event, cross-checked at runtime by the executor via
``cost_model_miss`` events, consumed by ``adapt/`` as priors) and the
DTA2xx diagnostic family (:func:`cost_diagnostics`).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from dryad_tpu.analysis.diagnostics import Diagnostic, Span
from dryad_tpu.analysis.domain import (AbsState, ColSpec, Interval,
                                       abstract_batch, fmt_bytes,
                                       out_bytes, schema_from_columns,
                                       schema_from_host_columns,
                                       schema_from_store_schema,
                                       schema_of_abstract)

__all__ = ["StageCostEstimate", "CostReport", "estimate_graph",
           "estimate_query", "cost_diagnostics", "estimate_plan_json",
           "cache_diagnostic", "check_stage_measurement", "COST_CODES"]

# DTA2xx codes this analyzer emits (subset of diagnostics.CODES)
COST_CODES = ("DTA200", "DTA201", "DTA202", "DTA203", "DTA204",
              "DTA205")

# fraction of device_hbm_bytes a cache()'d dataset may occupy before the
# DTA204 edge-scale warning fires (cache residency is permanent, unlike
# a stage's transient working set)
CACHE_HBM_FRACTION = 0.5

# coarse per-op working-set multipliers over the op's OUTPUT bytes:
# sort-based kernels build key lanes + a permutation payload alongside
# the data; joins hold build + probe + output; the tokenizer builds a
# slot grid.  These feed the OOM gate only — out_bytes predictions stay
# exact — so they are calibrated upper-bound-ish, not measurements.
_WORK_MULT = {
    "sort": 3.0, "group": 3.0, "distinct": 3.0, "group_top_k": 3.0,
    "group_rank": 3.0, "dgroup_local": 3.0, "dgroup_partial": 3.0,
    "dgroup_merge": 3.0, "join": 2.0, "semi_anti": 2.0,
    "group_apply": 2.0, "flat_tokens": 2.0, "tokens_group_count": 2.0,
    "flat_map": 2.0,
}


class _Streamed(Exception):
    """Plan reads a chunk-streamed source: device working set is
    O(chunk_rows) by construction — the HBM cost model does not apply."""


@dataclasses.dataclass
class StageCostEstimate:
    """Predicted resources of one stage."""

    stage: int
    label: str
    rows: Interval                    # total output rows, all partitions
    capacity: int                     # per-partition output capacity
    out_bytes: Interval               # materialized output bytes (total)
    work_bytes: Interval              # peak per-DEVICE working set
    approx: bool = False
    span: Optional[Tuple[str, int, str]] = None
    notes: Tuple[str, ...] = ()

    def to_payload(self) -> dict:
        return {"stage": self.stage, "label": self.label,
                "rows": list(self.rows.as_tuple()),
                "capacity": self.capacity,
                "out_bytes": list(self.out_bytes.as_tuple()),
                "work_bytes": list(self.work_bytes.as_tuple()),
                "approx": self.approx, "notes": list(self.notes)}

    @staticmethod
    def from_payload(d: dict) -> "StageCostEstimate":
        return StageCostEstimate(
            d["stage"], d.get("label", ""),
            Interval(*d["rows"]), d.get("capacity", 0),
            Interval(*d["out_bytes"]), Interval(*d["work_bytes"]),
            d.get("approx", False), None, tuple(d.get("notes", ())))


@dataclasses.dataclass
class CostReport:
    """Machine-readable output of one cost pass.

    ``stages`` follows plan topo order; ``bounds``/``rows_bounds``/
    ``capacity_of`` are the executor/adapt consumption surface."""

    nparts: int
    stages: List[StageCostEstimate] = dataclasses.field(
        default_factory=list)
    device_hbm_bytes: int = 0
    streamed: bool = False

    def __post_init__(self):
        self._by_stage = {s.stage: s for s in self.stages}

    def stage(self, sid: int) -> Optional[StageCostEstimate]:
        return self._by_stage.get(sid)

    def bounds(self, sid: int
               ) -> Optional[Tuple[Interval, Interval]]:
        """(rows, out_bytes) intervals for the runtime cross-check."""
        s = self._by_stage.get(sid)
        if s is None:
            return None
        return s.rows, s.out_bytes

    def rows_bounds(self, sid: int) -> Optional[Tuple[int, Optional[int]]]:
        s = self._by_stage.get(sid)
        return s.rows.as_tuple() if s is not None else None

    def capacity_of(self, sid: int) -> int:
        s = self._by_stage.get(sid)
        return s.capacity if s is not None else 0

    @property
    def peak_work(self) -> Interval:
        out = Interval(0, 0)
        for s in self.stages:
            hi = (None if out.hi is None or s.work_bytes.hi is None
                  else max(out.hi, s.work_bytes.hi))
            out = Interval(max(out.lo, s.work_bytes.lo), hi)
        return out

    def to_payload(self) -> dict:
        return {"nparts": self.nparts,
                "device_hbm_bytes": self.device_hbm_bytes,
                "streamed": self.streamed,
                "stages": [s.to_payload() for s in self.stages]}

    def to_json(self) -> str:
        return json.dumps(self.to_payload(), indent=1)

    @staticmethod
    def from_payload(d: dict) -> "CostReport":
        return CostReport(
            d.get("nparts", 1),
            [StageCostEstimate.from_payload(s)
             for s in d.get("stages", ())],
            d.get("device_hbm_bytes", 0), d.get("streamed", False))

    def render(self) -> str:
        if self.streamed:
            return ("streamed plan: device working set is O(chunk_rows)"
                    " — HBM cost model not applicable")
        lines = [f"{'stage':>6} {'label':<16} {'cap':>9} "
                 f"{'rows':>19} {'out_bytes':>15} {'work/dev':>15}"]
        for s in self.stages:
            rows = f"[{s.rows.lo}, " + (
                f"{s.rows.hi}]" if s.rows.hi is not None else "inf)")
            ob = (fmt_bytes(s.out_bytes.hi)
                  if s.out_bytes.hi is not None else "?")
            wk = (fmt_bytes(s.work_bytes.hi)
                  if s.work_bytes.hi is not None else "?")
            flag = " ~" if s.approx else ""
            lines.append(f"{s.stage:>6} {s.label:<16} {s.capacity:>9} "
                         f"{rows:>19} {ob:>15} {wk:>15}{flag}")
        pk = self.peak_work
        budget = (f" / budget {fmt_bytes(self.device_hbm_bytes)}"
                  if self.device_hbm_bytes else "")
        lines.append(
            f"peak per-device working set: {fmt_bytes(pk.lo)}"
            + (f"..{fmt_bytes(pk.hi)}" if pk.hi is not None else "..?")
            + budget + ("  (~ = approximate)" if any(
                s.approx for s in self.stages) else ""))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# source seeding


def _source_state(data: Any, nparts: int, config) -> AbsState:
    """Abstract value of a bound source: real statistics where they
    exist (PData counts, store manifests, text line counts), sound
    widening where they don't."""
    # chunk-streamed sources: the whole model is out of scope
    if getattr(data, "cs", None) is not None:
        raise _Streamed()
    spec = getattr(data, "spec", None)
    if isinstance(spec, dict):
        kind = spec.get("kind")
        cap = int(spec.get("capacity", 0))
        if kind == "store_stream":
            raise _Streamed()
        if kind == "columns":
            schema = schema_from_host_columns(
                spec["columns"], spec.get("str_max_len", 64))
            rows = spec.get("rows")
            n = (int(rows) if rows is not None
                 else len(next(iter(spec["columns"].values()), ())))
            return AbsState(Interval.exact(n), cap, schema)
        if kind == "text":
            schema = {spec.get("column", "line"):
                      ColSpec("str", max_len=int(
                          spec.get("max_line_len", 256)))}
            rows = spec.get("rows")
            rv = (Interval.exact(int(rows)) if rows is not None
                  else Interval.upto(cap * nparts))
            return AbsState(rv, cap, schema)
        if kind == "store":
            schema = (schema_from_store_schema(spec["schema"])
                      if spec.get("schema") else None)
            rows = spec.get("rows")
            rv = (Interval.exact(int(rows)) if rows is not None
                  else Interval.upto(cap * nparts))
            return AbsState(rv, cap, schema, approx=schema is None)
        if kind == "resident":
            return AbsState(Interval.upto(cap * nparts), cap, None,
                            approx=True,
                            notes=["resident source: schema unknown"])
        return AbsState(Interval.upto(cap * nparts or None), cap, None,
                        approx=True,
                        notes=[f"unknown source kind {kind!r}"])
    batch = getattr(data, "batch", None)
    if batch is not None:                       # PData (device-resident)
        import numpy as np
        schema = schema_from_columns(batch.columns, lead_dims=2)
        total = int(np.asarray(data.counts).sum())
        return AbsState(Interval.exact(total), int(data.capacity),
                        schema)
    cap = int(getattr(data, "capacity", 0) or 0)
    return AbsState(Interval.upto(cap * nparts or None), cap, None,
                    approx=True, notes=["opaque source"])


# ---------------------------------------------------------------------------
# abstract op transfer functions


def _eval_abs(f, *args):
    """jax.eval_shape with the analyzer's failure contract: returns the
    abstract result or None (caller widens to approximate)."""
    try:
        import jax
        return jax.eval_shape(f, *args)
    except Exception:
        return None


def _abs_of_result(res: Any, rows: Interval, nparts: int,
                   fallback: AbsState, what: str) -> AbsState:
    """Build the post-op state from an eval_shape result (Batch or
    columns dict), widening to the (approximate) fallback on failure."""
    if res is None:
        out = AbsState(rows, fallback.capacity, fallback.schema,
                       approx=True, notes=list(fallback.notes))
        return out.note(f"{what}: not statically traceable — byte "
                        f"bounds widened")
    schema, cap = schema_of_abstract(res)
    st = AbsState(rows.clamp_hi(cap * nparts), cap, schema,
                  approx=fallback.approx, notes=list(fallback.notes))
    return st


def _abs_batch(s: AbsState):
    return abstract_batch(s.schema, s.capacity)


def _dist_lo(rows: Interval) -> Interval:
    """Rows interval after a distinct/group-style reduction: at least
    one group survives when the input is provably non-empty."""
    return Interval(1 if rows.lo >= 1 else 0, rows.hi)


def _abs_op(s: AbsState, op, nparts: int, config,
            others: List[AbsState]) -> AbsState:
    """Transfer function of one StageOp (mirrors executor._apply_op)."""
    from dryad_tpu.ops import kernels
    k, p = op.kind, op.params
    rows = s.rows_clamped(nparts)
    known = s.schema is not None

    if k == "fn":
        res = (_eval_abs(lambda c: p["fn"](dict(c)),
                         _abs_batch(s).columns) if known else None)
        return _abs_of_result(res, rows, nparts, s,
                              f"udf {p.get('label', 'map')!r}")
    if k == "filter":
        return AbsState(rows.relax_lo(), s.capacity, s.schema,
                        s.approx, list(s.notes))
    if k == "flat_tokens":
        from dryad_tpu.ops.text import split_tokens
        cap = int(p["out_capacity"])
        res = (_eval_abs(
            lambda b: split_tokens(
                b, p["column"], out_capacity=cap,
                max_token_len=p["max_token_len"], delims=p["delims"],
                max_tokens_per_row=p.get("max_tokens_per_row"))[0],
            _abs_batch(s)) if known else None)
        fb = AbsState(Interval.upto(cap * nparts), cap,
                      {p["column"]: ColSpec("str",
                                            max_len=p["max_token_len"])},
                      s.approx, list(s.notes))
        return _abs_of_result(res, Interval.upto(cap * nparts), nparts,
                              fb, "flat_tokens")
    if k == "tokens_group_count":
        from dryad_tpu.ops.text import tokenize_group_count
        vcap = int(p["vocab_capacity"])
        # valid vocab rows per partition cannot exceed the tokens that
        # fed them (the token capacity), even though the OUTPUT batch is
        # padded to vocab_capacity — rows and bytes bound separately
        rcap = min(vcap, int(p["out_capacity"]))
        res = (_eval_abs(
            lambda b: tokenize_group_count(
                b, p["column"], out_capacity=int(p["out_capacity"]),
                vocab_capacity=vcap, count_name=p["count_name"],
                max_token_len=p["max_token_len"], delims=p["delims"],
                lower=p["lower"],
                max_tokens_per_row=p.get("max_tokens_per_row"))[0],
            _abs_batch(s)) if known else None)
        fb = AbsState(Interval.upto(rcap * nparts), vcap, None, s.approx,
                      list(s.notes))
        out = _abs_of_result(res, Interval.upto(rcap * nparts), nparts,
                             fb, "tokens_group_count")
        return AbsState(out.rows.clamp_hi(rcap * nparts), out.capacity,
                        out.schema, out.approx, out.notes)
    if k == "group":
        res = (_eval_abs(
            lambda b: kernels.group_aggregate(b, list(p["keys"]),
                                              dict(p["aggs"])),
            _abs_batch(s)) if known else None)
        return _abs_of_result(res, _dist_lo(rows), nparts, s, "group")
    if k in ("dgroup_local", "dgroup_partial", "dgroup_merge"):
        fns = {"dgroup_local": kernels.group_decompose_local,
               "dgroup_partial": kernels.group_decompose_partial}
        if k == "dgroup_merge":
            res = (_eval_abs(
                lambda b: kernels.group_decompose_merge(
                    b, list(p["keys"]), p["decs"], p["box"],
                    p["finalize"]), _abs_batch(s)) if known else None)
        else:
            res = (_eval_abs(
                lambda b: fns[k](b, list(p["keys"]), p["decs"],
                                 p["box"]), _abs_batch(s))
                if known else None)
        return _abs_of_result(res, _dist_lo(rows), nparts, s, k)
    if k == "mean_fin":
        res = (_eval_abs(
            lambda c: kernels.mean_finalize_columns(dict(c), p["cols"]),
            _abs_batch(s).columns) if known else None)
        return _abs_of_result(res, rows, nparts, s, "mean_fin")
    if k == "group_apply":
        ocap = int(p["out_capacity"])
        res = (_eval_abs(
            lambda b: kernels.group_regroup_apply(
                b, list(p["keys"]), p["fn"], p["max_groups"],
                p["group_capacity"], p["out_rows"], ocap)[0],
            _abs_batch(s)) if known else None)
        fb = AbsState(Interval.upto(ocap * nparts), ocap, None, s.approx,
                      list(s.notes))
        return _abs_of_result(res, Interval.upto(ocap * nparts), nparts,
                              fb, "group_apply")
    if k == "group_top_k":
        return AbsState(rows.relax_lo(), s.capacity, s.schema, s.approx,
                        list(s.notes))
    if k == "group_rank":
        res = (_eval_abs(
            lambda b: kernels.group_rank_select(b, list(p["keys"]),
                                                p["by"], p["rank"],
                                                p["out"]),
            _abs_batch(s)) if known else None)
        return _abs_of_result(res, _dist_lo(rows), nparts, s,
                              "group_rank")
    if k == "distinct":
        return AbsState(_dist_lo(rows), s.capacity, s.schema, s.approx,
                        list(s.notes))
    if k == "sort":
        return s
    if k == "take":
        n = int(p["n"])
        return AbsState(Interval(min(rows.lo, n),
                                 n if rows.hi is None
                                 else min(rows.hi, n)),
                        s.capacity, s.schema, s.approx, list(s.notes))
    if k == "skip":
        return AbsState(Interval(max(0, rows.lo - int(p["n"])), rows.hi),
                        s.capacity, s.schema, s.approx, list(s.notes))
    if k in ("take_while", "skip_while"):
        return AbsState(rows.relax_lo(), s.capacity, s.schema, s.approx,
                        list(s.notes))
    if k == "recap":
        cap = int(p["capacity"])
        return AbsState(rows.clamp_hi(cap * nparts), cap, s.schema,
                        s.approx, list(s.notes))
    if k == "row_index":
        schema = (dict(s.schema, **{p["column"]: ColSpec("dense",
                                                         "int32")})
                  if known else None)
        return AbsState(rows, s.capacity, schema, s.approx,
                        list(s.notes))
    if k == "sliding_window":
        w = int(p["w"])
        schema = None
        if known:
            schema = {kk: dataclasses.replace(cs, repeat=cs.repeat * w)
                      for kk, cs in s.schema.items()}
        return AbsState(rows.relax_lo(), s.capacity, schema, s.approx,
                        list(s.notes))
    if k == "apply":
        if known:
            if p.get("with_index"):
                import numpy as _np

                import jax
                idx = jax.ShapeDtypeStruct((), _np.int32)
                res = _eval_abs(lambda b: p["fn"](b, idx),
                                _abs_batch(s))
            else:
                res = _eval_abs(p["fn"], _abs_batch(s))
        else:
            res = None
        out_rows = Interval.upto(rows.hi)   # apply may reshape rows
        st = _abs_of_result(res, out_rows, nparts, s,
                            f"apply {p.get('label', '')!r}")
        return AbsState(st.rows.clamp_hi(st.capacity * nparts
                                         if st.capacity else None),
                        st.capacity, st.schema, st.approx, st.notes)
    if k == "flat_map":
        cap = int(p["out_capacity"])
        res = (_eval_abs(
            lambda b: kernels.flat_map_expand(b, p["fn"], cap)[0],
            _abs_batch(s)) if known else None)
        fb = AbsState(Interval.upto(cap * nparts), cap, None, s.approx,
                      list(s.notes))
        return _abs_of_result(res, Interval.upto(cap * nparts), nparts,
                              fb, f"flat_map {p.get('label', '')!r}")
    # -- binary ops (consume `others`) ------------------------------------
    if k == "join":
        r = others[0]
        ocap = int(p["out_capacity"])
        hi = ocap * nparts
        if rows.hi is not None and r.rows.hi is not None:
            hi = min(hi, max(rows.hi, 1) * max(r.rows.hi, 1))
        lo = rows.lo if p.get("how") in ("left", "full") else 0
        res = None
        if known and r.schema is not None:
            res = _eval_abs(
                lambda lb, rb: kernels.hash_join(
                    lb, rb, list(p["left_keys"]), list(p["right_keys"]),
                    out_capacity=ocap, how=p.get("how", "inner"),
                    right_unique=p.get("right_unique", False))[0],
                _abs_batch(s), _abs_batch(r))
        fb = AbsState(Interval(lo, hi), ocap, None,
                      s.approx or r.approx,
                      list(s.notes) + list(r.notes))
        return _abs_of_result(res, Interval(lo, hi), nparts, fb, "join")
    if k == "semi_anti":
        return AbsState(rows.relax_lo(), s.capacity, s.schema,
                        s.approx or others[0].approx, list(s.notes))
    if k == "concat":
        r = others[0]
        res = None
        if known and r.schema is not None:
            res = _eval_abs(kernels.concat2, _abs_batch(s),
                            _abs_batch(r))
        fb = AbsState(rows + r.rows_clamped(nparts),
                      s.capacity + r.capacity, None,
                      s.approx or r.approx,
                      list(s.notes) + list(r.notes))
        return _abs_of_result(res, rows + r.rows_clamped(nparts),
                              nparts, fb, "concat")
    if k == "zip":
        r = others[0]
        schema = None
        if known and r.schema is not None:
            suffix = p.get("suffix", "_r")
            schema = dict(s.schema)
            for kk, cs in r.schema.items():
                schema[kk + suffix if kk in schema else kk] = cs
        cap = min(s.capacity, r.capacity) or max(s.capacity, r.capacity)
        hi = (None if rows.hi is None or r.rows.hi is None
              else min(rows.hi, r.rows.hi))
        return AbsState(Interval(0, hi).clamp_hi(cap * nparts), cap,
                        schema, s.approx or r.approx,
                        list(s.notes) + list(r.notes))
    if k == "apply2":
        r = others[0]
        res = None
        if known and r.schema is not None:
            res = _eval_abs(p["fn"], _abs_batch(s), _abs_batch(r))
        out_rows = Interval.upto(rows.hi)
        st = _abs_of_result(res, out_rows, nparts, s,
                            f"apply2 {p.get('label', '')!r}")
        return AbsState(st.rows.clamp_hi(st.capacity * nparts
                                         if st.capacity else None),
                        st.capacity, st.schema, st.approx, st.notes)
    # unknown op kind: pass through, widened
    return AbsState(Interval.upto(rows.hi), s.capacity, s.schema, True,
                    list(s.notes) + [f"unknown op kind {k!r}"])


def _abs_exchange(s: AbsState, ex, nparts: int, config) -> AbsState:
    cap = int(ex.out_capacity)
    if ex.kind == "broadcast":
        return AbsState(s.rows_clamped(nparts).scale(nparts)
                        .clamp_hi(cap * nparts), cap, s.schema,
                        s.approx, list(s.notes))
    # hash/range: rows conserved, re-placed; capacity re-declared
    return AbsState(s.rows_clamped(nparts).clamp_hi(cap * nparts), cap,
                    s.schema, s.approx, list(s.notes))


# ---------------------------------------------------------------------------
# the stage walk


def _add_hi(hi: Optional[int], s: AbsState,
            mult: float = 1.0) -> Optional[int]:
    """Accumulate one abstract value's per-device bytes into the
    working-set upper bound (None once any contribution is unknown)."""
    pb = s.part_bytes()
    if pb is None or hi is None:
        return None
    return hi + int(pb * mult)


def estimate_graph(graph, nparts: int, config=None) -> CostReport:
    """Abstractly interpret a lowered StageGraph.  Returns a CostReport
    whose stage ids match the graph's (and — because planning is
    deterministic — any re-plan of the same query)."""
    try:
        from dryad_tpu.exec.executor import _fuse_stage_ops
    except Exception:                       # jax-less environment
        def _fuse_stage_ops(ops):
            return ops
    hbm = int(getattr(config, "device_hbm_bytes", 0) or 0)
    slack = int(getattr(config, "initial_send_slack", 2) or 2)
    report = CostReport(nparts, [], device_hbm_bytes=hbm)
    states: Dict[int, AbsState] = {}
    try:
        for st in graph.topo_order():
            leg_states: List[AbsState] = []
            work_lo, work_hi = 0, 0
            notes: List[str] = []
            exchange_unbounded = False
            for leg in st.legs:
                if isinstance(leg.src, int):
                    s = states[leg.src]
                    s = AbsState(s.rows, s.capacity, s.schema, s.approx,
                                 [])
                elif leg.src[0] == "source":
                    s = _source_state(leg.src[1], nparts, config)
                else:                                   # placeholder
                    cap = 0
                    s = AbsState(Interval.upto(None), cap, None,
                                 approx=True,
                                 notes=[f"placeholder "
                                        f"{leg.src[1]!r}: rows "
                                        f"unbounded"])
                # the leg input is resident for the whole stage program
                in_pb = s.part_bytes()
                if in_pb is not None:
                    work_lo += in_pb
                work_hi = _add_hi(work_hi, s)
                for op in _fuse_stage_ops(list(leg.ops)):
                    s = _abs_op(s, op, nparts, config, [])
                    work_hi = _add_hi(work_hi, s,
                                      _WORK_MULT.get(op.kind, 1.0))
                if leg.exchange is not None:
                    if s.rows.hi is None:
                        exchange_unbounded = True
                    s = _abs_exchange(s, leg.exchange, nparts, config)
                    mult = (1.0 if leg.exchange.kind == "broadcast"
                            else 1.0 + slack)
                    work_hi = _add_hi(work_hi, s, mult)
                notes.extend(s.notes)
                leg_states.append(s)
            cur, rest = leg_states[0], leg_states[1:]
            for op in _fuse_stage_ops(list(st.body)):
                if op.kind in ("join", "semi_anti", "concat", "apply2",
                               "zip"):
                    cur = _abs_op(cur, op, nparts, config, rest)
                    rest = []
                else:
                    cur = _abs_op(cur, op, nparts, config, [])
                work_hi = _add_hi(work_hi, cur,
                                  _WORK_MULT.get(op.kind, 1.0))
                notes.extend(n for n in cur.notes if n not in notes)
            states[st.id] = cur
            ob = cur.part_bytes()
            if ob is not None:
                obt = out_bytes(cur.schema, cur.capacity, nparts)
                out_iv = Interval.exact(obt)
                work_lo += ob
            else:
                out_iv = Interval.upto(None)
            if exchange_unbounded:
                notes.append("unbounded rows reach an exchange")
            span = None
            for leg in st.legs:
                for op in leg.ops:
                    span = span or op.span
            for op in st.body:
                span = span or op.span
            report.stages.append(StageCostEstimate(
                st.id, st.label, cur.rows_clamped(nparts),
                cur.capacity, out_iv,
                Interval(work_lo, work_hi), approx=cur.approx
                or ob is None, span=span,
                notes=tuple(dict.fromkeys(notes))))
    except _Streamed:
        return CostReport(nparts, [], device_hbm_bytes=hbm,
                          streamed=True)
    report.__post_init__()
    return report


def estimate_query(node, nparts: int, hosts: int = 1, levels: tuple = (),
                   config=None) -> CostReport:
    """Plan ``node`` exactly like submission would and estimate the
    result.  Planning is deterministic, so the returned report's stage
    ids line up with the graph the executor will run."""
    from dryad_tpu.plan.planner import plan_query
    graph = plan_query(node, nparts, hosts=hosts, config=config,
                       levels=levels)
    return estimate_graph(graph, nparts, config=config)


# ---------------------------------------------------------------------------
# DTA2xx diagnostics


def cost_diagnostics(report: CostReport, config=None) -> List[Diagnostic]:
    """The DTA2xx findings of one cost pass: provable OOM (error),
    possible OOM/spill (warn), unbounded fan-out at an exchange (warn),
    and the per-stage cost table summary (info)."""
    out: List[Diagnostic] = []
    if report.streamed:
        return out
    hbm = report.device_hbm_bytes
    worst: Optional[StageCostEstimate] = None
    for s in report.stages:
        sp = Span.of(s.span)
        if hbm and s.work_bytes.lo > hbm:
            out.append(Diagnostic(
                "DTA201", "error",
                f"stage {s.stage} ({s.label}) provably exceeds the "
                f"device HBM budget: certain per-device footprint "
                f"{fmt_bytes(s.work_bytes.lo)} > device_hbm_bytes="
                f"{fmt_bytes(hbm)} — repartition over more devices, "
                f"lower capacities, or take the streamed (>HBM) path",
                sp, node=f"stage{s.stage}:{s.label}"))
        elif hbm and (s.work_bytes.hi is None
                      or s.work_bytes.hi > hbm):
            bound = (fmt_bytes(s.work_bytes.hi)
                     if s.work_bytes.hi is not None else "unbounded")
            out.append(Diagnostic(
                "DTA202", "warn",
                f"stage {s.stage} ({s.label}) may exceed the device "
                f"HBM budget (predicted spill): per-device working set "
                f"up to {bound} vs device_hbm_bytes={fmt_bytes(hbm)}",
                sp, node=f"stage{s.stage}:{s.label}"))
        if "unbounded rows reach an exchange" in s.notes:
            out.append(Diagnostic(
                "DTA203", "warn",
                f"stage {s.stage} ({s.label}): an input with no static "
                f"row bound feeds an exchange — the exchange buffer is "
                f"sized blind; bound it with with_capacity()/assume_* "
                f"or seed the source with real statistics",
                sp, node=f"stage{s.stage}:{s.label}"))
        if worst is None or (s.work_bytes.hi is not None
                             and (worst.work_bytes.hi is None
                                  or s.work_bytes.hi
                                  > worst.work_bytes.hi)):
            worst = s
    if report.stages:
        pk = report.peak_work
        out.append(Diagnostic(
            "DTA205", "info",
            f"predicted cost: {len(report.stages)} stage(s), peak "
            f"per-device working set {fmt_bytes(pk.lo)}"
            + (f"..{fmt_bytes(pk.hi)}" if pk.hi is not None else "..?")
            + (f" (driver: stage {worst.stage} {worst.label})"
               if worst is not None else "")
            + " — Dataset.explain(cost=True) for the full table",
            None, node="cost"))
    return out


def cache_diagnostic(report: CostReport, config=None
                     ) -> Optional[Diagnostic]:
    """DTA204: ``cache()`` of edge-scale data (a sizable fraction of the
    HBM budget).  Applies to the MATERIALIZED bytes of the cached
    dataset (the last stage's output), not a transient working set.

    Severity follows ``JobConfig.ooc_restream_cache``: with the
    store-backed re-streaming cache tier ON (default) the cache()
    LOWERS to a local chunked cache instead of pinning HBM, so the
    finding is informational and points at the tier's knobs; with the
    tier OFF it warns — the result pins device memory for the Context's
    lifetime."""
    hbm = int(getattr(config, "device_hbm_bytes", 0) or 0)
    if not hbm or report.streamed or not report.stages:
        return None
    last = report.stages[-1]
    ob = last.out_bytes.hi
    if ob is None or ob <= CACHE_HBM_FRACTION * hbm:
        return None
    scale = (f"{fmt_bytes(ob)} ({100.0 * ob / hbm:.0f}% of "
             f"device_hbm_bytes={fmt_bytes(hbm)})")
    if getattr(config, "ooc_restream_cache", False):
        return Diagnostic(
            "DTA204", "info",
            f"edge-scale cache(): {scale} lowers to the store-backed "
            f"re-streaming cache tier (local chunked cache, per-chunk "
            f"fingerprints; iterations re-stream local sequential "
            f"reads) — set JobConfig.ooc_cache_dir for restart reuse, "
            f"or ooc_restream_cache=False to pin device-resident",
            Span.of(last.span), node=f"stage{last.stage}:{last.label}")
    return Diagnostic(
        "DTA204", "warn",
        f"cache() would pin {scale} in device memory for the Context's "
        f"lifetime (ooc_restream_cache is off) — re-enable the "
        f"re-streaming cache tier, or persist with to_store() and "
        f"read_store_stream() (the >HBM path) instead of cache() at "
        f"this scale",
        Span.of(last.span), node=f"stage{last.stage}:{last.label}")


# ---------------------------------------------------------------------------
# runtime cross-check (executor-side model validation)


def check_stage_measurement(est: StageCostEstimate, scale: int,
                            rows: int, out_bytes: int,
                            nparts: int) -> List[dict]:
    """Compare one stage's MEASURED (rows, out_bytes) against the static
    prediction; returns ``cost_model_miss`` payload dicts (empty = the
    model held).

    Rows are checked unconditionally — a rows miss means a transfer
    function is unsound.  Bytes are checked only at capacity scale 1:
    the model predicts the PLANNED shapes exactly, and the executor's
    overflow retries right-size capacities from measured need (its own
    adaptive behavior, reported via the stage's ``scale``), so a scaled
    batch validates nothing about the model.  Approximate stages are
    skipped: their bounds were widened on purpose."""
    out: List[dict] = []
    if est.approx:
        return out
    if not est.rows.contains(int(rows)):
        out.append({"event": "cost_model_miss", "stage": est.stage,
                    "label": est.label, "what": "rows",
                    "measured": int(rows),
                    "predicted": list(est.rows.as_tuple())})
    if int(scale) == 1 and est.out_bytes.hi is not None \
            and not est.out_bytes.contains(int(out_bytes)):
        out.append({"event": "cost_model_miss", "stage": est.stage,
                    "label": est.label, "what": "out_bytes",
                    "measured": int(out_bytes), "scale": int(scale),
                    "predicted": list(est.out_bytes.as_tuple())})
    return out


# ---------------------------------------------------------------------------
# offline (serialized-plan) capacity model — no callables, no jax


def estimate_plan_json(plan_json: str, nparts: int = 1,
                       config=None) -> CostReport:
    """Row/capacity cost pass over a SERIALIZED plan (graph_to_json
    output): callables and sources are gone, so schemas (and therefore
    bytes) are unknown — but every capacity in the plan is structural,
    so the per-stage capacity/row-bound table still computes.  Used by
    ``python -m dryad_tpu.analysis plan.json --cost``."""
    d = json.loads(plan_json)
    report = CostReport(nparts, [])
    caps: Dict[int, int] = {}
    for st in d.get("stages", []):
        cap = 0
        for leg in st.get("legs", []):
            src = leg.get("src", {})
            leg_cap = caps.get(src.get("stage"), 0) \
                if "stage" in src else 0
            for op in leg.get("ops", []):
                pc = op.get("params", {})
                for key in ("out_capacity", "vocab_capacity",
                            "capacity"):
                    if isinstance(pc.get(key), int):
                        leg_cap = pc[key]
            ex = leg.get("exchange")
            if ex is not None:
                leg_cap = int(ex.get("out_capacity", leg_cap))
            cap = max(cap, leg_cap)
        for op in st.get("body", []):
            pc = op.get("params", {})
            for key in ("out_capacity", "vocab_capacity", "capacity"):
                if isinstance(pc.get(key), int):
                    cap = pc[key]
        caps[st["id"]] = cap
        report.stages.append(StageCostEstimate(
            st["id"], st.get("label", ""),
            Interval.upto(cap * nparts if cap else None), cap,
            Interval.upto(None), Interval.upto(None), approx=True))
    report.__post_init__()
    return report
