"""Self-lint core: the dependency-free unused-import scan.

The framework's own hygiene gate (``tests/test_selflint.py`` and the
``python -m dryad_tpu.analysis --selfcheck`` CLI) runs ``ruff check``
when the environment ships it, but the container may not — this module
is the always-available fallback: an AST unused-import scan honoring
``noqa`` and ``__all__``-by-string re-exports, the highest-value
pyflakes rule (F401) in ~60 lines.  Lives in the package (not the test
tree) so both entry points share ONE implementation.
"""

from __future__ import annotations

import ast
import pathlib
from typing import List, Tuple

__all__ = ["py_files", "unused_imports", "scan_package"]

PKG_DIR = pathlib.Path(__file__).resolve().parent.parent


def py_files(pkg: pathlib.Path = PKG_DIR) -> List[pathlib.Path]:
    return sorted(p for p in pkg.rglob("*.py"))


def unused_imports(path: pathlib.Path
                   ) -> List[Tuple[pathlib.Path, int, str, str]]:
    """(path, line, name, statement) for every import binding the module
    never reads.  Imports inside ``try:`` blocks (optional-dependency
    probes), ``noqa``-marked lines, underscore-prefixed names
    (side-effect/shim convention), and names re-exported by string
    (``__all__`` entries) are exempt."""
    src = path.read_text()
    lines = src.splitlines()
    tree = ast.parse(src, filename=str(path))

    bindings = {}  # name -> (lineno, text)
    in_try = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            for sub in ast.walk(node):
                in_try.add(id(sub))
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if id(node) in in_try:
            continue
        if isinstance(node, ast.ImportFrom) \
                and node.module == "__future__":
            continue
        stmt = " ".join(
            lines[i].strip()
            for i in range(node.lineno - 1,
                           (node.end_lineno or node.lineno)))
        if "noqa" in stmt:
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name.split(".")[0]
            if name.startswith("_"):
                continue
            bindings[name] = (node.lineno, stmt)

    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    used |= {n.value for n in ast.walk(tree)
             if isinstance(n, ast.Constant) and isinstance(n.value, str)
             and n.value in bindings}  # __all__ re-exports by string
    return [(path, line, name, stmt)
            for name, (line, stmt) in sorted(bindings.items(),
                                             key=lambda kv: kv[1][0])
            if name not in used]


def scan_package(pkg: pathlib.Path = PKG_DIR) -> List[str]:
    """Unused-import findings over the whole package, rendered one per
    line (empty list = clean)."""
    out = []
    for path in py_files(pkg):
        for p, line, name, stmt in unused_imports(path):
            out.append(f"{p}:{line}: unused import {name!r} ({stmt})")
    return out
