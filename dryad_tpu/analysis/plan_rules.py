"""Plan verifier: structural rules over the logical Node DAG.

DryadLINQ's phase-1 query generation statically validates the expression
tree (operator applicability, closure serializability) before any cluster
resource is touched (DryadLinqQueryGen.cs phase1).  This is the dryad_tpu
counterpart: ``check_plan`` walks the ``plan/expr.py`` DAG pre-trace and
reports ALL findings in one DiagnosticReport — the errors the runtime
would otherwise raise one at a time mid-job (runtime/stream_plan.py,
runtime/shiplan.py) plus hazards it never catches at all (redundant
exchanges, unsound assume_* claims, nondeterministic UDFs).

Each rule carries the stable code of the runtime raise site it mirrors
(diagnostics.CODES); tests/test_analysis.py asserts the mapping has no
drift.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from dryad_tpu.analysis.diagnostics import (Diagnostic, DiagnosticReport,
                                            Span)
from dryad_tpu.analysis.udf_lint import (fn_def_site, lint_udf,
                                         shippability_of)
from dryad_tpu.plan import expr as E

__all__ = ["check_plan", "RULES", "STATIC_RULE_CODES", "PlanCheck"]


def _is_stream_source(data: Any) -> bool:
    """Stream sources by duck type (no jax import): a StreamSource wraps
    a ChunkSource as ``.cs``; a cluster stream is a DeferredSource whose
    spec kind is "store_stream"."""
    spec = getattr(data, "spec", None)
    if isinstance(spec, dict) and spec.get("kind") == "store_stream":
        return True
    return getattr(data, "cs", None) is not None


def _is_deferred_source(data: Any) -> bool:
    return isinstance(getattr(data, "spec", None), dict)


def _node_label(n: E.Node) -> str:
    label = getattr(n, "label", "")
    t = type(n).__name__
    return f"{t}:{label}" if label and label != t.lower() else t


class PlanCheck:
    """Shared state one check pass's rules read: the walked DAG, consumer
    counts, stream-source presence, and the cluster-target flag."""

    def __init__(self, root: E.Node, cluster: bool = False,
                 fn_table: Optional[Dict[str, Any]] = None):
        self.root = root
        self.nodes: List[E.Node] = E.walk(root)
        self.cluster = bool(cluster)
        self.fn_table = dict(fn_table or {})
        self.registered_ids: Set[int] = {id(v)
                                         for v in self.fn_table.values()}
        # shiplan's process-global registry ships too (register_fn_table)
        # — the static view must match what serialize_for_cluster accepts
        # (lazy import: shiplan imports analysis.diagnostics)
        from dryad_tpu.runtime.shiplan import _GLOBAL_FN_TABLE
        self.registered_ids |= {id(v) for v in _GLOBAL_FN_TABLE.values()}
        self.consumers: Dict[int, int] = {}
        for n in self.nodes:
            for p in n.parents:
                self.consumers[p.id] = self.consumers.get(p.id, 0) + 1
        self.has_stream = any(
            isinstance(n, E.Source) and _is_stream_source(n.data)
            for n in self.nodes)
        # nodes with a WithCapacity descendant (transitive) — the
        # capacity-hazard rule keys off this
        self.capped: Set[int] = set()
        capped_frontier = [n for n in self.nodes
                           if isinstance(n, E.WithCapacity)]
        seen: Set[int] = set()
        while capped_frontier:
            n = capped_frontier.pop()
            if n.id in seen:
                continue
            seen.add(n.id)
            self.capped.add(n.id)
            capped_frontier.extend(n.parents)

    def udf_fields(self) -> List[Tuple[E.Node, str, Callable, bool]]:
        """(node, role, callable, ships) for every user callable reachable
        from the DAG.  ``ships`` marks the ones runtime/shiplan.py must
        ship by reference: ``host_fn`` (oracle-only) and Decomposable
        members (shipped via their registered parent object) never do."""
        out: List[Tuple[E.Node, str, Callable, bool]] = []
        for n in self.nodes:
            fn = getattr(n, "fn", None)
            if callable(fn):
                out.append((n, f"{_node_label(n)}.fn", fn, True))
            host_fn = getattr(n, "host_fn", None)
            if callable(host_fn):
                out.append((n, f"{_node_label(n)}.host_fn", host_fn,
                            False))
            if isinstance(n, E.GroupByAgg):
                for name, spec in n.aggs.items():
                    if isinstance(spec, E.Decomposable):
                        for part in ("seed", "merge", "finalize"):
                            pfn = getattr(spec, part)
                            if callable(pfn):
                                out.append((n, f"agg {name}.{part}", pfn,
                                            False))
        return out


@dataclasses.dataclass(frozen=True)
class Rule:
    code: str
    name: str
    fn: Callable[[PlanCheck], List[Diagnostic]]


def _span(n: E.Node) -> Optional[Span]:
    return Span.of(getattr(n, "span", None))


# ---------------------------------------------------------------------------
# stream-mode rules — mirror every StreamPlanError raise site
# (DTA001 — global take over cluster streams — RETIRED: the runtime
# grew a real lowering, runtime/stream_plan._global_take, so there is
# no raise site left to mirror)


def _rule_stream_placeholder(c: PlanCheck) -> List[Diagnostic]:
    if not (c.cluster and c.has_stream):
        return []
    out = []
    for n in c.nodes:
        if isinstance(n, E.Placeholder):
            out.append(Diagnostic(
                "DTA002", "error",
                f"placeholder {n.name!r} in a streamed cluster plan — "
                f"do_while ships loop state as residents; a streamed "
                f"pipeline cannot be a loop body input",
                _span(n), _node_label(n)))
    return out


# logical node types -> the physical op kind their lowering emits (the
# kind runtime/stream_plan checks against its _UNSUPPORTED map)
_NODE_OP_KINDS = {
    E.Map: "fn", E.Filter: "filter", E.FlatTokens: "flat_tokens",
    E.FlatMap: "flat_map", E.ApplyPerPartition: "apply",
    E.GroupByAgg: "group", E.GroupApply: "group_apply",
    E.GroupTopK: "group_top_k", E.GroupRankSelect: "group_rank",
    E.Join: "join", E.OrderBy: "sort", E.Distinct: "distinct",
    E.Concat: "concat", E.Zip: "zip", E.SlidingWindow: "sliding_window",
    E.WithRowIndex: "row_index", E.WithCapacity: "recap",
    E.CrossApply: "apply2",
}


def _rule_stream_unsupported(c: PlanCheck) -> List[Diagnostic]:
    """Mirror runtime/stream_plan._UNSUPPORTED (currently empty — every
    operator streams, channelinterface.h:212 parity — but the rule stays
    so a future entry there is caught statically the same day)."""
    if not (c.cluster and c.has_stream):
        return []
    from dryad_tpu.runtime.stream_plan import _UNSUPPORTED
    if not _UNSUPPORTED:
        return []
    out = []
    for n in c.nodes:
        kind = _NODE_OP_KINDS.get(type(n))
        if kind in _UNSUPPORTED:
            out.append(Diagnostic(
                "DTA003", "error",
                f"op {kind!r} is not supported over cluster streams: "
                f"{_UNSUPPORTED[kind]}", _span(n), _node_label(n)))
    return out


# ---------------------------------------------------------------------------
# hazard rules — contracts the runtime never checks


def _rule_capacity_hazard(c: PlanCheck) -> List[Diagnostic]:
    """DTA010, severity by whether the runtime can right-size the hazard
    from MEASUREMENT: a non-broadcast join's legs are hash exchanges —
    eligible for the r06 measured-slot machinery (the first-wave
    counts probe and the per-leg slot feedback,
    exec/executor._slot_hints) — and any fan-out op inside a do_while
    body re-runs with measured needs after wave 1, so the analyzer
    downgrades to info there instead of contradicting the exact-slot
    machinery.  First-wave-only hazards (flat_map / cross_apply /
    broadcast join in a one-shot job) keep warn: their only escape is
    the blind overflow-retry ladder."""
    has_loop = any(isinstance(n, E.Placeholder) for n in c.nodes)
    out = []
    for n in c.nodes:
        if not isinstance(n, (E.FlatMap, E.CrossApply, E.Join)):
            continue
        if n.id in c.capped:
            continue
        what = {E.FlatMap: "flat_map output capacity is a static guess",
                E.CrossApply: "cross_apply output rides the left "
                              "capacity",
                E.Join: "join output capacity is expansion x left "
                        "capacity"}[type(n)]
        measured = has_loop or (isinstance(n, E.Join)
                                and not n.broadcast_right)
        sev = "info" if measured else "warn"
        note = (" (measured-slot feedback right-sizes this leg after "
                "the first wave)" if measured else "")
        out.append(Diagnostic(
            "DTA010", sev,
            f"{what}; overflow triggers measured capacity retries — "
            f"bound it with .with_capacity() when the fan-out is known "
            f"(required inside do_while bodies)" + note,
            _span(n), _node_label(n)))
    return out


def _rule_redundant_exchange(c: PlanCheck) -> List[Diagnostic]:
    out = []
    for n in c.nodes:
        if isinstance(n, E.HashRepartition):
            want = E.Partitioning("hash", tuple(n.keys))
        elif isinstance(n, E.RangeRepartition):
            want = E.Partitioning("range", tuple(n.keys))
        else:
            continue
        have = n.parents[0].partitioning
        if have == want:
            out.append(Diagnostic(
                "DTA011", "warn",
                f"redundant {have.kind} repartition on "
                f"{', '.join(want.keys)}: the input already carries this "
                f"placement — the exchange moves every row for nothing",
                _span(n), _node_label(n)))
    return out


def _rule_tee_without_cache(c: PlanCheck) -> List[Diagnostic]:
    out = []
    for n in c.nodes:
        if c.consumers.get(n.id, 0) <= 1 or isinstance(n, E.Source):
            continue
        out.append(Diagnostic(
            "DTA012", "info",
            f"consumed by {c.consumers[n.id]} downstream branches: the "
            f"planner materializes it once per query (Tee), but separate "
            f"queries recompute it — .cache() if reused across terminals "
            f"or do_while iterations", _span(n), _node_label(n)))
    return out


def _rule_unsound_assume(c: PlanCheck) -> List[Diagnostic]:
    out = []
    for n in c.nodes:
        if not isinstance(n, E.AssumePartitioning):
            continue
        have = n.parents[0].partitioning
        claim = E.Partitioning(n.kind, tuple(n.keys))
        if have.kind != "none" and have != claim:
            out.append(Diagnostic(
                "DTA013", "warn",
                f"assume_{n.kind}_partition({', '.join(n.keys)}) "
                f"contradicts the input's known placement "
                f"{have.kind}({', '.join(have.keys)}) — downstream "
                f"shuffle elimination will trust the claim and silently "
                f"mis-group if it is wrong", _span(n), _node_label(n)))
        if not n.keys and n.kind in ("hash", "range"):
            out.append(Diagnostic(
                "DTA013", "warn",
                f"assume_{n.kind}_partition with no keys claims nothing "
                f"a lowering can use", _span(n), _node_label(n)))
    return out


_PINNING_NODES = (E.AssumePartitioning, E.HashRepartition,
                  E.RangeRepartition)


def _pinning_ancestor(n: E.Node, claim) -> Optional[E.Node]:
    """Walk the primary-parent chain while it carries ``claim``
    unchanged; return the assume_*/repartition node the claim
    originates from, or None when it arose naturally (e.g. a group_by
    output's placement)."""
    cur = n
    for _ in range(10_000):           # cycle guard (DAGs only, but cheap)
        if isinstance(cur, _PINNING_NODES):
            return cur
        if not cur.parents:
            return None
        nxt = cur.parents[0]
        if nxt.partitioning != claim:
            return None
        cur = nxt
    return None


def _rule_pinned_partitioning(c: PlanCheck) -> List[Diagnostic]:
    """DTA017: an assume_*/explicit repartition pins the placement a
    downstream consumer's exchange elimination trusts — the planner
    emits no exchange there and marks the chain placement-dependent, so
    adaptive execution (JobConfig.adaptive) has nothing left to
    repartition, salt, or right-size if that consumer skews.  The span
    points at the PINNING op (the thing to relax), not the consumer."""
    out = []
    for n in c.nodes:
        # (parent, the claim whose match makes the planner elide that
        # consumer's exchange) — hash claims for the co-location family,
        # range claims for ascending prefix sorts (planner.py OrderBy)
        sides: List[Tuple[E.Node, E.Partitioning]] = []
        if isinstance(n, (E.GroupByAgg, E.GroupApply, E.GroupTopK,
                          E.GroupRankSelect, E.Distinct)):
            if tuple(n.keys):
                sides = [(n.parents[0],
                          E.Partitioning("hash", tuple(n.keys)))]
        elif isinstance(n, E.Join):
            if n.broadcast_right:
                # broadcast joins never consult the placement claims:
                # lex is dropped and rex replicates regardless, so no
                # exchange elision happens for a pin to block
                continue
            sides = [(n.parents[0],
                      E.Partitioning("hash", tuple(n.left_keys))),
                     (n.parents[1],
                      E.Partitioning("hash", tuple(n.right_keys)))]
        elif isinstance(n, E.OrderBy):
            have = n.parents[0].partitioning
            sort_keys = tuple(k for k, _ in n.keys)
            if (have.kind == "range" and have.keys
                    and all(not d for _, d in n.keys)
                    and sort_keys == have.keys[:len(sort_keys)]):
                sides = [(n.parents[0], have)]
        if not sides:
            continue
        for parent, claim in sides:
            if not claim.keys or parent.partitioning != claim:
                continue           # no elision -> nothing pinned
            keys = claim.keys
            pin = _pinning_ancestor(parent, claim)
            if pin is None:
                continue
            what = (f"assume_{pin.kind}_partition"
                    if isinstance(pin, E.AssumePartitioning)
                    else ("hash_partition"
                          if isinstance(pin, E.HashRepartition)
                          else "range_partition"))
            out.append(Diagnostic(
                "DTA017", "warn",
                f"{what}({', '.join(keys)}) pins the placement "
                f"{_node_label(n)} relies on: the planner elides that "
                f"consumer's exchange, so adaptive execution cannot "
                f"repartition, salt, or right-size it under skew — drop "
                f"the pin (let the consumer own its exchange) if the "
                f"key distribution is not known to be balanced",
                _span(pin), _node_label(pin)))
    return out


# ---------------------------------------------------------------------------
# shippability rules — mirror every PlanShipError raise site


def _rule_ship_udfs(c: PlanCheck) -> List[Diagnostic]:
    if not c.cluster:
        return []
    out = []
    seen: Set[int] = set()
    for n, role, fn, ships in c.udf_fields():
        if not ships or id(fn) in c.registered_ids or id(fn) in seen:
            continue
        why = shippability_of(fn)
        if why is None:
            continue
        seen.add(id(fn))
        site = fn_def_site(fn)
        out.append(Diagnostic(
            "DTA014", "error", f"{role}: {why}",
            site or _span(n), _node_label(n)))
    return out


def _rule_ship_sources(c: PlanCheck) -> List[Diagnostic]:
    if not c.cluster:
        return []
    out = []
    for n in c.nodes:
        if isinstance(n, E.Source) and n.data is not None \
                and not _is_deferred_source(n.data):
            out.append(Diagnostic(
                "DTA015", "error",
                "cluster execution needs deferred sources — create "
                "datasets through a Context constructed with cluster=...",
                _span(n), _node_label(n)))
    return out


def _rule_ship_params(c: PlanCheck) -> List[Diagnostic]:
    """Non-callable op params that cannot ship: user Decomposable
    aggregates must be registered by name (shiplan's 'not serializable'
    raise)."""
    if not c.cluster:
        return []
    out = []
    for n in c.nodes:
        if not isinstance(n, E.GroupByAgg):
            continue
        for name, spec in n.aggs.items():
            if isinstance(spec, E.Decomposable) \
                    and id(spec) not in c.registered_ids:
                out.append(Diagnostic(
                    "DTA016", "error",
                    f"agg {name!r}: Decomposable is not serializable for "
                    f"cluster execution — register it by name in "
                    f"Context(fn_table=...) and export it from a worker "
                    f"--fn-module FN_TABLE", _span(n), _node_label(n)))
    return out


# ---------------------------------------------------------------------------
# UDF determinism lint (DTA10x) — applied to every reachable callable


def _rule_udf_determinism(c: PlanCheck) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    linted: Set[int] = set()
    for n, role, fn, _ships in c.udf_fields():
        if id(fn) in linted:
            continue
        linted.add(id(fn))
        out.extend(lint_udf(fn, role=role))
    return out


RULES: List[Rule] = [
    Rule("DTA002", "stream-placeholder", _rule_stream_placeholder),
    Rule("DTA003", "stream-unsupported-op", _rule_stream_unsupported),
    Rule("DTA010", "capacity-hazard", _rule_capacity_hazard),
    Rule("DTA011", "redundant-exchange", _rule_redundant_exchange),
    Rule("DTA012", "tee-without-cache", _rule_tee_without_cache),
    Rule("DTA013", "unsound-assume", _rule_unsound_assume),
    Rule("DTA014", "udf-not-shippable", _rule_ship_udfs),
    Rule("DTA015", "source-not-shippable", _rule_ship_sources),
    Rule("DTA016", "param-not-serializable", _rule_ship_params),
    Rule("DTA017", "pinned-partitioning", _rule_pinned_partitioning),
    # the UDF determinism rule fans out to DTA101..DTA104
    Rule("DTA101", "udf-determinism", _rule_udf_determinism),
]

# codes a static rule can emit (the drift test checks runtime raise sites
# against this set ∪ RUNTIME_ONLY_CODES)
STATIC_RULE_CODES = frozenset(
    {r.code for r in RULES} | {"DTA102", "DTA103", "DTA104", "DTA105"})


def check_plan(root: E.Node, cluster: bool = False,
               fn_table: Optional[Dict[str, Any]] = None
               ) -> DiagnosticReport:
    """Run every rule over the DAG rooted at ``root``; returns ALL
    findings at once.  ``cluster`` turns on the shippability family and
    hardens stream rules to the cluster-stream contract; ``fn_table``
    names callables that are pre-registered for shipping."""
    check = PlanCheck(root, cluster=cluster, fn_table=fn_table)
    report = DiagnosticReport()
    for rule in RULES:
        report.diagnostics.extend(rule.fn(check))
    # identical findings reached via several Tee'd consumer paths (e.g.
    # a pinned repartition feeding two group_bys) collapse to one record
    # with a consumer count
    return report.dedup()
