"""Abstract domain for the static cost analyzer (analysis/cost.py).

The cost interpreter propagates two things over the plan:

* an :class:`Interval` of TOTAL valid row counts (lo certain, hi a sound
  upper bound, ``None`` = unbounded) — the row-count half of the domain;
* a concrete column schema (:class:`ColSpec` per column) plus the static
  per-partition capacity — the byte half.  Capacities are exact in this
  system (every batch is a fixed-shape padded tensor), so when the
  schema is known the materialized bytes of a stage output are KNOWN,
  not estimated: ``nparts * (capacity * row_bytes + 4)`` matches the
  executor's ``out_bytes`` (sum of leaf ``size * itemsize`` over the
  ``[P, cap, ...]`` batch, count vector included) to the byte.

Schema propagation through user callables uses ``jax.eval_shape`` — the
UDF is traced abstractly (zero FLOPs, zero device work), which is the
TPU-native way to "type-check" a Python callable.  Dependency note: this
module itself imports only numpy; jax is imported lazily inside the
abstract-batch helpers so the offline CLI path (serialized plans, no
callables) never needs it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["Interval", "ColSpec", "AbsState", "schema_row_bytes",
           "schema_from_store_schema", "schema_from_columns",
           "schema_from_host_columns", "abstract_batch",
           "schema_of_abstract", "part_bytes", "out_bytes"]

# the executor materializes the [P] int32 count vector with every batch
_COUNT_BYTES_PER_PART = 4


@dataclasses.dataclass(frozen=True)
class Interval:
    """Integer interval [lo, hi]; ``hi=None`` means unbounded above.
    ``lo`` is a certain lower bound, ``hi`` a sound upper bound."""

    lo: int = 0
    hi: Optional[int] = None

    @staticmethod
    def exact(v: int) -> "Interval":
        return Interval(int(v), int(v))

    @staticmethod
    def upto(hi: Optional[int]) -> "Interval":
        return Interval(0, None if hi is None else int(hi))

    @property
    def bounded(self) -> bool:
        return self.hi is not None

    def __add__(self, other: "Interval") -> "Interval":
        hi = (None if self.hi is None or other.hi is None
              else self.hi + other.hi)
        return Interval(self.lo + other.lo, hi)

    def scale(self, k: int) -> "Interval":
        return Interval(self.lo * k,
                        None if self.hi is None else self.hi * k)

    def mul(self, other: "Interval") -> "Interval":
        hi = (None if self.hi is None or other.hi is None
              else self.hi * other.hi)
        return Interval(self.lo * other.lo, hi)

    def clamp_hi(self, cap: Optional[int]) -> "Interval":
        """Intersect with [0, cap] (a capacity bound)."""
        if cap is None:
            return self
        hi = cap if self.hi is None else min(self.hi, cap)
        return Interval(min(self.lo, hi), hi)

    def relax_lo(self) -> "Interval":
        """Drop the lower bound (ops that may shed rows)."""
        return Interval(0, self.hi)

    def contains(self, v: int) -> bool:
        return v >= self.lo and (self.hi is None or v <= self.hi)

    def union(self, other: "Interval") -> "Interval":
        hi = (None if self.hi is None or other.hi is None
              else max(self.hi, other.hi))
        return Interval(min(self.lo, other.lo), hi)

    def as_tuple(self) -> Tuple[int, Optional[int]]:
        return (self.lo, self.hi)


@dataclasses.dataclass(frozen=True)
class ColSpec:
    """Static description of one column's device representation.

    * dense: ``[capacity, *shape] dtype`` — row_bytes = itemsize * prod
    * str: ``[capacity, repeat?, max_len] u8`` data + int32 lengths —
      row_bytes = repeat * (max_len + 4)

    ``repeat`` models window axes (sliding_window) on either kind.
    """

    kind: str                      # "dense" | "str"
    dtype: str = "int32"
    shape: Tuple[int, ...] = ()
    max_len: int = 0
    repeat: int = 1

    @property
    def row_bytes(self) -> int:
        if self.kind == "str":
            return self.repeat * (self.max_len + 4)
        n = 1
        for d in self.shape:
            n *= int(d)
        return self.repeat * np.dtype(self.dtype).itemsize * n


Schema = Dict[str, ColSpec]


def schema_row_bytes(schema: Schema) -> int:
    return sum(c.row_bytes for c in schema.values())


def part_bytes(schema: Schema, capacity: int) -> int:
    """Device bytes of ONE partition of a materialized batch."""
    return capacity * schema_row_bytes(schema) + _COUNT_BYTES_PER_PART


def out_bytes(schema: Schema, capacity: int, nparts: int) -> int:
    """Exact materialized bytes of a [P, cap, ...] stage output — the
    number the executor reports as ``out_bytes``."""
    return nparts * part_bytes(schema, capacity)


def schema_from_store_schema(store_schema: Dict[str, Any]) -> Schema:
    """From a store meta.json ``schema`` block (io/store.py layout)."""
    out: Schema = {}
    for k, spec in store_schema.items():
        if spec["kind"] == "str":
            out[k] = ColSpec("str", max_len=int(spec["max_len"]))
        else:
            out[k] = ColSpec("dense", dtype=str(spec["dtype"]),
                             shape=tuple(int(d)
                                         for d in spec.get("shape", ())))
    return out


def _leaf_spec(v: Any, lead_dims: int) -> ColSpec:
    """ColSpec of one dense column value (array / ShapeDtypeStruct /
    StringColumn handled by callers), dropping ``lead_dims`` leading
    dims ([P, cap] for PData columns, [cap] for per-shard batches)."""
    shape = tuple(int(d) for d in v.shape[lead_dims:])
    return ColSpec("dense", dtype=str(np.dtype(str(v.dtype))),
                   shape=shape)


def schema_from_columns(columns: Dict[str, Any],
                        lead_dims: int = 1) -> Schema:
    """From a Batch-style columns dict whose values are arrays /
    ShapeDtypeStructs or StringColumns.  ``lead_dims``: leading dims
    before the per-row shape (1 for per-shard [cap, ...], 2 for stacked
    PData [P, cap, ...])."""
    out: Schema = {}
    for k, v in columns.items():
        data = getattr(v, "data", None)
        if data is not None and hasattr(v, "lengths"):
            # StringColumn: data [..., cap, (repeat,) max_len]
            extra = data.shape[lead_dims:-1]
            rep = 1
            for d in extra:
                rep *= int(d)
            out[k] = ColSpec("str", max_len=int(data.shape[-1]),
                             repeat=rep)
        else:
            out[k] = _leaf_spec(v, lead_dims)
    return out


def schema_from_host_columns(columns: Dict[str, Any],
                             str_max_len: int) -> Schema:
    """From user host columns (the from_columns / columns_spec shape):
    lists of str/bytes become StringColumns at ``str_max_len``."""
    out: Schema = {}
    for k, v in columns.items():
        if isinstance(v, (list, tuple)) and (
                len(v) == 0 or isinstance(v[0], (str, bytes))):
            out[k] = ColSpec("str", max_len=int(str_max_len))
        else:
            arr = np.asarray(v)
            out[k] = ColSpec("dense", dtype=str(arr.dtype),
                             shape=tuple(int(d) for d in arr.shape[1:]))
    return out


def abstract_batch(schema: Schema, capacity: int):
    """Build a per-shard Batch of ``jax.ShapeDtypeStruct`` leaves for
    ``jax.eval_shape`` — the abstract value a stage op is interpreted
    over.  Window-axis (repeat > 1) columns are not reconstructed; the
    analyzer treats post-window UDFs as approximate."""
    import jax

    from dryad_tpu.data.columnar import Batch, StringColumn
    sds = jax.ShapeDtypeStruct
    cols: Dict[str, Any] = {}
    for k, spec in schema.items():
        if spec.kind == "str":
            mid = () if spec.repeat == 1 else (spec.repeat,)
            cols[k] = StringColumn(
                sds((capacity,) + mid + (spec.max_len,), np.uint8),
                sds((capacity,) + mid, np.int32))
        else:
            rep = () if spec.repeat == 1 else (spec.repeat,)
            cols[k] = sds((capacity,) + rep + spec.shape,
                          np.dtype(spec.dtype))
    return Batch(cols, sds((), np.int32))


def schema_of_abstract(batch_or_cols: Any) -> Tuple[Schema, int]:
    """(schema, capacity) of an eval_shape result — a Batch or a columns
    dict whose leaves are ShapeDtypeStructs."""
    cols = getattr(batch_or_cols, "columns", batch_or_cols)
    schema = schema_from_columns(cols, lead_dims=1)
    cap = 0
    for v in cols.values():
        data = getattr(v, "data", None)
        lead = data if data is not None else v
        cap = int(lead.shape[0])
        break
    return schema, cap


@dataclasses.dataclass
class AbsState:
    """Abstract value of one dataflow edge: total valid rows across all
    partitions, the static per-partition capacity, and (when known) the
    concrete column schema.  ``approx`` marks a state whose schema could
    not be derived (opaque UDF, unknown source) — byte predictions
    downstream of it are reported unbounded instead of wrong."""

    rows: Interval
    capacity: int
    schema: Optional[Schema] = None
    approx: bool = False
    notes: List[str] = dataclasses.field(default_factory=list)

    def rows_clamped(self, nparts: int) -> Interval:
        return self.rows.clamp_hi(
            self.capacity * nparts if self.capacity else None)

    def part_bytes(self) -> Optional[int]:
        if self.schema is None:
            return None
        return part_bytes(self.schema, self.capacity)

    def note(self, msg: str) -> "AbsState":
        if msg not in self.notes:
            self.notes.append(msg)
        return self


def fmt_bytes(b: Optional[float]) -> str:
    if b is None:
        return "?"
    if b == 0:
        return "0"
    units = ["B", "KiB", "MiB", "GiB", "TiB"]
    i = min(int(math.log(max(b, 1), 1024)), len(units) - 1)
    v = b / (1024 ** i)
    return f"{v:.0f}{units[i]}" if v >= 10 else f"{v:.1f}{units[i]}"
