"""The chaos scenario driver (see package docstring).

``run_scenario`` is the whole arc: spawn a victim daemon process, wait
until its target job is past ``plan.kill_after_spills`` settled stages,
SIGKILL it, optionally tear the journal tail, start a successor daemon
over the same service dir, drain the recovered fleet, and return a
verdict dict with the invariant checks.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, Optional

from dryad_tpu.chaos import faults, invariants
from dryad_tpu.chaos.plan import FaultPlan

__all__ = ["run_scenario"]


def _wait_for(pred, timeout: float, interval: float = 0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    return None


def _event_count(path: str, kind: str) -> int:
    try:
        with open(path) as f:
            return sum(1 for line in f
                       if json.loads(line).get("event") == kind)
    except (OSError, ValueError):
        return 0


def run_scenario(seed: int = 0, workdir: Optional[str] = None,
                 timeout: float = 300.0) -> Dict[str, Any]:
    """One full kill-and-recover scenario.  Returns the report dict
    (``report["ok"]`` is the overall verdict); raises only on harness
    bugs, never on an invariant violation."""
    plan = FaultPlan(seed)
    d = workdir or tempfile.mkdtemp(prefix="dryad-chaos-")
    os.makedirs(d, exist_ok=True)
    report: Dict[str, Any] = {"seed": seed, "plan": plan.to_json(),
                              "workdir": d, "ok": False}

    # -- phase 1: the victim daemon, killed for real ------------------------
    with open(os.path.join(d, "victim.log"), "wb") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "dryad_tpu.chaos._victim",
             "--dir", d, "--seed", str(seed)],
            stdout=logf, stderr=subprocess.STDOUT, env=dict(os.environ))
        try:
            mpath = os.path.join(d, "manifest.json")
            man = _wait_for(
                lambda: (os.path.exists(mpath) or None)
                and json.load(open(mpath)), timeout)
            if man is None:
                raise RuntimeError(
                    f"victim never wrote a manifest (victim.log in {d})")
            report["manifest"] = {k: man[k] for k in
                                  ("running", "queued", "standing")}
            spills = _wait_for(
                lambda: _event_count(man["target_events"],
                                     "stage_spilled")
                >= plan.kill_after_spills or None, timeout)
            report["spills_at_kill"] = _event_count(
                man["target_events"], "stage_spilled")
            if spills is None:
                raise RuntimeError("target job never spilled a stage")
        finally:
            faults.sigkill(proc.pid)
            proc.wait()
    report["killed_pid"] = proc.pid

    # -- phase 2: optional torn write over the journal tail -----------------
    jpath = os.path.join(man["durable_dir"], "journal.jsonl")
    if plan.torn_tail:
        faults.torn_tail(jpath, plan.torn_bytes)
    report["torn_injected"] = plan.torn_tail

    # -- phase 3: the successor daemon adopts and drains --------------------
    from dryad_tpu.service.daemon import JobService
    from dryad_tpu.service.tenancy import ServiceConfig
    from dryad_tpu.chaos._victim import catalog_for
    svc = JobService(
        ServiceConfig(service_dir=man["service_dir"], slots=1,
                      durable_spill=True),
        catalog=catalog_for(man["stores"]))
    try:
        report["recovery"] = svc.recovery
        # the injected faults become part of the successor's forensic
        # record — a post-hoc reader of the service log sees WHY the
        # journal shows a dirty epoch
        svc.log({"event": "chaos_fault", "fault": "sigkill",
                 "pid": proc.pid, "seed": seed,
                 "spills_at_kill": report["spills_at_kill"]})
        if plan.torn_tail:
            svc.log({"event": "chaos_fault", "fault": "torn_tail",
                     "bytes": plan.torn_bytes, "seed": seed})
        results: Dict[str, Any] = {}
        for jid in (man["running"], man["queued"]):
            row = svc.wait(jid, timeout=timeout)
            report.setdefault("jobs", {})[jid] = {
                "state": row["state"], "error": row.get("error"),
                "archived": bool(row.get("archived"))}
            if row["state"] == "done" and "result" in row:
                results[jid] = row["result"]
        # the resumed target must have RESTORED its settled stages, not
        # recomputed them (that is what "durable" buys)
        restored = _event_count(man["target_events"], "stage_restored")
        report["stages_restored"] = restored
        # oracle: the same query, fresh, on the successor
        oracle = svc.wait(svc.submit_sql(man["query"], tenant="alice"),
                          timeout=timeout)["result"]
        sq = svc.standing.get(man["standing"])
        report["standing_recovered"] = (sq is not None
                                        and sq.state == "running")
    finally:
        svc.close()

    # -- verdict ------------------------------------------------------------
    inv = invariants.check_invariants(man["durable_dir"],
                                      results=results, oracle=oracle)
    report["invariants"] = inv
    report["all_terminal"] = all(
        j["state"] in ("done", "failed", "cancelled")
        for j in report["jobs"].values())
    report["ok"] = bool(
        inv["ok"] and report["all_terminal"]
        and report["standing_recovered"]
        and all(j["state"] == "done"
                for j in report["jobs"].values())
        # past-a-settled-stage proof: the target either restored its
        # spilled stages on resume, or had already finished pre-kill
        and (restored >= 1 or report["spills_at_kill"] == 0
             or report["jobs"][man["running"]]["archived"]))
    return report
