"""Fault injectors: the two failure modes the durable service promises
to survive — an uncooperative process death and a torn trailing write.
Both are REAL (a SIGKILL, actual bytes on disk), not monkeypatches, so
the recovery path under test is the production one."""

from __future__ import annotations

import os
import signal
import time

__all__ = ["sigkill", "torn_tail", "chop_tail"]


def sigkill(pid: int, wait_s: float = 10.0) -> bool:
    """SIGKILL ``pid`` and reap it (when it is our child).  Returns
    False if the process was already gone."""
    try:
        os.kill(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        return False
    deadline = time.time() + wait_s
    while time.time() < deadline:
        try:
            done, _ = os.waitpid(pid, os.WNOHANG)
        except ChildProcessError:
            return True          # not our child / already reaped
        if done == pid:
            return True
        time.sleep(0.02)
    return True


def torn_tail(path: str, nbytes: int = 40) -> None:
    """Append a PARTIAL record — what a crash mid-append leaves behind:
    valid-looking JSON prefix, no closing brace, no newline.  Recovery
    must truncate exactly this and replay the rest."""
    frag = ('{"rec": "job_terminal", "id": "torn-'
            + "x" * max(1, int(nbytes)))
    with open(path, "a") as f:
        f.write(frag)
        f.flush()
        os.fsync(f.fileno())


def chop_tail(path: str, nbytes: int) -> None:
    """Truncate the last ``nbytes`` bytes mid-record — the other shape
    of a torn write (the tail of the final record never hit the
    platter)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, size - max(1, int(nbytes))))
