"""Durability invariant checkers.

These read the journal the same way daemon recovery does (checkpoint +
suffix replay) but WITHOUT opening a new epoch — pure observers a test
or the chaos harness can point at any service dir, live or dead.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from dryad_tpu.service.durable.journal import (ReplayState,
                                               TERMINAL_STATES,
                                               _read_records)

__all__ = ["read_state", "zero_lost_jobs", "exactly_once_terminal",
           "oracle_identical", "check_invariants"]


def read_state(durable_dir: str) -> ReplayState:
    """Fold checkpoint + journal of ``<service_dir>/durable`` into a
    ReplayState, read-only (no truncation side effects are needed here
    because ``_read_records`` only truncates a torn tail — which is
    exactly what recovery would do anyway)."""
    import json
    ckpt = os.path.join(durable_dir, "checkpoint.json")
    if os.path.exists(ckpt):
        with open(ckpt) as f:
            state = ReplayState.from_checkpoint(json.load(f))
    else:
        state = ReplayState()
    records, torn = _read_records(os.path.join(durable_dir,
                                               "journal.jsonl"))
    for r in records:
        if int(r.get("n", 0)) > state.counter:
            state.fold(r)
    state.torn = torn
    return state


def zero_lost_jobs(state: ReplayState) -> List[str]:
    """Ids admitted but never driven to a terminal state — must be
    empty once the successor daemon has drained the recovered fleet.
    (A job the successor could not rebuild still terminates: it fails
    with forensics, which IS a terminal record.)"""
    return [jid for jid, j in state.jobs.items()
            if j["phase"] not in TERMINAL_STATES]


def exactly_once_terminal(state: ReplayState) -> List[str]:
    """Ids journaled terminal more than once — must be empty, or a
    tenant could be charged twice for one job."""
    return list(state.dup_terminals)


def oracle_identical(results: Dict[str, Any],
                     oracle: Any) -> List[str]:
    """Recovered-job results that diverge from the fresh oracle run."""
    return [jid for jid, res in results.items() if res != oracle]


def check_invariants(durable_dir: str,
                     results: Optional[Dict[str, Any]] = None,
                     oracle: Any = None) -> Dict[str, Any]:
    """The full verdict the chaos harness asserts on."""
    state = read_state(durable_dir)
    lost = zero_lost_jobs(state)
    dups = exactly_once_terminal(state)
    diverged = (oracle_identical(results, oracle)
                if results is not None else [])
    return {"jobs": len(state.jobs), "epochs": state.epochs,
            "torn": state.torn, "lost": lost, "dup_terminals": dups,
            "diverged": diverged,
            "ok": not (lost or dups or diverged)}
