"""Chaos harness for the durable job service.

Kills a real daemon process (SIGKILL, no grace) at a seeded point in a
fleet of in-flight jobs, optionally tears the journal tail the way a
power cut mid-append would, restarts a successor daemon over the same
service dir, and checks the durability invariants:

* **zero lost jobs** — every job the dead daemon ever admitted reaches
  a terminal state on the successor (done, failed-with-forensics, or
  superseded — never silently missing);
* **exactly-once terminal** — no job is journaled terminal twice (the
  tenant fair-share ledger is charged at most once per job);
* **oracle-identical results** — a recovered job's result equals a
  fresh same-query run on the successor.

Runnable: ``python -m dryad_tpu.chaos [--seed N]``.
"""

from dryad_tpu.chaos.plan import FaultPlan
from dryad_tpu.chaos.harness import run_scenario
from dryad_tpu.chaos.invariants import (check_invariants, read_state,
                                        exactly_once_terminal,
                                        zero_lost_jobs)

__all__ = ["FaultPlan", "run_scenario", "check_invariants",
           "read_state", "exactly_once_terminal", "zero_lost_jobs"]
