"""Seeded, deterministic fault plans.

Every scenario decision — when to kill the victim, whether to tear the
journal tail afterwards, how the victim sizes its stores — is derived
from one integer seed through ``random.Random``.  The same seed always
produces the same plan, so a failing run is reproducible with
``python -m dryad_tpu.chaos --seed N`` and nothing else.

(The OS still schedules threads; what the plan pins down is the
*trigger*: the kill fires when the target job's event log shows
``kill_after_spills`` settled-and-spilled stages, not after a wall-clock
sleep.)
"""

from __future__ import annotations

import json
import random
from typing import Any, Dict

__all__ = ["FaultPlan"]


class FaultPlan:
    """The deterministic scenario derived from ``seed``.

    * ``kill_after_spills`` — SIGKILL the victim once its target job
      has journaled this many ``stage_spilled`` events (the job is then
      provably PAST a settled stage, so recovery must restore — not
      recompute — that work).
    * ``torn_tail`` / ``torn_bytes`` — after the kill, append a partial
      journal record (a torn write): recovery must truncate it and
      proceed, never refuse.
    * ``store_rows`` / ``store_keys`` — victim dataset shape, varied so
      different seeds exercise different plan shapes and timings.
    """

    def __init__(self, seed: int = 0):
        rng = random.Random(int(seed))
        self.seed = int(seed)
        self.kill_after_spills = rng.choice((1, 1, 2))
        self.torn_tail = rng.random() < 0.5
        self.torn_bytes = rng.randint(8, 120)
        self.store_rows = 24000 + 512 * rng.randint(0, 15)
        self.store_keys = rng.choice((256, 512, 1024))
        self.standing_period_s = round(0.2 + 0.1 * rng.random(), 3)

    def to_json(self) -> Dict[str, Any]:
        return dict(vars(self))

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FaultPlan":
        plan = cls(int(obj.get("seed", 0)))
        for k, v in obj.items():
            setattr(plan, k, v)
        return plan

    def __repr__(self) -> str:
        return f"FaultPlan({json.dumps(self.to_json(), sort_keys=True)})"
