"""The chaos victim: a real daemon process the harness SIGKILLs.

Builds a small star-schema dataset, starts a durable ``JobService``
over ``--dir``, submits a standing query plus two multi-stage one-shot
join jobs (slots=1, so one runs while one queues), writes a manifest
for the harness, and then waits to be killed.  Everything it does is
the production submission path — the only test-only thing here is that
it never exits on its own.
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

from dryad_tpu.api import Context
from dryad_tpu import sql
from dryad_tpu.service.daemon import JobService
from dryad_tpu.service.tenancy import ServiceConfig
from dryad_tpu.utils.atomic import atomic_write_json
from dryad_tpu.chaos.plan import FaultPlan

# three stores -> the 3-way join lowers to THREE stages, so there are
# real interior stage boundaries for the kill to land between
QUERY = ("SELECT a.k, SUM(a.v + b.w + c.u) AS s FROM a "
         "JOIN b ON a.k = b.k JOIN c ON a.k = c.k "
         "GROUP BY a.k ORDER BY s DESC LIMIT 16")


def build_stores(root: str, plan: FaultPlan) -> dict:
    ctx = Context(install_trace=False)
    n, keys = plan.store_rows, plan.store_keys
    paths = {name: os.path.join(root, "stores", name)
             for name in ("a", "b", "c")}
    ctx.from_columns({"k": (np.arange(n) % keys).astype(np.int32),
                      "v": np.arange(n, dtype=np.int32)}
                     ).to_store(paths["a"])
    ctx.from_columns({"k": np.arange(keys, dtype=np.int32),
                      "w": (np.arange(keys) * 3).astype(np.int32)}
                     ).to_store(paths["b"])
    ctx.from_columns({"k": np.arange(keys, dtype=np.int32),
                      "u": (np.arange(keys) * 7).astype(np.int32)}
                     ).to_store(paths["c"])
    return paths


def catalog_for(paths: dict) -> sql.Catalog:
    cat = sql.Catalog()
    for name, p in paths.items():
        cat.register_store(name, p)
    return cat


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    plan = FaultPlan(args.seed)

    paths = build_stores(args.dir, plan)
    svc = JobService(
        ServiceConfig(service_dir=os.path.join(args.dir, "svc"),
                      slots=1, durable_spill=True),
        catalog=catalog_for(paths))
    standing_id = svc.submit_sql(
        f"SELECT k, SUM(v) AS s FROM a GROUP BY k "
        f"EMIT EVERY {plan.standing_period_s}", tenant="carol")
    running = svc.submit_sql(QUERY, tenant="alice")
    queued = svc.submit_sql(QUERY, tenant="bob")

    atomic_write_json(os.path.join(args.dir, "manifest.json"), {
        "pid": os.getpid(), "plan": plan.to_json(), "query": QUERY,
        "stores": paths, "service_dir": svc.root,
        "durable_dir": os.path.join(svc.root, "durable"),
        "standing": standing_id, "running": running, "queued": queued,
        "target_events": os.path.join(svc.jobs[running].dir,
                                      "events.jsonl")})
    while True:                  # the harness ends this process, not us
        time.sleep(0.5)
    return 0                     # unreachable


if __name__ == "__main__":
    raise SystemExit(main())
