"""``python -m dryad_tpu.chaos`` — run kill-and-recover scenarios and
exit nonzero if any durability invariant breaks."""

from __future__ import annotations

import argparse
import json
import shutil
import sys

from dryad_tpu.chaos.harness import run_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dryad_tpu.chaos",
        description="SIGKILL a durable job-service daemon mid-fleet, "
                    "restart it, and check the durability invariants.")
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-plan seed (same seed = same scenario)")
    ap.add_argument("--runs", type=int, default=1,
                    help="scenarios to run (seeds seed..seed+runs-1)")
    ap.add_argument("--dir", default=None,
                    help="work dir (default: a fresh temp dir per run)")
    ap.add_argument("--keep", action="store_true",
                    help="keep work dirs even on success")
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args(argv)

    failed = 0
    for i in range(args.runs):
        seed = args.seed + i
        report = run_scenario(seed=seed, workdir=args.dir,
                              timeout=args.timeout)
        print(json.dumps(report, indent=2, sort_keys=True,
                         default=str))
        if not report["ok"]:
            failed += 1
            print(f"chaos: seed {seed} FAILED (work dir kept: "
                  f"{report['workdir']})", file=sys.stderr)
        elif not args.keep and args.dir is None:
            shutil.rmtree(report["workdir"], ignore_errors=True)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
