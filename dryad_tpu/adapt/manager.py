"""Adaptive execution manager: the stage-boundary rewrite driver.

``exec/recovery.Run`` calls :meth:`AdaptiveManager.on_stage_materialized`
after every synchronous stage completion (the one host-sync point where
measured per-partition rows exist); the manager records the
:class:`~dryad_tpu.adapt.stats.StageStats`, opens a
:class:`~dryad_tpu.adapt.rewrite.PlanRewriter` window over the
unexecuted suffix, and runs the registered
:class:`~dryad_tpu.adapt.rules.ConnectionManager` rules — the
counterpart of the reference GM dispatching
``NotifyUpstreamVertexCompleted`` to each stage's attached
DrConnectionManager.

Contract:

* ``JobConfig.adaptive == "off"`` means this object is never
  constructed — zero plan mutation, byte-identical serialized plans,
  and the deferred-needs fast path stays on (adaptation requires the
  per-stage stats sync, so ``"on"`` trades the O(1)-round-trip
  optimization for observability — exactly the reference's
  stage-boundary barrier cost).
* A rule failure must never fail the job: rules raising (including
  :class:`~dryad_tpu.adapt.rewrite.RewriteError` guard trips) are
  reported as ``adapt_skipped`` events and the plan proceeds
  un-rewritten.
* Determinism across a gang: rules are pure functions of
  (graph, stats, config, topology); stats arrive replicated on
  multi-process meshes, so every worker rewrites identically — the
  mirrored-execution contract of ``runtime/exec_common.py``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from dryad_tpu.adapt.rewrite import PlanRewriter, RewriteError
from dryad_tpu.adapt.rules import RuleContext, default_rules
from dryad_tpu.adapt.stats import StageStats
from dryad_tpu.plan.stages import StageGraph

__all__ = ["AdaptiveManager", "levels_of_mesh"]


def levels_of_mesh(mesh) -> tuple:
    """Mesh -> ((axis, size), ...) INNERMOST FIRST — the planner's
    ``levels`` orientation.  On a worker gang the outermost axis is the
    process boundary (dcn), so topology-aware rules see the host
    structure the driver-side ``cluster.worker_hosts()`` exposes."""
    if mesh is None:
        return ()
    names = tuple(mesh.axis_names)
    shape = tuple(mesh.devices.shape)
    return tuple(zip(reversed(names), reversed(shape)))


class AdaptiveManager:
    """One per :class:`~dryad_tpu.exec.recovery.Run` when
    ``JobConfig.adaptive == "on"``."""

    def __init__(self, graph: StageGraph, config, nparts: int,
                 levels: tuple = (),
                 event: Optional[Callable[[dict], None]] = None,
                 rules=None, cost_report=None):
        self.graph = graph
        self.config = config
        self.nparts = nparts
        self.levels = tuple(levels)
        self._event = event or (lambda e: None)
        self.rules = list(rules) if rules is not None else default_rules()
        self.stats: Dict[int, StageStats] = {}
        # static per-stage bounds from the lint gate's cost pass
        # (analysis/cost.CostReport) — rules consume them as PRIORS for
        # stages that have not materialized yet (rules.rows_bounds);
        # None when the cost pass didn't run (lint off), and always
        # None on worker gangs (driver-side analysis), so gang members
        # stay mirrored
        self.cost = cost_report
        self.applied: List[dict] = []   # graph_rewrite payloads, in order

    @property
    def rewrite_count(self) -> int:
        return len(self.applied)

    def on_stage_materialized(self, st: StageStats,
                              executed: Set[int]) -> None:
        """The boundary hook.  ``executed`` is the set of stage ids with
        materialized results (including ``st.stage``)."""
        import time as _time
        self.stats[st.stage] = st

        def emit(e: dict) -> None:
            # stamp emission time here: bare-callable sinks (a list
            # append) don't, and the Chrome exporter draws rewrites as
            # instants at their timestamp
            e.setdefault("ts", round(_time.time(), 4))
            self._event(e)

        emit(st.event())
        rw = PlanRewriter(self.graph, executed)
        ctx = RuleContext(rw=rw, stats=self.stats, config=self.config,
                          nparts=self.nparts, levels=self.levels,
                          cost=self.cost)
        from dryad_tpu.obs.metrics import REGISTRY, family_counter
        for rule in self.rules:
            try:
                events = rule.on_stage_done(ctx, st)
            except RewriteError as e:
                events = [{"event": "adapt_skipped", "rule": rule.name,
                           "stage": st.stage, "reason": str(e)}]
            except Exception as e:   # a rule bug must not fail the job
                events = [{"event": "adapt_skipped", "rule": rule.name,
                           "stage": st.stage,
                           "reason": f"rule error: {e!r}"}]
            for ev in events:
                emit(ev)
                if ev.get("event") == "graph_rewrite":
                    self.applied.append(ev)
                    family_counter(REGISTRY, "graph_rewrites",
                                   rule=ev.get("rule", "?"),
                                   kind=ev.get("kind", "?")).inc()
