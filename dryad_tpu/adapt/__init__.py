"""Adaptive query execution: stage-boundary graph rewriting.

The reference's defining runtime capability — graph-rewriting
"connection managers" that restructure the DAG mid-job from observed
data sizes (``DrDynamicAggregateManager`` machine->pod->overall trees,
``DrDynamicDistributionManager``, ``DrDynamicBroadcastManager``; Dryad
EuroSys'07 §5.2, DryadLINQ OSDI'08 §4.3) — as a subsystem over the
StageGraph executor:

* ``adapt/thresholds.py`` — the shared skew constants (diagnosis and
  action single-sourced);
* ``adapt/stats.py`` — observed per-stage stats (rows/bytes/capacity);
* ``adapt/rewrite.py`` — the unexecuted-suffix mutation window with
  stable stage-id remapping;
* ``adapt/rules.py`` — the three connection-manager rules behind the
  ``ConnectionManager`` plug-in interface;
* ``adapt/manager.py`` — the boundary driver ``exec/recovery.Run``
  invokes after each synchronous stage materialization.

Enabled by ``JobConfig(adaptive="on")``; off (the default) constructs
nothing and leaves plans byte-identical.

This ``__init__`` stays import-light on purpose: ``utils/config.py``
and ``obs/profile.py`` import ``adapt.thresholds`` at module load, so
pulling the rule machinery in here would create an import cycle.
"""

from dryad_tpu.adapt.thresholds import (SKEW_SIBLING_MEDIAN_FACTOR,
                                        sibling_median, skew_ratio)

__all__ = ["SKEW_SIBLING_MEDIAN_FACTOR", "sibling_median", "skew_ratio"]
