"""Stage-graph mutation API over the NOT-YET-EXECUTED suffix.

The reference's connection managers restructure the running DrGraph by
splicing vertices into stages whose inputs have not started
(``DrDynamicAggregateManager`` building machine->pod->overall trees from
completed-vertex sizes).  Our physical plan is a ``StageGraph`` executed
demand-driven (``exec/recovery.Run``), so the same capability is a
mutation window: between one stage's materialization and its dependents'
execution, rules may rewrite any stage that has not produced output yet.

Invariants this module enforces (the "stable stage-id remapping"
contract):

* executed stages are IMMUTABLE — their ids, legs, and results stand;
  ``check()`` raises on any attempt to touch one;
* new stages get fresh ids appended at ``len(stages)`` — an id, once
  assigned, never changes meaning, so stage events / spill files /
  lineage edges recorded before a rewrite stay valid after it;
* redirecting consumers (``redirect_consumers``) only rewrites legs of
  unexecuted stages plus ``out_stage``; a bypassed stage becomes an
  orphan the demand-driven walk simply never visits.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from dryad_tpu.plan.stages import Exchange, Leg, Stage, StageGraph, StageOp

__all__ = ["RewriteError", "PlanRewriter", "describe_stage"]


class RewriteError(RuntimeError):
    """An adaptive rule attempted an illegal mutation (executed-stage
    touch, unknown stage).  Caught by the manager: the rewrite is
    skipped, the job proceeds on the un-rewritten plan."""


def _ex_desc(ex: Optional[Exchange]) -> Optional[str]:
    if ex is None:
        return None
    keys = ",".join(ex.keys)
    ax = f"@{ex.axis}" if ex.axis else ""
    return f"{ex.kind}({keys}){ax} cap={ex.out_capacity}"


def describe_stage(st: Stage) -> Dict[str, Any]:
    """Compact topology snapshot for ``graph_rewrite`` before/after
    payloads — enough for a viewer to draw the rewrite, small enough to
    ride every event."""
    return {"stage": st.id, "label": st.label,
            "legs": [{"src": (leg.src if isinstance(leg.src, int)
                              else leg.src[0]),
                      "ops": [op.kind for op in leg.ops],
                      "exchange": _ex_desc(leg.exchange)}
                     for leg in st.legs],
            "body": [op.kind for op in st.body],
            "salted": bool(st._salted),
            "slack": st._send_slack}


class PlanRewriter:
    """One rewrite window over ``graph`` given the set of executed stage
    ids.  Rules snapshot topology, mutate via the helpers, and return
    event payloads; the manager re-creates a rewriter per window so the
    executed set is always current."""

    def __init__(self, graph: StageGraph, executed: Set[int]):
        self.graph = graph
        self.executed = set(executed)

    # -- guards ------------------------------------------------------------

    def check(self, sid: int) -> Stage:
        if not (0 <= sid < len(self.graph.stages)):
            raise RewriteError(f"unknown stage {sid}")
        if sid in self.executed:
            raise RewriteError(
                f"stage {sid} already materialized — the executed prefix "
                f"is immutable")
        return self.graph.stage(sid)

    def is_executed(self, sid: int) -> bool:
        return sid in self.executed

    # -- queries -----------------------------------------------------------

    def consumers_of(self, sid: int) -> List[Stage]:
        """Unexecuted stages with a leg fed by ``sid``."""
        return [st for st in self.graph.stages
                if st.id not in self.executed
                and any(leg.src == sid for leg in st.legs)]

    def snapshot(self, *sids: int) -> List[Dict[str, Any]]:
        return [describe_stage(self.graph.stage(s)) for s in sids]

    # -- mutations ---------------------------------------------------------

    def new_stage(self, legs: List[Leg], body: List[StageOp],
                  label: str) -> Stage:
        """Append a stage under a fresh id (stable remapping: existing
        ids keep their meaning)."""
        st = Stage(id=len(self.graph.stages), legs=legs, body=body,
                   label=label)
        self.graph.stages.append(st)
        return st

    def redirect_consumers(self, old: int, new: int,
                           exclude=()) -> int:
        """Repoint every unexecuted consumer leg (and ``out_stage``)
        from ``old`` to ``new``; returns the number of edges moved.
        ``exclude`` lists stages whose legs must keep reading ``old`` —
        the stages a rule just inserted BETWEEN old and new (rewriting
        those would close a cycle: the first inserted hop reads old by
        construction)."""
        moved = 0
        skip = {new, *exclude}
        for st in self.graph.stages:
            if st.id in self.executed or st.id in skip:
                continue
            for leg in st.legs:
                if leg.src == old:
                    leg.src = new
                    moved += 1
                if (leg.exchange is not None
                        and leg.exchange.bounds_from == old):
                    leg.exchange.bounds_from = new
                    moved += 1
        if self.graph.out_stage == old:
            self.graph.out_stage = new
            moved += 1
        return moved
