"""Shared adaptivity thresholds — single-sourced so detection and action
cannot drift.

``obs/profile.diagnose_events`` FLAGS data skew (a partition holding
>= factor x its sibling median) and ``adapt/rules.SkewRepartition`` ACTS
on the same condition; both import :data:`SKEW_SIBLING_MEDIAN_FACTOR`
from here.  A diagnosis the runtime would not act on — or an action the
diagnosis would not explain — is a bug class this module removes.

Dependency-free by design: ``utils/config.py`` (JobConfig defaults) and
``obs/profile.py`` both import it, so it must sit below everything.
"""

from __future__ import annotations

__all__ = ["SKEW_SIBLING_MEDIAN_FACTOR", "sibling_median", "skew_ratio"]

# a partition is SKEWED when it holds at least this multiple of the
# median of its sibling partitions' row counts (reference: the
# DrDynamicDistributionManager splits a part when it exceeds its
# per-bucket target the same relative way)
SKEW_SIBLING_MEDIAN_FACTOR = 4.0


def sibling_median(rows) -> int:
    """Median of ``rows`` EXCLUDING the peak entry — the denominator of
    the skew ratio used by both diagnosis and the adapt rules."""
    rows = [int(r) for r in rows]
    if len(rows) < 2:
        return rows[0] if rows else 0
    peak_i = rows.index(max(rows))
    sib = sorted(r for i, r in enumerate(rows) if i != peak_i)
    return sib[len(sib) // 2]


def skew_ratio(rows) -> float:
    """peak / sibling-median (>= 1.0); 1.0 for degenerate inputs."""
    rows = [int(r) for r in rows]
    if len(rows) < 2:
        return 1.0
    return max(rows) / max(sibling_median(rows), 1)
