"""Connection-manager rules: the three stage-boundary rewrites.

Each rule mirrors one of the reference's dynamic graph managers
(``DrConnectionManager`` subclasses, GraphBuilder.cs:620-729 wiring):

* :class:`DynamicAggregationTree` — ``DrDynamicAggregateManager``: pick
  the combine-tree depth from MEASURED partial-output sizes and the mesh
  topology instead of the planner's fixed per-axis lowering
  (plan/planner.py levels): collapse a hierarchical merge chain to one
  global exchange when the measured data is tiny, or expand a flat merge
  into per-axis hops when it is huge and the mesh is multi-level.
* :class:`SkewRepartition` — ``DrDynamicDistributionManager``: right-size
  a downstream exchange from observed rows (coalesce: shrink the padded
  capacity the planner guessed; split: pre-salt a saltable join or
  pre-raise send slack) when a partition exceeds the shared
  sibling-median skew factor (adapt/thresholds.py — the SAME constant
  ``obs/profile.diagnose_events`` flags on).
* :class:`BroadcastManager` — ``DrDynamicBroadcastManager``: flip a
  planned broadcast join to a hash exchange when the measured build side
  blew its estimate, and promote a hash-hash join to broadcast when the
  build side measured tiny.

Rules receive a :class:`~dryad_tpu.adapt.rewrite.PlanRewriter` window
plus the accumulated :class:`~dryad_tpu.adapt.stats.StageStats`; they
mutate only after every precondition holds and return event payloads
(``kind`` + before/after topology) the manager emits as
``graph_rewrite`` events.  SPMD partition COUNT is fixed by the mesh, so
"repartitioning" here reshapes capacity, salting, slack, and tree depth
— the placement levers that exist under static SPMD shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

from dryad_tpu.adapt.rewrite import PlanRewriter
from dryad_tpu.adapt.stats import StageStats
from dryad_tpu.plan.stages import Exchange, Leg, Stage, StageOp

__all__ = ["ConnectionManager", "RuleContext", "rows_bounds",
           "DynamicAggregationTree", "SkewRepartition",
           "BroadcastManager", "default_rules", "NON_EXPANDING_OPS"]

# op kinds that can only PRESERVE or REDUCE row counts: a producer's
# measured rows upper-bound the exchange input through any chain of
# these, so capacity decisions made from producer stats stay sound.
# Expanders (flat_tokens / flat_map / join / group_apply / apply / zip /
# concat / apply2 / sliding_window) are deliberately absent.
NON_EXPANDING_OPS = frozenset({
    "fn", "filter", "group", "dgroup_partial", "dgroup_local",
    "dgroup_merge", "distinct", "sort", "take", "skip", "take_while",
    "skip_while", "mean_fin", "row_index", "group_top_k", "group_rank",
    "recap",
})

_MERGE_KINDS = ("group", "dgroup_merge")


def _round_cap(rows: int) -> int:
    """Row bound -> exchange capacity: 128-lane multiples keep shapes
    TPU-friendly and bound the compile-cache variant count."""
    return max(128, -(-int(rows) // 128) * 128)


def _non_expanding(ops) -> bool:
    return all(op.kind in NON_EXPANDING_OPS for op in ops)


@dataclasses.dataclass
class RuleContext:
    """Everything a rule may consult: the rewrite window, all stats
    observed so far (stage id -> StageStats), the JobConfig, and the
    mesh topology as (axis, size) pairs INNERMOST FIRST — the same
    orientation as the planner's ``levels`` (on a driver this derives
    from the mesh; on a gang the process axis is the outermost entry,
    the role ``cluster.worker_hosts()`` plays for task placement)."""

    rw: PlanRewriter
    stats: Dict[int, StageStats]
    config: Any
    nparts: int
    levels: tuple  # ((axis_name, size), ...) innermost first
    # static per-stage bounds from the pre-submit cost pass
    # (analysis/cost.CostReport), or None: PRIORS for stages that have
    # not materialized yet — see :func:`rows_bounds`
    cost: Any = None


def rows_bounds(ctx: RuleContext, sid: int):
    """(lo, hi) total-row bounds for stage ``sid``: the MEASURED rows
    when the stage has materialized (exact — lo == hi), else the static
    cost analyzer's interval as a prior (analysis/cost.py), else None.
    Rules that needed both join sides measured can act one boundary
    earlier when the static bound for the other side is tight — the
    'static plan optimizer seeds the dynamic managers' direction of the
    reference's DrDynamicBroadcastManager."""
    st = ctx.stats.get(sid)
    if st is not None:
        return (st.total_rows, st.total_rows)
    if ctx.cost is not None:
        b = ctx.cost.rows_bounds(sid)
        if b is not None and b[1] is not None:
            return (int(b[0]), int(b[1]))
    return None


class ConnectionManager:
    """Plug-in interface (DrConnectionManager parity): one instance per
    run, ``on_stage_done`` called at every stage-materialization
    boundary with that stage's observed stats.  Return a list of event
    payload dicts; mutate the graph only through ``ctx.rw``."""

    name = "?"

    def on_stage_done(self, ctx: RuleContext,
                      st: StageStats) -> List[dict]:  # pragma: no cover
        raise NotImplementedError


# ---------------------------------------------------------------------------
# 1. dynamic aggregation trees


class DynamicAggregationTree(ConnectionManager):
    name = "agg_tree"

    def _merge_chain(self, ctx: RuleContext, first: Stage) -> List[Stage]:
        """Follow a hierarchical merge chain (single-leg stages whose
        exchange is axis-scoped hash on the same keys) starting at
        ``first``; returns [] unless it is a >=2-stage chain ending in
        the finalizing level."""
        chain = [first]
        keys = first.legs[0].exchange.keys
        while True:
            cur = chain[-1]
            nxt = [s for s in ctx.rw.consumers_of(cur.id)
                   if len(s.legs) == 1 and not s.legs[0].ops
                   and s.legs[0].exchange is not None
                   and s.legs[0].exchange.kind == "hash"
                   and s.legs[0].exchange.axis is not None
                   and s.legs[0].exchange.keys == keys
                   and s.body and s.body[0].kind in _MERGE_KINDS]
            if len(nxt) != 1 or len(ctx.rw.consumers_of(cur.id)) != 1 \
                    or ctx.rw.graph.out_stage == cur.id:
                break
            chain.append(nxt[0])
        return chain if len(chain) >= 2 else []

    def _collapse(self, ctx: RuleContext, st: StageStats) -> List[dict]:
        out = []
        limit = getattr(ctx.config, "adapt_agg_collapse_rows", 4096)
        for c in ctx.rw.consumers_of(st.stage):
            if (len(c.legs) != 1 or c.legs[0].src != st.stage
                    or c.legs[0].exchange is None
                    or c.legs[0].exchange.kind != "hash"
                    or c.legs[0].exchange.axis is None
                    or not c.body or c.body[0].kind not in _MERGE_KINDS
                    or not _non_expanding(c.legs[0].ops)):
                continue
            if st.total_rows > limit:
                out.append({"event": "adapt_skipped", "rule": self.name,
                            "stage": c.id,
                            "reason": f"measured rows {st.total_rows} > "
                                      f"collapse limit {limit}"})
                continue
            chain = self._merge_chain(ctx, c)
            if not chain:
                continue
            first, last = chain[0], chain[-1]
            before = ctx.rw.snapshot(*(s.id for s in chain))
            # one global exchange replaces the whole per-axis ladder:
            # the measured data is small enough that hop-per-fabric
            # buys nothing over a single all-to-all
            ex = last.legs[0].exchange
            last.legs[0] = Leg(first.legs[0].src, first.legs[0].ops,
                               Exchange("hash", keys=ex.keys,
                                        out_capacity=ex.out_capacity,
                                        axis=None))
            out.append({"event": "graph_rewrite", "rule": self.name,
                        "kind": "agg_tree_collapse", "stage": last.id,
                        "trigger_stage": st.stage,
                        "orphaned": [s.id for s in chain[:-1]],
                        "levels_before": len(chain), "levels_after": 1,
                        "before": before,
                        "after": ctx.rw.snapshot(last.id)})
        return out

    def _final_aggs_clone(self, op: StageOp) -> StageOp:
        """A merge level applied on top of another merge level: builtin
        final aggs (out -> (sum|min|max|any|all, out)) are idempotent
        under re-application, so the op clones as-is."""
        return StageOp(op.kind, dict(op.params), span=op.span)

    def _expand(self, ctx: RuleContext, st: StageStats) -> List[dict]:
        out = []
        limit = getattr(ctx.config, "adapt_agg_expand_rows", 1 << 20)
        if len(ctx.levels) < 2 or st.total_rows < limit:
            return out
        for c in ctx.rw.consumers_of(st.stage):
            if (len(c.legs) != 1 or c.legs[0].src != st.stage
                    or c.legs[0].exchange is None
                    or c.legs[0].exchange.kind != "hash"
                    or c.legs[0].exchange.axis is not None
                    or not c.legs[0].exchange.keys
                    or not c.body or c.body[0].kind not in _MERGE_KINDS
                    or c._salted):
                continue
            before = ctx.rw.snapshot(c.id)
            ex = c.legs[0].exchange
            axes = [name for name, _size in ctx.levels]
            # innermost axis hop stays on this stage; it stops finalizing
            ex.axis = axes[0]
            mean_fin = None
            if c.body[0].kind == "dgroup_merge":
                c.body[0].params["finalize"] = False
            if len(c.body) > 1 and c.body[-1].kind == "mean_fin":
                mean_fin = c.body.pop()
            # one appended merge stage per remaining (scarcer) fabric;
            # the LAST level finalizes (mean_fin / dgroup finalize)
            prev, new_ids = c, []
            for i, ax in enumerate(axes[1:], start=1):
                last = i == len(axes) - 1
                body_op = self._final_aggs_clone(c.body[0])
                if body_op.kind == "dgroup_merge":
                    body_op.params["finalize"] = last
                body = [body_op]
                if last and mean_fin is not None:
                    body.append(mean_fin)
                nst = ctx.rw.new_stage(
                    [Leg(prev.id, [],
                         Exchange("hash", keys=ex.keys,
                                  out_capacity=ex.out_capacity,
                                  axis=ax))],
                    body, f"{c.label}-{ax}")
                new_ids.append(nst.id)
                prev = nst
            ctx.rw.redirect_consumers(c.id, prev.id, exclude=new_ids)
            out.append({"event": "graph_rewrite", "rule": self.name,
                        "kind": "agg_tree_expand", "stage": c.id,
                        "trigger_stage": st.stage,
                        "levels_before": 1, "levels_after": len(axes),
                        "new_stages": new_ids,
                        "before": before,
                        "after": ctx.rw.snapshot(c.id, prev.id)})
        return out

    def on_stage_done(self, ctx: RuleContext,
                      st: StageStats) -> List[dict]:
        return self._collapse(ctx, st) + self._expand(ctx, st)


# ---------------------------------------------------------------------------
# 2. skew-aware repartitioning


class SkewRepartition(ConnectionManager):
    name = "skew_repartition"

    def on_stage_done(self, ctx: RuleContext,
                      st: StageStats) -> List[dict]:
        out: List[dict] = []
        cfg = ctx.config
        factor = getattr(cfg, "adapt_skew_factor", 4.0)
        shrink_at = getattr(cfg, "adapt_shrink_factor", 2.0)
        skewed = st.is_skewed(factor)
        for c in ctx.rw.consumers_of(st.stage):
            for li, leg in enumerate(c.legs):
                if leg.src != st.stage or leg.exchange is None:
                    continue
                if not _non_expanding(leg.ops):
                    out.append({"event": "adapt_skipped",
                                "rule": self.name, "stage": c.id,
                                "reason": "leg ops may expand rows — "
                                          "measured bound unusable"})
                    continue
                ex = leg.exchange
                # COALESCE: the planner sized this exchange at the
                # static capacity envelope; the destination can never
                # receive more rows than the measured total, so the
                # padded lanes past that bound are pure waste in every
                # downstream program
                cap_bound = _round_cap(st.total_rows)
                if (ex.out_capacity >= shrink_at * max(st.total_rows, 1)
                        and cap_bound < ex.out_capacity):
                    before = ctx.rw.snapshot(c.id)
                    old = ex.out_capacity
                    ex.out_capacity = cap_bound
                    out.append({"event": "graph_rewrite",
                                "rule": self.name,
                                "kind": "repartition_shrink",
                                "stage": c.id, "leg": li,
                                "trigger_stage": st.stage,
                                "cap_before": old,
                                "cap_after": cap_bound,
                                "before": before,
                                "after": ctx.rw.snapshot(c.id)})
                if not skewed:
                    continue
                # SPLIT: a >=factor-x-median partition is about to feed
                # this exchange.  For a saltable join, rewrite to the
                # hot-key-salted exchange BEFORE the first attempt (the
                # overflow-retry path reaches the same program one
                # wasted compile+run later); otherwise pre-size the
                # send-slot slack for the worst case of the peak
                # partition landing on one destination.
                if c.salt_ok and not c._salted and len(c.legs) == 2:
                    before = ctx.rw.snapshot(c.id)
                    c._salted = True
                    out.append({"event": "graph_rewrite",
                                "rule": self.name, "kind": "pre_salt",
                                "stage": c.id,
                                "trigger_stage": st.stage,
                                "skew_ratio": round(st.skew_ratio, 1),
                                "before": before,
                                "after": ctx.rw.snapshot(c.id)})
                elif ex.kind in ("hash", "range"):
                    need = -(-st.peak_rows * ctx.nparts
                             // max(ex.out_capacity, 1))
                    need = max(1, min(ctx.nparts, need))
                    cur = c._send_slack or getattr(
                        cfg, "initial_send_slack", 2)
                    if need > cur:
                        before = ctx.rw.snapshot(c.id)
                        c._send_slack = need
                        out.append({"event": "graph_rewrite",
                                    "rule": self.name,
                                    "kind": "send_slack",
                                    "stage": c.id, "leg": li,
                                    "trigger_stage": st.stage,
                                    "slack_before": cur,
                                    "slack_after": need,
                                    "skew_ratio":
                                        round(st.skew_ratio, 1),
                                    "before": before,
                                    "after": ctx.rw.snapshot(c.id)})
        return out


# ---------------------------------------------------------------------------
# 3. broadcast demotion / promotion


class BroadcastManager(ConnectionManager):
    name = "broadcast"

    @staticmethod
    def _cap_of(ctx: RuleContext, sid: int) -> int:
        """Per-partition capacity of stage ``sid``'s output: measured
        when available, else the static cost pass's prediction."""
        st = ctx.stats.get(sid)
        if st is not None and st.capacity:
            return st.capacity
        if ctx.cost is not None:
            return ctx.cost.capacity_of(sid)
        return 0

    def on_stage_done(self, ctx: RuleContext,
                      st: StageStats) -> List[dict]:
        out: List[dict] = []
        ratio = getattr(ctx.config, "adapt_broadcast_max_ratio", 0.25)
        for c in ctx.rw.graph.stages:
            if (ctx.rw.is_executed(c.id) or len(c.legs) != 2
                    or not c.body or c.body[0].kind != "join"):
                continue
            lsrc, rsrc = c.legs[0].src, c.legs[1].src
            # act only at the boundary that completed one of OUR inputs;
            # the OTHER side may ride the static cost pass's bounds as a
            # prior (rows_bounds) instead of waiting to be measured
            if st.stage not in (lsrc, rsrc):
                continue
            if not (isinstance(lsrc, int) and isinstance(rsrc, int)):
                continue
            lb, rb = rows_bounds(ctx, lsrc), rows_bounds(ctx, rsrc)
            if lb is None or rb is None:
                continue
            if not (_non_expanding(c.legs[0].ops)
                    and _non_expanding(c.legs[1].ops)):
                continue
            jop = c.body[0]
            how = jop.params.get("how", "inner")
            # conservative ends of the intervals: a flip must hold for
            # EVERY row count the bounds admit (measured sides are
            # exact, lo == hi)
            lt_lo, lt_hi = lb
            rt_lo, rt_hi = rb
            lt, rt = lt_hi, rt_hi
            lex, rex = c.legs[0].exchange, c.legs[1].exchange
            if rex is not None and rex.kind == "broadcast":
                # DEMOTE: the "small" side measured past the planner's
                # estimate — replicating it nparts-ways loses to a pair
                # of hash exchanges
                if how not in ("inner", "left"):
                    continue
                # demotion must hold at the interval ends that FAVOR
                # keeping the broadcast: certainly-oversized build side
                # (rt_lo) vs the largest possible probe side (lt_hi)
                if rt_lo <= ratio * max(lt_hi, 1):
                    continue
                if getattr(c, "placement_relied", False):
                    out.append({"event": "adapt_skipped",
                                "rule": self.name, "stage": c.id,
                                "reason": "downstream relied on this "
                                          "join's output placement"})
                    continue
                before = ctx.rw.snapshot(c.id)
                c.legs[1].exchange = Exchange(
                    "hash", keys=tuple(jop.params["right_keys"]),
                    out_capacity=self._cap_of(ctx, rsrc)
                    or _round_cap(rt))
                if lex is None:
                    c.legs[0].exchange = Exchange(
                        "hash", keys=tuple(jop.params["left_keys"]),
                        out_capacity=self._cap_of(ctx, lsrc)
                        or _round_cap(lt))
                # now the canonical 2-hash inner/left shape: the salted
                # skew escape applies to it like any planned hash join
                c.salt_ok = True
                out.append({"event": "graph_rewrite", "rule": self.name,
                            "kind": "broadcast_demote", "stage": c.id,
                            "trigger_stage": st.stage,
                            "left_rows": lt, "right_rows": rt,
                            "before": before,
                            "after": ctx.rw.snapshot(c.id)})
            elif (c.salt_ok and not c._salted
                  and lex is not None and rex is not None
                  and lex.kind == "hash" and rex.kind == "hash"
                  and how in ("inner", "left")):
                # PROMOTE: the build side is tiny — replicate it and
                # keep the probe side IN PLACE (drops the expensive
                # big-side exchange entirely).  salt_ok guarantees no
                # downstream stage assumed this join's output placement.
                # Conservative ends: the LARGEST possible build side
                # (rt_hi) must stay within ratio of the SMALLEST
                # possible probe side (lt_lo).
                if not rt_hi or rt_hi > ratio * max(lt_lo, 1):
                    continue
                before = ctx.rw.snapshot(c.id)
                c.legs[1].exchange = Exchange(
                    "broadcast", out_capacity=_round_cap(rt))
                c.legs[0].exchange = None
                c.salt_ok = False
                out.append({"event": "graph_rewrite", "rule": self.name,
                            "kind": "broadcast_promote", "stage": c.id,
                            "trigger_stage": st.stage,
                            "left_rows": lt, "right_rows": rt,
                            "before": before,
                            "after": ctx.rw.snapshot(c.id)})
        return out


def default_rules() -> List[ConnectionManager]:
    """Rule order matters: tree shape first, then join strategy, then
    capacity/slack sizing — so the sizing pass sees post-flip
    exchanges."""
    return [DynamicAggregationTree(), BroadcastManager(),
            SkewRepartition()]
