"""Observed per-stage statistics driving adaptive rewrites.

The executor already measures per-partition output row counts (the
``info`` vector it fetches once per stage) and output bytes on every
synchronous stage completion; :class:`StageStats` is that measurement as
a value object the connection managers consume.  This is the counterpart
of the reference's vertex-completion size reports that
``DrConnectionManager`` subclasses receive
(``NotifyUpstreamVertexCompleted``): observed sizes, not estimates.

Mirrored determinism: on a multi-process gang the rows arrive replicated
(``exec/data.replicate_tree``), so every worker constructs the identical
StageStats and therefore applies the identical rewrites — the same
contract runtime salting already relies on.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from dryad_tpu.adapt.thresholds import sibling_median, skew_ratio

__all__ = ["StageStats"]


@dataclasses.dataclass(frozen=True)
class StageStats:
    """One materialized stage's observed output.

    ``rows`` is per-partition valid row counts; ``capacity`` the static
    per-partition batch capacity the output was materialized at (the
    padding envelope downstream exchanges inherit); ``out_bytes`` the
    device bytes of the materialized output.  A key sketch (per-key
    heavy-hitter evidence) can ride in a future field — rules must treat
    absent evidence as "unknown", never as "balanced"."""

    stage: int
    rows: Tuple[int, ...]
    capacity: int = 0
    out_bytes: int = 0
    wall_s: float = 0.0

    @property
    def total_rows(self) -> int:
        return int(sum(self.rows))

    @property
    def peak_rows(self) -> int:
        return int(max(self.rows)) if self.rows else 0

    @property
    def sibling_median(self) -> int:
        return sibling_median(self.rows)

    @property
    def skew_ratio(self) -> float:
        return skew_ratio(self.rows)

    def is_skewed(self, factor: float) -> bool:
        """Same predicate as ``obs/profile.diagnose_events``: peak >=
        factor x sibling median, with the same tiny-partition guard."""
        return self.peak_rows >= 2 and self.skew_ratio >= factor

    def event(self) -> dict:
        """The ``adapt_stats`` event payload (level 2)."""
        return {"event": "adapt_stats", "stage": self.stage,
                "rows": list(self.rows), "capacity": self.capacity,
                "out_bytes": self.out_bytes,
                "skew_ratio": round(self.skew_ratio, 2)}
