"""SQL compile errors: DTA3xx findings over the shared diagnostics
engine.

A failed compile raises ONE :class:`SqlError` carrying a full
``DiagnosticReport`` — the binder reports every unresolved name / type
mismatch at once (the analysis-engine contract), each finding with a
line:column span into the query text.  ``SqlError`` subclasses
``DiagnosticError``, so the job service surfaces it exactly like its
other typed rejections (HTTP 400, CLI exit 2, zero work started).
"""

from __future__ import annotations

from typing import Any

from dryad_tpu.analysis.diagnostics import (DiagnosticError,
                                            DiagnosticReport, Span)

__all__ = ["SqlError", "sql_report"]


def sql_report(code: str, message: str, span: Span) -> DiagnosticReport:
    """One-finding report (the lexer/parser stop at the first error;
    the binder builds multi-finding reports itself)."""
    rep = DiagnosticReport()
    rep.add(code, "error", message, span=span, node="sql")
    return rep


class SqlError(DiagnosticError):
    """SQL front-end rejection: parse/bind/type findings, all at once.
    ``code`` is the first (sorted most-severe-first) finding's code;
    ``report`` has everything."""

    def __init__(self, report: DiagnosticReport):
        self.report = report
        first = next(iter(report.sorted()), None)
        super().__init__(
            "SQL query rejected:\n" + report.render(),
            code=first.code if first is not None else "DTA301",
            span=first.span if first is not None else None)

    def codes(self) -> Any:
        return self.report.codes()
