"""Recursive-descent SQL parser.

Grammar (the DryadLINQ-parity declarative surface over the plan DAG —
SELECT / WHERE / GROUP BY + aggregates / JOIN / ORDER BY / LIMIT)::

    query     := SELECT [DISTINCT] items FROM table_ref join* [WHERE expr]
                 [GROUP BY col ("," col)*] [HAVING expr]
                 [ORDER BY ord ("," ord)*] [LIMIT int]
                 [EMIT EVERY num [SECONDS]] [";"]
    items     := "*" | item ("," item)*
    item      := expr [[AS] ident]
    table_ref := ident [[AS] ident]
    join      := [INNER | LEFT|RIGHT|FULL [OUTER]] JOIN table_ref ON expr
    ord       := ident [ASC | DESC]
    expr      := or-tree over NOT / comparisons / + - / * / / unary- /
                 "(" expr ")" / literal / [ident "."] ident /
                 SUM|COUNT|MIN|MAX|AVG "(" expr | "*" ")"

A syntax error raises :class:`SqlError` with DTA301 and the offending
token's line:column; recognized-but-unsupported constructs (subqueries,
CROSS/NATURAL JOIN, UNION/INTERSECT/EXCEPT, OFFSET, IN/LIKE/BETWEEN/
CASE/IS NULL) raise DTA306 so the message says "unsupported", not
"syntax error".
"""

from __future__ import annotations

from typing import List, Optional

from dryad_tpu.sql import nodes as N
from dryad_tpu.sql.errors import SqlError, sql_report
from dryad_tpu.sql.lexer import Token, tokenize

__all__ = ["parse", "parse_statement"]

_UNSUPPORTED_KW = {
    "UNION": "UNION", "INTERSECT": "INTERSECT", "EXCEPT": "EXCEPT",
    "OFFSET": "OFFSET", "IN": "IN (...)", "LIKE": "LIKE",
    "BETWEEN": "BETWEEN", "CASE": "CASE", "IS": "IS [NOT] NULL",
}


class _Parser:
    def __init__(self, toks: List[Token], origin: str):
        self.toks = toks
        self.i = 0
        self.origin = origin

    # -- token plumbing ----------------------------------------------------

    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def _span(self, tok: Token):
        return tok.span(self.origin)

    def err(self, msg: str, tok: Optional[Token] = None,
            code: str = "DTA301") -> SqlError:
        tok = tok or self.cur
        at = f" (at {tok.kind} {tok.text!r})" if tok.kind != "eof" \
            else " (at end of query)"
        return SqlError(sql_report(code, msg + at, self._span(tok)))

    def at_kw(self, *names: str) -> bool:
        return self.cur.kind == "kw" and self.cur.text in names

    def at_punct(self, text: str) -> bool:
        return self.cur.kind == "punct" and self.cur.text == text

    def take(self) -> Token:
        t = self.cur
        self.i += 1
        return t

    def expect_kw(self, name: str) -> Token:
        if not self.at_kw(name):
            raise self.err(f"expected {name}")
        return self.take()

    def expect_punct(self, text: str) -> Token:
        if not self.at_punct(text):
            raise self.err(f"expected {text!r}")
        return self.take()

    def expect_ident(self, what: str) -> Token:
        if self.cur.kind != "ident":
            raise self.err(f"expected {what}")
        return self.take()

    def _check_unsupported(self) -> None:
        if self.cur.kind == "kw" and self.cur.text in _UNSUPPORTED_KW:
            raise self.err(
                f"{_UNSUPPORTED_KW[self.cur.text]} is not supported",
                code="DTA306")

    # -- query -------------------------------------------------------------

    def parse_select(self) -> N.Select:
        head = self.expect_kw("SELECT")
        distinct = False
        if self.at_kw("DISTINCT"):
            self.take()
            distinct = True
        items = self.select_items()
        self.expect_kw("FROM")
        table = self.table_ref()
        joins = []
        while self.at_kw("JOIN", "INNER", "LEFT", "RIGHT", "FULL",
                         "CROSS", "NATURAL"):
            joins.append(self.join_clause())
        where = None
        if self.at_kw("WHERE"):
            self.take()
            where = self.expr()
        group_by: List[N.Col] = []
        if self.at_kw("GROUP"):
            self.take()
            self.expect_kw("BY")
            group_by.append(self.col_ref("GROUP BY column"))
            while self.at_punct(","):
                self.take()
                group_by.append(self.col_ref("GROUP BY column"))
        having = None
        if self.at_kw("HAVING"):
            self.take()
            having = self.expr()
        order_by: List[N.OrderItem] = []
        if self.at_kw("ORDER"):
            self.take()
            self.expect_kw("BY")
            order_by.append(self.order_item())
            while self.at_punct(","):
                self.take()
                order_by.append(self.order_item())
        limit = None
        if self.at_kw("LIMIT"):
            self.take()
            t = self.take()
            if t.kind != "int":
                raise self.err("LIMIT needs an integer literal", t)
            limit = int(t.text)
        emit_every = None
        emit_span = None
        if self.at_kw("EMIT"):
            e0 = self.take()
            self.expect_kw("EVERY")
            t = self.take()
            if t.kind not in ("int", "float"):
                raise self.err("EMIT EVERY needs a numeric interval "
                               "(seconds)", t)
            emit_every = float(t.text)
            if self.at_kw("SECONDS"):
                self.take()
            emit_span = self._span(e0)
        if self.at_punct(";"):
            self.take()
        self._check_unsupported()
        if self.cur.kind != "eof":
            raise self.err("unexpected trailing input")
        return N.Select(items=items, distinct=distinct, table=table,
                        joins=tuple(joins), where=where,
                        group_by=tuple(group_by), having=having,
                        order_by=tuple(order_by), limit=limit,
                        span=self._span(head), emit_every=emit_every,
                        emit_span=emit_span)

    def select_items(self) -> List[N.SelectItem]:
        if self.at_punct("*"):
            t = self.take()
            return [N.SelectItem(N.Col(None, "*", self._span(t)), None,
                                 self._span(t))]
        items = [self.select_item()]
        while self.at_punct(","):
            self.take()
            items.append(self.select_item())
        return items

    def select_item(self) -> N.SelectItem:
        t0 = self.cur
        e = self.expr()
        alias = None
        if self.at_kw("AS"):
            self.take()
            alias = self.expect_ident("alias after AS").text
        elif self.cur.kind == "ident":
            alias = self.take().text
        return N.SelectItem(e, alias, self._span(t0))

    def table_ref(self) -> N.TableRef:
        self._check_unsupported()
        if self.at_punct("("):
            raise self.err("subqueries are not supported", code="DTA306")
        t = self.expect_ident("table name")
        alias = t.text
        if self.at_kw("AS"):
            self.take()
            alias = self.expect_ident("alias after AS").text
        elif self.cur.kind == "ident":
            alias = self.take().text
        return N.TableRef(t.text, alias, self._span(t))

    def join_clause(self) -> N.JoinClause:
        t0 = self.cur
        if self.at_kw("CROSS", "NATURAL"):
            raise self.err(f"{self.cur.text} JOIN is not supported",
                           code="DTA306")
        how = "inner"
        if self.at_kw("INNER"):
            self.take()
        elif self.at_kw("LEFT", "RIGHT", "FULL"):
            how = self.take().text.lower()
            if self.at_kw("OUTER"):
                self.take()
        self.expect_kw("JOIN")
        table = self.table_ref()
        self.expect_kw("ON")
        on = self.expr()
        return N.JoinClause(table, how, on, self._span(t0))

    def col_ref(self, what: str) -> N.Col:
        t = self.expect_ident(what)
        if self.at_punct("."):
            self.take()
            c = self.expect_ident("column name after '.'")
            return N.Col(t.text, c.text, self._span(t))
        return N.Col(None, t.text, self._span(t))

    def order_item(self) -> N.OrderItem:
        t = self.expect_ident("ORDER BY column")
        desc = False
        if self.at_kw("ASC", "DESC"):
            desc = self.take().text == "DESC"
        return N.OrderItem(t.text, desc, self._span(t))

    # -- expressions (precedence: OR < AND < NOT < cmp < +- < */ < unary) --

    def expr(self):
        e = self.and_expr()
        while self.at_kw("OR"):
            t = self.take()
            e = N.Bin("or", e, self.and_expr(), self._span(t))
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.at_kw("AND"):
            t = self.take()
            e = N.Bin("and", e, self.not_expr(), self._span(t))
        return e

    def not_expr(self):
        if self.at_kw("NOT"):
            t = self.take()
            return N.Un("not", self.not_expr(), self._span(t))
        return self.cmp_expr()

    def cmp_expr(self):
        e = self.add_expr()
        self._check_unsupported()
        if self.cur.kind == "punct" and self.cur.text in (
                "=", "!=", "<", "<=", ">", ">="):
            t = self.take()
            return N.Bin(t.text, e, self.add_expr(), self._span(t))
        return e

    def add_expr(self):
        e = self.mul_expr()
        while self.cur.kind == "punct" and self.cur.text in ("+", "-"):
            t = self.take()
            e = N.Bin(t.text, e, self.mul_expr(), self._span(t))
        return e

    def mul_expr(self):
        e = self.unary_expr()
        while self.cur.kind == "punct" and self.cur.text in ("*", "/"):
            t = self.take()
            e = N.Bin(t.text, e, self.unary_expr(), self._span(t))
        return e

    def unary_expr(self):
        if self.at_punct("-"):
            t = self.take()
            return N.Un("neg", self.unary_expr(), self._span(t))
        return self.atom()

    def atom(self):
        self._check_unsupported()
        t = self.cur
        if t.kind == "punct" and t.text == "(":
            self.take()
            if self.at_kw("SELECT"):
                raise self.err("subqueries are not supported",
                               code="DTA306")
            e = self.expr()
            self.expect_punct(")")
            return e
        if t.kind == "int":
            self.take()
            return N.Lit(int(t.text), "int", self._span(t))
        if t.kind == "float":
            self.take()
            return N.Lit(float(t.text), "float", self._span(t))
        if t.kind == "str":
            self.take()
            return N.Lit(t.text, "str", self._span(t))
        if t.kind == "kw" and t.text == "NULL":
            raise self.err("NULL literals are not supported",
                           code="DTA306")
        if t.kind == "ident":
            name = self.take()
            up = name.text.upper()
            if up in N.AGG_FUNCS and self.at_punct("("):
                self.take()
                if self.at_punct("*"):
                    star = self.take()
                    if up != "COUNT":
                        raise self.err(
                            f"{up}(*) is not supported (only COUNT(*))",
                            star, code="DTA306")
                    arg = None
                else:
                    if self.at_kw("DISTINCT"):
                        raise self.err(
                            "aggregate DISTINCT is not supported",
                            code="DTA306")
                    arg = self.expr()
                self.expect_punct(")")
                return N.Agg(up, arg, self._span(name))
            if self.at_punct("("):
                raise self.err(
                    f"unknown function {name.text!r} (supported: "
                    f"{', '.join(sorted(N.AGG_FUNCS))})", name,
                    code="DTA306")
            if self.at_punct("."):
                self.take()
                c = self.expect_ident("column name after '.'")
                return N.Col(name.text, c.text, self._span(name))
            return N.Col(None, name.text, self._span(name))
        raise self.err("expected an expression")


def parse(query: str, origin: str = "<sql>") -> N.Select:
    """Parse one SELECT statement (any leading EXPLAIN [COST] must be
    stripped by the caller — sql.split_explain)."""
    return _Parser(tokenize(query, origin), origin).parse_select()


def parse_statement(query: str, origin: str = "<sql>"):
    """(mode, Select) where mode is "run" | "explain" | "explain_cost"
    | "explain_analyze" depending on a leading ``EXPLAIN [COST |
    ANALYZE]``.  ANALYZE is deliberately NOT a reserved keyword (it
    stays usable as a column/table name) — it only has meaning directly
    after EXPLAIN."""
    toks = tokenize(query, origin)
    mode = "run"
    if toks and toks[0].kind == "kw" and toks[0].text == "EXPLAIN":
        toks = toks[1:]
        mode = "explain"
        if toks and toks[0].kind == "kw" and toks[0].text == "COST":
            toks = toks[1:]
            mode = "explain_cost"
        elif (toks and toks[0].kind == "ident"
                and toks[0].text.upper() == "ANALYZE"):
            toks = toks[1:]
            mode = "explain_analyze"
    return mode, _Parser(toks, origin).parse_select()
