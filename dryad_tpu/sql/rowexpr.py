"""Row-expression programs: the SQL front end's compiled callables.

The reference compiles LINQ expression trees to C# vertex code shipped
as a DLL (DryadLinqCodeGen.cs).  Here a bound SQL scalar expression
compiles to a small JSON program (nested lists) interpreted over a
columns dict — the SAME callable runs in three places:

* the in-memory executor (jnp arrays / StringColumns under jit+vmap),
* the sequential oracle (numpy arrays / lists of bytes),
* cluster workers, where the program crosses the wire AS DATA via the
  shippable-value protocol (plan/serialize.ship_ref_of): a SQL plan
  ships with zero fn_table registration and no ``--fn-module``.

Program grammar (JSON-able, deterministic)::

    ["col", name]                  column reference (physical name)
    ["lit", value, type]           scalar literal; type "str" encodes
                                   the value utf-8 at eval time
    ["const", value, type]         literal broadcast to a whole column
    ["bin", op, lhs, rhs]          op in + - * / = != < <= > >= and or
    ["not", x] / ["neg", x]

Only dtype-generic array operators are used, so the interpreter is
backend-agnostic by construction; string equality handles both the
device representation (StringColumn byte matrix) and host lists of
bytes.
"""

from __future__ import annotations

from typing import Any, Dict, List

__all__ = ["Predicate", "Projector", "render_prog", "prog_columns",
           "fold_prog"]


def _is_strcol(v: Any) -> bool:
    """Device string column (data/columnar.StringColumn duck-typed —
    this module must import on workers before jax is configured)."""
    return hasattr(v, "data") and hasattr(v, "lengths")


def _is_host_str(v: Any) -> bool:
    if isinstance(v, (list, tuple)):
        return len(v) == 0 or isinstance(v[0], (bytes, str))
    dt = getattr(v, "dtype", None)
    return dt is not None and getattr(dt, "kind", "") in ("S", "U", "O")


def _str_eq(a: Any, b: Any):
    """Elementwise string equality across representations; either side
    may be a column (StringColumn / host list) or a bytes literal."""
    if isinstance(a, bytes):
        a, b = b, a
    if _is_strcol(a):
        import jax.numpy as jnp
        if isinstance(b, bytes):
            if len(b) > a.max_len:
                # no stored string can equal a literal longer than the
                # column's max_len — comparing the truncation instead
                # would spuriously match its own prefix
                return jnp.zeros(a.lengths.shape, bool)
            pad = b + b"\x00" * (a.max_len - len(b))
            row = jnp.asarray(bytearray(pad), dtype=jnp.uint8)
            return ((a.lengths == len(b))
                    & (a.data == row[None, :]).all(axis=1))
        # column vs column: compare over the common width, then the
        # longer side's overhang must be empty (padding is zero)
        w = min(a.max_len, b.max_len)
        same = (a.data[:, :w] == b.data[:, :w]).all(axis=1)
        return same & (a.lengths == b.lengths)
    # host representations (oracle): lists / object arrays of bytes
    import numpy as np

    def norm(x):
        return x if isinstance(x, bytes) else str(x).encode()

    if isinstance(b, bytes):
        return np.asarray([norm(x) == b for x in a], dtype=bool)
    return np.asarray([norm(x) == norm(y) for x, y in zip(a, b)],
                      dtype=bool)


def _const_like(cols: Dict[str, Any], value: Any, typ: str):
    """A whole column holding ``value``, row-count matched to the batch
    (the lowering's global-aggregate key; api.dataset._const_key_like
    pattern)."""
    v = next(iter(cols.values()))
    if _is_strcol(v):
        n = v.lengths.shape[0]
    elif hasattr(v, "shape"):
        n = v.shape[0]
    else:
        n = len(v)
    if hasattr(v, "shape") or _is_strcol(v):
        import jax.numpy as jnp
        return jnp.full((n,), value, _np_dtype(typ))
    import numpy as np
    return np.full((n,), value, dtype=_np_dtype(typ))


def _np_dtype(typ: str):
    return {"int": "int32", "float": "float32",
            "bool": "bool_"}.get(typ, "int32")


def _ev(prog: List, cols: Dict[str, Any]) -> Any:
    head = prog[0]
    if head == "col":
        return cols[prog[1]]
    if head == "lit":
        v, t = prog[1], prog[2]
        return v.encode() if t == "str" else v
    if head == "const":
        return _const_like(cols, prog[1], prog[2])
    if head == "not":
        v = _ev(prog[1], cols)
        # column-free subtrees fold to Python scalars (WHERE NOT(1=1));
        # ~True is -2, not False
        return (not v) if isinstance(v, bool) else ~v
    if head == "neg":
        return -_ev(prog[1], cols)
    if head == "bin":
        op = prog[1]
        a = _ev(prog[2], cols)
        b = _ev(prog[3], cols)
        str_sides = (isinstance(a, bytes) or isinstance(b, bytes)
                     or _is_strcol(a) or _is_strcol(b)
                     or _is_host_str(a) or _is_host_str(b))
        if op == "=":
            return _str_eq(a, b) if str_sides else a == b
        if op == "!=":
            return ~_str_eq(a, b) if str_sides else a != b
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
        if op == "and":
            return a & b
        if op == "or":
            return a | b
    raise ValueError(f"bad row-expression program node {prog!r}")


def render_prog(prog: List) -> str:
    """SQL-ish rendering for EXPLAIN / repr."""
    head = prog[0]
    if head == "col":
        return prog[1]
    if head in ("lit", "const"):
        v = prog[1]
        return f"'{v}'" if prog[2] == "str" else repr(v)
    if head == "not":
        return f"(NOT {render_prog(prog[1])})"
    if head == "neg":
        return f"(-{render_prog(prog[1])})"
    op = prog[1].upper() if prog[1] in ("and", "or") else prog[1]
    return f"({render_prog(prog[2])} {op} {render_prog(prog[3])})"


def prog_columns(prog: List) -> set:
    """Set of physical column names a program reads (dead-column
    pruning + scan-prefix analysis, analysis/canon.py)."""
    head = prog[0]
    if head == "col":
        return {prog[1]}
    if head in ("lit", "const"):
        return set()
    if head in ("not", "neg"):
        return prog_columns(prog[1])
    if head == "bin":
        return prog_columns(prog[2]) | prog_columns(prog[3])
    raise ValueError(f"bad row-expression program node {prog!r}")


def fold_prog(prog: List) -> List:
    """Constant-fold column-free subtrees to ``["lit", v, typ]`` —
    pure data-to-data, mirroring :func:`_ev`'s scalar semantics, so
    the folded program computes the SAME function.  Division by zero
    (and any other eval-time surprise) leaves the subtree unfolded;
    the runtime keeps its behavior."""
    head = prog[0]
    if head in ("col", "lit", "const"):
        return list(prog)
    if head in ("not", "neg"):
        x = fold_prog(prog[1])
        if x[0] == "lit":
            if head == "not":
                return ["lit", not x[1], "bool"]
            return ["lit", -x[1], x[2]]
        return [head, x]
    # head == "bin"
    op = prog[1]
    a, b = fold_prog(prog[2]), fold_prog(prog[3])
    if a[0] == "lit" and b[0] == "lit":
        va, vb = a[1], b[1]
        try:
            v = {"+": lambda: va + vb, "-": lambda: va - vb,
                 "*": lambda: va * vb, "/": lambda: va / vb,
                 "=": lambda: va == vb, "!=": lambda: va != vb,
                 "<": lambda: va < vb, "<=": lambda: va <= vb,
                 ">": lambda: va > vb, ">=": lambda: va >= vb,
                 "and": lambda: bool(va) and bool(vb),
                 "or": lambda: bool(va) or bool(vb)}[op]()
        except (ZeroDivisionError, TypeError):
            return ["bin", op, a, b]
        if op in ("=", "!=", "<", "<=", ">", ">=", "and", "or"):
            return ["lit", bool(v), "bool"]
        typ = ("float" if op == "/" or "float" in (a[2], b[2])
               else a[2])
        return ["lit", v, typ]
    return ["bin", op, a, b]


class _Shippable:
    """Base: the shippable-value protocol (plan/serialize.ship_ref_of).
    Content-identical instances fingerprint identically
    (plan/stages.Stage.fingerprint), so resubmitting a query hits the
    executor's compile cache."""

    def __ship_payload__(self):
        raise NotImplementedError

    @classmethod
    def __from_payload__(cls, payload):
        raise NotImplementedError

    def __eq__(self, other):
        return (type(other) is type(self)
                and other.__ship_payload__() == self.__ship_payload__())

    def __hash__(self):
        import json
        return hash(json.dumps(self.__ship_payload__(), sort_keys=True))


class Predicate(_Shippable):
    """Boolean row filter: ``Predicate(prog)(cols) -> bool mask``."""

    def __init__(self, prog: List):
        self.prog = list(prog)

    def __call__(self, cols: Dict[str, Any]):
        mask = _ev(self.prog, cols)
        if isinstance(mask, (bool, int)):
            # column-free predicate (WHERE 1 = 1): broadcast the
            # scalar verdict to a whole mask column
            return _const_like(cols, bool(mask), "bool")
        return mask if getattr(mask, "dtype", None) is not None \
            and str(mask.dtype) == "bool" else mask.astype(bool)

    def __ship_payload__(self):
        return {"prog": self.prog}

    @classmethod
    def __from_payload__(cls, payload):
        return cls(payload["prog"])

    def __repr__(self):
        return f"sql:{render_prog(self.prog)}"


class Projector(_Shippable):
    """Columnwise projection: ``Projector({out: prog})(cols) -> cols``.
    Plain ``["col", name]`` programs pass the column object through
    untouched (renames are free — string columns included)."""

    def __init__(self, outputs: Dict[str, List]):
        self.outputs = dict(outputs)

    def __call__(self, cols: Dict[str, Any]) -> Dict[str, Any]:
        return {name: _ev(prog, cols)
                for name, prog in self.outputs.items()}

    def __ship_payload__(self):
        return {"outputs": self.outputs}

    @classmethod
    def __from_payload__(cls, payload):
        return cls(payload["outputs"])

    def __repr__(self):
        inner = ", ".join(f"{render_prog(p)} AS {n}"
                          for n, p in self.outputs.items())
        return f"sql:[{inner}]"
