"""Catalog: named tables the SQL front end resolves FROM clauses against.

The reference binds LINQ queries to typed ``PartitionedTable<T>`` inputs
whose schemas are .NET types; here a :class:`Catalog` maps table names
to one of

* a **store** path (io/store.py partitioned store — schema + row counts
  + byte sizes come from the manifest, so the static cost analyzer's
  DTA2xx forecasts are seeded with REAL statistics),
* **inline host columns** (tests / small dimension tables),
* a **schema-only** declaration (offline EXPLAIN against a serialized
  catalog — ``python -m dryad_tpu.sql`` and the golden-plan drift gate
  plan real queries with no data anywhere).

``fingerprint()`` hashes the full registration (names, schemas, store
paths, row counts): it salts the service's FileCache plan-cache key and
rides every ``sql_query`` event, so history/forensics bundles identify
exactly which catalog a query compiled against.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Catalog", "CatalogTable", "SchemaContext",
           "SchemaOnlyTableError", "normalize_schema",
           "table_fingerprint"]


class SchemaOnlyTableError(ValueError):
    """Execution was requested over a table registered schema-only
    (no store path, no inline columns) — it supports offline EXPLAIN
    only.  Typed so the service can map it to a client error."""


def _norm_schema(schema: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Normalize a store-manifest / user schema to
    ``{col: {"kind": "str", "max_len": n} | {"kind": "num",
    "dtype": dtype_str}}``."""
    out: Dict[str, Dict[str, Any]] = {}
    for col, spec in schema.items():
        if isinstance(spec, str):
            spec = ({"kind": "str"} if spec == "str"
                    else {"kind": "num", "dtype": spec})
        if spec.get("kind") == "str":
            out[col] = {"kind": "str",
                        "max_len": int(spec.get("max_len", 64))}
        else:
            out[col] = {"kind": "num",
                        "dtype": str(spec.get("dtype", "int32"))}
    return out


def normalize_schema(schema: Dict[str, Any]
                     ) -> Dict[str, Dict[str, Any]]:
    """COLUMN-ORDER-INSENSITIVE normalized schema: ``_norm_schema``
    sorted by column name.  The ONE normalization both
    ``Catalog.fingerprint()`` and the semantic plan fingerprint
    (analysis/canon.py) hash, so a schema re-registered with its
    columns in a different order cannot produce a different
    fingerprint and orphan warm cache entries."""
    n = _norm_schema(schema)
    return {col: n[col] for col in sorted(n)}


def _inline_content_hash(t: "CatalogTable") -> str:
    """Content hash of an inline table's columns (column-order
    insensitive: iterates sorted names)."""
    h = hashlib.sha256()
    for col in sorted(t.columns):
        v = t.columns[col]
        h.update(col.encode())
        if isinstance(v, (list, tuple)):
            for x in v:
                h.update(x if isinstance(x, bytes)
                         else str(x).encode())
                h.update(b"\x00")
        else:
            import numpy as np
            h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()


def table_fingerprint(t: "CatalogTable") -> str:
    """Per-table CONTENT fingerprint (16 hex chars): normalized schema
    + row stats + store path / inline column bytes.  Two catalog
    registrations with the same fingerprint serve the same rows, so a
    scan of one can be shared by queries over the other — the identity
    the service's scan-share cache and analysis/subsume.py key on.
    Shares its normalization with :meth:`Catalog.fingerprint` (the
    satellite contract: the two can never disagree on column order)."""
    d: Dict[str, Any] = {"kind": t.kind,
                         "schema": normalize_schema(t.schema),
                         "rows": t.rows}
    if t.path is not None:
        d["path"] = t.path
    if t.kind == "inline":
        d["content"] = _inline_content_hash(t)
    blob = json.dumps(d, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def sql_type_of(spec: Dict[str, Any]) -> str:
    """Binder-facing type name: "int" | "float" | "bool" | "str"."""
    if spec["kind"] == "str":
        return "str"
    dt = spec["dtype"]
    if dt.startswith("float"):
        return "float"
    if dt.startswith("bool"):
        return "bool"
    return "int"


class CatalogTable:
    def __init__(self, name: str, schema: Dict[str, Any],
                 path: Optional[str] = None,
                 columns: Optional[Dict[str, Any]] = None,
                 rows: int = 0, str_max_len: Optional[int] = None):
        self.name = name
        self.schema = _norm_schema(schema)
        self.path = path
        self.columns = columns
        self.rows = int(rows)
        self.str_max_len = str_max_len

    @property
    def kind(self) -> str:
        if self.path is not None:
            return "store"
        return "inline" if self.columns is not None else "schema"

    def meta(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"kind": self.kind, "schema": self.schema,
                             "rows": self.rows}
        if self.path is not None:
            d["path"] = self.path
        return d


class Catalog:
    """Mutable registry of tables; see module docstring."""

    def __init__(self):
        self.tables: Dict[str, CatalogTable] = {}

    # -- registration ------------------------------------------------------

    def register_store(self, name: str, path: str) -> "Catalog":
        """Register a persisted io/store.py store (local / s3:// /
        hdfs://); schema and row statistics come from its manifest."""
        from dryad_tpu.io.store import store_meta
        meta = store_meta(path)
        self.tables[name] = CatalogTable(
            name, meta["schema"], path=path,
            rows=sum(meta.get("counts", ())))
        return self

    def register_columns(self, name: str, columns: Dict[str, Any],
                         str_max_len: Optional[int] = None) -> "Catalog":
        """Register in-memory host columns (numpy arrays / lists;
        lists of bytes|str are string columns)."""
        import numpy as np
        schema: Dict[str, Any] = {}
        cols: Dict[str, Any] = {}
        rows = 0
        for col, v in columns.items():
            # numpy string/object arrays are string columns too — the
            # numeric branch would otherwise type them "int"
            if not isinstance(v, (list, tuple)) and \
                    getattr(getattr(v, "dtype", None), "kind", "") \
                    in ("U", "S", "O"):
                v = [x if isinstance(x, bytes) else str(x).encode()
                     for x in v]
            if isinstance(v, (list, tuple)) and (
                    len(v) == 0 or isinstance(v[0], (bytes, str))):
                ml = max((len(x if isinstance(x, bytes)
                              else str(x).encode()) for x in v),
                         default=1)
                schema[col] = {"kind": "str",
                               "max_len": str_max_len or max(ml, 1)}
                rows = len(v)
                cols[col] = list(v)
            else:
                arr = np.asarray(v)
                schema[col] = {"kind": "num", "dtype": str(arr.dtype)}
                rows = arr.shape[0]
                cols[col] = v
        self.tables[name] = CatalogTable(name, schema,
                                         columns=cols, rows=rows,
                                         str_max_len=str_max_len)
        return self

    def register_schema(self, name: str, schema: Dict[str, Any],
                        rows: int = 0) -> "Catalog":
        """Schema-only registration (offline EXPLAIN / golden plans)."""
        self.tables[name] = CatalogTable(name, schema, rows=rows)
        return self

    # -- lookup ------------------------------------------------------------

    def names(self) -> List[str]:
        return sorted(self.tables)

    def get(self, name: str) -> Optional[CatalogTable]:
        return self.tables.get(name)

    def watermark(self, name: str) -> int:
        """Current append generation of a store-backed table (re-read
        from the live manifest — the standing-query scheduler polls
        this to decide whether a refresh has anything to scan)."""
        from dryad_tpu.io.store import store_generation, store_meta
        t = self.tables[name]
        if t.kind != "store":
            raise ValueError(f"table {name!r} is {t.kind}-backed — only "
                             f"store tables carry an append watermark")
        return store_generation(store_meta(t.path))

    def parts_since(self, name: str, watermark: int) -> List[int]:
        """Store partition ids of ``name`` appended after ``watermark``
        — the chunk delta an incremental refresh scopes its scan to."""
        from dryad_tpu.io.store import parts_since, store_meta
        t = self.tables[name]
        if t.kind != "store":
            raise ValueError(f"table {name!r} is {t.kind}-backed — only "
                             f"store tables carry an append watermark")
        return parts_since(store_meta(t.path), watermark)

    def refresh_store(self, name: str) -> "Catalog":
        """Re-read a store table's manifest statistics (row counts grow
        as generations land; cost forecasts should see them)."""
        t = self.tables[name]
        if t.kind == "store":
            self.register_store(name, t.path)
        return self

    def fingerprint(self) -> str:
        """Hashes the full registration INCLUDING inline column
        CONTENT (the service's plan cache stores inline source data
        keyed on this — two catalogs with equal schemas but different
        values must not collide).  Schemas hash through
        :func:`normalize_schema` (shared with the per-table
        :func:`table_fingerprint` and the semantic plan fingerprint),
        so re-registering a table with its columns reordered yields
        the SAME fingerprint — warm cache entries survive."""
        meta = {}
        for n, t in self.tables.items():
            d = t.meta()
            d["schema"] = normalize_schema(t.schema)
            if t.kind == "inline":
                d["content"] = _inline_content_hash(t)
            meta[n] = d
        blob = json.dumps(meta, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    # -- dataset construction ----------------------------------------------

    def dataset(self, ctx, name: str, loader=None):
        """Root Dataset for ``name`` under ``ctx`` (a real api.Context
        or a :class:`SchemaContext`).  Returns ``(dataset, source
        data-handle)`` — the handle identity lets the service map plan
        source slots back to table names for warm-cache rebinding.

        ``loader`` (optional, ``name -> PData``) supplies the source
        data instead of a fresh store/columns read — the service's
        scan-share hook: queued/concurrent jobs over the same table
        bind ONE loaded PData (one cold scan) instead of re-reading.
        Only honored on an in-process Context (a real mesh) for tables
        below the auto-stream threshold; streamed and cluster paths
        keep their own source construction."""
        from dryad_tpu.api.dataset import Dataset
        t = self.tables[name]
        if isinstance(ctx, SchemaContext):
            from dryad_tpu.plan import expr as E
            cap = max(1, -(-max(t.rows, 1) // ctx.nparts))
            node = E.Source(parents=(), data=_SchemaData(cap),
                            _npartitions=ctx.nparts)
            return Dataset(ctx, node), node.data
        use_loader = (loader is not None
                      and getattr(ctx, "mesh", None) is not None
                      and getattr(ctx, "cluster", None) is None)
        if t.kind == "store":
            auto = getattr(ctx.config, "ooc_auto_stream_rows", 0)
            if use_loader and not (auto and t.rows >= auto):
                from dryad_tpu.io.store import store_meta
                from dryad_tpu.plan import expr as E
                meta = store_meta(t.path)
                pmeta = meta.get("partitioning", {"kind": "none"})
                part = E.Partitioning(pmeta.get("kind", "none"),
                                      tuple(pmeta.get("keys", ())))
                if meta["npartitions"] != ctx.nparts:
                    part = E.Partitioning.none()
                ds = ctx.from_pdata(loader(name), partitioning=part)
            else:
                ds = ctx.from_store(t.path)
        elif t.kind == "inline":
            if use_loader:
                ds = ctx.from_pdata(loader(name),
                                    host=dict(t.columns))
            else:
                ds = ctx.from_columns(dict(t.columns),
                                      str_max_len=t.str_max_len)
        else:
            raise SchemaOnlyTableError(
                f"table {name!r} is schema-only (no store path or "
                f"inline columns) — it supports offline EXPLAIN, not "
                f"execution")
        return ds, ds.node.data

    def load_pdata(self, mesh, name: str, config=None):
        """PData for a warm plan-cache rebind (service in-process
        fleet): the plan JSON is reused, only source slots re-read."""
        from dryad_tpu.exec.data import pdata_from_host
        from dryad_tpu.io.store import read_store
        t = self.tables[name]
        if t.kind == "store":
            verify = (config.store_verify_checksums
                      if config is not None else True)
            return read_store(t.path, mesh, verify=verify)
        if t.kind == "inline":
            # the same default Context.from_columns applies on the cold
            # path — warm-rebound batches must be SHAPE-IDENTICAL or
            # the compile cache misses
            sml = t.str_max_len or (getattr(config, "string_max_len", 0)
                                    if config is not None else 0) or 64
            return pdata_from_host(dict(t.columns), mesh,
                                   str_max_len=sml)
        raise SchemaOnlyTableError(f"table {name!r} is schema-only")

    # -- (de)serialization -------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        """JSON form for ``save``/``load``.  Inline tables serialize
        their columns too (bytes ride as latin-1 strings — a LOSSLESS
        byte<->str round trip, unlike utf-8-with-replacement) plus
        their ``str_max_len``, so a saved catalog reloads to the SAME
        schema and fingerprint and stays executable."""
        out: Dict[str, Any] = {"tables": {}}
        for n, t in self.tables.items():
            d = t.meta()
            if t.kind == "inline":
                cols = {}
                for c, v in t.columns.items():
                    if isinstance(v, (list, tuple)):
                        cols[c] = [x.decode("latin1")
                                   if isinstance(x, bytes) else x
                                   for x in v]
                    else:
                        cols[c] = [x.item() if hasattr(x, "item") else x
                                   for x in v]
                d["columns"] = cols
                if t.str_max_len is not None:
                    d["str_max_len"] = t.str_max_len
            out["tables"][n] = d
        return out

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "Catalog":
        cat = cls()
        for n, d in obj.get("tables", {}).items():
            if d["kind"] == "store":
                # trust the serialized schema (the store may be remote/
                # unmounted at load time); the path re-resolves at
                # dataset() time
                cat.tables[n] = CatalogTable(n, d["schema"],
                                             path=d["path"],
                                             rows=d.get("rows", 0))
            elif d["kind"] == "inline" and "columns" in d:
                cols = {}
                for c, v in d["columns"].items():
                    if d["schema"].get(c, {}).get("kind") == "str":
                        cols[c] = [str(x).encode("latin1") for x in v]
                    else:
                        import numpy as np
                        cols[c] = np.asarray(
                            v, dtype=d["schema"][c]["dtype"])
                cat.register_columns(n, cols,
                                     str_max_len=d.get("str_max_len"))
            else:
                cat.register_schema(n, d["schema"],
                                    rows=d.get("rows", 0))
        return cat

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "Catalog":
        with open(path) as f:
            return cls.from_json(json.load(f))


class _SchemaData:
    """Source.data stand-in for schema-only planning: the planner needs
    only ``.capacity`` (plan/planner.py Source lowering)."""

    def __init__(self, capacity: int):
        self.capacity = capacity


class SchemaContext:
    """Context-shaped shim for OFFLINE planning: enough of
    api.Context's surface (nparts/hosts/levels/config/fn_table) to
    build and plan a query DAG with no mesh, no data, and no jax
    device work — the golden-plan gate and the offline EXPLAIN CLI
    run on it.  Terminals (collect/count/...) are unavailable by
    construction (executor is None)."""

    def __init__(self, nparts: int = 8, config=None):
        from dryad_tpu.utils.config import JobConfig
        self.nparts = nparts
        self.hosts = 1
        self.levels: Tuple[str, ...] = ()
        self.cluster = None
        self.local_debug = False
        self.mesh = None
        self.executor = None
        self.fn_table: Dict[str, Any] = {}
        self.config = config or JobConfig()
        self._event_log = None
