"""SQL abstract syntax: small frozen dataclasses with query-text spans.

The front end's analogue of the reference's LINQ expression tree
(PAPER.md layer 1) — every node keeps the :class:`Span` of the token
that introduced it so the binder's DTA3xx findings point into the query
text.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from dryad_tpu.analysis.diagnostics import Span

__all__ = ["Lit", "Col", "Bin", "Un", "Agg", "SelectItem", "TableRef",
           "JoinClause", "OrderItem", "Select", "Expr", "AGG_FUNCS"]

# SQL aggregate -> group_by agg kind (api.Dataset.group_by)
AGG_FUNCS = {"SUM": "sum", "COUNT": "count", "MIN": "min", "MAX": "max",
             "AVG": "mean"}


@dataclasses.dataclass(frozen=True)
class Lit:
    value: object            # int | float | str
    typ: str                 # "int" | "float" | "str"
    span: Span


@dataclasses.dataclass(frozen=True)
class Col:
    table: Optional[str]     # alias qualifier, or None for bare names
    name: str
    span: Span


@dataclasses.dataclass(frozen=True)
class Bin:
    op: str                  # + - * / = != < <= > >= and or
    left: "Expr"
    right: "Expr"
    span: Span


@dataclasses.dataclass(frozen=True)
class Un:
    op: str                  # "not" | "neg"
    operand: "Expr"
    span: Span


@dataclasses.dataclass(frozen=True)
class Agg:
    func: str                # key of AGG_FUNCS
    arg: Optional["Expr"]    # None for COUNT(*)
    span: Span


Expr = object  # Lit | Col | Bin | Un | Agg


@dataclasses.dataclass(frozen=True)
class SelectItem:
    expr: Expr               # or the "*" marker (Col(None, "*"))
    alias: Optional[str]
    span: Span


@dataclasses.dataclass(frozen=True)
class TableRef:
    name: str
    alias: str               # defaults to the table name
    span: Span


@dataclasses.dataclass(frozen=True)
class JoinClause:
    table: TableRef
    how: str                 # inner | left | right | full
    on: Expr                 # conjunction of equality comparisons
    span: Span


@dataclasses.dataclass(frozen=True)
class OrderItem:
    name: str                # output-scope column name
    descending: bool
    span: Span


@dataclasses.dataclass(frozen=True)
class Select:
    items: List[SelectItem]
    distinct: bool
    table: TableRef
    joins: Tuple[JoinClause, ...]
    where: Optional[Expr]
    group_by: Tuple[Col, ...]
    having: Optional[Expr]
    order_by: Tuple[OrderItem, ...]
    limit: Optional[int]
    span: Span
    # standing query: refresh cadence in seconds (EMIT EVERY <n>
    # [SECONDS]); None for plain batch queries
    emit_every: Optional[float] = None
    emit_span: Optional[Span] = None
