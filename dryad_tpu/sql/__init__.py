"""SQL front end: declarative queries compiling to the plan DAG.

DryadLINQ's thesis is a language-integrated query layer over a general
DAG engine (PAPER.md layer 1; the reference's ``LinqToDryad/`` query
compiler).  This package is the second front end ROADMAP item 5 calls
for: a dependency-free SQL compiler — lexer -> recursive-descent parser
-> binder/catalog -> lowering — whose output is ordinary
:class:`api.Dataset` calls, so a query inherits pre-submit analysis,
``EXPLAIN [COST]``, adaptive rewrites, and multi-tenant service
admission with zero new engine code.

Entry points::

    from dryad_tpu import sql
    cat = sql.Catalog().register_store("lineitem", "file:///...")
    ds  = sql.query(ctx, cat, "SELECT k, SUM(v) AS s FROM t GROUP BY k")
    ds.collect()                      # ... or .explain(cost=True), etc.

    python -m dryad_tpu.sql --catalog cat.json          # REPL
    python -m dryad_tpu.sql --catalog cat.json \
        -e "EXPLAIN COST SELECT ..."                     # one-shot

Compile errors raise :class:`SqlError` — ONE exception carrying every
DTA3xx finding with line:column spans into the query text.  Every
successful lowering emits a ``sql_query`` event (normalized query text
+ catalog fingerprint) so history/forensics bundles identify SQL jobs.
"""

from __future__ import annotations

from typing import Tuple

from dryad_tpu.sql.binder import BoundSelect, bind
from dryad_tpu.sql.catalog import (Catalog, CatalogTable, SchemaContext,
                                   SchemaOnlyTableError)
from dryad_tpu.sql.errors import SqlError
from dryad_tpu.sql.lower import lower, source_tables
from dryad_tpu.sql.parser import parse, parse_statement

__all__ = [
    "Catalog", "CatalogTable", "SchemaContext", "SchemaOnlyTableError",
    "SqlError",
    "parse", "parse_statement", "bind", "lower", "source_tables",
    "normalize_query", "compile_query", "query", "explain",
    "offline_explain", "offline_plan_json",
]


def normalize_query(text: str) -> str:
    """Whitespace-collapsed query text: the identity used for the
    ``sql_query`` event and the service's plan-cache key (two spellings
    of one query hit the same cache entry)."""
    return " ".join(text.split())


def compile_query(catalog: Catalog, text: str,
                  origin: str = "<sql>") -> Tuple[str, BoundSelect]:
    """Parse + bind (no Context needed): returns (mode, BoundSelect)
    where mode reflects a leading ``EXPLAIN [COST]``.  Raises
    :class:`SqlError` with all DTA3xx findings."""
    mode, stmt = parse_statement(text, origin=origin)
    return mode, bind(catalog, stmt)


def query(ctx, catalog: Catalog, text: str, origin: str = "<sql>",
          event=None):
    """Compile ``text`` to a lazy :class:`api.Dataset` under ``ctx``.
    A leading EXPLAIN is rejected here (use :func:`explain`)."""
    ds, _handles = _lowered(ctx, catalog, text, origin=origin,
                            event=event)
    return ds


def _lowered(ctx, catalog: Catalog, text: str, origin: str = "<sql>",
             event=None):
    mode, bound = compile_query(catalog, text, origin=origin)
    if mode != "run":
        raise ValueError(
            "EXPLAIN statements build no dataset — use sql.explain()")
    ds, handles = lower(ctx, catalog, bound)
    _emit(ctx, event, text, catalog, bound)
    return ds, handles


def _emit(ctx, event, text: str, catalog: Catalog,
          bound: BoundSelect) -> None:
    sink = event if event is not None else getattr(ctx, "_event_log",
                                                   None)
    if sink is None:
        return
    sink({"event": "sql_query", "query": normalize_query(text),
          "catalog": catalog.fingerprint(),
          "tables": list(bound.tables)})
    sink({"event": "sql_lowered",
          "outputs": list(bound.outputs),
          "grouped": bound.grouped, "joins": len(bound.joins),
          "limit": bound.limit})


def explain(ctx, catalog: Catalog, text: str, origin: str = "<sql>",
            event=None) -> str:
    """EXPLAIN text for a query (with or without a leading EXPLAIN
    [COST | ANALYZE] keyword).  COST adds the DTA2xx predicted-cost
    table and the static diagnostics; ANALYZE **executes the query
    once** under an event capture and appends the measured per-stage
    actuals annotated against the cost model (obs/analyze.py — needs a
    real in-process Context with loadable tables, like running the
    query does)."""
    mode, bound = compile_query(catalog, text, origin=origin)
    ds, _ = lower(ctx, catalog, bound)
    _emit(ctx, event, text, catalog, bound)
    cost = mode == "explain_cost"
    out = ds.explain(verify=cost, cost=cost,
                     analyze=mode == "explain_analyze")
    if bound.emit_every is not None:
        # continuous queries: the static refresh verdict (DTA401/402 —
        # incremental merge vs full re-run) so a user knows BEFORE
        # registering whether each refresh pays O(delta) or O(store)
        from dryad_tpu.inc.delta_plan import plan_delta, render_verdict
        out += "\n" + render_verdict(catalog, bound,
                                     plan_delta(catalog, bound))
    return out


def offline_explain(catalog: Catalog, text: str, nparts: int = 8,
                    origin: str = "<sql>") -> str:
    """Textual EXPLAIN with NO mesh/devices/data (schema-only catalogs
    suffice) — the CLI's offline mode."""
    from dryad_tpu.plan.planner import plan_query
    _mode, bound = compile_query(catalog, text, origin=origin)
    ctx = SchemaContext(nparts=nparts)
    ds, _ = lower(ctx, catalog, bound)
    out = plan_query(ds.node, nparts, hosts=1,
                     config=ctx.config).explain()
    if bound.emit_every is not None:
        from dryad_tpu.inc.delta_plan import plan_delta, render_verdict
        out += "\n" + render_verdict(catalog, bound,
                                     plan_delta(catalog, bound))
    return out


def offline_plan_json(catalog: Catalog, text: str, nparts: int = 8,
                      origin: str = "<sql>") -> str:
    """Deterministic lowered-plan JSON with NO mesh/devices/data: the
    golden-plan drift gate (``python -m dryad_tpu.analysis
    --selfcheck``) and the offline CLI's EXPLAIN run on this.  Row-
    expression callables serialize as data (``__shipped__``), so the
    output round-trips through graph_from_json."""
    from dryad_tpu.plan.planner import plan_query
    from dryad_tpu.plan.serialize import graph_to_json
    mode, bound = compile_query(catalog, text, origin=origin)
    ctx = SchemaContext(nparts=nparts)
    ds, _ = lower(ctx, catalog, bound)
    graph = plan_query(ds.node, nparts, hosts=1, config=ctx.config)
    return graph_to_json(graph)
