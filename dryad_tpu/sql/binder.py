"""Binder: resolve a parsed SELECT against a Catalog, type-check every
expression, and produce the lowering-ready :class:`BoundSelect`.

All findings report AT ONCE through one DiagnosticReport (the
dryad_tpu/analysis contract — a query with three typos gets three
DTA3xx findings in one rejection, each with a line:column span into the
query text):

* DTA302 unknown table, DTA303 unknown column, DTA304 ambiguous
  column / duplicate alias / duplicate output name,
* DTA305 type mismatches (including aggregate-shape errors: a
  non-grouped column in an aggregated SELECT),
* DTA306 recognized-but-unsupported constructs.

Internally every column gets a unique physical name ``alias.col`` the
moment its table enters scope, so downstream joins can never collide
names and EXPLAIN output stays readable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from dryad_tpu.analysis.diagnostics import DiagnosticReport, Span

from dryad_tpu.sql import nodes as N
from dryad_tpu.sql.catalog import Catalog, sql_type_of
from dryad_tpu.sql.errors import SqlError

__all__ = ["BoundSelect", "BoundJoin", "bind"]

Prog = list  # rowexpr program node


@dataclasses.dataclass
class BoundJoin:
    table: str                       # catalog table name
    alias: str
    how: str                         # inner | left | right | full
    left_keys: List[str]             # physical names in the left scope
    right_keys: List[str]            # physical names in the new table
    renames: Dict[str, str]          # phys -> source column
    span: Optional[Span] = None


@dataclasses.dataclass
class BoundSelect:
    """Everything lower.py needs; all names physical."""

    base_table: str
    base_alias: str
    base_renames: Dict[str, str]          # phys -> source column
    joins: List[BoundJoin]
    where: Optional[Prog]
    # aggregation (empty group_keys + aggs means a GLOBAL aggregate)
    grouped: bool
    group_keys: List[str]                 # physical names
    pre_projection: Optional[Dict[str, Prog]]
    aggs: Dict[str, Tuple[str, Optional[str]]]
    having: Optional[Prog]
    # final projection over the current scope -> output names
    outputs: Dict[str, Prog]
    output_types: Dict[str, str]
    distinct: bool
    order_by: List[Tuple[str, bool]]
    limit: Optional[int]
    tables: List[str]                     # catalog names, FROM order
    # query-text provenance: lowering stamps these onto the plan nodes
    # it builds, so plan spans (and any runtime error quoting them)
    # point INTO THE QUERY, and offline plan JSON is deterministic
    span: Optional[Span] = None           # the SELECT keyword
    where_span: Optional[Span] = None
    having_span: Optional[Span] = None
    # standing query (EMIT EVERY <n>): refresh cadence in seconds; the
    # lowered batch plan is IDENTICAL — the cadence only drives the
    # service's standing-query scheduler and the inc/ refresh planner
    emit_every: Optional[float] = None
    emit_span: Optional[Span] = None


class _Scope:
    """Ordered (alias -> {col: (phys, type)}) with bare-name lookup."""

    def __init__(self):
        self.order: List[str] = []
        self.by_alias: Dict[str, Dict[str, Tuple[str, str]]] = {}

    def add_table(self, alias: str, cols: Dict[str, Tuple[str, str]]):
        self.order.append(alias)
        self.by_alias[alias] = dict(cols)

    def lookup(self, table: Optional[str], name: str):
        """(phys, type) | ("unknown-table"|"unknown"|"ambiguous", None)"""
        if table is not None:
            t = self.by_alias.get(table)
            if t is None:
                return ("unknown-table", None)
            hit = t.get(name)
            return hit if hit is not None else ("unknown", None)
        hits = [a for a in self.order if name in self.by_alias[a]]
        if not hits:
            return ("unknown", None)
        if len(hits) > 1:
            return ("ambiguous", hits)
        return self.by_alias[hits[0]][name]

    def all_columns(self):
        """[(alias, col, phys, type)] in FROM order."""
        out = []
        for a in self.order:
            for c, (phys, typ) in self.by_alias[a].items():
                out.append((a, c, phys, typ))
        return out


class _Binder:
    def __init__(self, catalog: Catalog, stmt: N.Select):
        self.catalog = catalog
        self.stmt = stmt
        self.report = DiagnosticReport()

    def diag(self, code: str, msg: str, span: Span) -> None:
        self.report.add(code, "error", msg, span=span, node="sql")

    def fail_if_dirty(self) -> None:
        if self.report.errors:
            raise SqlError(self.report)

    # -- FROM / JOIN -------------------------------------------------------

    def _table_scope(self, ref: N.TableRef, scope: _Scope,
                     seen_aliases: set) -> Optional[Dict[str, str]]:
        t = self.catalog.get(ref.name)
        if t is None:
            known = ", ".join(self.catalog.names()) or "none registered"
            self.diag("DTA302",
                      f"unknown table {ref.name!r} (catalog tables: "
                      f"{known})", ref.span)
            return None
        if ref.alias in seen_aliases:
            self.diag("DTA304",
                      f"duplicate table alias {ref.alias!r} makes "
                      f"column references ambiguous", ref.span)
            return None
        seen_aliases.add(ref.alias)
        renames: Dict[str, str] = {}
        cols: Dict[str, Tuple[str, str]] = {}
        for col, spec in t.schema.items():
            phys = f"{ref.alias}.{col}"
            renames[phys] = col
            cols[col] = (phys, sql_type_of(spec))
        scope.add_table(ref.alias, cols)
        return renames

    def _bind_on(self, on, left_aliases: set, right_alias: str,
                 scope: _Scope):
        """Decompose an ON conjunction into cross-side equi-key pairs."""
        lks: List[str] = []
        rks: List[str] = []

        def conjuncts(e):
            if isinstance(e, N.Bin) and e.op == "and":
                return conjuncts(e.left) + conjuncts(e.right)
            return [e]

        for c in conjuncts(on):
            if not (isinstance(c, N.Bin) and c.op == "="
                    and isinstance(c.left, N.Col)
                    and isinstance(c.right, N.Col)):
                self.diag("DTA306",
                          "JOIN ... ON supports conjunctions of "
                          "column equalities only (put residual "
                          "predicates in WHERE)",
                          getattr(c, "span", self.stmt.span))
                continue
            sides = []
            for col in (c.left, c.right):
                phys, typ = self._bind_col(col, scope)
                sides.append((col, phys, typ))
            if any(p is None for _, p, _ in sides):
                continue

            def side_of(phys: str) -> Optional[str]:
                alias = phys.split(".", 1)[0]
                if alias == right_alias:
                    return "right"
                if alias in left_aliases:
                    return "left"
                return None

            tags = [side_of(phys) for _, phys, _ in sides]
            if set(tags) != {"left", "right"}:
                self.diag("DTA306",
                          "each JOIN ... ON equality must compare a "
                          "column of the joined table with one of the "
                          "tables to its left", c.span)
                continue
            (l_i, r_i) = (0, 1) if tags[0] == "left" else (1, 0)
            lt, rt = sides[l_i][2], sides[r_i][2]
            if lt != rt and {lt, rt} != {"int", "float"}:
                self.diag("DTA305",
                          f"JOIN key type mismatch: {sides[l_i][1]} is "
                          f"{lt}, {sides[r_i][1]} is {rt}", c.span)
                continue
            lks.append(sides[l_i][1])
            rks.append(sides[r_i][1])
        return lks, rks

    # -- expressions -------------------------------------------------------

    def _bind_col(self, col: N.Col, scope: _Scope):
        hit = scope.lookup(col.table, col.name)
        if hit[0] == "unknown-table":
            self.diag("DTA302",
                      f"unknown table alias {col.table!r} in column "
                      f"reference {col.table}.{col.name}", col.span)
            return None, None
        if hit[0] == "unknown":
            cands = sorted({c for _, c, _, _ in scope.all_columns()})
            self.diag("DTA303",
                      f"unknown column "
                      f"{(col.table + '.') if col.table else ''}"
                      f"{col.name!r} (in scope: {', '.join(cands)})",
                      col.span)
            return None, None
        if hit[0] == "ambiguous":
            self.diag("DTA304",
                      f"ambiguous column {col.name!r} (in tables: "
                      f"{', '.join(hit[1])}) — qualify with an alias",
                      col.span)
            return None, None
        return hit

    def bind_expr(self, e, scope: _Scope,
                  want: Optional[str] = None) -> Tuple[Optional[Prog],
                                                       Optional[str]]:
        """(program, type); records diagnostics and returns (None, None)
        on any error in the subtree."""
        if isinstance(e, N.Agg):
            self.diag("DTA306",
                      "aggregates are only allowed at the top level of "
                      "SELECT items (with GROUP BY or as a global "
                      "aggregate) and in HAVING via their output name",
                      e.span)
            return None, None
        if isinstance(e, N.Lit):
            return ["lit", e.value, e.typ], e.typ
        if isinstance(e, N.Col):
            phys, typ = self._bind_col(e, scope)
            if phys is None:
                return None, None
            return ["col", phys], typ
        if isinstance(e, N.Un):
            prog, typ = self.bind_expr(e.operand, scope)
            if prog is None:
                return None, None
            if e.op == "not":
                if typ != "bool":
                    self.diag("DTA305",
                              f"NOT needs a boolean operand, got {typ}",
                              e.span)
                    return None, None
                return ["not", prog], "bool"
            if typ not in ("int", "float"):
                self.diag("DTA305",
                          f"unary minus needs a numeric operand, got "
                          f"{typ}", e.span)
                return None, None
            return ["neg", prog], typ
        if isinstance(e, N.Bin):
            lp, lt = self.bind_expr(e.left, scope)
            rp, rt = self.bind_expr(e.right, scope)
            if lp is None or rp is None:
                return None, None
            op = e.op
            if op in ("and", "or"):
                if lt != "bool" or rt != "bool":
                    self.diag("DTA305",
                              f"{op.upper()} needs boolean operands, "
                              f"got {lt} {op.upper()} {rt}", e.span)
                    return None, None
                return ["bin", op, lp, rp], "bool"
            if op in ("+", "-", "*", "/"):
                if lt not in ("int", "float") or rt not in ("int",
                                                            "float"):
                    self.diag("DTA305",
                              f"arithmetic {op!r} needs numeric "
                              f"operands, got {lt} {op} {rt}", e.span)
                    return None, None
                typ = ("float" if op == "/" or "float" in (lt, rt)
                       else "int")
                return ["bin", op, lp, rp], typ
            # comparisons
            numeric = {"int", "float"}
            if op in ("=", "!="):
                ok = (({lt, rt} <= numeric) or lt == rt)
            else:
                ok = {lt, rt} <= numeric
            if not ok:
                what = ("ordering comparisons need numeric operands"
                        if op not in ("=", "!=") else
                        "equality needs same-typed operands")
                self.diag("DTA305", f"{what}, got {lt} {op} {rt}",
                          e.span)
                return None, None
            return ["bin", op, lp, rp], "bool"
        raise AssertionError(f"unexpected AST node {e!r}")

    # -- the main walk -----------------------------------------------------

    def bind(self) -> BoundSelect:
        stmt = self.stmt
        scope = _Scope()
        seen: set = set()
        base_renames = self._table_scope(stmt.table, scope, seen)
        joins: List[BoundJoin] = []
        left_aliases = {stmt.table.alias}
        for jc in stmt.joins:
            renames = self._table_scope(jc.table, scope, seen)
            if renames is None:
                continue
            lks, rks = self._bind_on(jc.on, left_aliases,
                                     jc.table.alias, scope)
            if not lks and not self.report.errors:
                self.diag("DTA306",
                          "JOIN needs at least one equi-key in ON",
                          jc.span)
            left_aliases.add(jc.table.alias)
            joins.append(BoundJoin(jc.table.name, jc.table.alias,
                                   jc.how, lks, rks, renames,
                                   span=jc.span))
        # name resolution is hopeless without the FROM scope
        self.fail_if_dirty()

        where = None
        if stmt.where is not None:
            where, wt = self.bind_expr(stmt.where, scope)
            if where is not None and wt != "bool":
                self.diag("DTA305",
                          f"WHERE must be boolean, got {wt}",
                          getattr(stmt.where, "span", stmt.span))

        has_agg = any(isinstance(it.expr, N.Agg) for it in stmt.items)
        grouped = bool(stmt.group_by) or has_agg
        if stmt.having is not None and not grouped:
            self.diag("DTA306",
                      "HAVING needs GROUP BY (or an aggregated SELECT)",
                      stmt.span)

        outputs: Dict[str, Prog] = {}
        output_types: Dict[str, str] = {}

        def add_output(name: str, prog: Prog, typ: str,
                       span: Span) -> None:
            if name in outputs:
                self.diag("DTA304",
                          f"duplicate output column {name!r} — use AS "
                          f"to disambiguate", span)
                return
            outputs[name] = prog
            output_types[name] = typ

        group_keys: List[str] = []
        pre_projection: Optional[Dict[str, Prog]] = None
        aggs: Dict[str, Tuple[str, Optional[str]]] = {}
        having = None

        if grouped:
            if any(isinstance(it.expr, N.Col) and it.expr.name == "*"
                   for it in stmt.items):
                self.diag("DTA306",
                          "SELECT * is not supported with GROUP BY / "
                          "aggregates", stmt.span)
                self.fail_if_dirty()
            pre_projection = {}
            key_types: Dict[str, str] = {}
            for g in stmt.group_by:
                phys, typ = self._bind_col(g, scope)
                if phys is None:
                    continue
                group_keys.append(phys)
                key_types[phys] = typ
                pre_projection[phys] = ["col", phys]
            agg_i = 0
            for it in stmt.items:
                e = it.expr
                if isinstance(e, N.Col):
                    phys, typ = self._bind_col(e, scope)
                    if phys is None:
                        continue
                    if phys not in group_keys:
                        self.diag("DTA305",
                                  f"column {e.name!r} is neither "
                                  f"aggregated nor in GROUP BY", e.span)
                        continue
                    add_output(it.alias or e.name, ["col", phys], typ,
                               it.span)
                elif isinstance(e, N.Agg):
                    kind = N.AGG_FUNCS[e.func]
                    if e.arg is None:            # COUNT(*)
                        in_col, in_typ = None, "int"
                    else:
                        prog, in_typ = self.bind_expr(e.arg, scope)
                        if prog is None:
                            continue
                        if kind != "count" and in_typ not in ("int",
                                                              "float"):
                            self.diag(
                                "DTA305",
                                f"{e.func} needs a numeric argument, "
                                f"got {in_typ}", e.span)
                            continue
                        if kind == "count":
                            in_col = None  # COUNT(expr) == row count
                        else:
                            in_col = f"__sqlagg{agg_i}"
                            agg_i += 1
                            pre_projection[in_col] = prog
                    if it.alias:
                        name = it.alias
                    elif e.arg is not None and isinstance(e.arg, N.Col):
                        name = f"{e.func.lower()}_{e.arg.name}"
                    elif e.arg is None:
                        name = "count"
                    else:
                        name = f"{e.func.lower()}_{agg_i}"
                    out_typ = ("int" if kind == "count" else
                               "float" if kind == "mean" else in_typ)
                    if name in aggs or name in outputs:
                        self.diag("DTA304",
                                  f"duplicate output column {name!r} — "
                                  f"use AS to disambiguate", it.span)
                        continue
                    aggs[name] = (kind, in_col)
                    add_output(name, ["col", name], out_typ, it.span)
                else:
                    self.diag("DTA306",
                              "in a grouped SELECT each item must be a "
                              "group key or a single aggregate (no "
                              "expressions over aggregates)", it.span)
            if not aggs:
                self.diag("DTA306",
                          "GROUP BY needs at least one aggregate in "
                          "SELECT", stmt.span)
            # HAVING binds the POST-aggregation scope: group keys stay
            # under their own table aliases (so qualified refs work and
            # same-named keys from two tables are properly AMBIGUOUS,
            # not silently first-wins) plus the aggregate output names
            if stmt.having is not None and not self.report.errors:
                hscope = _Scope()
                per_alias: Dict[str, Dict[str, Tuple[str, str]]] = {}
                for phys in group_keys:
                    alias, col = phys.split(".", 1)
                    per_alias.setdefault(alias, {})[col] = \
                        (phys, key_types[phys])
                for alias, cols in per_alias.items():
                    hscope.add_table(alias, cols)
                hscope.add_table("__aggs", {
                    name: (name, output_types.get(name, "int"))
                    for name in aggs})
                having, ht = self.bind_expr(stmt.having, hscope)
                if having is not None and ht != "bool":
                    self.diag("DTA305",
                              f"HAVING must be boolean, got {ht}",
                              stmt.span)
        else:
            for it in stmt.items:
                e = it.expr
                if isinstance(e, N.Col) and e.name == "*":
                    all_cols = scope.all_columns()
                    bare_counts: Dict[str, int] = {}
                    for _, c, _, _ in all_cols:
                        bare_counts[c] = bare_counts.get(c, 0) + 1
                    for alias, c, phys, typ in all_cols:
                        name = c if bare_counts[c] == 1 else phys
                        add_output(name, ["col", phys], typ, it.span)
                    continue
                prog, typ = self.bind_expr(e, scope)
                if prog is None:
                    continue
                if it.alias:
                    name = it.alias
                elif isinstance(e, N.Col):
                    name = e.name
                else:
                    name = f"col{len(outputs)}"
                add_output(name, prog, typ, it.span)

        if stmt.emit_every is not None:
            # standing-query shape checks (DTA307): the interval must
            # be positive, and the base table must be able to GROW —
            # inline registrations are immutable host columns
            espan = stmt.emit_span or stmt.span
            if not stmt.emit_every > 0:
                self.diag("DTA307",
                          f"EMIT EVERY needs a positive interval, got "
                          f"{stmt.emit_every:g}", espan)
            base = self.catalog.get(stmt.table.name)
            if base is not None and base.kind == "inline":
                self.diag("DTA307",
                          f"EMIT EVERY over inline table "
                          f"{stmt.table.name!r}: inline registrations "
                          f"cannot grow — a standing query needs a "
                          f"store-backed base table", espan)

        order_by: List[Tuple[str, bool]] = []
        for o in stmt.order_by:
            if o.name not in outputs:
                self.diag("DTA303",
                          f"ORDER BY {o.name!r} is not an output "
                          f"column of this SELECT (order by a selected "
                          f"column or alias; outputs: "
                          f"{', '.join(outputs) or 'none'})", o.span)
                continue
            order_by.append((o.name, o.descending))

        self.fail_if_dirty()
        return BoundSelect(
            base_table=stmt.table.name, base_alias=stmt.table.alias,
            base_renames=base_renames or {}, joins=joins, where=where,
            grouped=grouped, group_keys=group_keys,
            pre_projection=pre_projection, aggs=aggs, having=having,
            outputs=outputs, output_types=output_types,
            distinct=stmt.distinct, order_by=order_by,
            limit=stmt.limit,
            tables=[stmt.table.name] + [j.table for j in joins],
            span=stmt.span,
            where_span=getattr(stmt.where, "span", None),
            having_span=getattr(stmt.having, "span", None),
            emit_every=stmt.emit_every, emit_span=stmt.emit_span)


def bind(catalog: Catalog, stmt: N.Select) -> BoundSelect:
    return _Binder(catalog, stmt).bind()
