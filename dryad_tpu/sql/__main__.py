"""SQL CLI — ``python -m dryad_tpu.sql --catalog cat.json [...]``.

* one-shot: ``-e "EXPLAIN [COST | ANALYZE] SELECT ..."`` or ``-f
  query.sql`` prints the plan (EXPLAIN; ANALYZE executes once and
  appends measured per-stage actuals vs the cost model) or executes
  and prints rows (plain SELECT, when the catalog's tables are
  loadable);
* REPL (default): reads ``;``-terminated statements; ``\\d`` lists
  catalog tables, ``\\q`` quits.

Offline contract: ``EXPLAIN`` works against SCHEMA-ONLY serialized
catalogs with no data and no devices (--nparts sizes the plan);
executing a SELECT needs store-backed or inline tables.  DTA3xx
compile errors print with their line:column spans and exit 2 (one-shot
mode); malformed invocations exit 3.
"""

from __future__ import annotations

import argparse
import sys

from dryad_tpu.sql import Catalog, SqlError, offline_plan_json

_PROMPT = "dryad-sql> "


def _print_table(table, limit: int = 50) -> None:
    cols = list(table)
    if not cols:
        print("(no columns)")
        return
    n = len(table[cols[0]]) if cols else 0
    print(" | ".join(cols))
    print("-+-".join("-" * len(c) for c in cols))
    for i in range(min(n, limit)):
        row = []
        for c in cols:
            v = table[c][i]
            if isinstance(v, bytes):
                v = v.decode("utf-8", "replace")
            elif hasattr(v, "item"):
                v = v.item()
            row.append(str(v))
        print(" | ".join(row))
    if n > limit:
        print(f"... ({n - limit} more rows)")
    print(f"({n} row{'s' if n != 1 else ''})")


class _Session:
    """Lazily builds the real Context only when a statement executes;
    EXPLAIN stays offline (SchemaContext) so schema-only catalogs
    work."""

    def __init__(self, catalog: Catalog, nparts: int):
        self.catalog = catalog
        self.nparts = nparts
        self._ctx = None

    def ctx(self):
        if self._ctx is None:
            from dryad_tpu.api.dataset import Context
            self._ctx = Context()
        return self._ctx

    def run(self, text: str) -> int:
        from dryad_tpu.plan.planner import plan_query
        from dryad_tpu.sql import (SchemaContext, compile_query, lower)
        mode, bound = compile_query(self.catalog, text)  # compile ONCE
        if mode == "explain":
            # plain EXPLAIN stays fully offline (schema-only catalogs,
            # zero devices)
            sctx = SchemaContext(nparts=self.nparts)
            ds, _ = lower(sctx, self.catalog, bound)
            print(plan_query(ds.node, self.nparts, hosts=1,
                             config=sctx.config).explain())
            return 0
        # cost needs real source statistics -> real Context; ANALYZE
        # additionally EXECUTES the query once and annotates the
        # executed stages with measured actuals (obs/analyze.py)
        ds, _ = lower(self.ctx(), self.catalog, bound)
        if mode == "explain_cost":
            print(ds.explain(verify=True, cost=True))
            return 0
        if mode == "explain_analyze":
            print(ds.explain(analyze=True))
            return 0
        _print_table(ds.collect())
        return 0


def _repl(sess: _Session) -> int:
    print(f"dryad_tpu sql — tables: "
          f"{', '.join(sess.catalog.names()) or '(empty catalog)'}; "
          f"\\d describes, \\q quits; terminate statements with ';'")
    buf = []
    while True:
        try:
            line = input(_PROMPT if not buf else "      ... ")
        except EOFError:
            print()
            return 0
        except KeyboardInterrupt:
            buf = []
            print()
            continue
        s = line.strip()
        if not buf and s in ("\\q", "exit", "quit"):
            return 0
        if not buf and s == "\\d":
            for name in sess.catalog.names():
                t = sess.catalog.get(name)
                cols = ", ".join(f"{c} {spec['kind']}"
                                 + (f"({spec['max_len']})"
                                    if spec["kind"] == "str" else
                                    f":{spec['dtype']}")
                                 for c, spec in t.schema.items())
                print(f"  {name} [{t.kind}, ~{t.rows} rows]: {cols}")
            continue
        buf.append(line)
        if not s.endswith(";"):
            continue
        text = "\n".join(buf)
        buf = []
        try:
            sess.run(text)
        except SqlError as e:
            print(e.report.render(), file=sys.stderr)
        except Exception as e:                     # keep the REPL alive
            print(f"error: {e}", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dryad_tpu.sql",
        description="SQL front end: REPL / one-shot EXPLAIN+execute "
                    "over a registered catalog")
    ap.add_argument("--catalog", required=True,
                    help="serialized catalog JSON (sql.Catalog.save)")
    ap.add_argument("-e", "--execute", default=None, metavar="QUERY",
                    help="one-shot statement (EXPLAIN [COST | ANALYZE]"
                         " ... or SELECT ...)")
    ap.add_argument("-f", "--file", default=None,
                    help="read the one-shot statement from a .sql file")
    ap.add_argument("--nparts", type=int, default=8,
                    help="partition count for offline EXPLAIN plans "
                         "(default 8)")
    ap.add_argument("--plan-json", action="store_true",
                    help="with -e/-f EXPLAIN: print the lowered plan "
                         "JSON instead of the textual plan")
    args = ap.parse_args(argv)
    try:
        catalog = Catalog.load(args.catalog)
    except (OSError, ValueError, KeyError) as e:
        print(f"dryad_tpu.sql: cannot load catalog "
              f"{args.catalog!r}: {e}", file=sys.stderr)
        return 3
    text = args.execute
    if args.file:
        try:
            with open(args.file) as f:
                text = f.read()
        except OSError as e:
            print(f"dryad_tpu.sql: {e}", file=sys.stderr)
            return 3
    sess = _Session(catalog, args.nparts)
    if text is None:
        return _repl(sess)
    try:
        if args.plan_json:
            print(offline_plan_json(catalog, text, nparts=args.nparts))
            return 0
        return sess.run(text)
    except SqlError as e:
        print(e.report.render(), file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"dryad_tpu.sql: {e}", file=sys.stderr)
        return 3


if __name__ == "__main__":
    sys.exit(main())
