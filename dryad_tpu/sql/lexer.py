"""SQL tokenizer with line:column provenance.

Every token carries its 1-based (line, col) into the ORIGINAL query
text, so parse/bind diagnostics (DTA3xx) point at the exact spot the
user typed — the SQL analogue of the Python UDF lint's file:line spans
(analysis/diagnostics.Span with ``col`` set).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from dryad_tpu.analysis.diagnostics import Span
from dryad_tpu.sql.errors import SqlError, sql_report

__all__ = ["Token", "tokenize", "KEYWORDS"]

KEYWORDS = frozenset({
    "SELECT", "DISTINCT", "FROM", "WHERE", "GROUP", "BY", "HAVING",
    "ORDER", "LIMIT", "AS", "AND", "OR", "NOT", "JOIN", "INNER", "LEFT",
    "RIGHT", "FULL", "OUTER", "CROSS", "NATURAL", "ON", "ASC", "DESC",
    "UNION", "INTERSECT", "EXCEPT", "OFFSET", "EXPLAIN", "COST", "NULL",
    "IN", "LIKE", "BETWEEN", "CASE", "IS", "EMIT", "EVERY", "SECONDS",
})

_PUNCT = ("<=", ">=", "!=", "<>", "=", "<", ">", "(", ")", ",", ".",
          "+", "-", "*", "/", ";")


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str         # "kw" | "ident" | "int" | "float" | "str" | "punct" | "eof"
    text: str         # keyword/punct text, identifier, or literal lexeme
    line: int
    col: int

    def span(self, origin: str = "<sql>") -> Span:
        return Span(origin, self.line, "", self.col)


def tokenize(query: str, origin: str = "<sql>") -> List[Token]:
    """Tokens + a trailing ``eof`` token.  Raises :class:`SqlError`
    (DTA301) on an unterminated string or an illegal character."""
    toks: List[Token] = []
    line, col = 1, 1
    i, n = 0, len(query)

    def err(msg: str, ln: int, cl: int) -> SqlError:
        return SqlError(sql_report(
            "DTA301", msg, Span(origin, ln, "", cl)))

    while i < n:
        c = query[i]
        if c == "\n":
            line += 1
            col = 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            col += 1
            continue
        if c == "-" and query[i + 1:i + 2] == "-":   # -- comment to EOL
            while i < n and query[i] != "\n":
                i += 1
            continue
        start_line, start_col = line, col
        if c == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise err("unterminated string literal", start_line,
                              start_col)
                if query[j] == "'":
                    if query[j + 1:j + 2] == "'":    # '' escapes a quote
                        buf.append("'")
                        j += 2
                        continue
                    break
                if query[j] == "\n":
                    raise err("unterminated string literal", start_line,
                              start_col)
                buf.append(query[j])
                j += 1
            toks.append(Token("str", "".join(buf), start_line, start_col))
            col += (j + 1 - i)
            i = j + 1
            continue
        if c.isdigit() or (c == "." and query[i + 1:i + 2].isdigit()):
            j = i
            seen_dot = False
            while j < n and (query[j].isdigit()
                             or (query[j] == "." and not seen_dot
                                 and query[j + 1:j + 2].isdigit())):
                seen_dot = seen_dot or query[j] == "."
                j += 1
            text = query[i:j]
            toks.append(Token("float" if "." in text else "int", text,
                              start_line, start_col))
            col += j - i
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (query[j].isalnum() or query[j] == "_"):
                j += 1
            text = query[i:j]
            up = text.upper()
            toks.append(Token("kw" if up in KEYWORDS else "ident",
                              up if up in KEYWORDS else text,
                              start_line, start_col))
            col += j - i
            i = j
            continue
        matched: Optional[str] = None
        for p in _PUNCT:
            if query.startswith(p, i):
                matched = p
                break
        if matched is None:
            raise err(f"illegal character {c!r}", start_line, start_col)
        # normalize the <> spelling so the parser sees one token text
        toks.append(Token("punct", "!=" if matched == "<>" else matched,
                          start_line, start_col))
        col += len(matched)
        i += len(matched)
    toks.append(Token("eof", "", line, col))
    return toks
