"""Lowering: BoundSelect -> api.Dataset operator chain.

The DryadLINQ layer-1 translation (LINQ expression tree -> query plan),
re-targeted: a bound SQL statement becomes the SAME ``Dataset`` calls a
Python user would write, so every query inherits the whole stack for
free — pre-submit lint + DTA2xx cost forecasts, ``EXPLAIN [COST]`` via
``Dataset.explain()``, adaptive stage-boundary rewrites, streamed
sources, and per-tenant admission when submitted through the service.

Shape of the lowered chain::

    FROM t [JOIN ...]      catalog.dataset() roots + rename Projector
                           (every column becomes ``alias.col``)
    WHERE                  .where(Predicate)
    GROUP BY + aggregates  pre-Projector (keys + agg-input exprs)
                           -> .group_by(keys, aggs) [-> .where(HAVING)]
    SELECT list            final Projector (output names)
    DISTINCT               .distinct()
    ORDER BY               .order_by([(name, desc)])
    LIMIT                  .take(n)

All callables are :mod:`dryad_tpu.sql.rowexpr` programs — shippable as
data (plan/serialize.ship_ref_of) and content-fingerprinted for the
executor's compile cache, so a resubmitted query is a warm hit.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from dryad_tpu.sql.binder import BoundSelect
from dryad_tpu.sql.catalog import Catalog
from dryad_tpu.sql.rowexpr import Predicate, Projector

__all__ = ["lower", "GLOBAL_AGG_KEY"]

GLOBAL_AGG_KEY = "__sqlagg_key"


def _rename_projector(renames: Dict[str, str]) -> Projector:
    return Projector({phys: ["col", src] for phys, src in
                      renames.items()})


def _stamp(ds, span):
    """Point the node's provenance INTO THE QUERY TEXT (file slot =
    query origin, func slot = ``sql:<col>``): analyzer findings and
    runtime errors for SQL-lowered nodes quote the query, and offline
    plan JSON is deterministic regardless of which Python frame drove
    the lowering."""
    if span is not None:
        object.__setattr__(ds.node, "span",
                           (span.file, span.line, f"sql:{span.col}"))
    return ds


def lower(ctx, catalog: Catalog, bound: BoundSelect, loader=None
          ) -> Tuple[Any, Dict[int, str]]:
    """(dataset, source-handle map) for a bound statement under ``ctx``
    (api.Context or sql.catalog.SchemaContext).  The handle map
    (``id(Source.data) -> table name``) lets the service re-bind plan
    source slots on a warm plan-cache hit.  ``loader`` (optional,
    ``name -> PData``) is forwarded to :meth:`Catalog.dataset` — the
    service's scan-share hook (one cold scan for concurrent jobs over
    the same table)."""
    handles: Dict[int, str] = {}

    def root(table: str, alias: str, renames: Dict[str, str], span):
        ds, data = catalog.dataset(ctx, table, loader=loader)
        handles[id(data)] = table
        _stamp(ds, span)
        return _stamp(ds.select(_rename_projector(renames),
                                label=f"sql-scan {alias}"), span)

    cur = root(bound.base_table, bound.base_alias, bound.base_renames,
               bound.span)
    for j in bound.joins:
        right = root(j.table, j.alias, j.renames, j.span)
        cur = _stamp(cur.join(right, j.left_keys, j.right_keys,
                              how=j.how), j.span)
    if bound.where is not None:
        cur = _stamp(cur.where(Predicate(bound.where),
                               label="sql-where"),
                     bound.where_span or bound.span)
    if bound.grouped:
        pre = dict(bound.pre_projection or {})
        keys = list(bound.group_keys)
        if not keys:
            # global aggregate: one constant key, dropped again by the
            # final projection (api.Dataset.aggregate pattern)
            pre[GLOBAL_AGG_KEY] = ["const", 0, "int"]
            keys = [GLOBAL_AGG_KEY]
        cur = _stamp(cur.select(Projector(pre), label="sql-agg-in"),
                     bound.span)
        cur = _stamp(cur.group_by(keys, dict(bound.aggs)), bound.span)
        if bound.having is not None:
            cur = _stamp(cur.where(Predicate(bound.having),
                                   label="sql-having"),
                         bound.having_span or bound.span)
    cur = _stamp(cur.select(Projector(bound.outputs),
                            label="sql-select"), bound.span)
    if bound.distinct:
        cur = _stamp(cur.distinct(), bound.span)
    if bound.order_by:
        cur = _stamp(cur.order_by(list(bound.order_by)), bound.span)
    if bound.limit is not None:
        cur = _stamp(cur.take(bound.limit), bound.span)
    # belt+braces: any node a Context helper built internally (e.g. a
    # streamed from_store chain) still carries a Python creation span —
    # restamp everything reachable so the whole SQL plan points at the
    # query
    from dryad_tpu.plan import expr as E
    for n in E.walk(cur.node):
        sp = getattr(n, "span", None)
        if sp is None or not str(sp[2] if sp else "").startswith("sql:"):
            object.__setattr__(n, "span",
                               (bound.span.file, bound.span.line,
                                f"sql:{bound.span.col}")
                               if bound.span is not None else None)
    return cur, handles


def source_tables(graph, handles: Dict[int, str]
                  ) -> Dict[str, Optional[str]]:
    """Map a planned StageGraph's source slots ("sid:leg", the
    runtime/shiplan spec key format) back to catalog table names via
    the handle identities recorded by :func:`lower`."""
    out: Dict[str, Optional[str]] = {}
    for st in graph.stages:
        for li, leg in enumerate(st.legs):
            if isinstance(leg.src, tuple) and leg.src[0] == "source":
                out[f"{st.id}:{li}"] = handles.get(id(leg.src[1]))
    return out
