from dryad_tpu.plan import expr  # noqa: F401
from dryad_tpu.plan.planner import plan_query  # noqa: F401
from dryad_tpu.plan.stages import StageGraph  # noqa: F401
