"""Logical query expression DAG.

The counterpart of the reference's plan node model
(LinqToDryad/DryadLinqQueryNode.cs:39 — `QueryNodeType` with 33 node kinds,
`DLinqQueryNode` carrying partition count/scheme/channel info).  A user's
``Dataset`` method chain builds this DAG lazily; the planner
(dryad_tpu/plan/planner.py) lowers it to physical stages.

Unlike the reference — whose nodes emit C# vertex code strings
(DryadLinqCodeGen.cs) — our nodes carry Python callables over columnar
Batches that will be traced and fused by XLA inside each stage's jit.

Partitioning metadata (`Partitioning`) mirrors the reference's partition-info
tracking used for shuffle elimination (DryadLinqQueryNode partition info /
`AssumeHashPartition`, DryadLinqQueryable.cs:3408).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import sys
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "Partitioning", "Node", "Source", "Placeholder", "Map", "Filter",
    "FlatTokens", "GroupByAgg", "GroupApply", "GroupTopK", "GroupRankSelect",
    "Join", "OrderBy", "Distinct", "Concat",
    "HashRepartition", "RangeRepartition", "Broadcast", "ApplyPerPartition",
    "Take", "SetOp", "WithCapacity", "CrossApply", "FlatMap", "Zip",
    "SlidingWindow", "WithRowIndex", "AssumePartitioning", "SkipTake",
    "walk",
]

_ids = itertools.count()

# creation-site provenance: every Node captures the first stack frame
# OUTSIDE the framework (dryad_tpu/* except apps/, which are user-shaped
# samples), so diagnostics (dryad_tpu/analysis) and runtime errors point
# at the user's query line — the reference keeps the LINQ expression's
# source info for exactly this (DryadLinqQueryGen error reporting)
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_APPS_DIR = os.path.join(_PKG_ROOT, "apps")


def _creation_span() -> Optional[Tuple[str, int, str]]:
    f = sys._getframe(1)
    depth = 0
    while f is not None and depth < 32:
        fn = f.f_code.co_filename
        internal = (fn.startswith("<")
                    or (fn.startswith(_PKG_ROOT)
                        and not fn.startswith(_APPS_DIR)))
        if not internal:
            return (fn, f.f_lineno, f.f_code.co_name)
        f = f.f_back
        depth += 1
    return None


@dataclasses.dataclass(frozen=True)
class Partitioning:
    """How a dataset's rows are distributed over partitions."""

    kind: str  # "none" | "hash" | "range" | "replicated" | "single"
    keys: Tuple[str, ...] = ()

    @staticmethod
    def none() -> "Partitioning":
        return Partitioning("none")


class Node:
    """Base logical node.  Subclasses are dataclasses with `parents`."""

    id: int
    parents: Tuple["Node", ...]
    # (file, line, function) of the user call that created the node —
    # not a dataclass field (set in __post_init__, excluded from eq/repr)
    span: Optional[Tuple[str, int, str]]

    def __post_init__(self):
        object.__setattr__(self, "id", next(_ids))
        object.__setattr__(self, "span", _creation_span())

    @property
    def npartitions(self) -> int:
        return self.parents[0].npartitions

    @property
    def partitioning(self) -> Partitioning:
        """Partitioning of the output; default: destroyed by the op unless
        the op is row-local (preserves parent partitioning)."""
        return self.parents[0].partitioning


def _node(cls):
    return dataclasses.dataclass(frozen=True, eq=False)(cls)


@_node
class Source(Node):
    """Materialized input: a PBatch handle (exec.data.PartitionedData) or a
    store reference resolved by the executor.  Reference: DLinqInputNode
    (DryadLinqQueryNode.cs:837)."""

    parents: Tuple[Node, ...]
    data: Any
    _npartitions: int
    _partitioning: Partitioning = Partitioning.none()
    host: Any = None  # host-side copy of the columns, for the oracle

    @property
    def npartitions(self) -> int:
        return self._npartitions

    @property
    def partitioning(self) -> Partitioning:
        return self._partitioning


@_node
class Placeholder(Node):
    """Loop-carried input for do_while bodies; bound at execution time."""

    parents: Tuple[Node, ...]
    name: str
    _npartitions: int
    capacity: int = 0
    _partitioning: Partitioning = Partitioning.none()

    @property
    def npartitions(self) -> int:
        return self._npartitions

    @property
    def partitioning(self) -> Partitioning:
        return self._partitioning


@_node
class Map(Node):
    """Columnwise projection/transform: fn(cols) -> cols.
    Reference: DLinqSelectNode (DryadLinqQueryNode.cs:1155)."""

    parents: Tuple[Node, ...]
    fn: Callable
    label: str = "map"


@_node
class Filter(Node):
    """fn(cols) -> bool mask.  Reference: Where."""

    parents: Tuple[Node, ...]
    fn: Callable
    label: str = "where"


@_node
class FlatTokens(Node):
    """Tokenizing SelectMany over a string column (the WordCount kernel)."""

    parents: Tuple[Node, ...]
    column: str
    out_capacity: int
    max_token_len: int = 24
    delims: bytes = b" \t\r\n.,;:!?\"'()[]{}<>"
    lower: bool = False
    # static per-row token bound (None = the ceil(L/2) worst case); the
    # tokenizer's slot grid is cap x bound, so a workload-tuned bound
    # shrinks its dominant sort; overflow feeds the NEED retry channel
    max_tokens_per_row: int | None = None

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning.none()


@_node
class ApplyPerPartition(Node):
    """Arbitrary per-partition Batch -> Batch function (escape hatch).
    Reference: ApplyPerPartition (DryadLinqQueryable.cs:1084)."""

    parents: Tuple[Node, ...]
    fn: Callable
    label: str = "apply"
    preserves_partitioning: bool = False
    with_index: bool = False  # fn(batch, partition_index) when True
    host_fn: Any = None  # oracle interpretation (fn over the whole table)

    @property
    def partitioning(self) -> Partitioning:
        if self.preserves_partitioning:
            return self.parents[0].partitioning
        return Partitioning.none()


@dataclasses.dataclass(frozen=True)
class Decomposable:
    """User-defined decomposable aggregate (IDecomposable.cs:34 parity:
    Initialize/Seed -> ``seed``, Accumulate/RecursiveAccumulate ->
    ``merge``, FinalReduce -> ``finalize``).

    * ``seed(columns) -> state``: map the row columns (arrays, vectorized
      over rows) to a state pytree;
    * ``merge(a, b) -> state``: ASSOCIATIVE combine of two states
      (elementwise over rows — it runs inside a segmented scan);
    * ``finalize(state) -> value | dict[str, value]``: per-group result
      (None = identity; a dict fans out to multiple columns).
    """

    seed: Any
    merge: Any
    finalize: Any = None


@_node
class GroupByAgg(Node):
    """GroupBy + decomposable aggregation.
    aggs: out_name -> (kind, value_col | None) builtin aggregate, or a
    ``Decomposable`` for user-defined seed/merge/finalize.
    Reference: DLinqGroupByNode (DryadLinqQueryNode.cs:1581) +
    IDecomposable (IDecomposable.cs:34)."""

    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]
    aggs: Dict[str, Any]

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.keys))


@_node
class GroupApply(Node):
    """GroupBy yielding group CONTENTS to an arbitrary per-group fn — the
    reference's general GroupBy result selector
    (DryadLinqVertex.cs:510-753, IGrouping to user code).
    fn(cols, count) -> (out_cols [out_rows, ...], mask [out_rows]); group
    keys are auto-attached to the output.  None capacities resolve to the
    input capacity at plan time."""

    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]
    fn: Callable
    group_capacity: int
    max_groups: Optional[int] = None
    out_rows: int = 1
    out_capacity: Optional[int] = None

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.keys))


@_node
class GroupTopK(Node):
    """Per-group top-k rows by a column (all columns kept)."""

    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]
    k: int
    by: str
    descending: bool = True

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.keys))


@_node
class GroupRankSelect(Node):
    """One row per group at a sorted rank of a column (median/min/max)."""

    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]
    by: str
    rank: str = "median"
    out: Optional[str] = None

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.keys))


@_node
class Join(Node):
    """Equi-join (inner, or left-outer with zero-filled right columns).
    Reference: DLinqJoinNode (DryadLinqQueryNode.cs:2053); how="left" is
    the GroupJoin empty-group case."""

    parents: Tuple[Node, ...]  # (left, right)
    left_keys: Tuple[str, ...]
    right_keys: Tuple[str, ...]
    expansion: float = 1.0  # out_capacity multiplier over left capacity
    broadcast_right: bool = False
    how: str = "inner"
    # caller hint: right keys are unique (a lookup/dimension table) —
    # enables the gather-free merge-fill join path, VERIFIED at runtime
    # (falls back to the general path when duplicates appear)
    right_unique: bool = False

    @property
    def npartitions(self) -> int:
        return self.parents[0].npartitions

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.left_keys))


@_node
class OrderBy(Node):
    """Global sort via sampling + range partition + local sort.
    Reference: DLinqOrderByNode; sampling DryadLinqSampler.cs:42."""

    parents: Tuple[Node, ...]
    keys: Tuple[Tuple[str, bool], ...]  # (column, descending)

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("range", tuple(k for k, _ in self.keys))


@_node
class Distinct(Node):
    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]  # empty = all columns

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.keys))


@_node
class SetOp(Node):
    """Union/Intersect/Except with set semantics (dedup), over all columns."""

    parents: Tuple[Node, ...]  # (left, right)
    op: str  # "union" | "intersect" | "except"

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", ())


@_node
class Concat(Node):
    parents: Tuple[Node, ...]  # (left, right)

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning.none()


@_node
class HashRepartition(Node):
    """Explicit HashPartition (DryadLinqQueryable.cs:275)."""

    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("hash", tuple(self.keys))


@_node
class RangeRepartition(Node):
    """Explicit RangePartition (DryadLinqQueryable.cs:518)."""

    parents: Tuple[Node, ...]
    keys: Tuple[str, ...]

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("range", tuple(self.keys))


@_node
class Broadcast(Node):
    """Replicate a (small) dataset to every partition.
    Reference: DrDynamicBroadcastManager (DrDynamicBroadcast.h:23)."""

    parents: Tuple[Node, ...]

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning("replicated")


@_node
class Take(Node):
    parents: Tuple[Node, ...]
    n: int


@_node
class FlatMap(Node):
    """Generic SelectMany: fn(cols) -> (out_cols each [cap, m, ...],
    mask [cap, m]); rows flattened in row-major order then compacted.
    Reference: SelectMany (DryadLinqQueryable.cs SelectMany overloads)."""

    parents: Tuple[Node, ...]
    fn: Callable
    out_capacity: int
    label: str = "flat_map"

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning.none()


@_node
class Zip(Node):
    """Pairwise combination by GLOBAL position (shorter-side semantics).
    Lowered to a realignment exchange: right rows move to the partition
    holding the same global row index on the left, so misaligned
    per-partition counts (e.g. after a filter) pair correctly
    (parallel/shuffle.zip_exchange).  Reference: DryadLinqQueryable Zip."""

    parents: Tuple[Node, ...]  # (left, right)
    suffix: str = "_r"

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning.none()


@_node
class SlidingWindow(Node):
    """Each row becomes the window of ``w`` consecutive rows starting at it
    (windows crossing the dataset end are dropped); columns gain a window
    axis.  Distributed via a halo exchange: every partition receives the
    first w-1 rows of the next partition over ICI (ppermute).
    Reference: SlidingWindow (DryadLinqQueryable.cs:1318)."""

    parents: Tuple[Node, ...]
    w: int


@_node
class WithRowIndex(Node):
    """Add a global row-index column (reference: the Long*/indexed operator
    variants, e.g. LongSelect with (elem, index) lambdas)."""

    parents: Tuple[Node, ...]
    column: str = "row_index"


@_node
class AssumePartitioning(Node):
    """Declare (without shuffling) that the data is already partitioned this
    way.  Reference: AssumeHashPartition / AssumeRangePartition
    (DryadLinqQueryable.cs:3408,3478)."""

    parents: Tuple[Node, ...]
    kind: str
    keys: Tuple[str, ...]

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning(self.kind, tuple(self.keys))


@_node
class SkipTake(Node):
    """Global skip / take_while / skip_while."""

    parents: Tuple[Node, ...]
    op: str  # "skip" | "take_while" | "skip_while"
    n: int = 0
    fn: Any = None


@_node
class WithCapacity(Node):
    """Coerce per-partition capacity (pad or truncate-with-overflow-check).
    Needed so do_while loop bodies keep shapes stable across iterations."""

    parents: Tuple[Node, ...]
    capacity: int


@_node
class CrossApply(Node):
    """Binary per-partition op: fn(left_batch, right_broadcast_batch) ->
    Batch.  The right side is replicated to every partition (small data).
    host_fn(table_l, table_r) -> table is the oracle's interpretation.
    Reference: the Apply overloads taking a second source
    (DryadLinqQueryable.cs:930-1045)."""

    parents: Tuple[Node, ...]  # (left, right)
    fn: Any
    host_fn: Any = None
    label: str = "cross_apply"

    @property
    def npartitions(self) -> int:
        return self.parents[0].npartitions

    @property
    def partitioning(self) -> Partitioning:
        return Partitioning.none()


def walk(root: Node):
    """Topological (parents-first) walk, each node once."""
    seen = set()
    order = []

    def visit(n: Node):
        if n.id in seen:
            return
        seen.add(n.id)
        for p in n.parents:
            visit(p)
        order.append(n)

    visit(root)
    return order
