"""Physical plan (de)serialization.

Parity with the reference's XML query plan contract: the client writes an
XML plan (DryadLinqQueryGen.cs GenerateDryadProgram :814) that the GM parses
back into its graph (DryadLinqGraphManager/QueryParser.cs:360, Query.cs).
Our plan is JSON; Python callables inside ops are serialized as opaque
references (a plan with UDFs round-trips structurally for inspection/
tooling; re-execution requires re-binding the callables via ``fn_table``,
the analogue of the reference's `assembly!class.method` vertex-entry names,
QueryParser.cs:100).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional

from dryad_tpu.plan.stages import Exchange, Leg, Stage, StageGraph, StageOp

__all__ = ["graph_to_json", "graph_from_json"]


def _op_to_json(op: StageOp, fn_names: Dict[int, str]) -> dict:
    params = {}
    for k, v in op.params.items():
        if not isinstance(v, (str, int, float, bool, type(None))) \
                and id(v) in fn_names:
            # explicitly registered shipping name (runtime/shiplan.py) —
            # covers non-callable opaque values (decomposable boxes) too
            params[k] = {"__fn__": fn_names[id(v)]}
        elif callable(v):
            params[k] = {"__fn__": fn_names.get(id(v), f"fn_{id(v):x}")}
        elif isinstance(v, bytes):
            params[k] = {"__bytes__": v.decode("latin1")}
        elif isinstance(v, tuple):
            params[k] = {"__tuple__": list(v)}
        elif isinstance(v, dict):
            try:
                enc = {kk: list(vv) if isinstance(vv, tuple) else vv
                       for kk, vv in v.items()}
                json.dumps(enc)
                params[k] = {"__dict__": enc}
            except TypeError:
                # opaque structured param (e.g. decomposable seed/merge/
                # finalize triples, treedef boxes): structurally noted only;
                # re-execution re-binds via fn_table like other UDFs
                params[k] = {"__opaque__": f"{op.kind}.{k}"}
        else:
            params[k] = v
    return {"kind": op.kind, "params": params}


def _op_from_json(d: dict, fn_table: Optional[Dict[str, Callable]]) -> StageOp:
    params: Dict[str, Any] = {}
    for k, v in d["params"].items():
        if isinstance(v, dict) and "__fn__" in v:
            name = v["__fn__"]
            if fn_table is None or name not in fn_table:
                raise KeyError(
                    f"plan references callable {name!r}; pass it in fn_table")
            params[k] = fn_table[name]
        elif isinstance(v, dict) and "__bytes__" in v:
            params[k] = v["__bytes__"].encode("latin1")
        elif isinstance(v, dict) and "__opaque__" in v:
            name = v["__opaque__"]
            if fn_table is None or name not in fn_table:
                raise KeyError(
                    f"plan references opaque param {name!r}; pass it in "
                    f"fn_table")
            params[k] = fn_table[name]
        elif isinstance(v, dict) and "__tuple__" in v:
            params[k] = tuple(tuple(x) if isinstance(x, list) else x
                              for x in v["__tuple__"])
        elif isinstance(v, dict) and "__dict__" in v:
            params[k] = {kk: tuple(vv) if isinstance(vv, list) else vv
                         for kk, vv in v["__dict__"].items()}
        else:
            params[k] = v
    return StageOp(d["kind"], params)


def graph_to_json(graph: StageGraph,
                  fn_names: Optional[Dict[int, str]] = None) -> str:
    fn_names = fn_names or {}
    stages = []
    for st in graph.stages:
        legs = []
        for leg in st.legs:
            if isinstance(leg.src, int):
                src: Any = {"stage": leg.src}
            elif leg.src[0] == "placeholder":
                src = {"placeholder": leg.src[1]}
            else:
                src = {"source": True}
            ex = None
            if leg.exchange is not None:
                e = leg.exchange
                ex = {"kind": e.kind, "keys": list(e.keys),
                      "out_capacity": e.out_capacity,
                      "descending": e.descending,
                      "bounds_from": e.bounds_from,
                      "bounds_key": e.bounds_key,
                      "axis": e.axis}
            legs.append({"src": src,
                         "ops": [_op_to_json(o, fn_names) for o in leg.ops],
                         "exchange": ex})
        stages.append({"id": st.id, "label": st.label, "legs": legs,
                       "body": [_op_to_json(o, fn_names) for o in st.body]})
    return json.dumps({"version": 1, "stages": stages,
                       "out_stage": graph.out_stage}, indent=1)


def graph_from_json(s: str, fn_table: Optional[Dict[str, Callable]] = None,
                    sources: Optional[Dict[int, Any]] = None) -> StageGraph:
    """Rebuild a StageGraph.  ``sources`` maps (stage_id, leg_index) source
    slots — keyed "sid:leg" — to bound data handles."""
    d = json.loads(s)
    stages = []
    for sd in d["stages"]:
        legs = []
        for li, ld in enumerate(sd["legs"]):
            src = ld["src"]
            if "stage" in src:
                lsrc: Any = src["stage"]
            elif "placeholder" in src:
                lsrc = ("placeholder", src["placeholder"])
            else:
                key = f"{sd['id']}:{li}"
                if sources is None or key not in sources:
                    raise KeyError(f"plan needs source binding for {key}")
                lsrc = ("source", sources[key])
            ex = None
            if ld["exchange"] is not None:
                e = ld["exchange"]
                ex = Exchange(e["kind"], tuple(e["keys"]), e["out_capacity"],
                              e["descending"], e["bounds_from"],
                              e["bounds_key"], axis=e.get("axis"))
            legs.append(Leg(lsrc, [_op_from_json(o, fn_table)
                                   for o in ld["ops"]], ex))
        stages.append(Stage(id=sd["id"], legs=legs,
                            body=[_op_from_json(o, fn_table)
                                  for o in sd["body"]],
                            label=sd["label"]))
    return StageGraph(stages, d["out_stage"])
