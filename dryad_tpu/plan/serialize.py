"""Physical plan (de)serialization.

Parity with the reference's XML query plan contract: the client writes an
XML plan (DryadLinqQueryGen.cs GenerateDryadProgram :814) that the GM parses
back into its graph (DryadLinqGraphManager/QueryParser.cs:360, Query.cs).
Our plan is JSON; Python callables inside ops are serialized as opaque
references (a plan with UDFs round-trips structurally for inspection/
tooling; re-execution requires re-binding the callables via ``fn_table``,
the analogue of the reference's `assembly!class.method` vertex-entry names,
QueryParser.cs:100).
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Callable, Dict, Optional

from dryad_tpu.plan.stages import Exchange, Leg, Stage, StageGraph, StageOp

__all__ = ["graph_to_json", "graph_from_json", "import_ref",
           "ship_ref_of"]


def import_ref(obj: Any) -> Optional[str]:
    """``module:qualname`` if re-importing it yields the SAME object
    (the reference's `assembly!class.method` vertex-entry contract,
    QueryParser.cs:100) — the one importability check shared by the
    shipper (runtime/shiplan.py) and the static analyzer
    (analysis/udf_lint.shippability_of)."""
    mod = getattr(obj, "__module__", None)
    qual = getattr(obj, "__qualname__", None)
    if not mod or not qual or "<" in qual:
        return None
    try:
        o: Any = importlib.import_module(mod)
        for part in qual.split("."):
            o = getattr(o, part)
    except (ImportError, AttributeError):
        return None
    return f"{mod}:{qual}" if o is obj else None


def ship_ref_of(v: Any) -> Optional[str]:
    """Shippable-VALUE protocol: an op-param object that serializes as
    DATA instead of by name.  A value qualifies when it implements
    ``__ship_payload__() -> jsonable`` plus the classmethod
    ``__from_payload__(payload)``, and its class is importable — it
    then crosses the wire as ``{"__shipped__": {cls, payload}}`` and
    rebuilds on the executing side with no fn_table registration.  The
    SQL front end's row-expression programs (dryad_tpu/sql/rowexpr.py)
    are the first users: a compiled query's Map/Filter callables are
    pure data, so SQL plans ship to workers exactly like structured
    ops.  Returns the class's import ref, or None when the protocol is
    absent/unusable."""
    if (not hasattr(v, "__ship_payload__")
            or not hasattr(type(v), "__from_payload__")):
        return None
    return import_ref(type(v))


# params carrying planner-internal mutable state shared between ops of one
# plan (decomposable treedef boxes): contents are rebuilt at trace time on
# the executing side, but IDENTITY must survive — partial and merge stages
# share one box instance
_EPHEMERAL_PARAMS = {"box"}


def _op_to_json(op: StageOp, fn_names: Dict[int, str],
                shared: Dict[int, int]) -> dict:
    def enc(v: Any, pname: str) -> Any:
        if isinstance(v, (str, int, float, bool, type(None))):
            return v
        if id(v) in fn_names:
            # explicitly registered shipping name (runtime/shiplan.py) —
            # covers non-callable values (user Decomposables) too
            return {"__fn__": fn_names[id(v)]}
        ref = ship_ref_of(v)
        if ref is not None:
            # shippable-value protocol: serialize as data, rebuild via
            # the class's __from_payload__ on the executing side
            return {"__shipped__": {"cls": ref,
                                    "payload": v.__ship_payload__()}}
        if callable(v):
            return {"__fn__": fn_names.get(id(v), f"fn_{id(v):x}")}
        if isinstance(v, bytes):
            return {"__bytes__": v.decode("latin1")}
        if pname in _EPHEMERAL_PARAMS and isinstance(v, dict):
            sid = shared.setdefault(id(v), len(shared))
            return {"__ephemeral__": sid}
        if isinstance(v, (tuple, list)):
            return {"__tuple__": [enc(x, pname) for x in v]}
        if isinstance(v, dict):
            try:
                json.dumps(v)
                return {"__dict__": dict(v)}
            except TypeError:
                return {"__dict__": {kk: enc(vv, pname)
                                     for kk, vv in v.items()}}
        # opaque leaf: structurally noted; re-execution re-binds via
        # fn_table like other UDFs
        return {"__opaque__": f"{op.kind}.{pname}"}

    d = {"kind": op.kind,
         "params": {k: enc(v, k) for k, v in op.params.items()}}
    if op.span is not None:
        d["span"] = list(op.span)
    return d


def _op_from_json(d: dict, fn_table: Optional[Dict[str, Callable]],
                  shared: Dict[int, dict]) -> StageOp:
    def dec(v: Any) -> Any:
        if isinstance(v, dict) and "__fn__" in v:
            name = v["__fn__"]
            if fn_table is None or name not in fn_table:
                raise KeyError(
                    f"plan references callable {name!r}; pass it in "
                    f"fn_table")
            return fn_table[name]
        if isinstance(v, dict) and "__bytes__" in v:
            return v["__bytes__"].encode("latin1")
        if isinstance(v, dict) and "__shipped__" in v:
            mod_name, qual = v["__shipped__"]["cls"].split(":", 1)
            cls: Any = importlib.import_module(mod_name)
            for part in qual.split("."):
                cls = getattr(cls, part)
            return cls.__from_payload__(v["__shipped__"]["payload"])
        if isinstance(v, dict) and "__ephemeral__" in v:
            return shared.setdefault(v["__ephemeral__"], {})
        if isinstance(v, dict) and "__opaque__" in v:
            name = v["__opaque__"]
            if fn_table is None or name not in fn_table:
                raise KeyError(
                    f"plan references opaque param {name!r}; pass it in "
                    f"fn_table")
            return fn_table[name]
        if isinstance(v, dict) and "__tuple__" in v:
            return tuple(dec(x) for x in v["__tuple__"])
        if isinstance(v, dict) and "__dict__" in v:
            return {kk: dec(vv) for kk, vv in v["__dict__"].items()}
        if isinstance(v, list):   # legacy tuple-in-dict encoding
            return tuple(dec(x) for x in v)
        return v

    span = tuple(d["span"]) if d.get("span") else None
    return StageOp(d["kind"], {k: dec(v) for k, v in d["params"].items()},
                   span=span)


def graph_to_json(graph: StageGraph,
                  fn_names: Optional[Dict[int, str]] = None) -> str:
    fn_names = fn_names or {}
    shared: Dict[int, int] = {}
    stages = []
    for st in graph.stages:
        legs = []
        for leg in st.legs:
            if isinstance(leg.src, int):
                src: Any = {"stage": leg.src}
            elif leg.src[0] == "placeholder":
                src = {"placeholder": leg.src[1]}
            else:
                src = {"source": True}
            ex = None
            if leg.exchange is not None:
                e = leg.exchange
                ex = {"kind": e.kind, "keys": list(e.keys),
                      "out_capacity": e.out_capacity,
                      "descending": e.descending,
                      "bounds_from": e.bounds_from,
                      "bounds_key": e.bounds_key,
                      "axis": e.axis}
            legs.append({"src": src,
                         "ops": [_op_to_json(o, fn_names, shared)
                                 for o in leg.ops],
                         "exchange": ex})
        sd = {"id": st.id, "label": st.label, "legs": legs,
              "salt_ok": st.salt_ok,
              "body": [_op_to_json(o, fn_names, shared)
                       for o in st.body]}
        # emitted only when set: plans without placement reliance stay
        # byte-identical to the pre-adaptive wire format
        if st.placement_relied:
            sd["placement_relied"] = True
        stages.append(sd)
    return json.dumps({"version": 1, "stages": stages,
                       "out_stage": graph.out_stage}, indent=1)


def graph_from_json(s: str, fn_table: Optional[Dict[str, Callable]] = None,
                    sources: Optional[Dict[int, Any]] = None) -> StageGraph:
    """Rebuild a StageGraph.  ``sources`` maps (stage_id, leg_index) source
    slots — keyed "sid:leg" — to bound data handles."""
    d = json.loads(s)
    shared: Dict[int, dict] = {}
    stages = []
    for sd in d["stages"]:
        legs = []
        for li, ld in enumerate(sd["legs"]):
            src = ld["src"]
            if "stage" in src:
                lsrc: Any = src["stage"]
            elif "placeholder" in src:
                lsrc = ("placeholder", src["placeholder"])
            else:
                key = f"{sd['id']}:{li}"
                if sources is None or key not in sources:
                    raise KeyError(f"plan needs source binding for {key}")
                lsrc = ("source", sources[key])
            ex = None
            if ld["exchange"] is not None:
                e = ld["exchange"]
                ex = Exchange(e["kind"], tuple(e["keys"]), e["out_capacity"],
                              e["descending"], e["bounds_from"],
                              e["bounds_key"], axis=e.get("axis"))
            legs.append(Leg(lsrc, [_op_from_json(o, fn_table, shared)
                                   for o in ld["ops"]], ex))
        stages.append(Stage(id=sd["id"], legs=legs,
                            body=[_op_from_json(o, fn_table, shared)
                                  for o in sd["body"]],
                            label=sd["label"],
                            salt_ok=sd.get("salt_ok", False),
                            placement_relied=sd.get("placement_relied",
                                                    False)))
    return StageGraph(stages, d["out_stage"])
