"""Logical -> physical lowering.

The counterpart of the reference's three query-gen phases
(DryadLinqQueryGen.cs: phase1 node creation :269, phase2 pipelining into
supernodes + Tee insertion :391-456, phase3 :459) plus GraphBuilder's dynamic
manager wiring (GraphBuilder.cs:620-729).  Our phases:

1. walk the expression DAG, counting consumers;
2. grow "fragments" (chains of local ops) along each edge — the supernode
   pipelining: everything row-local fuses into one stage program;
3. cut stages at exchange points (group-by, join, repartition, sort) and at
   fan-out (Tee: a multiply-consumed node is materialized once);
4. lower aggregations into partial + exchange + final (the IDecomposable /
   PARTIALAGGR pattern), sorts into sample -> range exchange -> local sort
   (the RANGEDISTRIBUTOR pattern), small-side joins into broadcast
   (BROADCAST pattern).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from dryad_tpu.plan import expr as E
from dryad_tpu.plan.stages import Exchange, Leg, Stage, StageGraph, StageOp

__all__ = ["Planner", "plan_query"]


@dataclasses.dataclass
class Fragment:
    src: Any  # int stage id | ("source", data) | ("placeholder", name)
    ops: List[StageOp]
    capacity: int
    partitioning: E.Partitioning


# Decomposition of aggregates into partial (pre-shuffle) and final
# (post-shuffle) parts — reference IDecomposable.cs:34
# (Initialize/Seed/Accumulate/RecursiveAccumulate/FinalReduce).
def _decompose_aggs(aggs: Dict[str, Tuple[str, Optional[str]]]):
    partial: Dict[str, Tuple[str, Optional[str]]] = {}
    final: Dict[str, Tuple[str, Optional[str]]] = {}
    mean_cols: List[str] = []
    for out, (kind, col) in aggs.items():
        if kind == "count":
            partial[out] = ("count", None)
            final[out] = ("sum", out)
        elif kind in ("sum", "min", "max", "any", "all"):
            partial[out] = (kind, col)
            merge_kind = "sum" if kind == "sum" else kind
            final[out] = (merge_kind, out)
        elif kind == "mean":
            partial[out + "__sum"] = ("sum", col)
            partial[out + "__cnt"] = ("count", None)
            final[out + "__sum"] = ("sum", out + "__sum")
            final[out + "__cnt"] = ("sum", out + "__cnt")
            mean_cols.append(out)
        else:
            raise ValueError(f"aggregate kind {kind!r} not decomposable")
    return partial, final, mean_cols


# Builtin aggregate kinds as Decomposable (seed, merge, finalize) triples —
# used when a group_by mixes builtin kinds with user-defined Decomposables
# so the whole aggregation runs through one segmented-scan path.
def _rowcount_of(cols) -> int:
    v = next(iter(cols.values()))
    return v.lengths.shape[0] if hasattr(v, "lengths") else v.shape[0]


def _builtin_as_decomposable(kind: str, col: Optional[str]):
    import jax.numpy as jnp

    if kind == "count":
        return E.Decomposable(
            lambda c: jnp.ones(_rowcount_of(c), jnp.int32),
            lambda a, b: a + b, None)
    if kind == "sum":
        return E.Decomposable(lambda c: c[col], lambda a, b: a + b, None)
    if kind == "min":
        return E.Decomposable(lambda c: c[col], jnp.minimum, None)
    if kind == "max":
        return E.Decomposable(lambda c: c[col], jnp.maximum, None)
    if kind == "any":
        return E.Decomposable(lambda c: c[col].astype(jnp.bool_),
                              lambda a, b: a | b, None)
    if kind == "all":
        return E.Decomposable(lambda c: c[col].astype(jnp.bool_),
                              lambda a, b: a & b, None)
    if kind == "mean":
        def fin(s):
            tot, cnt = s
            cf = jnp.maximum(cnt, 1)
            return tot / cf.astype(tot.dtype) \
                if jnp.issubdtype(tot.dtype, jnp.floating) \
                else tot.astype(jnp.float32) / cf
        return E.Decomposable(
            lambda c: (c[col],
                       jnp.ones(c[col].shape[0], jnp.int32)),
            lambda a, b: (a[0] + b[0], a[1] + b[1]), fin)
    raise ValueError(f"aggregate kind {kind!r} not decomposable")


def _normalize_decs(aggs: Dict[str, Any]) -> Dict[str, Any]:
    """aggs (builtin tuples and/or Decomposables) -> out -> SHIPPABLE dec
    spec: the user's Decomposable object itself (registrable by name for
    cluster shipping) or a ("__builtin__", kind, col) tag rebuilt on the
    executing side.  Kernels resolve specs to (seed, merge, finalize)
    triples at trace time (ops.kernels.resolve_dec_spec)."""
    out = {}
    for name, spec in aggs.items():
        if isinstance(spec, E.Decomposable):
            out[name] = spec
        else:
            kind, col = spec
            out[name] = ("__builtin__", kind, col)
    return out


def _has_user_decs(aggs: Dict[str, Any]) -> bool:
    return any(isinstance(v, E.Decomposable) for v in aggs.values())




class Planner:
    def __init__(self, npartitions: int, hosts: int = 1, config=None,
                 levels: tuple = ()):
        self.nparts = npartitions
        self.hosts = hosts  # >1 => multi-level mesh: hierarchical aggs
        # hierarchy axes INNERMOST FIRST ("dp", ["host",] "dcn") — one
        # combine stage per level (the reference's machine->pod->overall
        # aggregation trees, DrDynamicAggregateManager.h:99); 2-level
        # default keeps the classic ICI-then-DCN lowering
        self.levels = tuple(levels) or (("dp", "dcn") if hosts > 1
                                        else ())
        self.config = config
        self.stages: List[Stage] = []
        self.frags: Dict[int, Fragment] = {}
        self.consumers: Dict[int, int] = {}
        # stage ids whose OUTPUT PLACEMENT a later lowering relied on
        # (partition elimination): those stages must never be salted
        self.placement_dependent: set = set()

    def _rely_on_placement(self, f: Fragment) -> None:
        if isinstance(f.src, int):
            self.placement_dependent.add(f.src)

    # -- stage helpers -----------------------------------------------------

    def _new_stage(self, legs: List[Leg], body: List[StageOp],
                   label: str) -> Stage:
        st = Stage(id=len(self.stages), legs=legs, body=body, label=label)
        self.stages.append(st)
        return st

    def _materialize(self, frag: Fragment, label: str = "tee") -> Tuple[int, Fragment]:
        """Ensure the fragment is a stage output; return (stage_id, fresh frag)."""
        if isinstance(frag.src, int) and not frag.ops:
            return frag.src, frag
        st = self._new_stage([Leg(frag.src, frag.ops, None)], [], label)
        nf = Fragment(st.id, [], frag.capacity, frag.partitioning)
        return st.id, nf

    # -- main --------------------------------------------------------------

    def plan(self, root: E.Node) -> StageGraph:
        order = E.walk(root)
        for n in order:
            for p in n.parents:
                self.consumers[p.id] = self.consumers.get(p.id, 0) + 1
        for n in order:
            pre_stages = len(self.stages)
            frag = self._lower(n)
            if self.consumers.get(n.id, 0) > 1:
                _, frag = self._materialize(frag, label=f"tee:{type(n).__name__}")
            self.frags[n.id] = frag
            # provenance: ops created lowering THIS node (in the pending
            # fragment or in stages it cut) inherit its creation span;
            # ops carried over from earlier fragments keep their own
            span = getattr(n, "span", None)
            if span is not None:
                for op in frag.ops:
                    if op.span is None:
                        op.span = span
                for st in self.stages[pre_stages:]:
                    for leg in st.legs:
                        for op in leg.ops:
                            if op.span is None:
                                op.span = span
                    for op in st.body:
                        if op.span is None:
                            op.span = span
        out_id, _ = self._materialize(self.frags[root.id], label="output")
        # a placement claim flows backward through exchange-less legs
        # (Tee/materialize pass-throughs), so reliance must disable
        # salting on the whole ancestor chain that carries the claim —
        # conservative closure: it only forgoes an optimization
        dependent = set(self.placement_dependent)
        changed = True
        while changed:
            changed = False
            for st in self.stages:
                if st.id not in dependent:
                    continue
                for leg in st.legs:
                    if (leg.exchange is None and isinstance(leg.src, int)
                            and leg.src not in dependent):
                        dependent.add(leg.src)
                        changed = True
        for sid in dependent:
            self.stages[sid].salt_ok = False
            # the reliance itself is recorded for the adaptive rewriter:
            # rules that would change output placement must refuse here
            self.stages[sid].placement_relied = True
        return StageGraph(self.stages, out_id)

    def _lower_group_decomposable(self, n: "E.GroupByAgg", f: Fragment,
                                  keys: Tuple[str, ...]) -> Fragment:
        """GroupBy with user-defined Decomposable aggregates: seed+merge
        map-side combine -> hash exchange of flattened states -> merge (+
        FinalReduce).  The state treedefs travel through a shared box
        filled at partial-trace time (partial stages always trace before
        their merge stages).  Reference: IDecomposable.cs:34 feeding the
        GM's aggregation trees."""
        decs = _normalize_decs(n.aggs)
        box: Dict[str, Any] = {}  # shared mutable plan state (treedefs)
        if self.nparts == 1 or (f.partitioning.kind == "hash"
                                and f.partitioning.keys == keys):
            if self.nparts > 1:
                self._rely_on_placement(f)
            f.ops.append(StageOp("dgroup_local", {"keys": keys,
                                                  "decs": decs, "box": box}))
            f.partitioning = E.Partitioning("hash", keys)
            return f
        f.ops.append(StageOp("dgroup_partial", {"keys": keys, "decs": decs,
                                                "box": box}))
        if self.levels:
            src, ops = f.src, f.ops
            st = None
            for i, ax in enumerate(self.levels):
                last = i == len(self.levels) - 1
                ex = Exchange("hash", keys=keys, out_capacity=f.capacity,
                              axis=ax)
                st = self._new_stage(
                    [Leg(src, ops, ex)],
                    [StageOp("dgroup_merge",
                             {"keys": keys, "decs": decs, "box": box,
                              "finalize": last})],
                    f"dgroupby-{ax}")
                src, ops = st.id, []
            return Fragment(st.id, [], f.capacity,
                            E.Partitioning("hash", keys))
        ex = Exchange("hash", keys=keys, out_capacity=f.capacity)
        st = self._new_stage(
            [Leg(f.src, f.ops, ex)],
            [StageOp("dgroup_merge", {"keys": keys, "decs": decs,
                                      "box": box, "finalize": True})],
            "dgroupby")
        return Fragment(st.id, [], f.capacity, E.Partitioning("hash", keys))

    def _frag(self, n: E.Node) -> Fragment:
        f = self.frags[n.id]
        # fragments are single-use unless materialized; copy op list
        return Fragment(f.src, list(f.ops), f.capacity, f.partitioning)

    def _colocate_then(self, f: Fragment, keys: Tuple[str, ...],
                       op: StageOp, label: str,
                       out_capacity: Optional[int] = None) -> Fragment:
        """Hash-co-locate rows by ``keys`` then apply ``op`` — the shared
        lowering of the GroupBy-contents family (group_apply/top-k/rank).
        Partition elimination applies when the input already hashes on the
        same keys (AssumeHashPartition parity)."""
        cap = out_capacity or f.capacity
        if self.nparts == 1 or (f.partitioning.kind == "hash"
                                and f.partitioning.keys == keys and keys):
            if self.nparts > 1:
                self._rely_on_placement(f)
            f.ops.append(op)
            f.capacity = cap
            f.partitioning = E.Partitioning("hash", keys)
            return f
        ex = Exchange("hash", keys=keys, out_capacity=f.capacity)
        st = self._new_stage([Leg(f.src, f.ops, ex)], [op], label)
        return Fragment(st.id, [], cap, E.Partitioning("hash", keys))

    def _lower(self, n: E.Node) -> Fragment:
        if isinstance(n, E.Source):
            cap = getattr(n.data, "capacity", None)
            if cap is None:
                raise ValueError("Source.data must expose .capacity")
            return Fragment(("source", n.data), [], cap, n.partitioning)

        if isinstance(n, E.Placeholder):
            cap = getattr(n, "capacity", None) or 0
            return Fragment(("placeholder", n.name), [], cap, n.partitioning)

        if isinstance(n, E.Map):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("fn", {"fn": n.fn, "label": n.label}))
            return f

        if isinstance(n, E.Filter):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("filter", {"fn": n.fn, "label": n.label}))
            return f

        if isinstance(n, E.FlatTokens):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("flat_tokens", {
                "column": n.column, "out_capacity": n.out_capacity,
                "max_token_len": n.max_token_len, "delims": n.delims,
                "lower": n.lower,
                "max_tokens_per_row": n.max_tokens_per_row}))
            f.capacity = n.out_capacity
            f.partitioning = E.Partitioning.none()
            return f

        if isinstance(n, E.ApplyPerPartition):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("apply", {"fn": n.fn, "label": n.label,
                                           "with_index": n.with_index}))
            f.partitioning = n.partitioning
            return f

        if isinstance(n, E.FlatMap):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("flat_map", {
                "fn": n.fn, "out_capacity": n.out_capacity,
                "label": n.label}))
            f.capacity = n.out_capacity
            f.partitioning = E.Partitioning.none()
            return f

        if isinstance(n, E.Zip):
            lf = self._frag(n.parents[0])
            rf = self._frag(n.parents[1])
            st = self._new_stage(
                [Leg(lf.src, lf.ops, None), Leg(rf.src, rf.ops, None)],
                [StageOp("zip", {"suffix": n.suffix})], "zip")
            return Fragment(st.id, [], min(lf.capacity, rf.capacity),
                            E.Partitioning.none())

        if isinstance(n, E.SlidingWindow):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("sliding_window", {"w": n.w}))
            f.partitioning = E.Partitioning.none()
            return f

        if isinstance(n, E.WithRowIndex):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("row_index", {"column": n.column}))
            return f

        if isinstance(n, E.AssumePartitioning):
            f = self._frag(n.parents[0])
            f.partitioning = E.Partitioning(n.kind, tuple(n.keys))
            return f

        if isinstance(n, E.SkipTake):
            f = self._frag(n.parents[0])
            if n.op == "skip":
                f.ops.append(StageOp("skip", {"n": n.n}))
            else:
                f.ops.append(StageOp(n.op, {"fn": n.fn}))
            return f

        if isinstance(n, E.Take):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("take", {"n": n.n, "global": True}))
            return f

        if isinstance(n, E.WithCapacity):
            f = self._frag(n.parents[0])
            f.ops.append(StageOp("recap", {"capacity": n.capacity}))
            f.capacity = n.capacity
            return f

        if isinstance(n, E.CrossApply):
            lf = self._frag(n.parents[0])
            rf = self._frag(n.parents[1])
            rex = None if self.nparts == 1 else Exchange(
                "broadcast", out_capacity=rf.capacity * self.nparts)
            st = self._new_stage(
                [Leg(lf.src, lf.ops, None), Leg(rf.src, rf.ops, rex)],
                [StageOp("apply2", {"fn": n.fn, "label": n.label})],
                "cross_apply")
            return Fragment(st.id, [], lf.capacity, E.Partitioning.none())

        if isinstance(n, E.GroupByAgg):
            f = self._frag(n.parents[0])
            keys = tuple(n.keys)
            if _has_user_decs(n.aggs):
                return self._lower_group_decomposable(n, f, keys)
            if self.nparts == 1:
                # single partition: everything is trivially co-located; the
                # partial/exchange/merge pipeline would be 3 extra full-batch
                # sorts for nothing
                f.ops.append(StageOp("group", {"keys": keys,
                                               "aggs": dict(n.aggs)}))
                f.partitioning = E.Partitioning("hash", keys)
                return f
            if f.partitioning.kind == "hash" and f.partitioning.keys == keys:
                # partition elimination: already co-located by these keys
                self._rely_on_placement(f)
                f.ops.append(StageOp("group", {"keys": keys, "aggs": dict(n.aggs)}))
                return f
            partial, final, mean_cols = _decompose_aggs(n.aggs)
            f.ops.append(StageOp("group", {"keys": keys, "aggs": partial}))
            if self.levels:
                # hierarchical aggregation over mesh axes (the reference's
                # machine->pod->overall trees,
                # DrDynamicAggregateManager.h:99): combine innermost
                # first, so each scarcer fabric carries one partial per
                # (level, key) instead of one per (device, key); depth
                # follows the mesh rank (3-level: dp -> host -> dcn)
                src, ops = f.src, f.ops
                st = None
                for i, ax in enumerate(self.levels):
                    last = i == len(self.levels) - 1
                    ex = Exchange("hash", keys=keys,
                                  out_capacity=f.capacity, axis=ax)
                    body: List[StageOp] = [
                        StageOp("group", {"keys": keys, "aggs": final})]
                    if last and mean_cols:
                        body.append(StageOp("mean_fin",
                                            {"cols": mean_cols}))
                    st = self._new_stage([Leg(src, ops, ex)], body,
                                         f"groupby-{ax}")
                    src, ops = st.id, []
                return Fragment(st.id, [], f.capacity,
                                E.Partitioning("hash", keys))
            ex = Exchange("hash", keys=keys, out_capacity=f.capacity)
            body = [StageOp("group", {"keys": keys, "aggs": final})]
            if mean_cols:
                body.append(StageOp("mean_fin", {"cols": mean_cols}))
            st = self._new_stage([Leg(f.src, f.ops, ex)], body, "groupby")
            return Fragment(st.id, [], f.capacity,
                            E.Partitioning("hash", keys))

        if isinstance(n, E.GroupApply):
            f = self._frag(n.parents[0])
            keys = tuple(n.keys)
            mg = n.max_groups or f.capacity
            oc = n.out_capacity or f.capacity
            op = StageOp("group_apply", {
                "keys": keys, "fn": n.fn, "max_groups": mg,
                "group_capacity": n.group_capacity,
                "out_rows": n.out_rows, "out_capacity": oc})
            return self._colocate_then(f, keys, op, "group_apply",
                                       out_capacity=oc)

        if isinstance(n, E.GroupTopK):
            f = self._frag(n.parents[0])
            op = StageOp("group_top_k", {
                "keys": tuple(n.keys), "k": n.k, "by": n.by,
                "descending": n.descending})
            return self._colocate_then(f, tuple(n.keys), op, "group_top_k")

        if isinstance(n, E.GroupRankSelect):
            f = self._frag(n.parents[0])
            op = StageOp("group_rank", {
                "keys": tuple(n.keys), "by": n.by, "rank": n.rank,
                "out": n.out})
            return self._colocate_then(f, tuple(n.keys), op, "group_rank")

        if isinstance(n, E.Distinct):
            f = self._frag(n.parents[0])
            keys = tuple(n.keys)
            if self.nparts == 1:
                f.ops.append(StageOp("distinct", {"keys": keys}))
                return f
            if f.partitioning.kind == "hash" and f.partitioning.keys == keys \
                    and keys:
                self._rely_on_placement(f)
                f.ops.append(StageOp("distinct", {"keys": keys}))
                return f
            f.ops.append(StageOp("distinct", {"keys": keys}))
            ex = Exchange("hash", keys=keys, out_capacity=f.capacity)
            st = self._new_stage(
                [Leg(f.src, f.ops, ex)],
                [StageOp("distinct", {"keys": keys})], "distinct")
            return Fragment(st.id, [], f.capacity, E.Partitioning("hash", keys))

        if isinstance(n, E.Join):
            lf = self._frag(n.parents[0])
            rf = self._frag(n.parents[1])
            lkeys, rkeys = tuple(n.left_keys), tuple(n.right_keys)
            out_cap = max(1, int(lf.capacity * n.expansion))
            # auto-broadcast a small build side (JobConfig
            # .broadcast_join_threshold; the reference's small-side
            # broadcast-join rewrite, DrDynamicBroadcastManager role)
            bthresh = getattr(self.config, "broadcast_join_threshold", 0.0) \
                if self.config else 0.0
            broadcast_right = n.broadcast_right or (
                bthresh > 0
                and rf.capacity * self.nparts <= bthresh * lf.capacity)
            if n.how in ("right", "full"):
                # a replicated right side would emit its unmatched rows once
                # PER PARTITION — right/full joins must co-locate by key
                broadcast_right = False
            if self.nparts == 1:
                lex = rex = None
            elif broadcast_right:
                rex = Exchange("broadcast",
                               out_capacity=rf.capacity * self.nparts)
                lex = None
            else:
                lex = None if (lf.partitioning.kind == "hash"
                               and lf.partitioning.keys == lkeys) else \
                    Exchange("hash", keys=lkeys, out_capacity=lf.capacity)
                rex = None if (rf.partitioning.kind == "hash"
                               and rf.partitioning.keys == rkeys) else \
                    Exchange("hash", keys=rkeys, out_capacity=rf.capacity)
                if lex is None:
                    self._rely_on_placement(lf)
                if rex is None:
                    self._rely_on_placement(rf)
            st = self._new_stage(
                [Leg(lf.src, lf.ops, lex), Leg(rf.src, rf.ops, rex)],
                [StageOp("join", {"left_keys": lkeys, "right_keys": rkeys,
                                  "out_capacity": out_cap,
                                  "how": n.how,
                                  "right_unique": n.right_unique})],
                "join")
            # the executor may salt this stage's exchanges on hot-key skew
            # — only the 2-hash-exchange inner/left shape, and plan() later
            # clears it where downstream elimination assumed the placement
            st.salt_ok = (lex is not None and rex is not None
                          and n.how in ("inner", "left")
                          and not broadcast_right)
            # broadcast join keeps the LEFT side's distribution (each
            # partition holds matches for its own left rows only)
            out_part = lf.partitioning if broadcast_right \
                else E.Partitioning("hash", lkeys)
            return Fragment(st.id, [], out_cap, out_part)

        if isinstance(n, E.OrderBy):
            f = self._frag(n.parents[0])
            sort_keys = tuple(k for k, _ in n.keys)
            all_asc = all(not d for _, d in n.keys)
            if self.nparts == 1:
                f.ops.append(StageOp("sort", {"keys": tuple(n.keys)}))
                f.partitioning = (E.Partitioning("range", sort_keys)
                                  if all_asc else E.Partitioning.none())
                return f
            pkeys = f.partitioning.keys
            if (f.partitioning.kind == "range" and all_asc
                    and len(sort_keys) <= len(pkeys)
                    and sort_keys == pkeys[:len(sort_keys)]):
                self._rely_on_placement(f)
                # Exchange elimination (AssumeOrderBy,
                # DryadLinqQueryable.cs:3639): sound only when the requested
                # ascending sort keys are a PREFIX of the claimed range keys.
                # "range(keys)" guarantees globally-sorted-by-keys data in
                # partition order but NOT that key ties are co-located
                # (assume_order_by data may split a tie run across
                # partitions), so a sort introducing any key beyond the
                # claim — or any descending direction — must keep its
                # exchange.  A stable local prefix sort of
                # already-(claim-)sorted partitions preserves the FULL
                # claim, so the original partitioning survives.
                f.ops.append(StageOp("sort", {"keys": tuple(n.keys)}))
                return f
            src_id, f = self._materialize(f, label="sort-input")
            primary, desc = n.keys[0]
            ex = Exchange("range", keys=(primary,), out_capacity=f.capacity,
                          descending=desc, bounds_from=src_id,
                          bounds_key=primary)
            st = self._new_stage(
                [Leg(src_id, [], ex)],
                [StageOp("sort", {"keys": tuple(n.keys)})], "orderby")
            # the exchange ranges on the primary only, but it routes equal
            # primary lanes to ONE destination (ties co-located), and the
            # local sort orders each partition by the full key list — the
            # output is globally sorted by all sort keys when ascending
            return Fragment(st.id, [], f.capacity,
                            E.Partitioning("range", sort_keys)
                            if all_asc else E.Partitioning.none())

        if isinstance(n, E.SetOp):
            lf = self._frag(n.parents[0])
            rf = self._frag(n.parents[1])
            lf.ops.append(StageOp("distinct", {"keys": ()}))
            if n.op != "union":
                rf.ops.append(StageOp("distinct", {"keys": ()}))
            lex = rex = None
            if self.nparts > 1:
                lex = Exchange("hash", keys=(), out_capacity=lf.capacity)
                rex = Exchange("hash", keys=(), out_capacity=rf.capacity)
            # the per-leg distinct dedups within a partition; after the
            # exchange, copies arriving from different partitions are
            # co-located, so a post-exchange distinct finishes the dedup
            if n.op == "union":
                body = [StageOp("concat", {}), StageOp("distinct", {"keys": ()})]
                cap = lf.capacity + rf.capacity
            elif n.op == "intersect":
                body = [StageOp("semi_anti", {"anti": False}),
                        StageOp("distinct", {"keys": ()})]
                cap = lf.capacity
            elif n.op == "except":
                body = [StageOp("semi_anti", {"anti": True}),
                        StageOp("distinct", {"keys": ()})]
                cap = lf.capacity
            else:
                raise ValueError(n.op)
            st = self._new_stage(
                [Leg(lf.src, lf.ops, lex), Leg(rf.src, rf.ops, rex)],
                body, n.op)
            return Fragment(st.id, [], cap, E.Partitioning("hash", ()))

        if isinstance(n, E.Concat):
            lf = self._frag(n.parents[0])
            rf = self._frag(n.parents[1])
            st = self._new_stage(
                [Leg(lf.src, lf.ops, None), Leg(rf.src, rf.ops, None)],
                [StageOp("concat", {})], "concat")
            return Fragment(st.id, [], lf.capacity + rf.capacity,
                            E.Partitioning.none())

        if isinstance(n, E.HashRepartition):
            f = self._frag(n.parents[0])
            if self.nparts == 1:
                f.partitioning = E.Partitioning("hash", tuple(n.keys))
                return f
            ex = Exchange("hash", keys=tuple(n.keys), out_capacity=f.capacity)
            st = self._new_stage([Leg(f.src, f.ops, ex)], [], "hashpartition")
            return Fragment(st.id, [], f.capacity,
                            E.Partitioning("hash", tuple(n.keys)))

        if isinstance(n, E.RangeRepartition):
            f = self._frag(n.parents[0])
            if self.nparts == 1:
                f.partitioning = E.Partitioning("range", tuple(n.keys))
                return f
            src_id, f = self._materialize(f, label="range-input")
            key = n.keys[0]
            ex = Exchange("range", keys=(key,), out_capacity=f.capacity,
                          bounds_from=src_id, bounds_key=key)
            st = self._new_stage([Leg(src_id, [], ex)], [], "rangepartition")
            return Fragment(st.id, [], f.capacity,
                            E.Partitioning("range", tuple(n.keys)))

        if isinstance(n, E.Broadcast):
            f = self._frag(n.parents[0])
            if self.nparts == 1:
                f.partitioning = E.Partitioning("replicated")
                return f
            ex = Exchange("broadcast",
                          out_capacity=f.capacity * self.nparts)
            st = self._new_stage([Leg(f.src, f.ops, ex)], [], "broadcast")
            return Fragment(st.id, [], f.capacity * self.nparts,
                            E.Partitioning("replicated"))

        raise TypeError(f"planner: unhandled node {type(n).__name__}")


def plan_query(root: E.Node, npartitions: int, hosts: int = 1,
               config=None, levels: tuple = ()) -> StageGraph:
    return Planner(npartitions, hosts=hosts, config=config,
                   levels=levels).plan(root)
