"""Physical plan: a DAG of stages.

The counterpart of the reference's XML query plan + GM graph
(DryadLinqGraphManager/Query.cs — vertices with channel types and dynamic
managers; GraphBuilder.cs:564 building DrGraph stages).  Differences, by
design:

* A stage here is ONE jit+shard_map program executed SPMD over the partition
  mesh — local ops, an optional collective exchange, and post-exchange merge
  ops are fused into the same XLA program (the reference needs separate
  vertex processes + a materialized channel for each hop).
* Channel types (DISKFILE/TCPPIPE/MEMORYFIFO, Query.cs:64) collapse to:
  in-program XLA values (fusion), device-resident materialized arrays at
  stage boundaries (for fan-out/replay), and collective exchanges.
* Dynamic managers (SPLITTER/PARTIALAGGR/.../BROADCAST, Query.cs:34-43)
  become planner lowerings: partial+final aggregation around a hash
  exchange, broadcast via all_gather, range distribution via sampled bounds.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["StageOp", "Exchange", "Leg", "Stage", "StageGraph"]

_stage_tokens = itertools.count()


@dataclasses.dataclass
class StageOp:
    """One fused local operator.  kind in:
    fn(map) | filter | flat_tokens | group | sort | distinct | join |
    semi_anti | concat | take | apply
    params are kind-specific (see exec.executor._apply_op)."""

    kind: str
    params: Dict[str, Any]
    # user-source provenance of the logical node this op lowers
    # ((file, line, func), plan/expr._creation_span): diagnostics and
    # runtime errors cite the query line.  NOT part of fingerprint().
    span: Optional[Tuple[str, int, str]] = None


@dataclasses.dataclass
class Exchange:
    """Collective repartition at a leg boundary.

    kind: hash | range | broadcast.  out_capacity resolved by the planner
    and scaled up by the executor on overflow (dynamic-repartition parity
    with DrDynamicDistributionManager)."""

    kind: str
    keys: Tuple[str, ...] = ()
    out_capacity: int = 0
    descending: bool = False
    bounds_from: Optional[int] = None  # stage id whose output seeds range bounds
    bounds_key: Optional[str] = None
    # None = global exchange over all mesh axes; "dp"/"dcn" = only that axis
    # (hierarchical aggregation hops, DrDynamicAggregateManager.h:99 parity)
    axis: Optional[str] = None


@dataclasses.dataclass
class Leg:
    """One input arm of a stage: source stage (or bound source data), local
    ops applied before the exchange, optional exchange."""

    src: Any  # int stage id | ("source", data) | ("placeholder", name)
    ops: List[StageOp] = dataclasses.field(default_factory=list)
    exchange: Optional[Exchange] = None


@dataclasses.dataclass
class Stage:
    id: int
    legs: List[Leg]
    body: List[StageOp] = dataclasses.field(default_factory=list)
    label: str = ""
    token: int = dataclasses.field(default_factory=lambda: next(_stage_tokens))
    _capacity_scale: int = 1
    # send-slot slack factor for exchanges (C = ceil(slack*cap/D)); raised
    # by the executor from measured skew (dynamic-distribution feedback);
    # None = use JobConfig.initial_send_slack
    _send_slack: Optional[int] = None
    # True when the executor MAY rewrite this stage's exchanges into the
    # hot-key-salted form on skew overflow: a 2-leg hash-exchange join
    # whose output placement NO downstream stage assumed (the planner
    # clears it wherever partition elimination relied on the claim).
    # Reference: DrDynamicDistributor.h:79 dynamic redistribution.
    salt_ok: bool = False
    # True when a LATER lowering elided an exchange by trusting this
    # stage's output placement (the planner's placement_dependent
    # closure).  Adaptive rewrites that would change the output
    # placement (broadcast demotion, adapt/rules.BroadcastManager) must
    # refuse on these stages — the downstream elision would silently
    # mis-group.  salt_ok=False alone cannot encode this: broadcast
    # joins are born salt_ok=False without any reliance.
    placement_relied: bool = False
    _salted: bool = False   # executor runtime state (sticky per stage)

    def fingerprint(self) -> str:
        """Structural identity for the executor's compile cache.  Two stages
        with equal fingerprints and equal input shapes compute the same
        function, so a re-planned identical query (e.g. the same Dataset
        collected twice, or a do_while body) reuses compiled programs.
        Callables are identified by object id — fresh lambdas won't hit the
        cache, which is correct (their behavior is unknowable) just not
        optimal."""

        def val_fp(v) -> str:
            # shippable VALUES (plan/serialize.ship_ref_of — e.g. the
            # SQL front end's row-expression programs) fingerprint by
            # CONTENT: two submissions of the same query build fresh
            # objects computing the same function, and must hit the
            # compile cache (the service's warm-Nth-user story)
            if (hasattr(v, "__ship_payload__")
                    and hasattr(type(v), "__from_payload__")):
                import json
                return (f"ship:{type(v).__qualname__}:"
                        f"{json.dumps(v.__ship_payload__(), sort_keys=True)}")
            return "fn%x" % id(v) if callable(v) else repr(v)

        def op_fp(op: StageOp) -> str:
            items = []
            for k in sorted(op.params):
                items.append(f"{k}={val_fp(op.params[k])}")
            return f"{op.kind}({','.join(items)})"

        def ex_fp(ex: Optional[Exchange]) -> str:
            if ex is None:
                return "-"
            return (f"{ex.kind}[{','.join(ex.keys)}]cap{ex.out_capacity}"
                    f"{'desc' if ex.descending else ''}"
                    f"{ex.bounds_key or ''}@{ex.axis or '*'}")

        legs = ";".join(
            ",".join(op_fp(o) for o in leg.ops) + "=>" + ex_fp(leg.exchange)
            for leg in self.legs)
        body = ",".join(op_fp(o) for o in self.body)
        return f"legs:{legs}|body:{body}"

    def input_stage_ids(self) -> List[int]:
        out = []
        for leg in self.legs:
            if isinstance(leg.src, int):
                out.append(leg.src)
        bset = {leg.exchange.bounds_from for leg in self.legs
                if leg.exchange and leg.exchange.bounds_from is not None}
        out.extend(bset)
        return out


@dataclasses.dataclass
class StageGraph:
    stages: List[Stage]
    out_stage: int

    def stage(self, sid: int) -> Stage:
        return self.stages[sid]

    def topo_order(self) -> List[Stage]:
        # stages are created in topo order by the planner
        return self.stages

    def explain(self) -> str:
        """Plan pretty-printer (reference: DryadLinqQueryExplain.cs)."""
        lines = []
        for st in self.stages:
            srcs = []
            for leg in st.legs:
                if isinstance(leg.src, int):
                    s = f"stage{leg.src}"
                elif leg.src[0] == "placeholder":
                    s = f"placeholder:{leg.src[1]}"
                else:
                    s = "source"
                ops = ",".join(o.kind for o in leg.ops) or "-"
                ex = ""
                if leg.exchange:
                    ex = f" =>{leg.exchange.kind}({','.join(leg.exchange.keys)})"
                srcs.append(f"{s}[{ops}{ex}]")
            body = ",".join(o.kind for o in st.body) or "-"
            lines.append(f"stage{st.id} <{st.label}> legs: " +
                         " + ".join(srcs) + f" body: {body}")
        lines.append(f"output: stage{self.out_stage}")
        return "\n".join(lines)
