"""User-facing lazy Dataset API + execution context.

The counterpart of the reference's `DryadLinqContext` (DryadLinqContext.cs:566)
and the `IQueryable` operator surface (DryadLinqQueryable.cs — Select/Where/
GroupBy/Join/OrderBy/Distinct/Union/.../HashPartition/RangePartition/Apply/
DoWhile/Take/Submit).  A Dataset wraps a logical expr node; terminal calls
(`collect`, `count`, ...) plan + execute.

`Context(local_debug=True)` is the reference's LocalDebug: terminal calls
route through the sequential oracle instead of the mesh executor — the same
semantics contract the reference tests rely on (SURVEY.md §4).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from dryad_tpu import oracle as _oracle
from dryad_tpu.exec.data import PData, pdata_from_host, pdata_to_host
from dryad_tpu.exec.executor import Executor
from dryad_tpu.parallel.mesh import make_mesh
from dryad_tpu.plan import expr as E
from dryad_tpu.plan.expr import Decomposable  # noqa: F401 (re-export)
from dryad_tpu.plan.planner import plan_query


def _const_key_like(cols):
    """A zero int32 key column matching the batch's row count (used by the
    whole-dataset ``aggregate`` terminal to form one global group)."""
    import jax.numpy as jnp

    v = next(iter(cols.values()))
    if hasattr(v, "lengths"):
        n = v.lengths.shape[0]
    elif hasattr(v, "shape"):
        n = v.shape[0]
    else:
        n = len(v)
    return jnp.zeros((n,), jnp.int32)


def _add_agg_key(cols):
    """Module-level (importable, hence cluster-shippable) agg-key mapper."""
    return dict(cols, __agg_key=_const_key_like(cols))

__all__ = ["Context", "Dataset"]


# ---------------------------------------------------------------------------
# stable query fingerprints (re-streaming cache keys, exec/ooc cache tier)

# fallback salt for values with no restart-stable identity (device data,
# opaque closures): cache entries keyed through it stay valid within
# THIS process — warm do_while iterations still hit — but a restarted
# job re-streams cold (conservative, never stale)
import itertools as _itertools
import uuid as _uuid

_PROCESS_SALT = _uuid.uuid4().hex

# id() reuse guard for the process-salt fingerprint fallback: a cached
# dataset keyed by id(obj) whose object is GC'd could alias a NEW object
# allocated at the same address — a stale HIT, the one thing the salt
# contract forbids.  Pin a monotonic sequence to each object via weakref
# instead; un-weakrefable objects get a fresh sequence per call (pure
# miss every time, never stale).
_LOCAL_ID_SEQ = _itertools.count()
_LOCAL_IDS: Dict[int, Any] = {}     # id -> (weakref, seq)


def _local_identity(v) -> str:
    import weakref
    ent = _LOCAL_IDS.get(id(v))
    if ent is not None and ent[0]() is v:
        return f"local:{_PROCESS_SALT}:{ent[1]}"
    seq = next(_LOCAL_ID_SEQ)
    try:
        def _drop(ref, k=id(v)):
            cur = _LOCAL_IDS.get(k)
            if cur is not None and cur[0] is ref:
                del _LOCAL_IDS[k]
        _LOCAL_IDS[id(v)] = (weakref.ref(v, _drop), seq)
    except TypeError:
        pass
    return f"local:{_PROCESS_SALT}:{seq}"


def _code_const_fp(c) -> str:
    """repr() of a const, except nested code objects (whose repr embeds
    a memory address — it would silently defeat restart-stable keys for
    any callable with an inner def/lambda/comprehension) recurse into
    bytecode + consts, and frozensets repr in sorted order (their
    iteration order is PYTHONHASHSEED-dependent)."""
    import types
    if isinstance(c, types.CodeType):
        inner = ",".join(_code_const_fp(x) for x in c.co_consts)
        return f"code({c.co_name},{c.co_code.hex()},[{inner}])"
    if isinstance(c, frozenset):
        return "frozenset{" + ",".join(sorted(map(repr, c))) + "}"
    if isinstance(c, tuple):
        return "(" + ",".join(_code_const_fp(x) for x in c) + ")"
    return repr(c)


def _stable_fn_fp(fn) -> Optional[str]:
    """Restart-stable identity of a user callable: module/qualname +
    bytecode + consts + hashable closure/default values.  None when the
    callable's behavior depends on values we cannot hash byte-exactly
    (bound objects, large arrays) — callers fall back to the process
    salt, which can only cause a cache MISS, never a stale hit."""
    import hashlib
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    parts = [getattr(fn, "__module__", "") or "", fn.__qualname__,
             code.co_code.hex()]
    try:
        parts.append(_code_const_fp(code.co_consts))
    except Exception:
        return None
    captured = []
    if getattr(fn, "__closure__", None):
        try:
            captured.extend(c.cell_contents for c in fn.__closure__)
        except ValueError:          # empty cell
            return None
    captured.extend(getattr(fn, "__defaults__", None) or ())
    for v in captured:
        if isinstance(v, (int, float, complex, str, bytes, bool,
                          type(None))):
            parts.append(repr(v))
        elif isinstance(v, np.ndarray) and v.nbytes <= (1 << 20):
            parts.append(hashlib.sha256(
                np.ascontiguousarray(v).tobytes()).hexdigest())
        else:
            return None
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _stable_value_fp(v) -> str:
    import hashlib
    if callable(v):
        return _stable_fn_fp(v) or _local_identity(v)
    if isinstance(v, E.Decomposable):
        return "dec(" + ",".join(
            _stable_value_fp(getattr(v, part))
            for part in ("seed", "merge", "finalize")) + ")"
    if isinstance(v, np.ndarray):
        if v.nbytes <= (1 << 20):
            return hashlib.sha256(
                np.ascontiguousarray(v).tobytes()).hexdigest()
        return _local_identity(v)
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_stable_value_fp(v[k])}" for k in sorted(v)) + "}"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_stable_value_fp(x) for x in v) + "]"
    if isinstance(v, (int, float, complex, str, bytes, bool,
                      type(None))):
        return repr(v)
    import dataclasses as _dc
    if _dc.is_dataclass(v) and not isinstance(v, type):
        inner = ",".join(
            f"{f.name}={_stable_value_fp(getattr(v, f.name))}"
            for f in _dc.fields(v))
        return f"{type(v).__name__}({inner})"
    return _local_identity(v)


def _stable_source_fp(data) -> str:
    """Content identity of a Source node's data.  Store-backed streams
    carry a fingerprint over path + per-partition checksums (set by
    ChunkSource.from_store / from_text), so changed SOURCE BYTES change
    the cache key; everything else degrades to the process salt."""
    from dryad_tpu.exec.stream_exec import StreamSource
    cs = data.cs if isinstance(data, StreamSource) else data
    fp = getattr(cs, "fingerprint", None)
    if fp:
        return fp
    spec = getattr(data, "spec", None)
    if isinstance(spec, dict) and spec.get("kind") == "store_stream":
        try:
            from dryad_tpu.io.store import store_meta
            meta = store_meta(spec["path"])
            import hashlib
            return hashlib.sha256(repr(
                ("store", spec["path"], meta.get("counts"),
                 meta.get("checksums"))).encode()).hexdigest()
        except Exception:
            pass
    return _local_identity(data)


def _stable_node_fp(root: E.Node) -> str:
    """Restart-stable structural fingerprint of a query DAG — the
    re-streaming cache key (exec/ooc cache tier).  Walks the logical
    nodes parents-first and hashes type + every dataclass field
    (callables by bytecode+captures, sources by content identity);
    anything unhashable folds in the per-process salt, so an uncertain
    key can only MISS across restarts, never serve a stale entry."""
    import dataclasses as _dc
    import hashlib
    parts = []
    ids: Dict[int, int] = {}
    for i, n in enumerate(E.walk(root)):
        ids[n.id] = i
        fields = []
        for f in _dc.fields(n):
            if f.name in ("parents", "host"):
                continue
            v = getattr(n, f.name)
            if f.name == "data":
                fields.append(f"data={_stable_source_fp(v)}"
                              if v is not None else "data=None")
            else:
                fields.append(f"{f.name}={_stable_value_fp(v)}")
        parents = ",".join(str(ids[p.id]) for p in n.parents)
        parts.append(f"{type(n).__name__}({parents})[{';'.join(fields)}]")
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


class Context:
    """Owns the mesh + executor and creates root Datasets."""

    def __init__(self, mesh=None, local_debug: bool = False,
                 event_log: Optional[Callable[[dict], None]] = None,
                 spill_dir: Optional[str] = None,
                 cluster=None, fn_table: Optional[Mapping[str, Any]] = None,
                 config=None, install_trace: bool = True):
        from dryad_tpu.utils.config import JobConfig
        self.cluster = cluster
        self.fn_table = dict(fn_table or {})
        self.local_debug = local_debug
        self.spill_dir = spill_dir
        self.config = config or JobConfig()
        from dryad_tpu.utils.compile_cache import enable_persistent_cache
        enable_persistent_cache(self.config.compilation_cache_dir)
        # route driver-side spans (IO provider reads, job submission)
        # into this context's event stream (obs/trace.py).  The sink is
        # process-global and the LATEST Context owns it — including a
        # log-less Context, which detaches the previous sink: a later
        # job's spans must never leak into an earlier job's JSONL.
        # ``install_trace=False`` opts out of that latest-owner model
        # entirely: the multi-tenant service daemon builds Contexts for
        # plan/lint work with fully explicit per-job sinks, and must not
        # detach whatever sink the embedding process installed.
        if install_trace:
            from dryad_tpu.obs import trace as _trace
            _trace.install(event_log)
        # job-history archiving (obs/history.py): JobConfig.history_dir
        # makes the attached EventLog archive this job's {events, plan,
        # metrics, bundles} on close; an explicit EventLog(history_dir=)
        # wins over the config knob
        if (self.config.history_dir and event_log is not None
                and getattr(event_log, "history_dir", "absent") is None):
            event_log.history_dir = self.config.history_dir
        if cluster is not None:
            # multi-process mode (runtime.LocalCluster): the driver owns no
            # devices; plans + deferred sources ship to the worker gang
            # (LocalJobSubmission.cs:97-302 parity).  Workers build a 2-D
            # (dcn, dp) mesh with dcn = the process boundary.
            self.mesh = None
            self.nparts = cluster.nparts
            self.hosts = (cluster.n_processes
                          if cluster.n_processes > 1 else 1)
            self.levels = (("dp", "dcn") if self.hosts > 1 else ())
            self.executor = None
            self._event_log = event_log
            self._token_seq = 0
            # token -> producing plan node: a gang restart wipes resident
            # state, so a query touching a lost token re-materializes it
            # from lineage and retries (replay-based fault tolerance,
            # SURVEY.md §3.5)
            self._resident_producers: Dict[str, Any] = {}
            return
        self._event_log = event_log
        self.mesh = mesh if mesh is not None else make_mesh()
        self.nparts = self.mesh.devices.size
        # multi-level meshes trigger hierarchical aggregation plans; the
        # planner's level chain is the mesh's axes innermost-first
        # (2-D: dp -> dcn; 3-D: dp -> host -> dcn)
        self.hosts = (self.mesh.devices.shape[0]
                      if len(self.mesh.axis_names) >= 2 else 1)
        self.levels = (tuple(reversed(self.mesh.axis_names))
                       if len(self.mesh.axis_names) >= 2 else ())
        self.executor = Executor(self.mesh, event_log=event_log,
                                 config=self.config)

    # -- pre-submit static analysis (dryad_tpu/analysis) --------------------

    def _pre_submit_lint(self, node, cluster: bool, graph=None):
        """JobConfig.lint gate: verify the plan + lint its UDFs BEFORE any
        executor/cluster work starts (the reference's phase-1 static
        validation point, DryadLinqQueryGen.cs).  "warn" logs findings to
        the EventLog; "error" refuses to submit on error-severity
        findings (analysis.LintError).

        With ``graph`` (the already-planned StageGraph — planning is
        deterministic, so it matches what the executor runs) the static
        COST pass also runs (analysis/cost.py): per-stage row/byte
        predictions from real source statistics, DTA2xx OOM/spill
        forecasts against ``JobConfig.device_hbm_bytes``, and a
        ``cost_report`` event whose machine-readable payload the
        executor cross-checks at runtime (``cost_model_miss``).
        Returns the CostReport (or None)."""
        mode = getattr(self.config, "lint", "off")
        if mode == "off":
            return None
        from dryad_tpu.analysis import LintError, check_plan
        report = check_plan(node, cluster=cluster, fn_table=self.fn_table)
        cost_rep = None
        if graph is not None:
            from dryad_tpu.analysis.cost import (cost_diagnostics,
                                                 estimate_graph)
            try:
                cost_rep = estimate_graph(graph, self.nparts,
                                          config=self.config)
                report.diagnostics.extend(
                    cost_diagnostics(cost_rep, self.config))
            except Exception as e:
                # the cost model must never turn a runnable job into a
                # crashed one — skip it loudly (DTA200) and submit
                cost_rep = None
                report.add("DTA200", "info",
                           f"cost analyzer failed ({e!r}) — cost pass "
                           f"skipped", node="cost")
        report.dedup()
        ev = self._event_log
        if ev is not None:
            for d in report:
                ev({"event": "lint_finding", "code": d.code,
                    "severity": d.severity, "message": d.message,
                    "node": d.node,
                    "span": str(d.span) if d.span else None})
            if cost_rep is not None:
                ev({"event": "cost_report",
                    "report": cost_rep.to_payload()})
        if mode == "error" and report.errors:
            raise LintError(report)
        return cost_rep

    # -- cluster submission -------------------------------------------------

    def _cluster_run(self, node, collect: bool = True,
                     store_path: Optional[str] = None,
                     store_partitioning: Optional[Dict[str, Any]] = None,
                     keep_token: Optional[str] = None,
                     want_reply: bool = False,
                     store_compression: Optional[str] = None,
                     lint: bool = True):
        """Plan, serialize, and submit one query to the worker gang.
        Returns the host table (default) or, with ``want_reply``, worker
        0's full reply (resident-cache metadata included).  Queued token
        releases from dropped cached Datasets piggyback on every job."""
        from dryad_tpu.runtime import ClusterJobError, WorkerFailure
        from dryad_tpu.runtime.shiplan import serialize_for_cluster
        graph = plan_query(node, self.nparts, hosts=self.hosts,
                           levels=self.levels,
                           config=self.config)
        if lint:
            # plan first so the lint gate's cost pass sees the lowered
            # graph (pure host work — still zero cluster resources)
            self._pre_submit_lint(node, cluster=True, graph=graph)
        plan_json, specs = serialize_for_cluster(graph, self.fn_table)
        # route worker events to THIS context's logger for the duration of
        # the job (several Contexts may share one cluster)
        prev_log = self.cluster.event_log
        self.cluster.event_log = self._event_log
        replayed = False
        try:
            for heal in range(8):   # bound resident-healing retries
                try:
                    reply = self.cluster.execute(
                        plan_json, specs, collect=collect,
                        store_path=store_path,
                        store_partitioning=store_partitioning,
                        config=self.config,
                        timeout=self.config.cluster_job_timeout_s,
                        keep_token=keep_token,
                        store_compression=store_compression)
                    break
                except WorkerFailure:
                    # a wedged/dead worker tore the gang down (straggler
                    # watchdog or process death): the job is
                    # deterministic from its sources — replay ONCE on a
                    # fresh gang (lineage replay, SURVEY.md §3.5; any
                    # resident references heal below on the retry)
                    if replayed or heal == 7:
                        raise
                    replayed = True
                except ClusterJobError as e:
                    tok = self._lost_resident_token(e)
                    if tok is None or heal == 7:
                        raise
                    # a gang restart wiped this resident: re-materialize
                    # it from its producing plan, then retry the query
                    # (recursively heals chained residents)
                    self._cluster_run(self._resident_producers[tok],
                                      collect=False, keep_token=tok)
        finally:
            self.cluster.event_log = prev_log
        return reply if want_reply else reply.get("table")

    def _lost_resident_token(self, err) -> Optional[str]:
        """Healable token from a lost-resident job error, if its producer
        is registered.  The token arrives as STRUCTURED data on the
        exception (ClusterJobError.missing_token, set from the worker
        reply's ``missing_token`` field — runtime/worker.py
        _tag_missing_token), never parsed out of traceback text."""
        tok = getattr(err, "missing_token", None)
        if tok is not None and tok in self._resident_producers:
            return tok
        return None

    # -- cluster-resident intermediates ------------------------------------

    def _fresh_token(self, tag: str) -> str:
        self._token_seq += 1
        return f"__{tag}_{id(self):x}_{self._token_seq}"

    def _resident_dataset(self, token: str, capacity: int,
                          partitioning: E.Partitioning =
                          E.Partitioning.none(),
                          producer: Any = None) -> "Dataset":
        """Dataset over a cluster-resident intermediate: queries ship only
        the token.  When the Dataset's source node is garbage-collected,
        the token is queued on the CLUSTER's release list (piggybacked on
        the next job from ANY Context — the gang holds the device memory,
        so the queue must outlive this Context).  ``producer`` (the plan
        node that computed it) makes the resident survive gang restarts:
        a token miss re-materializes from lineage."""
        import weakref

        from dryad_tpu.runtime.sources import DeferredSource
        node = E.Source(parents=(), data=DeferredSource(
            {"kind": "resident", "token": token, "capacity": capacity}),
            _npartitions=self.nparts, _partitioning=partitioning)
        if producer is not None:
            self._resident_producers[token] = producer
            weakref.finalize(node, self._resident_producers.pop, token,
                             None)
        weakref.finalize(node, self.cluster.pending_release.append, token)
        return Dataset(self, node)

    # -- re-streaming cache plumbing (exec/ooc cache tier) ------------------

    def _ooc_cache_root(self) -> str:
        """Root directory for re-streaming cache entries:
        ``JobConfig.ooc_cache_dir`` (persistent — a restarted job with an
        intact cache dir skips the cold pass) or a lazily created
        per-Context temp dir removed at Context GC.  A REMOTE
        ``ooc_cache_dir`` (scheme://) falls through to the temp dir:
        entry sidecars are written with local file semantics, and
        naively os.makedirs-ing the URL would split-brain the entry
        (data remote, sidecar in a literal local 'scheme:/...' dir)."""
        if self.config.ooc_cache_dir and "://" not in \
                self.config.ooc_cache_dir:
            os.makedirs(self.config.ooc_cache_dir, exist_ok=True)
            return self.config.ooc_cache_dir
        root = getattr(self, "_ooc_cache_tmp", None)
        if root is None:
            import shutil
            import tempfile
            import weakref
            root = tempfile.mkdtemp(prefix="dryad-ooc-cache-",
                                    dir=self.spill_dir)
            weakref.finalize(self, shutil.rmtree, root,
                             ignore_errors=True)
            self._ooc_cache_tmp = root
        return root

    def _cache_event(self):
        """Event sink for ooc cache lifecycle records: forwards to the
        Context's log AND keeps the live ``dryad_ooc_cache_hits_total``
        counter current (the derived mirror counts the same events)."""
        sink = self._event_log

        def ev(e):
            kind = e.get("event")
            if kind in ("ooc_cache_hit", "ooc_cache_write"):
                from dryad_tpu.obs.metrics import (REGISTRY,
                                                   family_counter)
                family_counter(
                    REGISTRY,
                    "ooc_cache_hits" if kind == "ooc_cache_hit"
                    else "ooc_cache_writes").inc()
            if sink is not None:
                sink(e)
        return ev

    # -- dataset constructors ---------------------------------------------

    def from_columns(self, columns: Mapping[str, Any],
                     capacity: int | None = None,
                     str_max_len: int | None = None) -> "Dataset":
        """Create a partitioned dataset from host columns (FromEnumerable,
        DryadLinqContext.cs:1210)."""
        str_max_len = str_max_len or self.config.string_max_len
        if self.cluster is not None:
            from dryad_tpu.runtime.sources import (DeferredSource,
                                                   columns_spec)
            spec = columns_spec(columns, self.nparts, capacity=capacity,
                                str_max_len=str_max_len)
            node = E.Source(parents=(), data=DeferredSource(spec),
                            _npartitions=self.nparts, host=dict(columns))
            return Dataset(self, node)
        pdata = pdata_from_host(columns, self.mesh, nparts=self.nparts,
                                capacity=capacity, str_max_len=str_max_len)
        node = E.Source(parents=(), data=pdata, _npartitions=self.nparts,
                        host=dict(columns))
        return Dataset(self, node)

    def from_pdata(self, pdata: PData,
                   host: Optional[Mapping[str, Any]] = None,
                   partitioning: E.Partitioning = E.Partitioning.none()
                   ) -> "Dataset":
        node = E.Source(parents=(), data=pdata, _npartitions=self.nparts,
                        _partitioning=partitioning, host=host)
        return Dataset(self, node)

    def read_text(self, path, column: str = "line",
                  max_line_len: int | None = None) -> "Dataset":
        """Read text as one record per line (FromStore for LineRecord,
        DryadLinqContext.cs:1176 + LineRecord.cs).  ``path`` may be a single
        file, a glob pattern, a directory, or a list of those — multi-file
        inputs are enumerated and packed in parallel (DrPartitionFile
        input-partition enumeration, DataPath.cs:124).  Line splitting +
        padding runs in the native IO engine when built."""
        from dryad_tpu.io.providers import expand_paths, read_text_files
        max_line_len = max_line_len or self.config.text_max_line_len
        paths = expand_paths(path)
        if self.cluster is not None:
            from dryad_tpu.runtime.sources import DeferredSource, text_spec
            spec = text_spec(paths, self.nparts, column=column,
                             max_line_len=max_line_len)
            node = E.Source(parents=(), data=DeferredSource(spec),
                            _npartitions=self.nparts)
            return Dataset(self, node)
        from dryad_tpu.exec.data import pdata_from_packed_strings
        data, lens, _ = read_text_files(paths, max_line_len)
        pdata = pdata_from_packed_strings(data, lens, self.mesh,
                                          column=column)
        host = {column: [bytes(r[:l]) for r, l in
                         zip(data, lens)]} if self.local_debug else None
        return self.from_pdata(pdata, host=host)

    # -- streamed (out-of-core) sources ------------------------------------

    def from_stream(self, source) -> "Dataset":
        """Wrap an exec.ooc.ChunkSource as a streamed Dataset: the query
        plans with one logical partition and executes over chunk streams
        (exec/stream_exec.py) — device working set stays O(chunk_rows)
        no matter the total data size (the reference's transparent
        bounded-memory channels, channelbufferqueue.cpp:777)."""
        from dryad_tpu.exec.stream_exec import StreamSource
        if self.cluster is not None:
            # FromEnumerable parity (DryadLinqContext.cs:1210): a
            # driver-side generator cannot execute on workers, so the
            # client SPOOLS the stream into a store the workers can
            # reach (JobConfig.cluster_stream_spool_dir — shared fs or
            # hdfs://; s3:// is rejected, no atomic chunk-stream commit;
            # default: a driver temp dir, valid for single-machine
            # clusters) and the gang streams the store through the full
            # planned surface (runtime/stream_plan.py).
            import tempfile
            import uuid

            from dryad_tpu.exec.ooc import write_chunks_to_store
            root = (self.config.cluster_stream_spool_dir
                    or tempfile.mkdtemp(prefix="dryad-spool-"))
            path = os.path.join(root, f"stream-{uuid.uuid4().hex[:10]}")                 if "://" not in root else                 root.rstrip("/") + f"/stream-{uuid.uuid4().hex[:10]}"
            write_chunks_to_store(path, iter(source), source.schema)
            return self.read_store_stream(path,
                                          chunk_rows=source.chunk_rows)
        node = E.Source(parents=(), data=StreamSource(source),
                        _npartitions=1)
        return Dataset(self, node)

    def read_store_stream(self, path: str,
                          chunk_rows: int | None = None):
        """Stream a persisted store through the plain Dataset API —
        the >HBM path (1 TB TeraSort north star, BASELINE.md config 2).

        On a cluster Context this is an ORDINARY Dataset too: the query
        plans through the normal lowering (exchanges included) and the
        gang executes it as chunk waves + per-device bucket streams
        (runtime/stream_plan.py) — the full operator surface, not a
        restricted mini-API (VERDICT r3 item 3)."""
        cr = chunk_rows or self._auto_chunk_rows(path) \
            or self.config.ooc_chunk_rows
        if self.cluster is not None:
            from dryad_tpu.runtime.sources import DeferredSource
            spec = {"kind": "store_stream", "path": path,
                    "chunk_rows": cr, "capacity": cr}
            node = E.Source(parents=(), data=DeferredSource(spec),
                            _npartitions=self.nparts)
            return Dataset(self, node)
        from dryad_tpu.exec.ooc import ChunkSource
        cs = ChunkSource.from_store(path, cr)
        return self.from_stream(cs)

    def _auto_chunk_rows(self, store_path: str) -> int | None:
        """Measured chunk sizing (JobConfig.ooc_chunk_autotune): row
        width from the store's schema, link rate + dispatch floor from a
        one-time probe (exec/autotune)."""
        if not getattr(self.config, "ooc_chunk_autotune", False):
            return None
        try:
            from dryad_tpu.exec.autotune import pick_chunk_rows
            from dryad_tpu.io.store import store_meta
            meta = store_meta(store_path)
            row_bytes = 0
            lanes = 0
            for spec in meta["schema"].values():
                if spec["kind"] == "str":
                    row_bytes += int(spec["max_len"]) + 4
                    lanes += -(-int(spec["max_len"]) // 4) + 1
                else:
                    import numpy as np
                    w = int(np.dtype(spec["dtype"]).itemsize)
                    n_el = 1
                    for d in spec.get("shape", ()):
                        n_el *= int(d)
                    row_bytes += w * n_el
                    lanes += max(1, w // 4) * n_el
            return pick_chunk_rows(row_bytes, self.config,
                                   row_lanes=lanes)
        except Exception:
            return None   # sizing is a heuristic; never fail the query

    def read_text_stream(self, path, column: str = "line",
                         chunk_rows: int | None = None,
                         max_line_len: int | None = None) -> "Dataset":
        """Stream text files line by line (never holds a file in memory)."""
        from dryad_tpu.exec.ooc import ChunkSource
        from dryad_tpu.io.providers import expand_paths
        cs = ChunkSource.from_text(
            expand_paths(path),
            chunk_rows or self.config.ooc_chunk_rows,
            max_line_len or self.config.text_max_line_len, column)
        return self.from_stream(cs)

    def read(self, uri: str, **kw) -> "Dataset":
        """URI-scheme dispatch (DataProvider.cs / concreterchannel.cpp:44-49):
        ``file://`` text, ``store://`` partitioned store, ``http://``
        ranged reads, ``s3://`` objects, ``hdfs://`` WebHDFS
        (io/webhdfs.py — DrHdfsClient.cpp role), plus any scheme
        registered via io.providers.register_provider."""
        from dryad_tpu.io.providers import open_uri
        return open_uri(self, uri, **kw)

    def from_store(self, path: str, capacity: int | None = None) -> "Dataset":
        """Load a persisted dataset (FromStore, DryadLinqContext.cs:1176).
        Persisted partitioning metadata is honored for shuffle elimination
        (AssumeHashPartition parity, DryadLinqQueryable.cs:3408).
        ``path`` may be local, ``s3://``, or ``hdfs://`` (io/store.py
        scheme dispatch); the same goes for ``read_store_stream`` and
        ``to_store``."""
        from dryad_tpu.io.store import read_store, store_meta
        meta = store_meta(path)
        auto = self.config.ooc_auto_stream_rows
        if (auto and self.cluster is None
                and sum(meta.get("counts", [])) >= auto):
            # size-threshold streaming: a big store never tries to fit in
            # HBM (VERDICT r2 next-round item 1)
            return self.read_store_stream(path)
        pmeta = meta.get("partitioning", {"kind": "none"})
        part = E.Partitioning(pmeta.get("kind", "none"),
                              tuple(pmeta.get("keys", ())))
        # re-blocking across a different mesh size destroys hash placement
        if meta["npartitions"] != self.nparts:
            part = E.Partitioning.none()
        if self.cluster is not None:
            from dryad_tpu.runtime.sources import DeferredSource, store_spec
            spec = store_spec(path, self.nparts, meta, capacity=capacity)
            node = E.Source(parents=(), data=DeferredSource(spec),
                            _npartitions=self.nparts, _partitioning=part)
            return Dataset(self, node)
        pdata = read_store(path, self.mesh, capacity=capacity,
                           verify=self.config.store_verify_checksums)
        return self.from_pdata(pdata, partitioning=part)

    # -- iteration ---------------------------------------------------------

    def do_while(self, init: "Dataset",
                 body: Callable[["Dataset"], "Dataset"],
                 n_iters: int,
                 cond: Optional[Callable[[Dict[str, Any]], bool]] = None
                 ) -> "Dataset":
        """Iterative DAG execution (reference DoWhile,
        DryadLinqQueryable.cs:1281, VisitDoWhile DryadLinqQueryGen.cs:3353).

        The loop body is planned ONCE over a placeholder; each iteration
        binds the previous iteration's materialized output, so XLA programs
        are compiled once and reused (shapes are stable).  ``cond`` (host
        predicate on the collected current table) can stop early.
        """
        if n_iters > self.config.max_loop_iterations:
            raise ValueError(
                f"n_iters={n_iters} exceeds JobConfig.max_loop_iterations="
                f"{self.config.max_loop_iterations}; raise the knob "
                f"explicitly for longer loops")
        if self.cluster is not None:
            # iterate by re-submitting the planned body with the previous
            # iteration's output held CLUSTER-RESIDENT under a token —
            # only the plan + token cross the driver socket per iteration,
            # never the table (the reference keeps loop-carried data as
            # cluster-resident temp outputs read in place,
            # GraphManager/vertex/DrVertex.h:325-351; VERDICT r2 item 4).
            # The body plan's fingerprints are identical every round, so
            # workers (persistent executors, runtime/exec_common.py)
            # compile each stage once.  ``cond`` still collects the table
            # each round — it is a host predicate on the full table.
            import dataclasses as _dc

            from dryad_tpu.runtime import ClusterJobError, WorkerFailure
            from dryad_tpu.runtime.sources import DeferredSource

            ph = E.Placeholder(parents=(), name="__loop",
                               _npartitions=self.nparts)
            body_node = body(Dataset(self, ph)).node

            def subst(node, token, cap):
                if isinstance(node, E.Placeholder) and node.name == "__loop":
                    return E.Source(parents=(), data=DeferredSource(
                        {"kind": "resident", "token": token,
                         "capacity": cap}), _npartitions=self.nparts)
                new_parents = tuple(subst(p, token, cap)
                                    for p in node.parents)
                if new_parents == node.parents:
                    return node
                return _dc.replace(node, parents=new_parents)

            def run_loop():
                token = self._fresh_token("loop")
                try:
                    reply = self._cluster_run(init.node, collect=False,
                                              keep_token=token,
                                              want_reply=True)
                    cap = reply["resident_capacity"]
                    for it in range(n_iters):
                        # the body plan is structurally identical every
                        # round (subst only swaps the placeholder for the
                        # resident token): lint it ONCE, not per iteration
                        reply = self._cluster_run(
                            subst(body_node, token, cap),
                            collect=cond is not None, keep_token=token,
                            want_reply=True, lint=it == 0)
                        cap = reply["resident_capacity"]
                        if cond is not None and not cond(reply["table"]):
                            break
                    return token, cap
                except BaseException:
                    # the abandoned token must not pin a dataset-sized
                    # PData in surviving workers
                    self.cluster.pending_release.append(token)
                    raise

            try:
                token, cap = run_loop()
            except WorkerFailure:
                # a gang restart loses resident state; the loop is
                # deterministic from its sources — replay once from init
                # (lineage replay, SURVEY.md §3.5).  Deterministic job
                # errors (bad UDF etc.) propagate — re-running cannot fix
                # them.
                token, cap = run_loop()
            except ClusterJobError as e:
                # structured lost-resident tag (never message text)
                if e.missing_token is None:
                    raise
                token, cap = run_loop()
            return self._resident_dataset(token, cap)
        if self.local_debug:
            cur_host = _oracle.run_oracle(init.node)
            ph = E.Placeholder(parents=(), name="__loop",
                               _npartitions=self.nparts)
            body_node = body(Dataset(self, ph)).node
            for _ in range(n_iters):
                cur_host = _oracle.run_oracle(
                    body_node, bindings={"__loop": cur_host})
                if cond is not None and not cond(cur_host):
                    break
            node = E.Source(parents=(), data=None,
                            _npartitions=self.nparts, host=cur_host)
            return Dataset(self, node)
        probe_ph = E.Placeholder(parents=(), name="__loop",
                                 _npartitions=self.nparts, capacity=1)
        if (init._streaming()
                or body(Dataset(self, probe_ph))._streaming()):
            # streamed (>RAM) loop body on the single-process path: the
            # loop STATE is a small host table (ranks / centroids); the
            # body references stream sources (edges at 10x HBM) and
            # re-executes through the streamed engine every superstep —
            # re-reading its >RAM inputs from the store or, with
            # .cache(), the local re-streaming chunk cache.  This is the
            # iteration story Known-limit #3 was missing: loop-invariant
            # >HBM inputs now iterate with device working set
            # O(chunk_rows).
            cur_host = init.collect()
            for _ in range(n_iters):
                prev = self.from_columns(cur_host)
                cur_host = body(prev).collect()
                if cond is not None and not cond(cur_host):
                    break
            return self.from_columns(cur_host)
        cur = init._materialize()
        ph = E.Placeholder(parents=(), name="__loop", _npartitions=self.nparts,
                           capacity=cur.capacity)
        body_ds = body(Dataset(self, ph))
        graph = plan_query(body_ds.node, self.nparts,
                           hosts=self.hosts, levels=self.levels)
        for _ in range(n_iters):
            nxt = self.executor.run(graph, bindings={"__loop": cur})
            if nxt.capacity != cur.capacity:
                raise ValueError(
                    "do_while body must preserve per-partition capacity "
                    f"({cur.capacity} -> {nxt.capacity}); use explicit "
                    "capacities on flat_map/join ops inside the loop")
            cur = nxt
            if cond is not None and not cond(pdata_to_host(cur)):
                break
        return self.from_pdata(cur, host=None)


class Dataset:
    """A lazy, partitioned, columnar dataset (the IQueryable)."""

    def __init__(self, ctx: Context, node: E.Node):
        self.ctx = ctx
        self.node = node

    # -- row-local operators ----------------------------------------------

    def select(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]],
               label: str = "select") -> "Dataset":
        """Columnwise projection: fn(cols) -> new cols (replaces columns)."""
        return Dataset(self.ctx, E.Map(parents=(self.node,), fn=fn,
                                       label=label))

    def where(self, fn: Callable[[Dict[str, Any]], Any],
              label: str = "where") -> "Dataset":
        return Dataset(self.ctx, E.Filter(parents=(self.node,), fn=fn,
                                          label=label))

    def split_words(self, column: str, out_capacity: int,
                    max_token_len: int | None = None,
                    delims: bytes | None = None,
                    lower: bool = False,
                    max_tokens_per_row: int | None = None) -> "Dataset":
        """Tokenizing SelectMany (the WordCount flat-map).  Token length
        and delimiter defaults come from JobConfig (token_max_len,
        token_delims + punctuation)."""
        cfg = self.ctx.config
        if max_token_len is None:
            max_token_len = cfg.token_max_len
        if delims is None:
            delims = cfg.token_delims
        return Dataset(self.ctx, E.FlatTokens(
            parents=(self.node,), column=column, out_capacity=out_capacity,
            max_token_len=max_token_len, delims=delims, lower=lower,
            max_tokens_per_row=max_tokens_per_row))

    def apply_per_partition(self, fn, label: str = "apply",
                            preserves_partitioning: bool = False,
                            host_fn=None) -> "Dataset":
        """Arbitrary Batch -> Batch function per partition
        (ApplyPerPartition, DryadLinqQueryable.cs:1084).  Provide host_fn
        (table -> table) to make it interpretable by the oracle."""
        return Dataset(self.ctx, E.ApplyPerPartition(
            parents=(self.node,), fn=fn, label=label,
            preserves_partitioning=preserves_partitioning, host_fn=host_fn))

    def apply_with_partition_index(self, fn, label: str = "apply_idx"
                                   ) -> "Dataset":
        """fn(batch, partition_index) -> Batch (ApplyWithPartitionIndex,
        DryadLinqQueryable.cs:1356)."""
        return Dataset(self.ctx, E.ApplyPerPartition(
            parents=(self.node,), fn=fn, label=label, with_index=True))

    def flat_map(self, fn, out_capacity: int,
                 label: str = "flat_map") -> "Dataset":
        """Generic SelectMany: fn(cols) -> (out_cols [cap, m, ...],
        mask [cap, m]); flattened row-major."""
        return Dataset(self.ctx, E.FlatMap(
            parents=(self.node,), fn=fn, out_capacity=out_capacity,
            label=label))

    def zip_with(self, other: "Dataset", suffix: str = "_r") -> "Dataset":
        """Positional pairing by global row index (LINQ Zip).  Sides with
        differing per-partition counts are realigned via an exchange."""
        return Dataset(self.ctx, E.Zip(parents=(self.node, other.node),
                                       suffix=suffix))

    def sliding_window(self, w: int) -> "Dataset":
        """Windows of w consecutive rows (SlidingWindow,
        DryadLinqQueryable.cs:1318); columns gain a window axis."""
        return Dataset(self.ctx, E.SlidingWindow(parents=(self.node,), w=w))

    def with_row_index(self, column: str = "row_index") -> "Dataset":
        """Add a global row-index column (Long*/indexed operator parity)."""
        return Dataset(self.ctx, E.WithRowIndex(parents=(self.node,),
                                                column=column))

    def skip(self, n: int) -> "Dataset":
        return Dataset(self.ctx, E.SkipTake(parents=(self.node,), op="skip",
                                            n=n))

    def take_while(self, fn) -> "Dataset":
        return Dataset(self.ctx, E.SkipTake(parents=(self.node,),
                                            op="take_while", fn=fn))

    def skip_while(self, fn) -> "Dataset":
        return Dataset(self.ctx, E.SkipTake(parents=(self.node,),
                                            op="skip_while", fn=fn))

    def fork_by(self, fn) -> Tuple["Dataset", "Dataset"]:
        """Split one scan into (matching, non-matching) branches (Fork,
        DryadLinqQueryable.cs:3717); the shared parent is materialized once
        (Tee)."""
        t = self.where(fn, label="fork_t")
        f = self.where(lambda c, _fn=fn: ~_fn(c), label="fork_f")
        return t, f

    def fork(self, *predicates) -> Tuple["Dataset", ...]:
        """n-way Fork (reference Fork, DryadLinqQueryable.cs:3717-3852 is
        n-way): one branch per predicate over a single shared scan (the
        parent is Tee-materialized once by the planner's consumer count).
        Branches may overlap or under-cover; pair with fork_on for
        disjoint key-value splits."""
        return tuple(self.where(p, label=f"fork_{i}")
                     for i, p in enumerate(predicates))

    def fork_on(self, column: str, values: Sequence[Any]
                ) -> Tuple["Dataset", ...]:
        """n-way Fork by key value (the reference's Fork(keySelector,
        keys) overload): branch i holds rows where ``column == values[i]``.
        """
        import jax.numpy as jnp

        return tuple(
            self.where(lambda c, _v=v: c[column] == jnp.asarray(_v),
                       label=f"fork_{column}_{i}")
            for i, v in enumerate(values))

    def assume_hash_partition(self, keys: Sequence[str]) -> "Dataset":
        """Declare existing hash placement (AssumeHashPartition,
        DryadLinqQueryable.cs:3408) — skips the shuffle for matching keys."""
        return Dataset(self.ctx, E.AssumePartitioning(
            parents=(self.node,), kind="hash", keys=tuple(keys)))

    def assume_range_partition(self, keys: Sequence[str]) -> "Dataset":
        return Dataset(self.ctx, E.AssumePartitioning(
            parents=(self.node,), kind="range", keys=tuple(keys)))

    def assume_order_by(self, keys: Sequence[str]) -> "Dataset":
        """Declare (without sorting) that the data is globally sorted
        ascending by ``keys`` — partitions hold disjoint ascending key
        ranges (AssumeOrderBy, DryadLinqQueryable.cs:3639).  A subsequent
        ``order_by`` whose ascending keys are a prefix of ``keys`` skips
        the range exchange and only sorts locally."""
        return self.assume_range_partition(keys)

    def take(self, n: int) -> "Dataset":
        return Dataset(self.ctx, E.Take(parents=(self.node,), n=n))

    def with_capacity(self, capacity: int) -> "Dataset":
        """Coerce per-partition capacity (shape-stabilize do_while bodies)."""
        return Dataset(self.ctx, E.WithCapacity(parents=(self.node,),
                                                capacity=capacity))

    def cross_apply(self, other: "Dataset", fn, host_fn=None,
                    label: str = "cross_apply") -> "Dataset":
        """fn(left_batch, right_batch) with ``other`` broadcast to every
        partition; host_fn(table_l, table_r) is the oracle equivalent."""
        return Dataset(self.ctx, E.CrossApply(
            parents=(self.node, other.node), fn=fn, host_fn=host_fn,
            label=label))

    # -- shuffling operators ----------------------------------------------

    def group_by(self, keys: Sequence[str],
                 aggs: Dict[str, Tuple[str, Optional[str]]]) -> "Dataset":
        """GroupBy + decomposable aggregates: aggs maps output column ->
        (kind, value_column), kind in sum/count/min/max/mean/any/all.

        Supported-workload assumption: groups are identified by a 64-bit
        key hash (ops/hashing.py).  Keys that collide in all 64 bits are
        merged — vanishingly unlikely for organic data (~n^2/2^64) but
        possible for adversarially constructed keys; this differs from the
        reference's GroupBy, which compares real keys
        (DryadLinqVertex.cs:510).  ``join`` verifies true keys; ``group_by``
        / ``distinct`` / semi-joins do not.

        An agg value may also be a ``Decomposable(seed, merge, finalize)``
        for user-defined aggregation (IDecomposable.cs:34 parity) — see
        ``dryad_tpu.Decomposable``.

        NaN caveat: ``min``/``max`` over float columns containing NaN are
        LOWERING-DEPENDENT.  The segmented-scan path accumulates with
        jnp.minimum/jnp.maximum (NaN propagates into the group result);
        the boundary-carry fast path rides the value through a sort lane
        ordered by IEEE totalOrder (-NaN below -inf, +NaN above +inf),
        so a NaN may or may not surface depending on its sign bit.
        Neither matches a NaN-IGNORING host nanmin/nanmax — filter NaNs
        first when their handling matters."""
        return Dataset(self.ctx, E.GroupByAgg(
            parents=(self.node,), keys=tuple(keys), aggs=dict(aggs)))

    def group_apply(self, keys: Sequence[str], fn,
                    group_capacity: int, max_groups: int | None = None,
                    out_rows: int = 1, out_capacity: int | None = None
                    ) -> "Dataset":
        """GroupBy yielding group CONTENTS to an arbitrary per-group fn —
        the reference's general GroupBy result selector
        (DryadLinqVertex.cs:510-753): any non-decomposable per-group
        computation (median, mode, custom reductions) is expressible here.

        ``fn(cols, count) -> (out_cols, mask)``: cols are one group's
        columns as [group_capacity, ...] arrays (rows >= count are
        unspecified — mask by count); out_cols are [out_rows, ...] and
        mask is [out_rows] bool.  Group keys are attached to the output
        automatically.  ``group_capacity`` bounds a single group's rows
        (overflow triggers a measured-need retry); ``max_groups`` bounds
        per-partition distinct keys (default: the input capacity).  The
        dense regroup materializes max_groups x group_capacity cells per
        column — size both knobs for the workload."""
        return Dataset(self.ctx, E.GroupApply(
            parents=(self.node,), keys=tuple(keys), fn=fn,
            group_capacity=group_capacity, max_groups=max_groups,
            out_rows=out_rows, out_capacity=out_capacity))

    def group_top_k(self, keys: Sequence[str], k: int, by: str,
                    descending: bool = True) -> "Dataset":
        """Per-group top-k rows by ``by`` (all columns kept; ties keep
        original order).  Structured — no callable, ships to clusters
        without fn_table registration."""
        return Dataset(self.ctx, E.GroupTopK(
            parents=(self.node,), keys=tuple(keys), k=k, by=by,
            descending=descending))

    def group_median(self, keys: Sequence[str], by: str,
                     out: str | None = None) -> "Dataset":
        """One row per group: keys + the LOWER median of ``by`` (element
        (n-1)//2 of the ascending order — always an actual group element,
        unlike numpy's interpolated even-size median)."""
        return Dataset(self.ctx, E.GroupRankSelect(
            parents=(self.node,), keys=tuple(keys), by=by, rank="median",
            out=out))

    def aggregate(self, dec: "E.Decomposable"):
        """Whole-dataset user-defined aggregation (the reference's
        user-combinable Aggregate operator, DryadLinqQueryable.cs
        *AsQuery aggregates + IDecomposable.cs:34): runs the decomposable
        protocol over ONE global group and returns the finalized value(s).
        """
        const = self.select(_add_agg_key, label="agg-key")
        out = const.group_by(["__agg_key"], {"agg": dec}).collect()
        res = {k: v for k, v in out.items() if k != "__agg_key"}
        if set(res.keys()) == {"agg"}:
            v = np.asarray(res["agg"])
            return v[0] if v.shape and v.shape[0] == 1 else v
        return {k: (np.asarray(v)[0] if np.asarray(v).shape
                    and np.asarray(v).shape[0] == 1 else np.asarray(v))
                for k, v in res.items()}

    def join(self, other: "Dataset", left_keys: Sequence[str],
             right_keys: Sequence[str] | None = None,
             expansion: float | None = None,
             broadcast: bool = False, how: str = "inner",
             right_unique: bool = False) -> "Dataset":
        """Equi-join.  ``how`` in inner/left/right/full: "left" keeps
        unmatched left rows with right columns zero-filled; "right" keeps
        unmatched right rows (left non-key columns zero-filled, left key
        columns carrying the right key values); "full" keeps both.
        Broadcast is only honored for inner/left (a replicated right side
        cannot detect its unmatched rows without duplication).

        ``right_unique=True`` (inner/left only) declares the right side
        unique-keyed (lookup/dimension table) and routes matching through
        the gather-free merge-fill kernel (ops/kernels._lookup_join).
        Uniqueness itself is runtime-verified (duplicates fall back to
        the general kernel in the same compiled program).  When both
        sides' key columns pack to the SAME lane layout (same dtype /
        string max_len — the common case), matches are byte-verified
        against the carried key lanes, exactly like the default path;
        when the layouts differ (e.g. an i32 key joined to an i64
        column) verification falls back to the 64-bit key hash pair —
        two distinct keys agreeing in all 64 hash bits would mis-join,
        a ~n^2/2^64 probability budget (the same one group_by/distinct
        document).  Keep right_unique off for adversarially constructed
        keys with mismatched key dtypes."""
        return Dataset(self.ctx, E.Join(
            parents=(self.node, other.node), left_keys=tuple(left_keys),
            right_keys=tuple(right_keys or left_keys),
            expansion=expansion or self.ctx.config.join_expansion,
            broadcast_right=broadcast, how=how,
            right_unique=right_unique))

    def group_join(self, other: "Dataset", left_keys: Sequence[str],
                   aggs: Dict[str, Any],
                   right_keys: Sequence[str] | None = None,
                   expansion: float = 1.0) -> "Dataset":
        """GroupJoin (reference DryadLinqQueryable GroupJoin /
        DLinqGroupByNode): each left row is paired with the AGGREGATE of
        its matching right group.  Lowered as right.group_by(keys, aggs)
        followed by a left-outer join, so empty groups appear with
        zero/neutral aggregate values (include a ("count", None) agg to
        distinguish empties).  aggs values may be builtin kinds or
        Decomposables."""
        rkeys = list(right_keys or left_keys)
        agg = other.group_by(rkeys, aggs)
        return self.join(agg, left_keys, rkeys, expansion=expansion,
                         how="left")

    def order_by(self, keys: Sequence[Tuple[str, bool]]) -> "Dataset":
        """Global sort; keys = [(column, descending), ...]."""
        return Dataset(self.ctx, E.OrderBy(parents=(self.node,),
                                           keys=tuple(keys)))

    def distinct(self, keys: Sequence[str] = ()) -> "Dataset":
        """Distinct rows (by ``keys``, or all columns when empty).  Rows are
        deduplicated by 64-bit key hash — see the supported-workload
        assumption documented on :meth:`group_by`."""
        return Dataset(self.ctx, E.Distinct(parents=(self.node,),
                                            keys=tuple(keys)))

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(self.ctx, E.SetOp(parents=(self.node, other.node),
                                         op="union"))

    def intersect(self, other: "Dataset") -> "Dataset":
        return Dataset(self.ctx, E.SetOp(parents=(self.node, other.node),
                                         op="intersect"))

    def except_(self, other: "Dataset") -> "Dataset":
        return Dataset(self.ctx, E.SetOp(parents=(self.node, other.node),
                                         op="except"))

    def concat(self, other: "Dataset") -> "Dataset":
        return Dataset(self.ctx, E.Concat(parents=(self.node, other.node)))

    def hash_partition(self, keys: Sequence[str]) -> "Dataset":
        """Explicit repartition (HashPartition, DryadLinqQueryable.cs:275)."""
        return Dataset(self.ctx, E.HashRepartition(parents=(self.node,),
                                                   keys=tuple(keys)))

    def range_partition(self, keys: Sequence[str]) -> "Dataset":
        return Dataset(self.ctx, E.RangeRepartition(parents=(self.node,),
                                                    keys=tuple(keys)))

    def broadcast(self) -> "Dataset":
        """Replicate to every partition (small datasets)."""
        return Dataset(self.ctx, E.Broadcast(parents=(self.node,)))

    def cache(self) -> "Dataset":
        """Materialize NOW and reuse the result in later queries — the
        reference's materialized-temp-table pattern (ToStore + FromStore
        around loop-invariant subqueries; temp outputs committed at
        DrVertex.h:325).  Essential under ``do_while``: the loop body
        re-executes everything it references each iteration, so hoist
        loop-invariant joins/aggregations with ``.cache()`` first.

        Streamed / edge-scale data takes the store-backed RE-STREAMING
        cache tier (``JobConfig.ooc_restream_cache``, default on): the
        cold pass writes a local chunked cache — io/store layout, the
        spill-sidecar chunk format with its per-chunk fingerprints —
        keyed by the producing query's stable fingerprint, and warm
        passes (iteration 2..N of ``do_while`` bodies, or a restarted
        job with an intact ``ooc_cache_dir``) re-stream from local
        sequential reads instead of ranged hdfs://, s3://, or http://
        fetches.  A corrupt or stale entry falls back to a clean
        re-stream — never wrong rows."""
        if self.ctx.local_debug:
            t = _oracle.run_oracle(self.node)
            node = E.Source(parents=(), data=None,
                            _npartitions=self.ctx.nparts, host=t)
            return Dataset(self.ctx, node)
        cfg = self.ctx.config
        diag = None
        if not self._streaming():
            # DTA204: cache() of edge-scale data.  With the re-streaming
            # tier ON this is informational (the cache lowers to the
            # local chunked store below); with the tier OFF it warns —
            # the result pins device memory for the Context's lifetime.
            diag = self._cache_cost_diag()
        part = self.node.partitioning
        if self.ctx.cluster is not None:
            if cfg.ooc_restream_cache and (
                    self._stream_sourced()
                    or (diag is not None and diag.severity == "info")):
                return self._cache_restream_cluster()
            # materialize cluster-resident: later queries ship only the
            # token, and the partitioning claim SURVIVES (hash-partitioned
            # cache feeds shuffle-free joins/groupbys) — VERDICT r2 item 4
            token = self.ctx._fresh_token("cache")
            reply = self.ctx._cluster_run(self.node, collect=False,
                                          keep_token=token,
                                          want_reply=True)
            if reply.get("salted"):
                part = E.Partitioning.none()
            return self.ctx._resident_dataset(
                token, reply["resident_capacity"], partitioning=part,
                producer=self.node)
        if self._streaming():
            if cfg.ooc_restream_cache:
                return self._cache_restream_local()
            # legacy (ooc_restream_cache=False — the A/B lever):
            # materialize once to an unvalidated temp store, stream
            # reads from there; the dir lives as long as the Context
            import shutil
            import tempfile
            import weakref
            d = tempfile.mkdtemp(prefix="dryad-cache-",
                                 dir=self.ctx.spill_dir)
            weakref.finalize(self.ctx, shutil.rmtree, d,
                             ignore_errors=True)
            target = d + "/data"
            self.to_store(target)
            return self.ctx.read_store_stream(target)
        if diag is not None and diag.severity == "info" \
                and cfg.ooc_restream_cache:
            # edge-scale in-memory cache(): pin a LOCAL store instead of
            # device HBM — later queries stream it (the DTA204 story)
            return self._cache_restream_inmem()
        pd = self._materialize()
        if getattr(self, "_last_salted", False):
            part = E.Partitioning.none()
        return self.ctx.from_pdata(pd, partitioning=part)

    def _stream_sourced(self) -> bool:
        """True when any source is a stream (local StreamSource OR a
        cluster ``store_stream`` deferred source) — the streamed-data
        half of the re-streaming cache tier's applicability test."""
        from dryad_tpu.analysis.plan_rules import _is_stream_source
        return any(isinstance(n, E.Source) and n.data is not None
                   and _is_stream_source(n.data)
                   for n in E.walk(self.node))

    def _cache_cost_diag(self):
        """The DTA204 edge-scale-cache diagnostic for this query (None
        when not edge-scale or not computable).  Best effort — a
        cost-model failure must never block a cache().  Also emits the
        lint_finding when a sink is attached and lint is on."""
        cfg = self.ctx.config
        if not getattr(cfg, "device_hbm_bytes", 0):
            return None
        has_sink = (getattr(cfg, "lint", "off") != "off"
                    and self.ctx._event_log is not None)
        if not (cfg.ooc_restream_cache or has_sink):
            # neither a lowering decision nor a finding to surface:
            # skip the (planning + eval_shape) estimate entirely
            return None
        try:
            from dryad_tpu.analysis.cost import (cache_diagnostic,
                                                 estimate_query)
            rep = estimate_query(self.node, self.ctx.nparts,
                                 hosts=self.ctx.hosts,
                                 levels=self.ctx.levels,
                                 config=self.ctx.config)
            d = cache_diagnostic(rep, self.ctx.config)
        except Exception:
            return None
        if d is not None and has_sink:
            self.ctx._event_log(
                {"event": "lint_finding", "code": d.code,
                 "severity": d.severity, "message": d.message,
                 "node": d.node,
                 "span": str(d.span) if d.span else None})
        return d

    # -- re-streaming cache tier (exec/ooc.py cache machinery) --------------

    def _cache_restream_local(self) -> "Dataset":
        """Streamed cache(): fingerprinted local chunk cache.  Cold =
        one pass through the streamed engine writing the entry
        (``ooc_cache_write``); warm — including a fresh process with an
        intact ``ooc_cache_dir`` — skips the pass entirely and every
        later iteration re-streams local sequential reads
        (``ooc_cache_hit`` per pass)."""
        from dryad_tpu.exec import ooc
        root = self.ctx._ooc_cache_root()
        key = _stable_node_fp(self.node)
        ev = self.ctx._cache_event()
        warm = ooc.cached_chunk_source(root, key)
        if warm is None:
            cs = self._stream_run()
            sc = ooc.write_chunk_cache(root, key, cs)
            ev({"event": "ooc_cache_write",
                "path": ooc.cache_entry_paths(root, key)[0],
                "rows": sc["rows"], "bytes": sc["bytes"]})
            chunk_rows, schema = sc["chunk_rows"], cs.schema
        else:
            chunk_rows = int(warm[1]["chunk_rows"])
            schema = warm[0].schema
        src = ooc.cache_source(root, key, chunk_rows, schema,
                               make_producer=self._stream_run,
                               on_event=ev)
        return self.ctx.from_stream(src)

    def _cache_restream_inmem(self) -> "Dataset":
        """Edge-scale in-memory cache(): materialize once to a local
        partitioned store (per-chunk fingerprints) and hand back a
        streamed read over it — the result no longer pins HBM for the
        Context's lifetime."""
        from dryad_tpu.exec import ooc
        cfg = self.ctx.config
        root = self.ctx._ooc_cache_root()
        key = _stable_node_fp(self.node)
        ev = self.ctx._cache_event()
        warm = ooc.cached_chunk_source(root, key)
        if warm is None:
            entry, data, _side = ooc.cache_entry_paths(root, key)
            os.makedirs(entry, exist_ok=True)
            self.to_store(data)
            sc = ooc.adopt_chunk_cache(root, key, cfg.ooc_chunk_rows)
            ev({"event": "ooc_cache_write", "path": entry,
                "rows": sc["rows"], "bytes": sc["bytes"]})
            chunk_rows = sc["chunk_rows"]
            schema = ooc.cached_chunk_source(root, key)[0].schema
        else:
            chunk_rows = int(warm[1]["chunk_rows"])
            schema = warm[0].schema

        def producer():
            # fallback after a mid-stream invalidation: re-materialize
            # in memory and slice to chunks (it fit on device anyway)
            t = pdata_to_host(self._materialize())
            return ooc.ChunkSource.from_arrays(
                t, chunk_rows, str_max_len=cfg.string_max_len)

        src = ooc.cache_source(root, key, chunk_rows, schema,
                               make_producer=producer, on_event=ev)
        return self.ctx.from_stream(src)

    def _cache_restream_cluster(self) -> "Dataset":
        """Cluster cache() of streamed / edge-scale data: the gang
        writes the entry's data store in parallel (one writer per
        worker) instead of pinning a dataset-sized resident, and later
        queries stream the store.  Needs a worker-reachable local/shared
        filesystem root (``ooc_cache_dir`` > ``cluster_stream_spool_dir``
        > driver temp — valid for single-machine clusters)."""
        from dryad_tpu.exec import ooc
        cfg = self.ctx.config
        root = cfg.ooc_cache_dir or cfg.cluster_stream_spool_dir
        if root is None:
            root = self.ctx._ooc_cache_root()
        elif "://" in root:
            # remote roots have no sidecar file semantics — fall back
            # to the driver-local temp root (single-machine clusters)
            root = self.ctx._ooc_cache_root()
        else:
            os.makedirs(root, exist_ok=True)
        key = _stable_node_fp(self.node)
        ev = self.ctx._cache_event()
        entry, data, _side = ooc.cache_entry_paths(root, key)
        warm = ooc.cached_chunk_source(root, key)
        if warm is None:
            os.makedirs(entry, exist_ok=True)
            part = self.node.partitioning
            self.ctx._cluster_run(
                self.node, collect=False, store_path=data,
                store_partitioning={"kind": part.kind,
                                    "keys": list(part.keys)})
            sc = ooc.adopt_chunk_cache(root, key, cfg.ooc_chunk_rows)
            ev({"event": "ooc_cache_write", "path": entry,
                "rows": sc["rows"], "bytes": sc["bytes"]})
        else:
            sc = warm[1]
            ev({"event": "ooc_cache_hit", "path": entry,
                "rows": sc.get("rows"), "bytes": sc.get("bytes")})
        return self.ctx.read_store_stream(
            data, chunk_rows=int(sc["chunk_rows"]))

    # -- terminals ---------------------------------------------------------

    def _streaming(self) -> bool:
        from dryad_tpu.exec.stream_exec import StreamSource
        return any(isinstance(n, E.Source)
                   and isinstance(n.data, StreamSource)
                   for n in E.walk(self.node))

    def _stream_run(self):
        """Plan with ONE logical partition and execute over chunk streams
        (exec/stream_exec.py); returns the lazy output ChunkSource."""
        from dryad_tpu.exec.stream_exec import run_stream_graph
        graph = plan_query(self.node, 1, hosts=1, config=self.ctx.config)
        self.ctx._pre_submit_lint(self.node, cluster=False, graph=graph)
        return run_stream_graph(graph, self.ctx.config,
                                spill_dir=self.ctx.spill_dir,
                                event_log=self.ctx.executor._event
                                if self.ctx.executor else None)

    def _materialize(self) -> PData:
        graph = plan_query(self.node, self.ctx.nparts,
                           hosts=self.ctx.hosts,
                           levels=self.ctx.levels,
                           config=self.ctx.config)
        cost_rep = self.ctx._pre_submit_lint(self.node, cluster=False,
                                             graph=graph)
        pd = self.ctx.executor.run(graph, spill_dir=self.ctx.spill_dir,
                                   cost_report=cost_rep)
        # runtime hot-key salting — and adaptive broadcast flips
        # (dryad_tpu/adapt) — change the OUTPUT PLACEMENT: any
        # partitioning claim persisted from this materialization
        # (cache/to_store) must drop or a later shuffle-elided read
        # would silently mis-group
        self._last_salted = (any(st._salted for st in graph.stages)
                             or getattr(self.ctx.executor,
                                        "_last_run_placement_changed",
                                        False))
        return pd

    def collect(self) -> Dict[str, Any]:
        """Execute and pull all rows to host (Submit + read output)."""
        if self.ctx.local_debug:
            return _oracle.run_oracle(self.node)
        if self.ctx.cluster is not None:
            out = self.ctx._cluster_run(self.node)
        elif self._streaming():
            from dryad_tpu.exec.stream_exec import chunks_to_table
            out = chunks_to_table(self._stream_run())
        else:
            from dryad_tpu.exec.data import maybe_shrink_for_collect
            out = pdata_to_host(
                maybe_shrink_for_collect(self._materialize(),
                                         config=self.ctx.config))
        if isinstance(self.node, E.Take):
            n = self.node.n
            out = {k: v[:n] for k, v in out.items()}
        return out

    def to_store(self, path: str, compression: str | None = None) -> None:
        """Execute and persist (ToStore + Submit,
        DryadLinqQueryable.cs:3909,4032).  ``compression="gzip"`` enables
        the per-partition compression transform (reference
        GzipCompressionChannelTransform.cpp)."""
        from dryad_tpu.io.store import write_store
        part = self.node.partitioning
        if compression is None:
            compression = self.ctx.config.store_compression
        if compression not in (None, "gzip"):
            raise ValueError(f"unknown compression {compression!r}")
        if self.ctx.cluster is not None:
            # parallel output: every worker writes its own partitions
            # (compression included); process 0 merges meta + commits
            self.ctx._cluster_run(
                self.node, collect=False, store_path=path,
                store_partitioning={"kind": part.kind,
                                    "keys": list(part.keys)},
                store_compression=compression)
            return
        if self._streaming():
            from dryad_tpu.exec.ooc import write_chunks_to_store
            cs = self._stream_run()
            write_chunks_to_store(
                path, iter(cs), cs.schema,
                partitioning={"kind": part.kind, "keys": list(part.keys)},
                compression=compression)
            return
        pd = self._materialize()
        if getattr(self, "_last_salted", False):
            part = E.Partitioning.none()
        write_store(path, pd, partitioning={"kind": part.kind,
                                            "keys": list(part.keys)},
                    compression=compression)

    def count(self) -> int:
        if self.ctx.local_debug:
            t = _oracle.run_oracle(self.node)
            for v in t.values():
                return len(v)
            return 0
        if self.ctx.cluster is not None:
            # counts-only reduction: no row data crosses the control plane
            return self.ctx._cluster_run(self.node, collect="count")
        if self._streaming():
            return sum(c.n for c in self._stream_run())
        return self._materialize().total_rows()

    def _scalar(self, kind: str, column: str):
        """Terminal scalar aggregate (Count/Sum/Min/Max/Average/Any/All,
        DryadLinqQueryable.cs *AsQuery aggregates): per-partition partials
        on device, combined host-side."""
        import numpy as np

        from dryad_tpu import oracle as orc
        if self.ctx.local_debug:
            t = _oracle.run_oracle(self.node)
            return orc._agg(kind, list(t[column]))
        if self.ctx.cluster is not None:
            # ship a const-key group-by so only ONE aggregated row crosses
            # the control plane (not the whole table)
            const = self.select(_add_agg_key, label="agg-key")
            agg_node = E.GroupByAgg(parents=(const.node,),
                                    keys=("__agg_key",),
                                    aggs={"out": (kind, column)})
            t = self.ctx._cluster_run(agg_node)
            v = np.asarray(t["out"])
            return v[0] if v.shape and v.shape[0] == 1 else v
        if self._streaming():
            from dryad_tpu.exec.stream_exec import stream_scalar
            return stream_scalar(self._stream_run(), kind, column)
        pd = self._materialize()
        import jax
        import jax.numpy as jnp

        from dryad_tpu.ops.kernels import scalar_aggregate

        @jax.jit
        def partials(batch):
            return jax.vmap(lambda b: scalar_aggregate(
                b, {"out": (kind, column), "cnt": ("count", None)}))(batch)

        out = partials(pd.batch)
        vals = np.asarray(out["out"])
        cnts = np.asarray(out["cnt"])
        nonempty = cnts > 0
        if kind == "sum":
            return vals.sum(axis=0)
        if kind == "min":
            return vals[nonempty].min(axis=0) if nonempty.any() else None
        if kind == "max":
            return vals[nonempty].max(axis=0) if nonempty.any() else None
        if kind == "mean":
            total = cnts.sum()
            if total == 0:
                return None
            w = (vals.T * cnts).T.sum(axis=0) / total
            return w
        if kind == "any":
            return bool(vals[nonempty].any())
        if kind == "all":
            return bool(vals[nonempty].all()) if nonempty.any() else True
        raise ValueError(kind)

    def sum(self, column: str):
        return self._scalar("sum", column)

    def min(self, column: str):
        return self._scalar("min", column)

    def max(self, column: str):
        return self._scalar("max", column)

    def mean(self, column: str):
        return self._scalar("mean", column)

    def any(self, column: str) -> bool:
        return self._scalar("any", column)

    def all(self, column: str) -> bool:
        return self._scalar("all", column)

    def first(self) -> Dict[str, Any]:
        t = self.take(1).collect()
        return {k: v[0] for k, v in t.items()}

    # -- static analysis ---------------------------------------------------

    def check(self, cluster: Optional[bool] = None,
              cost: bool = False):
        """Statically verify this query — plan rules + UDF determinism/
        shippability lint — WITHOUT executing anything (the reference's
        phase-1 validation, DryadLinqQueryGen.cs, as a user call).
        Returns an ``analysis.DiagnosticReport`` with every finding at
        once (stable DTA0xx/DTA1xx codes, source spans).  ``cluster``
        forces the cluster-shipping rules on/off; default: whether this
        Context targets a cluster.  ``cost=True`` adds the DTA2xx
        resource findings (analysis/cost.py abstract interpretation —
        still zero execution: schemas propagate via jax.eval_shape)."""
        from dryad_tpu.analysis import check_plan
        if cluster is None:
            cluster = self.ctx.cluster is not None
        report = check_plan(self.node, cluster=cluster,
                            fn_table=self.ctx.fn_table)
        if cost:
            from dryad_tpu.analysis.cost import (cost_diagnostics,
                                                 estimate_query)
            rep = estimate_query(self.node, self.ctx.nparts,
                                 hosts=self.ctx.hosts,
                                 levels=self.ctx.levels,
                                 config=self.ctx.config)
            report.diagnostics.extend(
                cost_diagnostics(rep, self.ctx.config))
            report.dedup()
        return report

    def cost(self):
        """The static cost pass alone: a machine-readable
        ``analysis.cost.CostReport`` (per-stage row intervals, exact
        byte predictions, per-device working-set bounds) for the plan
        this query would execute.  Zero execution."""
        from dryad_tpu.analysis.cost import estimate_query
        return estimate_query(self.node, self.ctx.nparts,
                              hosts=self.ctx.hosts,
                              levels=self.ctx.levels,
                              config=self.ctx.config)

    def analyze(self):
        """EXPLAIN ANALYZE: execute this query ONCE under an explicit
        event capture and return the measured per-stage actuals
        annotated against the static cost model
        (:class:`~dryad_tpu.obs.analyze.AnalyzeReport` — rows, output
        bytes, wall/compile split, retries/replays/spills, compile-cache
        hits, adaptive rewrites, and predicted-vs-actual deltas with the
        runtime cross-check's ``cost_model_miss`` verdicts inline).

        The capture is an explicit opt-in consumer (its own
        ``EventLog(level=2)``), independent of ``DRYAD_LOGGING_LEVEL``
        — asking for ANALYZE *is* asking for the telemetry.  The
        pre-submit lint gate applies exactly as in ``collect()`` (a
        plan ``lint="error"`` refuses to submit raises LintError here
        too — ANALYZE executes, so it must not bypass the gate); the
        cost pass itself still runs under ``lint="off"`` and can never
        block the run (on failure the report simply carries no
        predictions).
        In-process mesh execution only — cluster/local_debug/streamed
        runs record their streams to JSONL, which ``python -m
        dryad_tpu.obs analyze`` annotates post-hoc."""
        if self.ctx.local_debug or self.ctx.executor is None:
            raise ValueError(
                "EXPLAIN ANALYZE needs an in-process mesh Context "
                "(local_debug and cluster contexts do not execute "
                "through the instrumented executor — record a JSONL "
                "and use `python -m dryad_tpu.obs analyze` instead)")
        if self._streaming():
            raise ValueError(
                "EXPLAIN ANALYZE does not cover streamed (>RAM) plans "
                "— per-stage HBM actuals do not apply; use `python -m "
                "dryad_tpu.obs analyze` over the recorded stream")
        from dryad_tpu.obs.analyze import analyze_events
        from dryad_tpu.utils.events import EventLog
        graph = plan_query(self.node, self.ctx.nparts,
                           hosts=self.ctx.hosts, levels=self.ctx.levels,
                           config=self.ctx.config)
        # the SAME gate _materialize runs: lint="error" findings refuse
        # to submit (LintError), "warn" logs them to the attached
        # context log, and the gate's cost pass feeds the annotation
        cost_rep = self.ctx._pre_submit_lint(self.node, cluster=False,
                                             graph=graph)
        cap = EventLog(level=2)
        if cost_rep is None:
            # lint="off" (or the gate's cost pass failed): ANALYZE
            # still wants predictions, but the model must never block
            # it — on failure the report carries actuals only
            try:
                from dryad_tpu.analysis.cost import estimate_graph
                cost_rep = estimate_graph(graph, self.ctx.nparts,
                                          config=self.ctx.config)
            except Exception:
                cost_rep = None
        if cost_rep is not None:
            cap({"event": "cost_report",
                 "report": cost_rep.to_payload()})
        self.ctx.executor.run(graph, spill_dir=self.ctx.spill_dir,
                              cost_report=cost_rep, event_log=cap)
        cap.close()
        rep = analyze_events(cap.events)
        if self.ctx._event_log is not None:
            # the annotation is job telemetry too: a context with a
            # JSONL attached records the machine-readable report
            self.ctx._event_log({"event": "analyze_report",
                                 "report": rep.to_payload()})
        return rep

    def explain(self, verify: bool = False, cost: bool = False,
                analyze: bool = False) -> str:
        text = plan_query(self.node, self.ctx.nparts,
                          hosts=self.ctx.hosts,
                          levels=self.ctx.levels,
                          config=self.ctx.config).explain()
        cost_rep = self.cost() if cost else None
        if verify:
            # the ONE cost pass feeds both sections: the diagnostics
            # include the DTA2xx resource findings, so an EXPLAIN COST
            # on a provably >HBM plan SHOWS its DTA201 rejection
            report = self.check()
            if cost_rep is not None:
                from dryad_tpu.analysis.cost import cost_diagnostics
                report.diagnostics.extend(
                    cost_diagnostics(cost_rep, self.ctx.config))
                report.dedup()
            text += "\n\ndiagnostics:\n" + report.render()
        if cost_rep is not None:
            text += "\n\npredicted cost:\n" + cost_rep.render()
        if analyze:
            # EXPLAIN ANALYZE: the plan above, then what actually
            # happened when it ran (measured actuals vs the model)
            text += ("\n\nEXPLAIN ANALYZE (executed):\n"
                     + self.analyze().render())
        return text
