from dryad_tpu.api.dataset import Context, Dataset  # noqa: F401
