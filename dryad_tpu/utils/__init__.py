from dryad_tpu.utils.events import EventLog, job_report  # noqa: F401
