"""Self-contained HTML job viewer — the JobBrowser role at 1% of the size.

The reference ships a 25 kLoC WinForms JobBrowser (JobBrowser/JOM/
jobinfo.cs: DAG drawing, per-stage Gantt, diagnosis from the Calypso
stream, live refresh).  Here the same views render from the EventLog
into ONE static HTML file with inline SVG — no dependencies:

* stage DAG (topological layers, status-ringed nodes for retries/replays)
* per-run Gantt (time from job start, overflow attempts marked)
* per-stage table (runs, retries, replays, scale, slack, wall time)
* FAILURE DIAGNOSIS (JobBrowser/Diagnosis.cs:929 role): worker errors,
  wedged-gang watchdog verdicts, replay history, worker log tails —
  rendered from the structured job_failed / worker_wedged /
  worker_failed / stage_replay events the runtime emits

LIVE VIEW (jobinfo.cs live model role): ``python -m
dryad_tpu.utils.viewer events.jsonl --serve 8123`` serves the report
re-rendered from the JSONL stream on every refresh (EventLog flushes
per event), auto-refreshing every 2 s.
"""

from __future__ import annotations

import html
import json
from typing import Any, Dict, List, Optional

__all__ = ["job_report_html", "diagnose", "serve_live", "serve_history"]

# palette roles (light, dark) — single accent series + reserved status hues
_ROLES = {
    "surface": ("#fcfcfb", "#1a1a19"),
    "ink": ("#0b0b0b", "#ffffff"),
    "ink2": ("#52514e", "#c3c2b7"),
    "grid": ("#e4e3df", "#33332f"),
    "series": ("#2a78d6", "#3987e5"),
    "warning": ("#fab219", "#fab219"),
    "critical": ("#d03b3b", "#d03b3b"),
}


def _stage_deps_from_plan(plan_json: str) -> Dict[int, List[int]]:
    d = json.loads(plan_json)
    deps: Dict[int, List[int]] = {}
    for st in d["stages"]:
        deps[st["id"]] = [leg["src"]["stage"] for leg in st["legs"]
                          if isinstance(leg["src"], dict)
                          and "stage" in leg["src"]]
    return deps


def _layers(deps: Dict[int, List[int]]) -> Dict[int, int]:
    """Longest-path layering (topological depth)."""
    depth: Dict[int, int] = {}

    def d(sid: int) -> int:
        if sid not in depth:
            depth[sid] = 0  # break cycles defensively
            depth[sid] = 1 + max((d(p) for p in deps.get(sid, [])
                                  if p in deps), default=-1)
        return depth[sid]

    for sid in deps:
        d(sid)
    return depth


def _collect_stages(events) -> Dict[int, Dict[str, Any]]:
    stages: Dict[int, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") not in ("stage_done", "stage_replay",
                                  "stage_restored", "stage_spilled"):
            continue
        sid = e.get("stage")
        s = stages.setdefault(sid, {
            "label": e.get("label", f"stage {sid}"), "runs": [],
            "retries": 0, "replays": 0, "scale": 1, "slack": 2,
            "wall_s": 0.0, "compile_s": 0.0, "rows": 0, "out_bytes": 0})
        if e.get("label"):
            s["label"] = e["label"]
        if e["event"] == "stage_done":
            wall = float(e.get("wall_s", 0.0))
            end = float(e.get("ts", 0.0))
            s["runs"].append({"start": end - wall, "end": end,
                              "overflow": bool(e.get("overflow")),
                              "scale": e.get("scale", 1),
                              "attempt": e.get("attempt", 0),
                              "slack": e.get("slack"),
                              "need_scale": e.get("need_scale", 0),
                              "need_slack": e.get("need_slack", 0),
                              "salted": e.get("salted", False),
                              "deferred": bool(e.get("deferred")),
                              "dispatches": e.get("dispatches"),
                              "compile_s": e.get("compile_s", 0.0)})
            s["wall_s"] += wall
            s["compile_s"] += float(e.get("compile_s", 0.0))
            if e.get("rows") is not None:
                s["rows"] = int(sum(e["rows"]))
            if e.get("out_bytes"):
                s["out_bytes"] = int(e["out_bytes"])
            s["scale"] = max(s["scale"], e.get("scale", 1))
            s["slack"] = max(s["slack"], e.get("slack", 2))
            if e.get("overflow"):
                s["retries"] += 1
        elif e["event"] == "stage_replay":
            s["replays"] += 1
    return stages


def _svg_dag(stages, deps, order) -> str:
    if not deps:
        deps = {sid: [] for sid in order}
    depth = _layers(deps)
    cols: Dict[int, List[int]] = {}
    for sid in order:
        cols.setdefault(depth.get(sid, 0), []).append(sid)
    ncols = max(cols) + 1 if cols else 1
    nrows = max(len(v) for v in cols.values()) if cols else 1
    W, H = 170, 64
    width, height = ncols * W + 30, nrows * H + 20
    pos = {}
    for c, sids in cols.items():
        for r, sid in enumerate(sids):
            pos[sid] = (20 + c * W, 14 + r * H)
    parts = [f'<svg role="img" aria-label="stage DAG" width="{width}" '
             f'height="{height}" viewBox="0 0 {width} {height}">']
    for sid, ps in deps.items():
        if sid not in pos:
            continue
        x2, y2 = pos[sid]
        for p in ps:
            if p not in pos:
                continue
            x1, y1 = pos[p]
            parts.append(
                f'<line x1="{x1 + 128}" y1="{y1 + 19}" x2="{x2}" '
                f'y2="{y2 + 19}" stroke="var(--grid)" stroke-width="2"/>')
    for sid in order:
        if sid not in pos:
            continue
        x, y = pos[sid]
        s = stages[sid]
        ring = ""
        badge = ""
        if s["replays"]:
            ring = ' stroke="var(--critical)" stroke-width="2"'
            badge = "&#8635; replayed"       # color never alone: icon+word
        elif s["retries"]:
            ring = ' stroke="var(--warning)" stroke-width="2"'
            badge = "&#9888; retried"
        label = html.escape(str(s["label"]))[:18]
        parts.append(
            f'<a href="#stage-{sid}">'
            f'<g><rect x="{x}" y="{y}" rx="6" width="128" height="38" '
            f'fill="var(--node)"{ring}/>'
            f'<title>stage {sid} {label}: {len(s["runs"])} run(s), '
            f'{s["retries"]} retries, {s["replays"]} replays, '
            f'{s["wall_s"]:.3f}s</title>'
            f'<text x="{x + 8}" y="{y + 16}" class="t1">{sid} '
            f'{label}</text>'
            f'<text x="{x + 8}" y="{y + 31}" class="t2">'
            f'{s["wall_s"]:.2f}s {badge}</text></g></a>')
    parts.append("</svg>")
    return "".join(parts)


def _svg_gantt(stages, order) -> str:
    runs = [(sid, r) for sid in order for r in stages[sid]["runs"]]
    if not runs:
        return "<p>no stage runs recorded</p>"
    t0 = min(r["start"] for _, r in runs)
    t1 = max(r["end"] for _, r in runs)
    span = max(t1 - t0, 1e-6)
    LABEL, BAR, ROW = 150, 560, 26
    height = len(runs) * ROW + 34
    width = LABEL + BAR + 90
    parts = [f'<svg role="img" aria-label="stage Gantt" width="{width}" '
             f'height="{height}" viewBox="0 0 {width} {height}">']
    for frac in (0.0, 0.25, 0.5, 0.75, 1.0):   # recessive time grid
        x = LABEL + frac * BAR
        parts.append(f'<line x1="{x}" y1="8" x2="{x}" '
                     f'y2="{height - 26}" stroke="var(--grid)"/>'
                     f'<text x="{x}" y="{height - 10}" class="t2" '
                     f'text-anchor="middle">{frac * span:.2f}s</text>')
    for i, (sid, r) in enumerate(runs):
        y = 10 + i * ROW
        x = LABEL + (r["start"] - t0) / span * BAR
        w = max((r["end"] - r["start"]) / span * BAR, 2)
        s = stages[sid]
        fill = "var(--warning)" if r["overflow"] else "var(--series)"
        note = " (overflow &#9888;)" if r["overflow"] else ""
        label = html.escape(str(s["label"]))[:20]
        parts.append(
            f'<g class="bar"><text x="{LABEL - 8}" y="{y + 13}" '
            f'class="t1" text-anchor="end">{sid} {label}</text>'
            f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" height="16" '
            f'rx="4" fill="{fill}"/>'
            f'<title>stage {sid} {label}: '
            f'{r["end"] - r["start"]:.3f}s at scale {r["scale"]}'
            f'{note}</title>'
            f'<text x="{x + w + 6:.1f}" y="{y + 13}" class="t2">'
            f'{r["end"] - r["start"]:.3f}s{note}</text></g>')
    parts.append("</svg>")
    return "".join(parts)


def _table(stages, order) -> str:
    head = ("<tr><th>stage</th><th>label</th><th>runs</th><th>retries</th>"
            "<th>replays</th><th>scale</th><th>slack</th>"
            "<th>rows</th><th>out&nbsp;MiB</th><th>compile&nbsp;s</th>"
            "<th>wall&nbsp;s</th></tr>")
    rows = []
    for sid in order:
        s = stages[sid]
        rows.append(
            f"<tr><td><a href='#stage-{sid}'>{sid}</a></td>"
            f"<td>{html.escape(str(s['label']))}</td>"
            f"<td>{len(s['runs'])}</td><td>{s['retries']}</td>"
            f"<td>{s['replays']}</td><td>{s['scale']}</td>"
            f"<td>{s['slack']}</td><td>{s['rows']}</td>"
            f"<td>{s['out_bytes'] / (1 << 20):.1f}</td>"
            f"<td>{s['compile_s']:.3f}</td>"
            f"<td>{s['wall_s']:.3f}</td></tr>")
    return f"<table>{head}{''.join(rows)}</table>"


def _stage_details(stages, order, events) -> str:
    """Per-stage drill-down (the JobBrowser vertex view role,
    JobBrowser/JOM/jobinfo.cs:3539): attempt history with the capacity
    knobs, measured needs, dispatch counts and compile/run split, plus
    this stage's replay records — every DAG node and table row links
    here."""
    replays: Dict[int, List[dict]] = {}
    for e in events:
        if e.get("event") in ("stage_replay", "stage_restored",
                              "stage_spilled", "settle_replay"):
            if e.get("event") == "settle_replay":
                for sid in e.get("stages", ()):
                    replays.setdefault(sid, []).append(e)
            else:
                replays.setdefault(e.get("stage"), []).append(e)
    blocks = []
    for sid in order:
        s = stages[sid]
        rows = []
        for r in s["runs"]:
            flags = []
            if r.get("deferred"):
                flags.append("deferred")
            if r.get("salted"):
                flags.append("salted")
            if r.get("overflow"):
                flags.append("&#9888; overflow")
            rows.append(
                f"<tr><td>{r.get('attempt', 0)}</td>"
                f"<td>{r.get('scale', 1)}</td>"
                f"<td>{r.get('slack') if r.get('slack') is not None else ''}</td>"
                f"<td>{r.get('need_scale') or 0}/"
                f"{r.get('need_slack') or 0}</td>"
                f"<td>{r.get('dispatches') if r.get('dispatches') is not None else ''}</td>"
                f"<td>{r.get('compile_s') or 0:.3f}</td>"
                f"<td>{r['end'] - r['start']:.3f}</td>"
                f"<td>{' '.join(flags)}</td></tr>")
        rep = "".join(
            f"<li>{html.escape(e.get('event', ''))} "
            f"(failures so far: {e.get('failures', '?')})</li>"
            for e in replays.get(sid, ()))
        rep_html = f"<ul>{rep}</ul>" if rep else ""
        blocks.append(
            f'<details id="stage-{sid}" class="stage">'
            f'<summary>stage {sid} — '
            f'{html.escape(str(s["label"]))}: {len(s["runs"])} attempt(s),'
            f' {s["replays"]} replay(s), {s["wall_s"]:.3f}s</summary>'
            f'<table><tr><th>attempt</th><th>scale</th><th>slack</th>'
            f'<th>need&nbsp;scale/slack</th><th>dispatches</th>'
            f'<th>compile&nbsp;s</th><th>wall&nbsp;s</th><th>flags</th>'
            f'</tr>{"".join(rows)}</table>{rep_html}</details>')
    return ("<h2>Stage drill-down</h2>" + "".join(blocks)) if blocks         else ""


def diagnose(events) -> List[Dict[str, Any]]:
    """Failure-diagnosis records from the event stream: what failed,
    where, why, and what the runtime did about it (replay/teardown) —
    plus the Artemis-style sibling-relative findings (data skew, slow
    workers, obs/profile.diagnose_events) and forensics-bundle
    breadcrumbs (task_forensics, obs/flight.py)."""
    from dryad_tpu.utils.events import EventLog
    if isinstance(events, EventLog):
        events = events.events
    events = list(events)
    out: List[Dict[str, Any]] = []
    for e in events:
        k = e.get("event")
        if k == "job_failed":
            first = (e.get("error") or "").strip().splitlines()
            out.append({
                "kind": "worker error", "workers": e.get("workers"),
                "headline": first[-1] if first else "(no message)",
                "detail": e.get("error", ""),
                "log_tails": e.get("log_tails", "")})
        elif k == "worker_wedged":
            out.append({
                "kind": "wedged gang member",
                "workers": e.get("workers"),
                "headline": f"{e.get('why', '')} — gang torn down for "
                            f"replay", "detail": "",
                "log_tails": e.get("log_tails", "")})
        elif k == "worker_failed":
            out.append({"kind": "worker process death",
                        "workers": [e.get("worker")],
                        "headline": e.get("error", "process exited"),
                        "detail": "",
                        "log_tails": e.get("log_tails", "")})
        elif k == "stage_replay":
            out.append({"kind": "stage replay",
                        "workers": None, "stage": e.get("stage"),
                        "headline": f"stage {e.get('stage')} replayed "
                                    f"(attempt {e.get('attempt', '?')})",
                        "detail": "", "log_tails": ""})
        elif k == "regression_suspect":
            # archive-time regression watch (obs/history.py): this run
            # measured past the app's history baseline
            out.append({
                "kind": "perf regression", "workers": None,
                "headline": f"{e.get('what')} "
                            f"{e.get('measured')} is "
                            f"{e.get('ratio')}x the baseline median "
                            f"{e.get('baseline_median')} over "
                            f"{e.get('baseline_runs')} prior run(s) "
                            f"of {e.get('app')}",
                "detail": "", "log_tails": ""})
        elif k == "task_forensics":
            out.append({
                "kind": "forensics bundle",
                "workers": ([e.get("worker")]
                            if e.get("worker") is not None else None),
                "headline": f"{e.get('error_type', 'failure')}: "
                            f"{e.get('error', '')} — reproduce with "
                            f"python -m dryad_tpu.obs replay "
                            f"{e.get('path', '<bundle>')}",
                "detail": "", "log_tails": ""})
    from dryad_tpu.obs.profile import diagnose_events
    for e in diagnose_events(events):
        if e["event"] == "diagnosis_skew":
            out.append({
                "kind": "data skew", "workers": None,
                "stage": e.get("stage"),
                "headline": f"stage {e.get('stage')} "
                            f"({e.get('label')}): partition "
                            f"{e.get('partition')} holds "
                            f"{e.get('ratio')}x the rows/bytes of its "
                            f"sibling median ({e.get('rows_max')} vs "
                            f"{e.get('rows_sibling_median')})",
                "detail": "", "log_tails": ""})
        elif e["event"] == "diagnosis_slow_worker":
            out.append({
                "kind": "slow worker", "workers": [e.get("worker")],
                "headline": f"worker {e.get('worker')} averaged "
                            f"{e.get('mean_s')}s/task over "
                            f"{e.get('tasks')} task(s) — "
                            f"{e.get('ratio')}x its siblings' median "
                            f"({e.get('sibling_median_s')}s)",
                "detail": "", "log_tails": ""})
    return out


def _lint_html(events) -> str:
    """Static-analysis findings ("lint_finding" events, emitted by the
    JobConfig.lint pre-submit gate in api/dataset.py) as a Diagnostics
    section — present only when the stream carries findings."""
    recs = [e for e in events if e.get("event") == "lint_finding"]
    if not recs:
        return ""
    sev_rank = {"error": 0, "warn": 1, "info": 2}
    icon = {"error": "&#10006; error", "warn": "&#9888; warn",
            "info": "&#8505; info"}
    rows = []
    for e in sorted(recs, key=lambda e: (sev_rank.get(e.get("severity"),
                                                      3),
                                         e.get("code", ""))):
        sev = e.get("severity", "info")
        cls = ("critical" if sev == "error"
               else "warning" if sev == "warn" else "ink2")
        rows.append(
            f'<tr><td style="color: var(--{cls})">'
            f'{icon.get(sev, sev)}</td>'
            f'<td>{html.escape(str(e.get("code", "")))}</td>'
            f'<td>{html.escape(str(e.get("message", "")))}</td>'
            f'<td>{html.escape(str(e.get("span") or ""))}</td></tr>')
    head = ("<tr><th>severity</th><th>code</th><th>finding</th>"
            "<th>source</th></tr>")
    return ("<h2>Diagnostics (static analysis)</h2>"
            f"<table class='lint'>{head}{''.join(rows)}</table>")


def _cost_html(events) -> str:
    """"Predicted cost" section: the pre-submit static cost analysis
    (``cost_report`` events, analysis/cost.py via the JobConfig.lint
    gate) as a per-stage table, plus any runtime ``cost_model_miss``
    cross-check verdicts — present only when a cost pass ran."""
    reps = [e for e in events if e.get("event") == "cost_report"]
    if not reps:
        return ""
    from dryad_tpu.analysis.cost import CostReport
    from dryad_tpu.analysis.domain import fmt_bytes
    try:
        rep = CostReport.from_payload(reps[-1]["report"])
    except Exception:
        return ""
    if rep.streamed:
        body = ("<p>streamed plan: device working set is "
                "O(chunk_rows) — the HBM cost model does not apply</p>")
    else:
        rows = []
        for s in rep.stages:
            rv = (f"[{s.rows.lo}, {s.rows.hi}]"
                  if s.rows.hi is not None else f"[{s.rows.lo}, ∞)")
            ob = (fmt_bytes(s.out_bytes.hi)
                  if s.out_bytes.hi is not None else "?")
            wk = (fmt_bytes(s.work_bytes.hi)
                  if s.work_bytes.hi is not None else "?")
            rows.append(
                f"<tr><td>{s.stage}</td>"
                f"<td>{html.escape(str(s.label))}</td>"
                f"<td>{s.capacity}</td><td>{html.escape(rv)}</td>"
                f"<td>{ob}</td><td>{wk}</td>"
                f"<td>{'~' if s.approx else ''}</td></tr>")
        pk = rep.peak_work
        budget = (f" / budget {fmt_bytes(rep.device_hbm_bytes)}"
                  if rep.device_hbm_bytes else "")
        body = ("<table><tr><th>stage</th><th>label</th><th>cap</th>"
                "<th>rows</th><th>out bytes</th><th>work/dev</th>"
                "<th>~</th></tr>" + "".join(rows) + "</table>"
                f"<p>peak per-device working set {fmt_bytes(pk.lo)}"
                + (f"..{fmt_bytes(pk.hi)}" if pk.hi is not None
                   else "..?") + budget
                + " &nbsp;(~ = approximate)</p>")
    misses = [e for e in events if e.get("event") == "cost_model_miss"]
    if misses:
        li = "".join(
            f"<li>stage {e.get('stage')} ({html.escape(str(e.get('label', '')))}): "
            f"measured {html.escape(str(e.get('what')))}="
            f"{e.get('measured')} outside predicted "
            f"{html.escape(str(e.get('predicted')))}</li>"
            for e in misses)
        body += (f'<p style="color: var(--warning)">&#9888; '
                 f'{len(misses)} cost-model miss(es) — the static '
                 f'prediction did not contain the measured value:</p>'
                 f"<ul>{li}</ul>")
    else:
        body += ("<p class='ink2'>runtime cross-check: no "
                 "cost-model misses</p>")
    return "<h2>Predicted cost (static analysis)</h2>" + body


def _analyze_html(events) -> str:
    """"EXPLAIN ANALYZE" section (obs/analyze.py): measured per-stage
    actuals against the static cost model's predictions, with the
    runtime cross-check's verdicts inline.  Rendered when the stream
    carries a ``cost_report`` (without one the per-stage table already
    shows the plain actuals)."""
    from dryad_tpu.obs.analyze import analyze_events
    if not any(e.get("event") == "cost_report" for e in events):
        return ""
    rep = analyze_events(events)
    if not rep.stages:
        return ""
    rows = []
    for s in rep.stages:
        if s.pred_rows is None:
            pr = "—"
        else:
            lo, hi = s.pred_rows
            pr = ("~" if s.approx else "") + (
                f"[{lo}, {hi}]" if hi is not None else f"[{lo}, ∞)")
        delta = ("—" if s.bytes_delta_pct is None
                 else f"{s.bytes_delta_pct:+.1f}%")
        dcls = ("warning" if s.bytes_in_bounds is False
                or s.rows_in_bounds is False else "ink2")
        flags = " ".join(
            (["cache"] if s.runs and s.cache_hits == s.runs else [])
            + list(s.rewrites)
            + [f"&#9888; miss: {m}" for m in s.misses])
        rows.append(
            f"<tr><td>{s.stage}</td>"
            f"<td>{html.escape(str(s.label))}</td><td>{s.runs}</td>"
            f"<td>{s.rows}</td><td>{html.escape(pr)}</td>"
            f"<td>{s.out_bytes / (1 << 20):.2f}</td>"
            f'<td style="color: var(--{dcls})">{delta}</td>'
            f"<td>{s.compile_s:.3f}</td><td>{s.wall_s:.3f}</td>"
            f"<td>{s.spills}</td><td>{s.replays}</td>"
            f"<td>{html.escape(flags)}</td></tr>")
    inb = len([s for s in rep.settled if s.bytes_in_bounds])
    cmp_n = len([s for s in rep.settled
                 if s.bytes_in_bounds is not None])
    verdict = (f"<p class='ink2'>predictions contained {inb}/{cmp_n} "
               f"settled stage(s); {rep.misses} cost-model miss(es); "
               f"{rep.rewrites} adaptive rewrite(s)</p>")
    head = ("<tr><th>stage</th><th>label</th><th>runs</th>"
            "<th>rows</th><th>pred rows</th><th>out&nbsp;MiB</th>"
            "<th>Δbytes</th><th>compile&nbsp;s</th><th>wall&nbsp;s</th>"
            "<th>spills</th><th>replays</th><th>flags</th></tr>")
    return ("<h2>EXPLAIN ANALYZE (measured vs predicted)</h2>"
            + verdict + f"<table>{head}{''.join(rows)}</table>")


def _critical_path_html(events) -> str:
    """Critical-path section (the Artemis question): top path segments
    plus the per-stage queue/compile/run/io split, computed from the
    span events (obs/critical_path.py).  Absent when the stream carries
    no timing at all."""
    from dryad_tpu.obs.critical_path import critical_path
    res = critical_path(events)
    if not res["segments"] and not res["per_stage"]:
        return ""
    total = res["total_s"]
    rows = []
    for i, s in enumerate(res["top"][:10], 1):
        pct = 100.0 * s["self_s"] / total if total > 0 else 0.0
        bar = (f'<div style="background: var(--series); height: 10px; '
               f'width: {max(pct, 0.5):.1f}%"></div>')
        rows.append(f"<tr><td>{i}</td>"
                    f"<td>{html.escape(str(s['name']))}</td>"
                    f"<td>{html.escape(str(s['kind']))}</td>"
                    f"<td>{s['self_s']:.3f}</td><td>{pct:.1f}%</td>"
                    f"<td style='min-width: 160px; text-align: left'>"
                    f"{bar}</td></tr>")
    seg_html = ""
    if rows:
        seg_html = (f"<p>total {total:.3f}s across "
                    f"{len(res['segments'])} segment(s)</p>"
                    "<table><tr><th>#</th><th>segment</th><th>kind</th>"
                    "<th>self&nbsp;s</th><th>%</th><th></th></tr>"
                    + "".join(rows) + "</table>")
    brows = []
    for r in res["per_stage"]:
        brows.append(f"<tr><td>{html.escape(str(r['stage']))}</td>"
                     f"<td>{html.escape(str(r['label']))}</td>"
                     f"<td>{r['queue_s']:.3f}</td>"
                     f"<td>{r['compile_s']:.3f}</td>"
                     f"<td>{r['run_s']:.3f}</td>"
                     f"<td>{r['io_s']:.3f}</td></tr>")
    br_html = ""
    if brows:
        br_html = ("<h3>per-stage time (queue / compile / run / io)</h3>"
                   "<table><tr><th>stage</th><th>label</th>"
                   "<th>queue&nbsp;s</th><th>compile&nbsp;s</th>"
                   "<th>run&nbsp;s</th><th>io&nbsp;s</th></tr>"
                   + "".join(brows) + "</table>")
    if not seg_html and not br_html:
        return ""
    return "<h2>Critical path</h2>" + seg_html + br_html


def _diagnosis_html(events) -> str:
    recs = diagnose(events)
    if not recs:
        return ""
    blocks = []
    for r in recs:
        who = (f" — worker(s) {r['workers']}" if r.get("workers") else "")
        body = ""
        if r["detail"]:
            body += (f"<details><summary>traceback</summary>"
                     f"<pre>{html.escape(r['detail'])}</pre></details>")
        if r["log_tails"]:
            body += (f"<details><summary>worker log tails</summary>"
                     f"<pre>{html.escape(r['log_tails'])}</pre></details>")
        link = (f' <a href="#stage-{r["stage"]}">&#8594; stage '
                f'{r["stage"]}</a>'
                if r.get("stage") is not None else "")
        blocks.append(
            f'<div class="diag"><b>{html.escape(r["kind"])}</b>'
            f'{html.escape(who)}<div class="hl">'
            f'{html.escape(r["headline"])}{link}</div>{body}</div>')
    return "<h2>Diagnosis</h2>" + "".join(blocks)


def _adaptive_html(events) -> str:
    """"Adaptive rewrites" section: one row per applied graph_rewrite
    (dryad_tpu/adapt), with the before/after stage topology behind a
    disclosure — the JobBrowser's dynamic-manager decisions view."""
    rewrites = [e for e in events if e.get("event") == "graph_rewrite"]
    skipped = [e for e in events if e.get("event") == "adapt_skipped"]
    if not rewrites and not skipped:
        return ""
    rows = []
    for e in rewrites:
        topo = json.dumps({"before": e.get("before"),
                            "after": e.get("after")}, indent=1)
        detail = {k: v for k, v in e.items()
                  if k not in ("event", "rule", "kind", "stage",
                               "trigger_stage", "before", "after", "ts",
                               "worker")}
        rows.append(
            f"<tr><td>{html.escape(str(e.get('rule', '?')))}</td>"
            f"<td>{html.escape(str(e.get('kind', '?')))}</td>"
            f"<td>{e.get('stage', '?')}</td>"
            f"<td>{e.get('trigger_stage', '?')}</td>"
            f"<td>{html.escape(json.dumps(detail))}</td>"
            f"<td><details><summary>topology</summary>"
            f"<pre>{html.escape(topo)}</pre></details></td></tr>")
    out = ("<h2>Adaptive rewrites</h2>"
           "<table class='lint'><tr><th>rule</th><th>kind</th>"
           "<th>stage</th><th>trigger</th><th>detail</th>"
           "<th>before &#8594; after</th></tr>"
           + "".join(rows) + "</table>") if rows else ""
    if skipped:
        li = "".join(
            f"<li><b>{html.escape(str(e.get('rule', '?')))}</b> "
            f"stage {e.get('stage', '?')}: "
            f"{html.escape(str(e.get('reason', '')))}</li>"
            for e in skipped)
        out += (f"<details><summary>{len(skipped)} declined "
                f"rewrite(s)</summary><ul>{li}</ul></details>")
    return out


_PHASE_COLORS = {"precheck": "#8da0cb", "bind": "#66c2a5",
                 "cache_lookup": "#a6d854", "queue": "#fc8d62",
                 "dispatch": "#ffd92f", "compile": "#e78ac3",
                 "run": "#4c78a8", "fetch": "#b3b3b3"}


def _latency_html(events) -> str:
    """"Latency waterfall" section: one stacked bar per recorded
    ``latency_waterfall`` (obs/latency.py) — the request's
    submit→result wall partitioned into phases — plus the per-tenant
    percentile/attribution table re-derived from the same records."""
    wfs = [e for e in events if e.get("event") == "latency_waterfall"]
    if not wfs:
        return ""
    from dryad_tpu.obs.latency import latency_from_events
    bars = []
    for wf in wfs[:20]:
        wall_us = max(1, int(wf.get("wall_us") or 0))
        segs = "".join(
            f'<div title="{html.escape(str(p.get("phase", "?")))}: '
            f'{int(p.get("us") or 0) / 1e6:.4f}s" style="background: '
            f'{_PHASE_COLORS.get(p.get("phase"), "#999")}; '
            f'width: {100.0 * int(p.get("us") or 0) / wall_us:.2f}%; '
            f'height: 14px"></div>'
            for p in wf.get("phases") or [])
        bars.append(
            f'<div style="margin: 4px 0">'
            f'<span style="color: var(--ink2); font-size: 12px">'
            f'{html.escape(str(wf.get("job", "?")))} '
            f'({html.escape(str(wf.get("tenant", "?")))}) '
            f'{wf.get("wall_s")}s</span>'
            f'<div style="display: flex; width: 480px; border: 1px '
            f'solid var(--grid); border-radius: 4px; overflow: hidden">'
            f'{segs}</div></div>')
    legend = " ".join(
        f'<span style="white-space: nowrap"><span style="display: '
        f'inline-block; width: 10px; height: 10px; background: {c}">'
        f'</span> {p}</span>' for p, c in _PHASE_COLORS.items())
    rows = []
    for tenant, r in latency_from_events(events).snapshot().items():
        ex = r.get("exemplar") or {}
        rows.append(
            f"<tr><td>{html.escape(tenant)}</td><td>{r['count']}</td>"
            f"<td>{r['p50_s']:.3f}</td><td>{r['p95_s']:.3f}</td>"
            f"<td>{r['p99_s']:.3f}</td>"
            f"<td>{html.escape(str(r['dominant'] or '—'))}</td>"
            f"<td>{html.escape(str(ex.get('job') or '—'))}</td></tr>")
    return ("<h2>Latency waterfall</h2>"
            f'<div style="color: var(--ink2); font-size: 12px">'
            f"{legend}</div>" + "".join(bars)
            + "<table><tr><th>tenant</th><th>n</th><th>p50&nbsp;s</th>"
              "<th>p95&nbsp;s</th><th>p99&nbsp;s</th><th>dominant</th>"
              "<th>slowest</th></tr>" + "".join(rows) + "</table>")


def job_report_html(events, plan_json: Optional[str] = None,
                    path: Optional[str] = None, title: str = "dryad job",
                    live_refresh_s: Optional[float] = None) -> str:
    """Render the event stream as a self-contained HTML report; optionally
    write it to ``path``.  ``plan_json`` (plan/serialize.graph_to_json)
    adds real DAG edges; without it stages are laid out flat."""
    from dryad_tpu.utils.events import EventLog
    if isinstance(events, EventLog):
        events = events.events
    stages = _collect_stages(events)
    order = sorted(stages)
    deps: Dict[int, List[int]] = {}
    # DAG edges come from the executed plans recorded in the event stream
    # (exec/recovery.py emits one "plan" event per run); an explicitly
    # passed plan_json is merged on top
    for e in events:
        if e.get("event") == "plan" and e.get("plan"):
            deps.update(_stage_deps_from_plan(e["plan"]))
    if plan_json:
        deps.update(_stage_deps_from_plan(plan_json))
    total_wall = sum(s["wall_s"] for s in stages.values())
    retries = sum(s["retries"] for s in stages.values())
    replays = sum(s["replays"] for s in stages.values())
    tasks = [e for e in events if e.get("event") == "task_done"]
    dups = [e for e in events if e.get("event") == "task_duplicated"]

    def roles(mode: int) -> str:
        extra = {"node": ("#eef3fa", "#23292f")}
        vals = {**{k: v[mode] for k, v in _ROLES.items()},
                **{k: v[mode] for k, v in extra.items()}}
        return ";".join(f"--{k}:{v}" for k, v in vals.items())

    tiles = [("stages", len(stages)), ("total wall", f"{total_wall:.2f}s"),
             ("retries", retries), ("replays", replays)]
    if tasks:
        tiles += [("farm tasks", len(tasks)), ("speculated", len(dups))]
    tile_html = "".join(
        f'<div class="tile"><div class="v">{v}</div>'
        f'<div class="k">{k}</div></div>' for k, v in tiles)
    _live_meta = (f'<meta http-equiv="refresh" '
                  f'content="{live_refresh_s:g}">'
                  if live_refresh_s else "")

    doc = f"""<!DOCTYPE html>
<html><head><meta charset="utf-8">{_live_meta}<title>{html.escape(title)}</title>
<style>
  :root {{ color-scheme: light; {roles(0)} }}
  @media (prefers-color-scheme: dark) {{ :root {{ color-scheme: dark;
    {roles(1)} }} }}
  body {{ background: var(--surface); color: var(--ink);
    font: 14px/1.45 system-ui, sans-serif; margin: 24px; }}
  h1 {{ font-size: 18px; }} h2 {{ font-size: 15px; margin-top: 28px; }}
  .tiles {{ display: flex; gap: 12px; flex-wrap: wrap; }}
  .tile {{ border: 1px solid var(--grid); border-radius: 8px;
    padding: 10px 16px; min-width: 90px; }}
  .tile .v {{ font-size: 20px; font-weight: 600; }}
  .tile .k {{ color: var(--ink2); font-size: 12px; }}
  svg text.t1 {{ fill: var(--ink); font: 12px system-ui; }}
  svg text.t2 {{ fill: var(--ink2); font: 11px system-ui; }}
  svg g.bar:hover rect {{ opacity: .75; }}
  table {{ border-collapse: collapse; }}
  th, td {{ border: 1px solid var(--grid); padding: 4px 10px;
    text-align: right; }}
  th {{ color: var(--ink2); font-weight: 600; }}
  td:nth-child(2), th:nth-child(2) {{ text-align: left; }}
  table.lint th, table.lint td {{ text-align: left; }}
  .diag {{ border: 1px solid var(--critical); border-radius: 8px;
    padding: 10px 14px; margin: 8px 0; }}
  .diag .hl {{ color: var(--critical); }}
  .diag pre {{ overflow-x: auto; font-size: 11px; }}
</style></head>
<body>
<h1>{html.escape(title)}</h1>
<div class="tiles">{tile_html}</div>
{_diagnosis_html(events)}
{_lint_html(events)}
{_cost_html(events)}
{_analyze_html(events)}
{_adaptive_html(events)}
{_critical_path_html(events)}
{_latency_html(events)}
<h2>Stage DAG</h2>{_svg_dag(stages, deps, order)}
<h2>Gantt (time from job start)</h2>{_svg_gantt(stages, order)}
<h2>Per-stage table</h2>{_table(stages, order)}
{_stage_details(stages, order, events)}
</body></html>"""
    if path:
        with open(path, "w") as f:
            f.write(doc)
    return doc


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Tolerant JSONL load: a partially-written trailing line (the
    writer may be mid-flush while a live refresh reads) is skipped
    instead of breaking the view."""
    events: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except FileNotFoundError:
        pass
    return events


def serve_live(jsonl_path: str, port: int = 0,
               refresh_s: float = 2.0):
    """Serve the report over HTTP, re-rendered from the JSONL event
    stream on every request (EventLog flushes per event, so an open
    browser follows a RUNNING job — the live JobBrowser model).
    ``/metrics`` exposes Prometheus text metrics: the counter families
    derived from the event stream (task/retry/straggler/shuffle-bytes/
    compile-cache), merged with this process's live registry (queue
    depth and friends when the job runs in-process).
    Returns the bound (server, port); call server.serve_forever()."""
    import http.server

    def render() -> bytes:
        return job_report_html(_read_jsonl(jsonl_path), title=jsonl_path,
                               live_refresh_s=refresh_s).encode()

    def render_metrics() -> bytes:
        from dryad_tpu.obs.metrics import REGISTRY, metrics_from_events
        reg = metrics_from_events(_read_jsonl(jsonl_path))
        return reg.merge_from(REGISTRY).render().encode()

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.split("?", 1)[0] == "/metrics":
                body = render_metrics()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body = render()
                ctype = "text/html; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), H)
    return srv, srv.server_address[1]


def serve_history(history_dir: str, port: int = 0):
    """Serve the job-history index page (obs/history.py), re-rendered
    from the directory on every request — the JobBrowser job-list view.
    Returns (server, port)."""
    import http.server

    def render() -> bytes:
        from dryad_tpu.obs.history import history_index, index_html
        return index_html(history_index(history_dir),
                          title=history_dir).encode()

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = render()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), H)
    return srv, srv.server_address[1]


def main(argv=None) -> int:
    import argparse
    import os

    ap = argparse.ArgumentParser(
        description="dryad_tpu job viewer: render an EventLog JSONL to "
                    "HTML (or a job-history DIRECTORY to its index "
                    "page), or serve it live")
    ap.add_argument("events", help="EventLog JSONL path, or a job "
                                   "history directory "
                                   "(JobConfig.history_dir)")
    ap.add_argument("-o", "--out", help="write static HTML here")
    ap.add_argument("--serve", type=int, metavar="PORT",
                    help="serve live (re-rendered per refresh)")
    args = ap.parse_args(argv)
    if os.path.isdir(args.events):
        # job-history index mode (obs/history.py)
        if args.serve is not None:
            srv, port = serve_history(args.events, args.serve)
            print(f"history index: http://127.0.0.1:{port}/", flush=True)
            srv.serve_forever()
            return 0
        from dryad_tpu.obs.history import history_index, index_html
        out = args.out or os.path.join(args.events, "index.html")
        with open(out, "w") as f:
            f.write(index_html(history_index(args.events),
                               title=args.events))
        print(out)
        return 0
    if args.serve is not None:
        srv, port = serve_live(args.events, args.serve)
        print(f"live viewer: http://127.0.0.1:{port}/", flush=True)
        srv.serve_forever()
        return 0
    events = _read_jsonl(args.events)
    out = args.out or (args.events + ".html")
    job_report_html(events, path=out, title=args.events)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
