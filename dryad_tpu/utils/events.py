"""Job event log — the Calypso reporter equivalent.

The reference streams timestamped key=value vertex/process/topology events to
``calypso.log`` on the job's DFS dir (GraphManager/reporting/
DrCalypsoReporting.cpp:163-187, attached at LinqToDryadJM.cs:81-83), consumed
by JobBrowser.  Here: structured JSONL with the same role — every stage
execution, retry, replay, spill, farm dispatch, and trace span is an event;
``job_report`` renders the per-stage summary (the JobBrowser per-stage
table).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

__all__ = ["EventLog", "job_report"]


# event kinds by verbosity level (DRYAD_LOGGING_LEVEL role,
# LinqToDryadJM.cs:213): 0=errors only, 1=+stage/job lifecycle, 2=all.
# EVERY kind the runtime emits must be registered here — unknown kinds
# default to level 0 (always emitted), so an unregistered kind would
# bypass the filter entirely; tests/test_obs.py drift-tests this table
# against the ``{"event": ...}`` literals in the source tree.
_LEVELS = {
    # failures / teardown verdicts — visible even at level 0
    "stage_replay": 0, "worker_failed": 0, "job_failed": 0,
    "worker_wedged": 0, "task_timeout": 0, "worker_ping_timeout": 0,
    "task_forensics": 0,
    # stage/job lifecycle + scheduling decisions
    "stage_done": 1, "plan": 1, "stage_spilled": 1, "stage_restored": 1,
    "task_done": 1, "task_duplicated": 1, "task_reassigned": 1,
    "lint_finding": 1, "settle_replay": 1, "stage_retry": 1,
    # static cost analyzer (dryad_tpu/analysis/cost.py): the pre-submit
    # prediction and the runtime model-validation misses
    "cost_report": 1, "cost_model_miss": 1,
    "stream_stage_done": 1, "stream_tee_spill": 1, "job_done": 1,
    # out-of-core re-streaming cache tier (exec/ooc.py + Dataset.cache):
    # a cold cache write, a warm pass served from the local entry, and
    # an entry invalidated by a chunk-fingerprint mismatch (falls back
    # to a clean re-stream) are job-lifecycle grade; prefetch_stall is
    # the "host IO was the bottleneck" chatter EXPLAIN ANALYZE folds in
    "ooc_cache_write": 1, "ooc_cache_hit": 1, "ooc_cache_invalid": 1,
    "job_archived": 1, "diagnosis_skew": 1, "diagnosis_slow_worker": 1,
    # adaptive execution: an applied stage-graph rewrite is a scheduling
    # decision (level 1, dryad_tpu/adapt)
    "graph_rewrite": 1,
    # multi-tenant job service lifecycle (dryad_tpu/service): admission,
    # start/finish, cancellation, and typed rejections are job-lifecycle
    # grade; daemon start/stop bookends the service log
    "job_submitted": 1, "job_started": 1, "job_cancelled": 1,
    "job_rejected": 1, "service_started": 1, "service_stopped": 1,
    "service_error": 0,
    # durable service (dryad_tpu/service/durable + chaos): the journal
    # replay summary, each recovered job's disposition, the rolling-
    # upgrade handoff protocol steps, and an injected chaos fault are
    # all job-lifecycle grade — an operator reading a post-restart log
    # at level 1 must see exactly what recovery did
    "journal_replay": 1, "job_resumed": 1, "job_readmitted": 1,
    "handoff_started": 1, "handoff_ready": 1, "handoff_adopted": 1,
    "handoff_paused": 1, "chaos_fault": 1,
    # live service observability (dryad_tpu/obs/{analyze,slo}.py,
    # obs/history.py regression watch): an EXPLAIN ANALYZE annotation,
    # an SLO error-budget breach, and a cross-run perf-regression
    # suspicion are job-lifecycle-grade findings
    "analyze_report": 1, "slo_breach": 1, "regression_suspect": 1,
    # continuous queries (dryad_tpu/inc): a standing-query registration,
    # each refresh's summary (delta chunks scanned + result delta — the
    # record SSE followers of the standing id consume), the atomic
    # state+watermark commit, and a refresh that fell back to a full
    # re-run are all job-lifecycle grade
    "standing_query_registered": 1, "standing_query_cancelled": 1,
    "inc_refresh": 1, "inc_state_write": 1, "inc_fallback_rescan": 1,
    # SQL front end (dryad_tpu/sql): every lowering emits sql_query
    # (normalized query text + catalog fingerprint — history/forensics
    # bundles identify SQL jobs by it); sql_lowered carries the lowered
    # shape (outputs/joins/grouping) and is chatter-grade
    "sql_query": 1, "sql_lowered": 2,
    # tail-latency observability (obs/latency.py + service wiring): the
    # settled per-request phase waterfall is job-lifecycle grade — the
    # record latency_from_events/metrics_from_events re-derive from;
    # the per-mark internals are chatter
    "latency_waterfall": 1, "latency_phase": 2,
    # semantic plan reuse (analysis/canon + subsume via the daemon): the
    # DTA501 verdict on a fingerprint-keyed plan-cache hit and a table
    # load served from another job's cold scan are amortization
    # evidence — job-lifecycle grade
    "reuse_verdict": 1, "scan_shared": 1,
    # chatter: progress ticks, losing duplicates, locality notes, spans,
    # periodic resource samples (obs/profile.py), per-stage adapt stats
    # and declined rewrites (dryad_tpu/adapt)
    "progress": 2, "task_duplicate_ignored": 2,
    "task_duplicate_failed_ignored": 2, "task_locality_dispatch": 2,
    "span": 2, "resource_sample": 2, "prefetch_stall": 2,
    "adapt_stats": 2, "adapt_skipped": 2,
}


class EventLog:
    """In-memory + optional JSONL-file event sink.

    ``level`` filters by verbosity (default: env ``DRYAD_LOGGING_LEVEL``
    or 2 = everything); unknown event kinds always pass.  Usable as a
    context manager so a failing job path cannot leak the JSONL handle::

        with EventLog(path) as log:
            ctx = Context(event_log=log)
            ...
    """

    def __init__(self, path: Optional[str] = None,
                 level: Optional[int] = None,
                 history_dir: Optional[str] = None,
                 app: Optional[str] = None):
        import os
        import threading
        # background emitters exist now (obs/profile.ResourceSampler):
        # the append+write pair must be atomic or two threads' JSONL
        # lines interleave into garbage the tolerant reader then drops
        self._lock = threading.Lock()
        self.events: List[Dict[str, Any]] = []
        self.path = path
        self._f = open(path, "a") if path else None
        self.closed = False
        # job-history archiving (obs/history.py): when set, close()
        # snapshots {events, plan, metrics, bundles} into history_dir
        # under the app's name (JobConfig.history_dir wires this)
        self.history_dir = history_dir
        self.app = app
        self.level = (level if level is not None
                      else int(os.environ.get("DRYAD_LOGGING_LEVEL", "2")))

    def admits(self, kind: Optional[str]) -> bool:
        """Would an event of ``kind`` pass this log's level filter?
        Consumers that do per-event side work (the service's live
        progress/SSE wakeups) gate on this so a level-0 log keeps the
        whole path a no-op."""
        return _LEVELS.get(kind, 0) <= self.level

    def __call__(self, event: Dict[str, Any]) -> None:
        if not self.admits(event.get("event")):
            return
        e = dict(event)
        e.setdefault("ts", round(time.time(), 4))
        with self._lock:
            self.events.append(e)
            # write-after-close guard: a straggler's late losing-
            # duplicate reply may still emit after the job closed the
            # log — keep the in-memory record, never touch the closed
            # handle
            if self._f is not None and not self.closed:
                self._f.write(json.dumps(e) + "\n")
                self._f.flush()

    def close(self):
        if self.closed:
            return
        if self.history_dir:
            # archive BEFORE closing so the job_archived pointer also
            # lands in this log's own JSONL; archiving must never turn
            # a successful job into a failed close
            try:
                from dryad_tpu.obs.history import archive_job
                self({"event": "job_archived",
                      "path": archive_job(self.history_dir, self.events,
                                          app=self.app)})
            except Exception:
                pass
        with self._lock:
            self.closed = True
            if self._f is not None:
                self._f.close()
                self._f = None
        # a closed log must stop being the process span sink, or later
        # jobs' spans would silently pile into this dead in-memory list
        from dryad_tpu.obs import trace
        trace.uninstall(self)

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def of_type(self, kind: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e.get("event") == kind]


def job_report(events) -> str:
    """Render a per-stage execution summary from an event stream.

    Covers gang stages (``stage_done``/``stage_replay``) AND stream-mode
    stages (``stream_stage_done``, with ``stream_tee_spill`` counted in
    the spills column) — a streamed run's stages must not silently drop
    out of the table."""
    if isinstance(events, EventLog):
        events = events.events
    stages: Dict[Any, Dict[str, Any]] = {}
    order = []
    kinds = ("stage_done", "stage_replay", "stage_retry",
             "stream_stage_done", "stream_tee_spill")
    for e in events:
        if e.get("event") in kinds:
            sid = e.get("stage")
            if sid not in stages:
                stages[sid] = {"label": e.get("label", "?"), "runs": 0,
                               "retries": 0, "replays": 0, "spills": 0,
                               "wall_s": 0.0, "scale": 1}
                order.append(sid)
            s = stages[sid]
            if e.get("label"):
                s["label"] = e["label"]
            if e["event"] in ("stage_done", "stream_stage_done"):
                s["runs"] += 1
                s["wall_s"] += e.get("wall_s", 0.0)
                s["scale"] = max(s["scale"], e.get("scale", 1))
                if e.get("overflow"):
                    s["retries"] += 1
            elif e["event"] == "stage_replay":
                s["replays"] += 1
            elif e["event"] == "stream_tee_spill":
                s["spills"] += 1
    lines = [f"{'stage':>6} {'label':<16} {'runs':>4} {'retries':>7} "
             f"{'replays':>7} {'spills':>6} {'scale':>5} {'wall_s':>8}"]
    for sid in order:
        s = stages[sid]
        lines.append(f"{sid:>6} {s['label']:<16} {s['runs']:>4} "
                     f"{s['retries']:>7} {s['replays']:>7} "
                     f"{s['spills']:>6} {s['scale']:>5} "
                     f"{s['wall_s']:>8.3f}")
    return "\n".join(lines)
