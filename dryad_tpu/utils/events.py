"""Job event log — the Calypso reporter equivalent.

The reference streams timestamped key=value vertex/process/topology events to
``calypso.log`` on the job's DFS dir (GraphManager/reporting/
DrCalypsoReporting.cpp:163-187, attached at LinqToDryadJM.cs:81-83), consumed
by JobBrowser.  Here: structured JSONL with the same role — every stage
execution, retry, replay, and spill is an event; ``job_report`` renders the
per-stage summary (the JobBrowser per-stage table).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

__all__ = ["EventLog", "job_report"]


# event kinds by verbosity level (DRYAD_LOGGING_LEVEL role,
# LinqToDryadJM.cs:213): 0=errors only, 1=+stage/job lifecycle, 2=all
_LEVELS = {
    "stage_replay": 0, "worker_failed": 0, "job_failed": 0,
    "worker_wedged": 0,
    "stage_done": 1, "plan": 1, "stage_spilled": 1, "stage_restored": 1,
    "task_done": 1, "task_duplicated": 1, "task_reassigned": 1,
    "lint_finding": 1,
    "progress": 2, "task_duplicate_ignored": 2,
}


class EventLog:
    """In-memory + optional JSONL-file event sink.

    ``level`` filters by verbosity (default: env ``DRYAD_LOGGING_LEVEL`` or
    2 = everything); unknown event kinds always pass."""

    def __init__(self, path: Optional[str] = None,
                 level: Optional[int] = None):
        import os
        self.events: List[Dict[str, Any]] = []
        self._f = open(path, "a") if path else None
        self.level = (level if level is not None
                      else int(os.environ.get("DRYAD_LOGGING_LEVEL", "2")))

    def __call__(self, event: Dict[str, Any]) -> None:
        if _LEVELS.get(event.get("event"), 0) > self.level:
            return
        e = dict(event)
        e.setdefault("ts", round(time.time(), 4))
        self.events.append(e)
        if self._f is not None:
            self._f.write(json.dumps(e) + "\n")
            self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def of_type(self, kind: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e.get("event") == kind]


def job_report(events) -> str:
    """Render a per-stage execution summary from an event stream."""
    if isinstance(events, EventLog):
        events = events.events
    stages: Dict[Any, Dict[str, Any]] = {}
    order = []
    for e in events:
        if e.get("event") in ("stage_done", "stage_replay", "stage_retry"):
            sid = e.get("stage")
            if sid not in stages:
                stages[sid] = {"label": e.get("label", "?"), "runs": 0,
                               "retries": 0, "replays": 0, "wall_s": 0.0,
                               "scale": 1}
                order.append(sid)
            s = stages[sid]
            if e["event"] == "stage_done":
                s["runs"] += 1
                s["wall_s"] += e.get("wall_s", 0.0)
                s["scale"] = max(s["scale"], e.get("scale", 1))
                if e.get("overflow"):
                    s["retries"] += 1
            elif e["event"] == "stage_replay":
                s["replays"] += 1
    lines = [f"{'stage':>6} {'label':<16} {'runs':>4} {'retries':>7} "
             f"{'replays':>7} {'scale':>5} {'wall_s':>8}"]
    for sid in order:
        s = stages[sid]
        lines.append(f"{sid:>6} {s['label']:<16} {s['runs']:>4} "
                     f"{s['retries']:>7} {s['replays']:>7} {s['scale']:>5} "
                     f"{s['wall_s']:>8.3f}")
    return "\n".join(lines)
