"""Persistent XLA compilation cache.

The reference pays its per-job codegen cost once: csc compiles the vertex
DLL in seconds and the artifact is reused for every vertex of the job
(DryadLinqCodeGen.cs:2140-2257 BuildAssembly).  Our counterpart cost is XLA
compilation of stage programs — tens of seconds per app through the device
tunnel — and by default it was paid again on EVERY driver restart, because
jit/AOT caches are per-process.

This module turns on JAX's persistent (on-disk) compilation cache so stage
programs are compiled once per (program, shapes, device kind) and then
loaded from disk in milliseconds by every later process: driver restarts,
bench re-runs, and all cluster worker processes (they share the directory;
the cache is multi-process safe — writes go through atomic renames).

Wired from Context.__init__, runtime.worker startup, and bench.py, keyed by
``JobConfig.compilation_cache_dir`` (set to None to disable).

:class:`FileCache` is the framework's OWN shared on-disk artifact cache
(serialized plans, lowered specs — anything bytes) with the same
concurrency contract the XLA cache relies on, made explicit: commits go
through same-directory atomic renames so a reader can never observe a
torn entry, every entry carries a content checksum so a corrupt or
crash-truncated file reads as a MISS (never as garbage), and concurrent
writers of one key are last-writer-wins.  The multi-tenant job service
(dryad_tpu/service) keys its per-app plan cache here so the Nth user of
an app pays zero planning, and per-JOB hit/miss counters land in the
metrics registry (the "did this tenant pay compile" dashboard signal).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Optional

__all__ = ["enable_persistent_cache", "machine_fingerprint",
           "DEFAULT_CACHE_DIR", "FileCache"]

DEFAULT_CACHE_DIR = os.path.join("~", ".cache", "dryad_tpu", "xla_cache")

_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def machine_fingerprint() -> str:
    """Short stable hash of this host's CPU feature set + architecture.

    XLA:CPU AOT artifacts embed the COMPILING machine's feature list and
    loading them on a host with a narrower set "could lead to execution
    errors such as SIGILL" (XLA's own warning, observed when the driver
    and workers — or two hosts sharing ~/.cache over NFS — share one
    cache directory).  Platform NAME alone cannot distinguish two x86
    hosts with different AVX-512 subsets, so the cache namespace includes
    this fingerprint.  ``DRYAD_CACHE_MACHINE_TAG`` overrides it (tests,
    or operators who know their fleet is feature-homogeneous)."""
    override = os.environ.get("DRYAD_CACHE_MACHINE_TAG")
    if override:
        return override
    import hashlib
    import platform
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    raw = f"{platform.machine()}|{feats}"
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def enable_persistent_cache(path: Optional[str] = DEFAULT_CACHE_DIR) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing), or DISABLE it for this process when ``path`` is None (the
    JAX config is process-global, so a None-configured Context must undo
    what an earlier Context enabled).  Idempotent; returns the resolved
    directory (None when disabled).  Safe to call before or after device
    init — the cache is consulted at compile time, not backend-init
    time."""
    global _enabled_dir
    from dryad_tpu.obs.metrics import REGISTRY, family_gauge
    with _lock:
        import jax

        if path is None:
            if _enabled_dir is not None:
                jax.config.update("jax_compilation_cache_dir", None)
                _enabled_dir = None
            family_gauge(REGISTRY, "persistent_cache").set(0)
            return None
        # namespace by platform selection AND machine feature set: CPU
        # worker processes and the accelerator-attached driver compile
        # with DIFFERENT machine feature sets, and two hosts sharing the
        # directory (NFS home) may differ in CPU features; sharing one
        # subdirectory makes XLA:CPU load AOT artifacts built for the
        # other configuration (SIGILL risk — XLA prints exactly that
        # warning).  See machine_fingerprint().
        tag = (os.environ.get("JAX_PLATFORMS") or "default").replace(
            ",", "-") + "-" + machine_fingerprint()
        resolved = os.path.join(os.path.abspath(os.path.expanduser(path)),
                                tag)
        if _enabled_dir == resolved:
            return resolved
        os.makedirs(resolved, exist_ok=True)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", resolved)
        # cache every compile: stage programs are small but numerous, and
        # even a 0.3 s compile is worth skipping across worker processes
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _enabled_dir = resolved
        family_gauge(REGISTRY, "persistent_cache").set(1)
        return resolved


# 8-byte magic + sha256 of the payload, prefixed so a reader validates
# BEFORE trusting the bytes; bumping the version invalidates old entries
_FC_MAGIC = b"DRYDFC1\n"


class FileCache:
    """Concurrent-writer-safe on-disk bytes cache (get/put by string key).

    * **Atomic commit:** ``put`` writes to a uniquely-named temp file in
      the SAME directory, fsyncs, then ``os.replace``s it into place —
      readers observe either the old complete entry or the new complete
      entry, never a partial write (the rename-commit contract the
      reference's partitioned stores and the XLA persistent cache both
      rely on).
    * **No torn reads:** every entry is ``magic + sha256(payload) +
      payload``; a file that fails the checksum (crash-truncated write
      on a filesystem without atomic rename, e.g. some NFS modes) is a
      MISS and is unlinked best-effort.
    * **Concurrent writers:** two processes putting the same key race
      benignly — both renames are atomic, last writer wins, and both
      committed values are valid (cache values must be deterministic
      functions of the key, which plans are).

    Hit/miss counters land in the canonical metrics families
    (``cache_hits``/``cache_misses``, labeled ``cache="file"`` plus the
    optional per-job label) so the service dashboard can show per-tenant
    amortization."""

    def __init__(self, root: str):
        self.root = os.path.abspath(os.path.expanduser(root))
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        h = hashlib.sha256(key.encode()).hexdigest()
        return os.path.join(self.root, h[:2], h[2:])

    def _count(self, hit: bool, job: Optional[str]) -> None:
        from dryad_tpu.obs.metrics import REGISTRY, family_counter
        labels = {"cache": "file"}
        if job is not None:
            labels["job"] = job
        family_counter(REGISTRY, "cache_hits" if hit else "cache_misses",
                       **labels).inc()

    def get(self, key: str, job: Optional[str] = None) -> Optional[bytes]:
        """The committed payload for ``key``, or None (miss / torn)."""
        p = self._path(key)
        try:
            with open(p, "rb") as f:
                blob = f.read()
                ino = os.fstat(f.fileno()).st_ino
        except OSError:
            self._count(False, job)
            return None
        head = len(_FC_MAGIC) + 32
        if (len(blob) < head or not blob.startswith(_FC_MAGIC)
                or hashlib.sha256(blob[head:]).digest()
                != blob[len(_FC_MAGIC):head]):
            # corrupt/torn entry: a miss, never garbage — and evict it
            # so the next writer's rename starts clean.  Only evict the
            # INODE we read: a concurrent put may have os.replace()d a
            # fresh valid entry in since, and unlinking that would throw
            # away a just-committed value (the remaining stat→unlink
            # window is benign: worst case one extra rebuildable miss)
            try:
                if os.stat(p).st_ino == ino:
                    os.unlink(p)
            except OSError:
                pass
            self._count(False, job)
            return None
        self._count(True, job)
        return blob[head:]

    def put(self, key: str, data: bytes, job: Optional[str] = None) -> None:
        """Commit ``data`` under ``key`` atomically (rename commit)."""
        from dryad_tpu.utils.atomic import atomic_write_bytes
        blob = _FC_MAGIC + hashlib.sha256(data).digest() + data
        atomic_write_bytes(self._path(key), blob)
