"""Persistent XLA compilation cache.

The reference pays its per-job codegen cost once: csc compiles the vertex
DLL in seconds and the artifact is reused for every vertex of the job
(DryadLinqCodeGen.cs:2140-2257 BuildAssembly).  Our counterpart cost is XLA
compilation of stage programs — tens of seconds per app through the device
tunnel — and by default it was paid again on EVERY driver restart, because
jit/AOT caches are per-process.

This module turns on JAX's persistent (on-disk) compilation cache so stage
programs are compiled once per (program, shapes, device kind) and then
loaded from disk in milliseconds by every later process: driver restarts,
bench re-runs, and all cluster worker processes (they share the directory;
the cache is multi-process safe — writes go through atomic renames).

Wired from Context.__init__, runtime.worker startup, and bench.py, keyed by
``JobConfig.compilation_cache_dir`` (set to None to disable).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

__all__ = ["enable_persistent_cache", "machine_fingerprint",
           "DEFAULT_CACHE_DIR"]

DEFAULT_CACHE_DIR = os.path.join("~", ".cache", "dryad_tpu", "xla_cache")

_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def machine_fingerprint() -> str:
    """Short stable hash of this host's CPU feature set + architecture.

    XLA:CPU AOT artifacts embed the COMPILING machine's feature list and
    loading them on a host with a narrower set "could lead to execution
    errors such as SIGILL" (XLA's own warning, observed when the driver
    and workers — or two hosts sharing ~/.cache over NFS — share one
    cache directory).  Platform NAME alone cannot distinguish two x86
    hosts with different AVX-512 subsets, so the cache namespace includes
    this fingerprint.  ``DRYAD_CACHE_MACHINE_TAG`` overrides it (tests,
    or operators who know their fleet is feature-homogeneous)."""
    override = os.environ.get("DRYAD_CACHE_MACHINE_TAG")
    if override:
        return override
    import hashlib
    import platform
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    raw = f"{platform.machine()}|{feats}"
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def enable_persistent_cache(path: Optional[str] = DEFAULT_CACHE_DIR) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing), or DISABLE it for this process when ``path`` is None (the
    JAX config is process-global, so a None-configured Context must undo
    what an earlier Context enabled).  Idempotent; returns the resolved
    directory (None when disabled).  Safe to call before or after device
    init — the cache is consulted at compile time, not backend-init
    time."""
    global _enabled_dir
    from dryad_tpu.obs.metrics import REGISTRY, family_gauge
    with _lock:
        import jax

        if path is None:
            if _enabled_dir is not None:
                jax.config.update("jax_compilation_cache_dir", None)
                _enabled_dir = None
            family_gauge(REGISTRY, "persistent_cache").set(0)
            return None
        # namespace by platform selection AND machine feature set: CPU
        # worker processes and the accelerator-attached driver compile
        # with DIFFERENT machine feature sets, and two hosts sharing the
        # directory (NFS home) may differ in CPU features; sharing one
        # subdirectory makes XLA:CPU load AOT artifacts built for the
        # other configuration (SIGILL risk — XLA prints exactly that
        # warning).  See machine_fingerprint().
        tag = (os.environ.get("JAX_PLATFORMS") or "default").replace(
            ",", "-") + "-" + machine_fingerprint()
        resolved = os.path.join(os.path.abspath(os.path.expanduser(path)),
                                tag)
        if _enabled_dir == resolved:
            return resolved
        os.makedirs(resolved, exist_ok=True)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", resolved)
        # cache every compile: stage programs are small but numerous, and
        # even a 0.3 s compile is worth skipping across worker processes
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _enabled_dir = resolved
        family_gauge(REGISTRY, "persistent_cache").set(1)
        return resolved
