"""JAX version compatibility shims.

The framework targets the current ``jax.shard_map`` API (top-level,
``check_vma=`` keyword) and ``jax.lax.axis_size``.  The late-0.4.x
band (0.4.36/0.4.37 — the jaxlib this image bakes in) only ships
``jax.experimental.shard_map.shard_map`` with the ``check_rep=``
keyword, and exposes the static named-axis size as
``jax.core.axis_frame(name)`` (an int on this band; EARLIER 0.4.x
returned a frame object — such builds are rejected loudly below
rather than silently miscomputing shapes).  Importing this module
installs top-level aliases translating the new spellings onto what
the installed jax provides, so every jit(shard_map(...)) stage
program compiles on either version.

Imported from ``dryad_tpu/__init__.py`` before anything traces a stage.
"""

from __future__ import annotations

import jax

__all__ = ["install"]


def install() -> None:
    if not hasattr(jax.lax, "axis_size"):
        import jax.core as _core

        def _frame_size(a) -> int:
            sz = _core.axis_frame(a)
            if not isinstance(sz, int):
                raise RuntimeError(
                    f"this jax build's core.axis_frame({a!r}) returns "
                    f"{type(sz).__name__}, not the axis size — the "
                    f"compat shim supports jax >= 0.4.36; upgrade jax")
            return sz

        def axis_size(name):
            if isinstance(name, (tuple, list)):
                n = 1
                for a in name:
                    n *= _frame_size(a)
                return n
            return _frame_size(name)

        jax.lax.axis_size = axis_size

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kw):
        # new-API ``check_vma`` maps onto the old ``check_rep`` (both
        # gate the replication/varying-manual-axes checker; the default
        # is "on" in both APIs)
        check = True if check_vma is None else bool(check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check, **kw)

    jax.shard_map = shard_map


install()
