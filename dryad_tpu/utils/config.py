"""Typed job configuration — the DryadLinqContext knob surface.

The reference exposes ~40 typed properties on DryadLinqContext
(DryadLinqContext.cs:728-1053: JobMinNodes/MaxNodes, PartitionUncPath,
CompressionScheme, EnableSpeculativeDuplication, MatchClientNetFrameworkVersion,
…).  This is the TPU-native equivalent: one frozen dataclass, validated at
construction, threaded to every subsystem.  Each field cites the subsystem
it controls; fields whose reference counterpart is Windows/cluster plumbing
that has no TPU meaning are deliberately absent rather than stubbed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from dryad_tpu.adapt.thresholds import (
    SKEW_SIBLING_MEDIAN_FACTOR as _SKEW_FACTOR)
from dryad_tpu.utils.compile_cache import (
    DEFAULT_CACHE_DIR as _DEFAULT_COMPILE_CACHE_DIR)

__all__ = ["JobConfig"]


@dataclasses.dataclass(frozen=True)
class JobConfig:
    """All knobs, grouped by subsystem.  Defaults reproduce the framework's
    historical behavior; construct with overrides and pass to
    ``Context(config=...)``."""

    # -- executor: capacity management (exec/executor.py) ------------------
    # retries after the first overflow; each retry is right-sized from the
    # measured need (DrDynamicDistributionManager role)
    max_capacity_retries: int = 3
    # initial send-slot slack factor for exchanges (C = ceil(slack*cap/D))
    initial_send_slack: int = 2
    # exact-first-wave exchanges: pure repartition legs (no ops) whose
    # input exceeds this many MB run a counts-only probe (one tiny
    # program + one scalar fetch) so even the FIRST wave ships measured
    # slots instead of the structural slack (the reference's pull
    # shuffle ships exact file sizes, kernel/DrCluster.cpp:553-569).
    # -1 disables; 0 probes always (wire_check/tests)
    exchange_probe_min_mb: float = 8.0
    # on-device sample lanes per partition for range bounds
    # (DryadLinqSampler.cs:38 samples 0.1%; we take a fixed per-part cap)
    range_samples_per_partition: int = 4096
    # compiled-stage LRU entries (per executor)
    compile_cache_size: int = 256
    # persistent (on-disk) XLA compilation cache shared by all processes:
    # the reference pays vertex codegen once per job (csc BuildAssembly,
    # DryadLinqCodeGen.cs:2283); this is our once-per-(program, shapes)
    # equivalent across driver restarts AND worker processes.  None
    # disables (utils/compile_cache.py — the single source of the
    # default path)
    compilation_cache_dir: Optional[str] = _DEFAULT_COMPILE_CACHE_DIR
    # device-time profiling: when set, every executor run is wrapped in a
    # jax.profiler trace written under this directory (open with
    # TensorBoard / xprof — the device-timeline view the reference
    # surfaces through Artemis; SURVEY.md §5 tracing).  Workers profile
    # into per-process subdirectories.
    profile_dir: Optional[str] = None
    # hot-key salting (exec/executor.py + parallel/shuffle.py
    # skew_join_exchange, DrDynamicDistributor.h:79 role): a saltable join
    # stage switches to the salted exchange when a retry would need
    # >= trigger x the current per-destination capacity
    salt_trigger_factor: int = 4
    # a key is hot when its global row count exceeds factor x (rows / P)
    salt_hot_factor: float = 4.0
    # per-partition heavy-hitter candidates nominated for the hot set
    salt_topk: int = 8

    # -- fault tolerance (exec/recovery.py) --------------------------------
    # replays allowed before FailureBudgetExceeded (DrFailureDictionary,
    # DrGraph.cpp:39)
    failure_budget: int = 16
    # durable stage-output spill: None disables; "gzip" compresses spill
    # partitions (GzipCompressionChannelTransform.cpp)
    spill_compression: Optional[str] = None

    # -- collect shrink policy (exec/data.py) ------------------------------
    # capacities at or under this are never shrunk before host transfer
    collect_shrink_min_capacity: int = 1024
    # shrink only when capacity exceeds this multiple of the max count
    collect_shrink_waste_factor: int = 4

    # -- text ingest (api read_text / ops/text.py) -------------------------
    text_max_line_len: int = 256
    # default delimiters for split_words (reference LineRecord tokenizers)
    token_delims: bytes = b" \t\r\n.,;:!?\"'()[]{}<>"
    token_max_len: int = 24
    string_max_len: int = 64          # from_columns string payload bytes

    # -- store (io/store.py) -----------------------------------------------
    # default compression for to_store (None | "gzip")
    store_compression: Optional[str] = None
    # verify fnv64 partition checksums on read (fingerprint.cpp role)
    store_verify_checksums: bool = True

    # -- out-of-core streaming (exec/ooc.py, exec/stream_exec.py) ----------
    # default chunk size for ChunkSource constructors
    ooc_chunk_rows: int = 1 << 16
    # default scatter fan-out for streaming_group_aggregate
    ooc_hash_buckets: int = 64
    # in-flight device batches for the double-buffered stream (depth)
    ooc_inflight: int = 2
    # memory-hierarchy-aware sort tier: a streamed sort whose TOTAL data
    # (counted by the sampling pass it already runs) fits this many bytes
    # skips the bucket round-trip — one H2D, one device sort, one D2H
    # (the reference's channels pick RAM FIFOs over disk files the same
    # way, channelbufferqueue vs channelbuffernativewriter).  0 forces
    # the out-of-core machinery regardless of size.
    ooc_incore_bytes: int = 1 << 30
    # from_store switches to streamed execution when the store holds at
    # least this many rows (0 = off); read_store_stream always streams
    ooc_auto_stream_rows: int = 0
    # max rows the materialized build side of a streamed join may hold
    ooc_join_build_rows: int = 1 << 18
    # host-IO prefetch depth for the chunk pipeline (exec/ooc.py
    # prefetch_iter): a background thread pulls up to this many chunks
    # ahead of the device, overlapping the next chunk's store read /
    # ranged fetch / unpack with the current chunk's compute (the
    # reference's completion-port double buffering,
    # channelbuffernativereader.cpp).  0 disables (the A/B lever the
    # regression guard keeps).
    ooc_prefetch_depth: int = 2
    # store-backed re-streaming cache tier for Dataset.cache() on
    # streamed / edge-scale data (exec/ooc.py cache_source): the cold
    # pass writes a LOCAL chunked cache (io/store layout, per-chunk
    # fingerprints) keyed by the producing query's stable fingerprint;
    # warm passes — iteration 2..N of do_while bodies, or a restarted
    # job with an intact cache dir — re-stream from local sequential
    # reads instead of ranged hdfs://, s3://, or http:// fetches.
    # False restores the legacy behavior (device-/cluster-resident
    # cache(); streamed cache() spools to an unvalidated temp store) —
    # the cache-off A/B lever.
    ooc_restream_cache: bool = True
    # root directory for re-streaming cache entries.  None = a
    # per-Context temp dir (removed at Context GC — warm iterations
    # still hit, restarts do not); set a persistent path to let a
    # restarted job with an intact cache dir skip the cold pass.
    ooc_cache_dir: Optional[str] = None

    # -- cluster runtime (runtime/cluster.py) ------------------------------
    cluster_processes: int = 2
    cluster_devices_per_process: int = 2
    cluster_startup_timeout_s: float = 180.0
    cluster_job_timeout_s: float = 600.0
    cluster_fn_modules: Tuple[str, ...] = ()
    # gang straggler/wedge watchdog (runtime/cluster.py; the reference
    # duplicates ANY slow vertex, DrVertex.h:195 + DrStageStatistics.cpp:
    # 24-25 — an SPMD gang can't duplicate one member, so a wedged worker
    # triggers teardown + one replay on a fresh gang instead of hanging
    # every collective until the hard job timeout):
    # workers send progress frames every hb_every seconds while a job
    # runs (0 disables the watchdog)...
    gang_heartbeat_s: float = 2.0
    # ...and a worker silent for longer than this is declared WEDGED
    gang_heartbeat_timeout_s: float = 60.0
    # once the FIRST worker reply lands, the rest must land within
    # max(rel x first-reply latency, abs seconds) — post-collective skew
    # between gang members is otherwise milliseconds
    gang_straggler_rel_margin: float = 1.0
    gang_straggler_abs_margin_s: float = 15.0

    # optimistic stage execution (exec/recovery.Run._settle): stages run
    # with ZERO per-stage host syncs; every needs vector is batch-fetched
    # once at job end, and overflows replay synchronously from the first
    # affected stage.  On a high-latency dispatch link (remote tunnel,
    # ~0.1 s/round trip) this is the difference between O(stages) and
    # O(1) round trips per job.  Reference: one DVertexCommandBlock start
    # per vertex — the GM does not chat mid-vertex (dvertexcommand.h:199).
    deferred_needs: bool = True

    # whole-group streamed operators (group_apply / group_median over
    # chunk streams, exec/ooc.streaming_group_whole): max raw rows one
    # key bucket may materialize on device — whole groups do not
    # compose, so this bound is the honest memory contract
    ooc_group_bucket_rows: int = 1 << 21

    # pick ooc chunk sizes from MEASURED link + dispatch rates instead of
    # the static ooc_chunk_rows (exec/autotune.pick_chunk_rows): on a
    # high-latency tunnel the tuner grows chunks until the per-dispatch
    # floor is amortized; on healthy hardware the lower clamp applies.
    # Opt-in: explicit chunk_rows arguments always win.
    ooc_chunk_autotune: bool = False

    # cluster streamed generator sources (Dataset.from_stream /
    # read_text_stream on a cluster Context): the driver SPOOLS the
    # stream into a store at this directory — which must be reachable by
    # the workers (shared filesystem or s3://) — then the gang streams
    # the store (FromEnumerable parity: the client writes the enumerable
    # into cluster storage, DryadLinqContext.cs:1210).  None = a driver
    # temp dir (valid for single-machine clusters).
    cluster_stream_spool_dir: str | None = None

    # -- task farm / speculation (runtime/farm.py) -------------------------
    # EnableSpeculativeDuplication + DrStageStatistics caps
    speculation_enabled: bool = True
    speculation_duplication_budget: float = 0.2
    speculation_outlier_sigma: float = 3.0
    speculation_min_samples: int = 5
    speculation_rel_margin: float = 0.5
    speculation_abs_margin_s: float = 0.5
    farm_task_timeout_s: float = 600.0

    # -- planner (plan/planner.py) -----------------------------------------
    # default fan-out allowance for join output capacity (out = expansion *
    # max(input caps)); per-join override via Dataset.join(expansion=...)
    join_expansion: float = 1.0
    # broadcast the build side instead of hash-exchanging both sides when
    # its capacity is at most this fraction of the probe side's
    broadcast_join_threshold: float = 0.0   # 0 disables auto-broadcast

    # -- iteration (api do_while) ------------------------------------------
    max_loop_iterations: int = 1000

    # -- observability: forensics / profiling / history (dryad_tpu/obs) ----
    # background resource sampler period (obs/profile.py): driver and
    # workers emit periodic resource_sample events (RSS, CPU%, device
    # buffer bytes, gc counts; level 2) that export as Chrome-trace
    # counter tracks.  0 disables.  The sampler only runs when an event
    # consumer exists (same no-consumer-zero-work contract as spans).
    resource_sample_s: float = 0.5
    # where task-failure forensics bundles persist (obs/flight.py);
    # None = a bundles/ dir next to the job's EventLog JSONL, or a temp
    # dir when the log is memory-only
    forensics_dir: Optional[str] = None
    # job history archive (obs/history.py): when set, every job's
    # EventLog snapshots {events, plan, metrics, bundles} here on close
    # (the JobBrowser job-history role); browse with
    # `python -m dryad_tpu.obs history <dir>`
    history_dir: Optional[str] = None

    # -- adaptive execution (dryad_tpu/adapt) ------------------------------
    # stage-boundary graph rewriting from observed per-partition stats
    # (the reference's DrDynamicAggregate/Distribution/BroadcastManager
    # roles).  "off" (default): the adapt subsystem is never constructed
    # — byte-identical plans and results to the non-adaptive runtime.
    # "on": the not-yet-executed suffix of the StageGraph may be
    # rewritten at each stage materialization; requires the per-stage
    # stats sync, so deferred-needs batching is disabled for the run.
    adaptive: str = "off"
    # a partition is skewed at >= this multiple of its sibling median —
    # SAME constant diagnose_events flags on (adapt/thresholds.py), so
    # detection and action cannot drift
    adapt_skew_factor: float = _SKEW_FACTOR
    # collapse a hierarchical aggregation tree to one global exchange
    # when the measured upstream rows are at most this many
    adapt_agg_collapse_rows: int = 4096
    # expand a flat merge into per-axis hops (multi-level meshes) when
    # measured upstream rows reach this many
    adapt_agg_expand_rows: int = 1 << 20
    # shrink a downstream exchange's capacity when the static plan
    # capacity exceeds this multiple of the measured row bound
    adapt_shrink_factor: float = 2.0
    # broadcast joins: measured build side must stay within this
    # fraction of the probe side's rows — above it a planned broadcast
    # demotes to hash exchange, below it a saltable hash join promotes
    adapt_broadcast_max_ratio: float = 0.25

    # -- pre-submit static analysis (dryad_tpu/analysis) -------------------
    # gate every executor/cluster/stream submission through the plan
    # verifier + UDF lint (the reference's phase-1 static validation,
    # DryadLinqQueryGen.cs): "off" = no checking, "warn" = run the job
    # but log findings to the EventLog (viewer Diagnostics section),
    # "error" = refuse to submit when error-severity findings exist
    # (analysis.LintError).  Dataset.check() is the interactive form.
    lint: str = "off"
    # per-device HBM budget for the static cost analyzer
    # (analysis/cost.py, DTA2xx): with lint enabled, a plan whose
    # predicted per-device working set PROVABLY exceeds this many bytes
    # fails pre-submit (DTA201); predicted-spill warnings (DTA202) and
    # the cache()-of-edge-scale-data warning (DTA204) key off it too.
    # 0 = unknown/disabled — the cost pass still runs (per-stage cost
    # table, unbounded-fan-out warnings, runtime cost_model_miss
    # cross-check) but never gates on a memory budget.
    device_hbm_bytes: int = 0

    def __post_init__(self):
        checks = [
            (self.ooc_group_bucket_rows > 0,
             "ooc_group_bucket_rows > 0"),
            (self.max_capacity_retries >= 0, "max_capacity_retries >= 0"),
            (self.initial_send_slack >= 1, "initial_send_slack >= 1"),
            (self.exchange_probe_min_mb >= -1,
             "exchange_probe_min_mb >= -1"),
            (self.range_samples_per_partition >= 2,
             "range_samples_per_partition >= 2"),
            (self.compile_cache_size >= 1, "compile_cache_size >= 1"),
            (self.salt_trigger_factor >= 2, "salt_trigger_factor >= 2"),
            (self.salt_hot_factor >= 1.0, "salt_hot_factor >= 1.0"),
            (self.salt_topk >= 1, "salt_topk >= 1"),
            (self.failure_budget >= 0, "failure_budget >= 0"),
            (self.spill_compression in (None, "gzip"),
             "spill_compression in (None, 'gzip')"),
            (self.store_compression in (None, "gzip"),
             "store_compression in (None, 'gzip')"),
            (self.collect_shrink_min_capacity >= 1,
             "collect_shrink_min_capacity >= 1"),
            (self.collect_shrink_waste_factor >= 1,
             "collect_shrink_waste_factor >= 1"),
            (self.text_max_line_len >= 1, "text_max_line_len >= 1"),
            (self.token_max_len >= 1, "token_max_len >= 1"),
            (self.string_max_len >= 1, "string_max_len >= 1"),
            (len(self.token_delims) >= 1, "token_delims non-empty"),
            (self.ooc_chunk_rows >= 1, "ooc_chunk_rows >= 1"),
            (self.ooc_hash_buckets >= 1, "ooc_hash_buckets >= 1"),
            (self.ooc_inflight >= 1, "ooc_inflight >= 1"),
            (self.ooc_incore_bytes >= 0, "ooc_incore_bytes >= 0"),
            (self.ooc_auto_stream_rows >= 0, "ooc_auto_stream_rows >= 0"),
            (self.ooc_join_build_rows >= 1, "ooc_join_build_rows >= 1"),
            (self.ooc_prefetch_depth >= 0, "ooc_prefetch_depth >= 0"),
            (self.cluster_processes >= 1, "cluster_processes >= 1"),
            (self.cluster_devices_per_process >= 1,
             "cluster_devices_per_process >= 1"),
            (self.gang_heartbeat_s >= 0, "gang_heartbeat_s >= 0"),
            (self.gang_heartbeat_timeout_s > 0,
             "gang_heartbeat_timeout_s > 0"),
            (self.gang_straggler_rel_margin >= 0,
             "gang_straggler_rel_margin >= 0"),
            (self.gang_straggler_abs_margin_s > 0,
             "gang_straggler_abs_margin_s > 0"),
            (0.0 <= self.speculation_duplication_budget <= 1.0,
             "speculation_duplication_budget in [0, 1]"),
            (self.speculation_min_samples >= 1,
             "speculation_min_samples >= 1"),
            (self.join_expansion > 0, "join_expansion > 0"),
            (self.broadcast_join_threshold >= 0,
             "broadcast_join_threshold >= 0"),
            (self.max_loop_iterations >= 1, "max_loop_iterations >= 1"),
            (self.lint in ("off", "warn", "error"),
             "lint in ('off', 'warn', 'error')"),
            (self.device_hbm_bytes >= 0, "device_hbm_bytes >= 0"),
            (self.adaptive in ("off", "on"),
             "adaptive in ('off', 'on')"),
            (self.adapt_skew_factor >= 1.0, "adapt_skew_factor >= 1.0"),
            (self.adapt_agg_collapse_rows >= 1,
             "adapt_agg_collapse_rows >= 1"),
            (self.adapt_agg_expand_rows >= 1,
             "adapt_agg_expand_rows >= 1"),
            (self.adapt_shrink_factor >= 1.0,
             "adapt_shrink_factor >= 1.0"),
            (self.adapt_broadcast_max_ratio > 0,
             "adapt_broadcast_max_ratio > 0"),
            (self.resource_sample_s >= 0, "resource_sample_s >= 0"),
        ]
        for ok, msg in checks:
            if not ok:
                raise ValueError(f"JobConfig: {msg}")

    def replace(self, **kw) -> "JobConfig":
        return dataclasses.replace(self, **kw)
