"""Rename-commit: the one atomic durable-write helper.

Every durable artifact in the tree commits the same way — write a
uniquely-named temp file in the SAME directory as the target, flush,
``os.fsync``, then ``os.replace`` into place.  Readers observe either
the old complete file or the new complete file, never a partial write
(the rename-commit contract the reference's partitioned stores rely
on; ``os.replace`` is only atomic within one filesystem, hence
same-directory temp names).  The temp name embeds pid + thread id +
random bytes so two writers racing on one target never scribble into
a shared temp file — both renames are atomic and last writer wins.

Call sites: the compile/plan FileCache (utils/compile_cache.py), the
store manifest commit (io/store.append_store), standing-query state
(inc/state.py), standing-query registrations (inc/standing.py), and
the service write-ahead journal + per-job checkpoints
(service/durable/).
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import Any, Iterator

__all__ = ["atomic_write", "atomic_write_bytes", "atomic_write_text",
           "atomic_write_json"]


def _tmp_path(path: str) -> str:
    d = os.path.dirname(os.path.abspath(path)) or "."
    return os.path.join(
        d, f".tmp-{os.getpid()}-{threading.get_ident()}-"
           f"{os.urandom(4).hex()}")


@contextmanager
def atomic_write(path: str, mode: str = "wb",
                 fsync: bool = True) -> Iterator[Any]:
    """Open a temp file for writing; commit it to ``path`` on clean
    exit (flush + fsync + ``os.replace``).  On an exception the temp
    file is unlinked and ``path`` is untouched."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    tmp = _tmp_path(path)
    try:
        with open(tmp, mode) as f:
            yield f
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        # reached with tmp still present only on the exception path
        try:
            os.unlink(tmp)
        except OSError:
            pass


def atomic_write_bytes(path: str, data: bytes,
                       fsync: bool = True) -> None:
    with atomic_write(path, "wb", fsync=fsync) as f:
        f.write(data)


def atomic_write_text(path: str, text: str,
                      fsync: bool = True) -> None:
    with atomic_write(path, "w", fsync=fsync) as f:
        f.write(text)


def atomic_write_json(path: str, obj: Any, fsync: bool = True,
                      **json_kw: Any) -> None:
    with atomic_write(path, "w", fsync=fsync) as f:
        json.dump(obj, f, **json_kw)
