"""Sequential semantics oracle (the reference's LocalDebug mode).

The reference runs every query twice in tests — cluster mode and
LINQ-to-objects (`context.LocalDebug = true`, LinqToDryad/DryadLinqQuery.cs:349,
DryadLinqEnumerable.cs) — and compares.  This module is our LINQ-to-objects:
a pure numpy/python interpreter of the logical expression DAG, independent of
JAX, batches, partitions, and collectives.  Tests run each query through both
paths and compare row multisets (tests/utils.py).
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List

import numpy as np

from dryad_tpu.plan import expr as E

__all__ = ["run_oracle"]

Table = Dict[str, Any]  # column name -> np.ndarray | list[bytes]


def _nrows(t: Table) -> int:
    for v in t.values():
        return len(v)
    return 0


def _row(t: Table, i: int):
    return {k: (v[i] if isinstance(v, list) else v[i]) for k, v in t.items()}


def _take_rows(t: Table, idx) -> Table:
    out = {}
    for k, v in t.items():
        if isinstance(v, list):
            out[k] = [v[i] for i in idx]
        else:
            out[k] = np.asarray(v)[idx]
    return out


def _to_np(cols: Table) -> Table:
    return {k: (v if isinstance(v, list) else np.asarray(v))
            for k, v in cols.items()}


def _tokenize(line: bytes, delims: bytes, max_len: int, lower: bool):
    out = []
    tok = bytearray()
    for b in line:
        if b in delims:
            if tok:
                out.append(bytes(tok[:max_len]))
                tok = bytearray()
        else:
            tok.append(b)
    if tok:
        out.append(bytes(tok[:max_len]))
    if lower:
        out = [t.lower() for t in out]
    return out


def _apply_device_fn(fn, tables: List[Table], with_index: bool = False
                     ) -> Table:
    """Oracle-side evaluation of a DEVICE UDF (Batch -> Batch) over whole
    tables treated as one partition: build Batches with jax (host
    backend), call the same callable the executor jits, and read the
    valid rows back.  This closes the oracle blind spot where
    apply_per_partition / cross_apply went unchecked without a host_fn
    (VERDICT r3 weak 7) — the reference's LocalDebug likewise runs the
    IDENTICAL user lambda through LINQ-to-objects
    (DryadLinqQuery.cs:349)."""
    import jax.numpy as jnp

    from dryad_tpu.data.columnar import batch_from_numpy, batch_to_numpy

    def widest(t: Table) -> int:
        w = 1
        for v in t.values():
            if isinstance(v, list):
                w = max(w, max((len(x) for x in v), default=1))
        return w

    batches = [batch_from_numpy(t, str_max_len=widest(t)) for t in tables]
    args = list(batches)
    if with_index:
        args.append(jnp.zeros((), jnp.int32))  # the single oracle "partition"
    out = fn(*args)
    return {k: (v if isinstance(v, list) else np.asarray(v))
            for k, v in batch_to_numpy(out).items()}


def _agg(kind: str, vals: List[Any]):
    if kind == "count":
        return len(vals)
    if kind == "sum":
        return np.sum(vals, axis=0)
    if kind == "min":
        return np.min(vals, axis=0)
    if kind == "max":
        return np.max(vals, axis=0)
    if kind == "mean":
        return np.mean(vals, axis=0)
    if kind == "any":
        return bool(np.any(vals))
    if kind == "all":
        return bool(np.all(vals))
    raise ValueError(kind)


def _eval_decomposable(dec: "E.Decomposable", t: Dict[str, Any],
                       idx: List[int], oname: str) -> Dict[str, Any]:
    """Sequential-reference evaluation of a Decomposable over one group:
    seed each row, left-fold merge, finalize.  Mirrors the kernel's
    segmented-scan semantics exactly (same seed/merge/finalize callables,
    applied per single-row state)."""
    import functools

    from dryad_tpu.data.columnar import string_column_from_list

    # string columns feed seed as 1-row StringColumns (the same columnar
    # repr the kernel's seed sees, width = the column's widest value so
    # every row state has matching shapes for merge)
    widths = {k: max((len(x) for x in v), default=1) or 1
              for k, v in t.items() if isinstance(v, list)}

    def row_state(i):
        cols = {}
        for k, v in t.items():
            if isinstance(v, list):  # bytes column
                cols[k] = string_column_from_list([v[i]], 1, widths[k])
            else:
                cols[k] = np.asarray(v)[i: i + 1]
        return dec.seed(cols)

    states = [row_state(i) for i in idx]
    merged = functools.reduce(dec.merge, states)
    val = dec.finalize(merged) if dec.finalize is not None else merged
    named = val if isinstance(val, dict) else {oname: val}
    return {k: np.asarray(v)[0] if np.asarray(v).shape
            and np.asarray(v).shape[0] == 1 else np.asarray(v)
            for k, v in named.items()}


def _key_of(row: dict, keys) -> tuple:
    names = keys if keys else sorted(row.keys())
    out = []
    for k in names:
        v = row[k]
        out.append(v if isinstance(v, bytes) else
                   (v.item() if hasattr(v, "item") else v))
    return tuple(out)


def run_oracle(root: E.Node, bindings: Dict[str, Table] | None = None) -> Table:
    bindings = bindings or {}
    memo: Dict[int, Table] = {}

    def ev(n: E.Node) -> Table:
        if n.id in memo:
            return memo[n.id]
        t = _ev(n)
        memo[n.id] = t
        return t

    def _ev(n: E.Node) -> Table:
        if isinstance(n, E.Source):
            if n.host is None:
                raise ValueError("Source has no host data for oracle")
            return _to_np(n.host)
        if isinstance(n, E.Placeholder):
            return _to_np(bindings[n.name])
        if isinstance(n, E.Map):
            t = ev(n.parents[0])
            out = n.fn(dict(t))
            return {k: (v if isinstance(v, list) else np.asarray(v))
                    for k, v in out.items()}
        if isinstance(n, E.Filter):
            t = ev(n.parents[0])
            mask = np.asarray(n.fn(dict(t))).astype(bool)
            return _take_rows(t, np.nonzero(mask)[0])
        if isinstance(n, E.FlatTokens):
            t = ev(n.parents[0])
            toks: List[bytes] = []
            for line in t[n.column]:
                toks.extend(_tokenize(line, n.delims, n.max_token_len,
                                      n.lower))
            return {n.column: toks}
        if isinstance(n, E.ApplyPerPartition):
            t = ev(n.parents[0])
            if n.host_fn is not None:
                out = n.host_fn(dict(t))
                return {k: (v if isinstance(v, list) else np.asarray(v))
                        for k, v in out.items()}
            # no host_fn: run the DEVICE fn itself over the whole table
            # as one partition (index 0)
            return _apply_device_fn(n.fn, [t], with_index=n.with_index)
        if isinstance(n, E.FlatMap):
            t = ev(n.parents[0])
            out_cols, mask = n.fn({k: np.asarray(v) for k, v in t.items()})
            mask = np.asarray(mask).astype(bool)
            idx = np.nonzero(mask.reshape(-1))[0]
            out = {}
            for k, v in out_cols.items():
                arr = np.asarray(v)
                flat = arr.reshape((-1,) + arr.shape[2:])
                out[k] = flat[idx]
            return out
        if isinstance(n, E.Zip):
            lt, rt = ev(n.parents[0]), ev(n.parents[1])
            nmin = min(_nrows(lt), _nrows(rt))
            out = {k: (v[:nmin] if isinstance(v, list) else
                       np.asarray(v)[:nmin]) for k, v in lt.items()}
            for k, v in rt.items():
                name = k if k not in out else k + n.suffix
                out[name] = (v[:nmin] if isinstance(v, list)
                             else np.asarray(v)[:nmin])
            return out
        if isinstance(n, E.SlidingWindow):
            t = ev(n.parents[0])
            nrows = _nrows(t)
            nwin = max(0, nrows - n.w + 1)
            out = {}
            for k, v in t.items():
                if isinstance(v, list):
                    out[k] = [[v[i + j] for j in range(n.w)]
                              for i in range(nwin)]
                else:
                    arr = np.asarray(v)
                    out[k] = np.stack([arr[i:i + n.w]
                                       for i in range(nwin)]) if nwin else \
                        np.zeros((0, n.w) + arr.shape[1:], arr.dtype)
            return out
        if isinstance(n, E.WithRowIndex):
            t = ev(n.parents[0])
            out = dict(t)
            out[n.column] = np.arange(_nrows(t), dtype=np.int32)
            return out
        if isinstance(n, E.AssumePartitioning):
            return ev(n.parents[0])
        if isinstance(n, E.SkipTake):
            t = ev(n.parents[0])
            nrows = _nrows(t)
            if n.op == "skip":
                return _take_rows(t, range(min(n.n, nrows), nrows))
            pred = np.asarray(n.fn({k: np.asarray(v) if not isinstance(v, list)
                                    else v for k, v in t.items()})).astype(bool)
            cut = nrows
            for i in range(nrows):
                if not pred[i]:
                    cut = i
                    break
            if n.op == "take_while":
                return _take_rows(t, range(cut))
            return _take_rows(t, range(cut, nrows))
        if isinstance(n, E.GroupByAgg):
            t = ev(n.parents[0])
            nrows = _nrows(t)
            groups: Dict[tuple, List[int]] = collections.defaultdict(list)
            order: List[tuple] = []
            for i in range(nrows):
                k = _key_of({kk: t[kk][i] for kk in n.keys}, tuple(n.keys))
                if k not in groups:
                    order.append(k)
                groups[k].append(i)
            out: Table = {k: [] for k in n.keys}
            agg_out_names: List[str] = []
            for k in order:
                idx = groups[k]
                for kk, kv in zip(n.keys, k):
                    out[kk].append(kv)
                for oname, spec in n.aggs.items():
                    if isinstance(spec, E.Decomposable):
                        named = _eval_decomposable(spec, t, idx, oname)
                    else:
                        kind, col = spec
                        vals = [t[col][i] for i in idx] if col \
                            else [None] * len(idx)
                        named = {oname: _agg(kind, vals)}
                    for cname, v in named.items():
                        out.setdefault(cname, []).append(v)
                        if cname not in agg_out_names:
                            agg_out_names.append(cname)
            return {k: (v if v and isinstance(v[0], bytes) else np.asarray(v))
                    for k, v in out.items()}
        if isinstance(n, (E.GroupApply, E.GroupTopK, E.GroupRankSelect)):
            t = ev(n.parents[0])
            nrows = _nrows(t)
            groups: Dict[tuple, List[int]] = collections.defaultdict(list)
            order: List[tuple] = []
            for i in range(nrows):
                k = _key_of({kk: t[kk][i] for kk in n.keys}, tuple(n.keys))
                if k not in groups:
                    order.append(k)
                groups[k].append(i)
            if isinstance(n, E.GroupTopK):
                idx: List[int] = []
                for k in order:
                    g = groups[k]
                    # python sorted is stable even with reverse=True, same
                    # as the device's stable inverted-lane lexsort
                    top = sorted(g, key=lambda i: t[n.by][i],
                                 reverse=n.descending)[:n.k]
                    idx.extend(top)
                return _take_rows(t, idx)
            if isinstance(n, E.GroupRankSelect):
                out: Table = {k: [] for k in n.keys}
                oname = n.out or n.by
                out[oname] = []
                for k in order:
                    g = sorted(groups[k], key=lambda i: t[n.by][i])
                    if n.rank == "median":
                        pick = g[(len(g) - 1) // 2]
                    elif n.rank == "min":
                        pick = g[0]
                    else:
                        pick = g[-1]
                    for kk, kv in zip(n.keys, k):
                        out[kk].append(kv)
                    out[oname].append(t[n.by][pick])
                return {k: (v if v and isinstance(v[0], bytes)
                            else np.asarray(v)) for k, v in out.items()}
            # GroupApply: run the SAME fn per group (jax works eagerly on
            # numpy inputs), padding each group to group_capacity — rows
            # past count are zeros, which fn must not read (the device
            # contract: rows >= count are unspecified)
            import jax.numpy as jnp

            from dryad_tpu.data.columnar import StringColumn
            # the device right-sizes group_capacity via measured-need
            # retries, so the eager reference must be exact regardless of
            # the declared capacity: pad to the largest group
            C = max([n.group_capacity] + [len(g) for g in groups.values()])
            out_rows: List[Dict[str, Any]] = []
            for k in order:
                g = groups[k]
                cols: Dict[str, Any] = {}
                for kk, v in t.items():
                    if isinstance(v, list):
                        L = max([len(b) for b in v] or [1]) or 1
                        data = np.zeros((C, L), np.uint8)
                        lens = np.zeros((C,), np.int32)
                        for r, i in enumerate(g[:C]):
                            b = v[i]
                            data[r, :len(b)] = np.frombuffer(b, np.uint8)
                            lens[r] = len(b)
                        cols[kk] = StringColumn(jnp.asarray(data),
                                                jnp.asarray(lens))
                    else:
                        arr = np.asarray(v)
                        p = np.zeros((C,) + arr.shape[1:], arr.dtype)
                        p[:min(len(g), C)] = arr[g[:C]]
                        # hand fn jax arrays, exactly as on device — numpy
                        # arrays fancy-indexed by jax index arrays return
                        # wrong results silently
                        cols[kk] = jnp.asarray(p)
                oc, mask = n.fn(cols, jnp.int32(len(g)))
                mask = np.asarray(mask).astype(bool)
                for r in np.nonzero(mask)[0]:
                    row: Dict[str, Any] = {}
                    for kk, kv in zip(n.keys, k):
                        row[kk] = kv
                    for cname, cv in oc.items():
                        if isinstance(cv, StringColumn):
                            d = np.asarray(cv.data)[r]
                            l = int(np.asarray(cv.lengths)[r])
                            row[cname] = bytes(d[:l])
                        else:
                            row[cname] = np.asarray(cv)[r]
                    out_rows.append(row)
            if not out_rows:
                names = list(n.keys)
            else:
                names = list(out_rows[0].keys())
            res: Table = {kk: [] for kk in names}
            for row in out_rows:
                for kk in names:
                    res[kk].append(row[kk])
            return {k: (v if v and isinstance(v[0], bytes)
                        else np.asarray(v)) for k, v in res.items()}
        if isinstance(n, E.Join):
            lt, rt = ev(n.parents[0]), ev(n.parents[1])
            rmap: Dict[tuple, List[int]] = collections.defaultdict(list)
            for j in range(_nrows(rt)):
                rmap[_key_of({k: rt[k][j] for k in n.right_keys},
                             tuple(n.right_keys))].append(j)
            rkeyset = set(n.right_keys)
            rextra = [k for k in rt.keys() if k not in rkeyset]
            out_names = list(lt.keys()) + [
                (k if k not in lt else k + "_r") for k in rextra]
            out: Table = {k: [] for k in out_names}
            how = getattr(n, "how", "inner")

            def _zero_of(proto):
                if isinstance(proto, list):
                    return b""
                p = np.asarray(proto)
                return np.zeros((1,) + p.shape[1:], p.dtype)[0]

            matched_right: set = set()
            for i in range(_nrows(lt)):
                k = _key_of({kk: lt[kk][i] for kk in n.left_keys},
                            tuple(n.left_keys))
                matches = rmap.get(k, ())
                matched_right.update(matches)
                for j in matches:
                    for kk in lt.keys():
                        out[kk].append(lt[kk][i])
                    for kk in rextra:
                        name = kk if kk not in lt else kk + "_r"
                        out[name].append(rt[kk][j])
                if how in ("left", "full") and not matches:
                    # unmatched left row: right columns zero-filled
                    for kk in lt.keys():
                        out[kk].append(lt[kk][i])
                    for kk in rextra:
                        name = kk if kk not in lt else kk + "_r"
                        out[name].append(_zero_of(rt[kk]))
            if how in ("right", "full"):
                key_map = dict(zip(n.left_keys, n.right_keys))
                for j in range(_nrows(rt)):
                    if j in matched_right:
                        continue
                    # unmatched right row: left key columns take the right
                    # key values, other left columns zero-filled
                    for kk in lt.keys():
                        if kk in key_map:
                            out[kk].append(rt[key_map[kk]][j])
                        else:
                            out[kk].append(_zero_of(lt[kk]))
                    for kk in rextra:
                        name = kk if kk not in lt else kk + "_r"
                        out[name].append(rt[kk][j])
            return {k: (v if v and isinstance(v[0], bytes) else np.asarray(v))
                    for k, v in out.items()}
        if isinstance(n, E.OrderBy):
            t = ev(n.parents[0])
            nrows = _nrows(t)
            # lexicographic multi-key sort via successive stable sorts from
            # the least significant key (handles bytes descending exactly)
            idx = list(range(nrows))
            for col, desc in reversed(n.keys):
                vals = t[col]
                idx.sort(key=lambda i: vals[i], reverse=desc)
            return _take_rows(t, idx)
        if isinstance(n, E.Distinct):
            t = ev(n.parents[0])
            seen = set()
            idx = []
            keys = tuple(n.keys) or tuple(sorted(t.keys()))
            for i in range(_nrows(t)):
                k = _key_of({kk: t[kk][i] for kk in keys}, keys)
                if k not in seen:
                    seen.add(k)
                    idx.append(i)
            return _take_rows(t, idx)
        if isinstance(n, E.SetOp):
            lt, rt = ev(n.parents[0]), ev(n.parents[1])
            names = list(lt.keys())
            lrows = [_key_of({k: lt[k][i] for k in names}, tuple(names))
                     for i in range(_nrows(lt))]
            rrows = {_key_of({k: rt[k][i] for k in names}, tuple(names))
                     for i in range(_nrows(rt))}
            seen = set()
            idx = []
            for i, k in enumerate(lrows):
                if k in seen:
                    continue
                if n.op == "union":
                    seen.add(k)
                    idx.append(i)
                elif n.op == "intersect" and k in rrows:
                    seen.add(k)
                    idx.append(i)
                elif n.op == "except" and k not in rrows:
                    seen.add(k)
                    idx.append(i)
            out = _take_rows(lt, idx)
            if n.op == "union":
                extra = []
                for i in range(_nrows(rt)):
                    k = _key_of({kk: rt[kk][i] for kk in names}, tuple(names))
                    if k not in seen:
                        seen.add(k)
                        extra.append(i)
                radd = _take_rows(rt, extra)
                out = {k: (list(out[k]) + list(radd[k])
                           if isinstance(out[k], list)
                           else np.concatenate([out[k], radd[k]]))
                       for k in names}
            return out
        if isinstance(n, E.Concat):
            lt, rt = ev(n.parents[0]), ev(n.parents[1])
            return {k: (list(lt[k]) + list(rt[k]) if isinstance(lt[k], list)
                        else np.concatenate([lt[k], rt[k]]))
                    for k in lt.keys()}
        if isinstance(n, (E.HashRepartition, E.RangeRepartition)):
            return ev(n.parents[0])
        if isinstance(n, E.Broadcast):
            t = ev(n.parents[0])
            reps = n.parents[0].npartitions
            return {k: (list(v) * reps if isinstance(v, list)
                        else np.tile(v, (reps,) + (1,) * (v.ndim - 1)))
                    for k, v in t.items()}
        if isinstance(n, E.Take):
            t = ev(n.parents[0])
            return _take_rows(t, range(min(n.n, _nrows(t))))
        if isinstance(n, E.WithCapacity):
            return ev(n.parents[0])
        if isinstance(n, E.CrossApply):
            lt, rt = ev(n.parents[0]), ev(n.parents[1])
            if n.host_fn is not None:
                out = n.host_fn(dict(lt), dict(rt))
                return {k: (v if isinstance(v, list) else np.asarray(v))
                        for k, v in out.items()}
            # no host_fn: the device fn sees (left partition, full right
            # table); with one oracle partition that is exactly (lt, rt)
            return _apply_device_fn(n.fn, [lt, rt])
        raise TypeError(f"oracle: unhandled node {type(n).__name__}")

    return ev(root)
