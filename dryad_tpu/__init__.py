"""dryad_tpu — a TPU-native data-parallel dataflow framework.

A brand-new implementation of the capabilities of Microsoft Research's
Dryad + DryadLINQ (declarative partitioned queries -> optimized DAG ->
fault-tolerant distributed execution), designed for TPUs: query stages trace
to jax.jit/shard_map programs over a device mesh; hash/range/group shuffles
are XLA collectives over ICI; a host-side DAG scheduler provides replay-based
fault tolerance.  See SURVEY.md for the reference analysis.
"""

__version__ = "0.1.0"
