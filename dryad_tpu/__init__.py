"""dryad_tpu — a TPU-native data-parallel dataflow framework.

A brand-new implementation of the capabilities of Microsoft Research's
Dryad + DryadLINQ (declarative partitioned queries -> optimized DAG ->
fault-tolerant distributed execution), designed for TPUs: query stages trace
to jax.jit/shard_map programs over a device mesh; hash/range/group shuffles
are XLA collectives over ICI; a host-side DAG scheduler provides replay-based
fault tolerance.  See SURVEY.md for the reference analysis.
"""

__version__ = "0.2.0"

from dryad_tpu.utils import jax_compat as _jax_compat  # noqa: F401,E402

from dryad_tpu.api.dataset import Context, Dataset  # noqa: F401,E402
from dryad_tpu.parallel.mesh import make_mesh  # noqa: F401,E402
from dryad_tpu.plan.expr import Decomposable  # noqa: F401,E402
