"""Vectorized text ops: tokenization (the WordCount SelectMany kernel).

The reference's WordCount does ``SelectMany(line => line.Split(' '))``
(reference samples/WordCount.cs.pp) with per-record C# string ops.  On TPU we
tokenize a whole batch of lines in one fused program: flatten all line bytes
into one stream (row boundaries act as delimiters), mark token starts with
elementwise compares, place tokens with a prefix-sum + scatter, and slice
token bytes with a windowed gather.  No per-row loop, no dynamic shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dryad_tpu.data.columnar import Batch, StringColumn

__all__ = ["split_tokens", "lower_ascii"]


def lower_ascii(col: StringColumn) -> StringColumn:
    d = col.data
    is_upper = (d >= ord("A")) & (d <= ord("Z"))
    return StringColumn(jnp.where(is_upper, d + 32, d), col.lengths)


def _is_delim(b: jax.Array, delims: bytes) -> jax.Array:
    m = jnp.zeros(b.shape, jnp.bool_)
    for ch in delims:
        m = m | (b == ch)
    return m


def split_tokens(batch: Batch, column: str, out_capacity: int,
                 max_token_len: int = 24,
                 delims: bytes = b" \t\r\n.,;:!?\"'()[]{}<>") -> Batch:
    """Split a string column into a batch of tokens (one row per token).

    Returns ``(tokens_batch, overflow)``: the batch has a single string
    column named ``column``; tokens longer than ``max_token_len`` are
    truncated (semantic); ``overflow`` is True when tokens beyond
    ``out_capacity`` were dropped (a capacity-planning failure — the
    executor retries the stage with scaled capacity).
    """
    col: StringColumn = batch.columns[column]
    cap, L = col.capacity, col.max_len
    valid_row = batch.valid_mask()

    # flatten to one byte stream; bytes past each row's length and rows past
    # count are forced to delimiter (0x20) so they never join tokens
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_row = (pos < col.lengths[:, None]) & valid_row[:, None]
    flat = jnp.where(in_row, col.data, ord(" ")).reshape(-1)  # [cap*L]
    N = cap * L

    nondelim = ~_is_delim(flat, delims)
    prev_nondelim = jnp.concatenate([jnp.zeros((1,), jnp.bool_), nondelim[:-1]])
    # row starts break tokens even without explicit delimiters because each
    # row's tail is padded with spaces; first byte of stream handled by prev=0
    is_start = nondelim & ~prev_nondelim

    # token id per start; scatter start positions into the output table
    tid = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    num_tokens = is_start.sum(dtype=jnp.int32)
    start_pos = jnp.full((out_capacity,), 0, jnp.int32)
    scatter_idx = jnp.where(is_start & (tid < out_capacity), tid,
                            out_capacity)  # OOB -> dropped
    start_pos = start_pos.at[scatter_idx].set(
        jnp.arange(N, dtype=jnp.int32), mode="drop")

    # token length = distance from each position to the next delimiter,
    # via a single reverse cummin primitive (a custom-combine
    # associative_scan here compiles pathologically at scale on TPU)
    delim_pos = jnp.where(~nondelim, jnp.arange(N, dtype=jnp.int32), N)
    next_delim = jnp.flip(jax.lax.cummin(jnp.flip(delim_pos)))
    tok_len_all = jnp.minimum(next_delim - jnp.arange(N, dtype=jnp.int32),
                              max_token_len)

    tok_valid = jnp.arange(out_capacity, dtype=jnp.int32) < jnp.minimum(
        num_tokens, out_capacity)
    tok_len = jnp.where(tok_valid, jnp.take(tok_len_all, start_pos), 0)

    # windowed gather of token bytes
    w = jnp.arange(max_token_len, dtype=jnp.int32)[None, :]
    idx = jnp.clip(start_pos[:, None] + w, 0, N - 1)
    tok_bytes = jnp.where(w < tok_len[:, None], jnp.take(flat, idx), 0)

    out = Batch({column: StringColumn(tok_bytes, tok_len)},
                jnp.minimum(num_tokens, out_capacity))
    # second return is the NEED channel: 0 = fits, else the actual row
    # requirement — lets the executor right-size the retry in one shot
    # (the dynamic-manager size-feedback idea, DrDynamicDistributor.cpp:388)
    need = jnp.where(num_tokens > out_capacity, num_tokens, 0)
    return out, need.astype(jnp.int32)
