"""Vectorized text ops: tokenization (the WordCount SelectMany kernel).

The reference's WordCount does ``SelectMany(line => line.Split(' '))``
(reference samples/WordCount.cs.pp) with per-record C# string ops.  On TPU
tokens cannot cross row boundaries, so everything is PER-ROW work on the
[cap, L] byte grid: batched L-wide sort networks cost ~log^2(L)/2
compare-exchange stages instead of a global byte-stream sort's
~log^2(cap*L)/2, and NO random gathers appear anywhere before the final
byte extraction (measured 9-16 ns per gathered element on this chip —
gathers, not compute, dominated every earlier tokenizer design).

``tokenize_group_count`` is the fused SelectMany+GroupBy+Count: tokens
are hashed IN PLACE on the grid (two 32-bit polynomial window hashes,
constant-shift adds only), grouped by hash, and the expensive windowed
byte extraction runs only for the per-group REPRESENTATIVES — cost
proportional to the vocabulary, not the token stream.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from dryad_tpu.data.columnar import Batch, StringColumn

__all__ = ["split_tokens", "tokenize_group_count", "lower_ascii"]


def lower_ascii(col: StringColumn) -> StringColumn:
    d = col.data
    is_upper = (d >= ord("A")) & (d <= ord("Z"))
    return StringColumn(jnp.where(is_upper, d + 32, d), col.lengths)


def _is_delim(b: jax.Array, delims: bytes) -> jax.Array:
    m = jnp.zeros(b.shape, jnp.bool_)
    for ch in delims:
        m = m | (b == ch)
    return m


def _lower_grid(g: jax.Array) -> jax.Array:
    is_upper = (g >= ord("A")) & (g <= ord("Z"))
    return jnp.where(is_upper, g + 32, g)


def _token_grid(batch: Batch, column: str, delims: bytes,
                max_token_len: int, lower: bool = False):
    """Per-row token structure on the [cap, L] byte grid: returns
    (grid, is_start, lenpos, tok_cnt_row).  ``lenpos[r, i]`` is the
    (clamped) length of the token starting at byte i, meaningful where
    ``is_start``."""
    col: StringColumn = batch.columns[column]
    cap, L = col.capacity, col.max_len
    valid_row = batch.valid_mask()
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_row = (pos < col.lengths[:, None]) & valid_row[:, None]
    grid = jnp.where(in_row, col.data, ord(" "))            # [cap, L]
    # delimiter classification sees the RAW bytes; lowering applies after
    # (identical to the unfused split -> lower_ascii order, so letter
    # delimiters classify the same way on both paths)
    nondelim = ~_is_delim(grid, delims)
    if lower:
        grid = _lower_grid(grid)
    prev_nd = jnp.pad(nondelim[:, :-1], ((0, 0), (1, 0)))
    is_start = nondelim & ~prev_nd                          # [cap, L]
    delim_pos = jnp.where(~nondelim, pos, L)
    next_delim = jnp.flip(jax.lax.cummin(
        jnp.flip(delim_pos, axis=1), axis=1), axis=1)       # [cap, L]
    lenpos = jnp.minimum(next_delim - pos, max_token_len)
    return grid, is_start, lenpos, is_start.sum(axis=1, dtype=jnp.int32)


def _token_slots(is_start, extra_grids, tok_cnt_row, cap: int, L: int,
                 out_capacity: int, max_tokens_per_row: int | None):
    """Compact per-START-cell lanes into flat token-slot order with NO
    random gathers: (1) a batched stable row sort on ~is_start lands the
    row's k-th token's lanes at column k; (2) every (row, k) cell knows
    its output slot base_excl[row] + k ELEMENTWISE, so one value-carry
    sort by slot id produces the flat order.  Returns (slot lanes
    [out_capacity] per extra grid, num_tokens, need_row_overflow)."""
    from dryad_tpu.ops.pallas_kernels import prefix_sum

    K = min(max_tokens_per_row or (L // 2 + 1), L // 2 + 1)
    srow = jax.lax.sort(
        ((~is_start).astype(jnp.uint8),) + tuple(extra_grids),
        dimension=1, num_keys=1, is_stable=True)            # [cap, L]
    cnt_k = jnp.minimum(tok_cnt_row, K)
    base_incl = prefix_sum(cnt_k)                           # [cap]
    num_tokens = base_incl[cap - 1]
    base_excl = (base_incl - cnt_k).astype(jnp.uint32)
    kk = jnp.arange(K, dtype=jnp.uint32)[None, :]
    slot = base_excl[:, None] + kk                          # [cap, K]
    slot = jnp.where(kk < cnt_k.astype(jnp.uint32)[:, None],
                     slot, jnp.uint32(0xFFFFFFFF))
    sorted_out = jax.lax.sort(
        (slot.reshape(-1),) + tuple(s[:, :K].reshape(-1) for s in srow[1:]),
        num_keys=1, is_stable=False)
    M = cap * K

    def _slots(a):
        if M >= out_capacity:
            return a[:out_capacity]
        return jnp.concatenate(
            [a, jnp.zeros((out_capacity - M,), a.dtype)])

    # rows beyond the static per-row token bound lose tokens: a NEED
    # (the executor retries with scale, like every capacity channel)
    over_row = jnp.max(tok_cnt_row) > K
    return [_slots(a) for a in sorted_out[1:]], num_tokens, over_row


def _extract_bytes(flat: jax.Array, start_pos, tok_len, T: int,
                   max_token_len: int):
    """Token bytes via PACKED u32 gather + byte realignment: gathering
    one u32 word moves 4 bytes, so a max_token_len window needs len/4 + 1
    word fetches instead of len byte fetches.  Little-endian bitcast:
    byte i of a word occupies bits [8i, 8i+8), so >> (8*s) realigns a
    window starting at sub-offset s.  Cost is ~10 ns per gathered WORD —
    callers keep T as small as semantics allow."""
    N = flat.shape[0]
    nw = -(-max_token_len // 4) + 1
    pad4 = (-N) % 4
    flat4 = jnp.concatenate([flat, jnp.zeros((pad4,), flat.dtype)]) \
        if pad4 else flat
    n_words = (N + pad4) // 4
    words = jax.lax.bitcast_convert_type(flat4.reshape(-1, 4), jnp.uint32)
    base = start_pos >> 2
    sub = (start_pos & 3).astype(jnp.uint32)[:, None]
    widx = jnp.clip(base[:, None] + jnp.arange(nw, dtype=jnp.int32)[None, :],
                    0, n_words - 1)
    toku32 = jnp.take(words, widx)                      # [T, nw]
    sh = 8 * sub
    lo = toku32[:, :nw - 1] >> sh
    hi = toku32[:, 1:nw] << ((jnp.uint32(32) - sh) & jnp.uint32(31))
    outw = jnp.where(sub == 0, toku32[:, :nw - 1], lo | hi)
    tok_bytes = jax.lax.bitcast_convert_type(outw, jnp.uint8) \
        .reshape(T, (nw - 1) * 4)[:, :max_token_len]
    w = jnp.arange(max_token_len, dtype=jnp.int32)[None, :]
    return jnp.where(w < tok_len[:, None], tok_bytes, 0)


def _poslen_lanes(abs_pos, lenpos, one_lane: bool):
    """(abs_pos, len) as slot-sort carry lanes: packed (abs_pos<<5 | len)
    when positions fit 2^27 and lengths fit 5 bits, else two lanes.  The
    single home of this bit layout (decode: _poslen_decode)."""
    if one_lane:
        return [(abs_pos << 5) | lenpos.astype(jnp.uint32)]
    return [abs_pos, lenpos.astype(jnp.uint32)]


def _poslen_decode(lanes, one_lane: bool, valid):
    if one_lane:
        pk = lanes[0]
        start_pos = (pk >> 5).astype(jnp.int32)
        tok_len = jnp.where(valid, (pk & 0x1F).astype(jnp.int32), 0)
    else:
        start_pos = lanes[0].astype(jnp.int32)
        tok_len = jnp.where(valid, lanes[1].astype(jnp.int32), 0)
    return start_pos, tok_len


def _one_lane_ok(cap: int, L: int, max_token_len: int) -> bool:
    return cap * L < (1 << 27) and max_token_len < 32


def split_tokens(batch: Batch, column: str, out_capacity: int,
                 max_token_len: int = 24,
                 delims: bytes = b" \t\r\n.,;:!?\"'()[]{}<>",
                 max_tokens_per_row: int | None = None
                 ) -> Tuple[Batch, jax.Array]:
    """Split a string column into a batch of tokens (one row per token).

    Returns ``(tokens_batch, need)``: the batch has a single string
    column named ``column``; tokens longer than ``max_token_len`` are
    truncated (semantic); ``need`` is nonzero when tokens beyond
    ``out_capacity`` (or rows beyond ``max_tokens_per_row``) were
    dropped — the executor retries the stage with scaled capacity.
    """
    col: StringColumn = batch.columns[column]
    cap, L = col.capacity, col.max_len
    grid, is_start, lenpos, tok_cnt_row = _token_grid(
        batch, column, delims, max_token_len)

    rowbase = (jnp.arange(cap, dtype=jnp.uint32) * jnp.uint32(L))[:, None]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    abs_pos = rowbase + pos.astype(jnp.uint32)
    one_lane = _one_lane_ok(cap, L, max_token_len)
    lanes_in = _poslen_lanes(abs_pos, lenpos, one_lane)
    slots, num_tokens, over_row = _token_slots(
        is_start, [jnp.broadcast_to(a, (cap, L)) for a in lanes_in],
        tok_cnt_row, cap, L, out_capacity, max_tokens_per_row)

    t = jnp.arange(out_capacity, dtype=jnp.int32)
    tok_valid = t < jnp.minimum(num_tokens, out_capacity)
    start_pos, tok_len = _poslen_decode(slots, one_lane, tok_valid)

    tok_bytes = _extract_bytes(grid.reshape(-1), start_pos, tok_len,
                               out_capacity, max_token_len)
    out = Batch({column: StringColumn(tok_bytes, tok_len)},
                jnp.minimum(num_tokens, out_capacity))
    # the NEED channel: 0 = fits, else the actual row requirement — lets
    # the executor right-size the retry in one shot (the dynamic-manager
    # size-feedback idea, DrDynamicDistributor.cpp:388)
    need = jnp.where(num_tokens > out_capacity, num_tokens, 0)
    need = jnp.where(over_row, jnp.maximum(need, out_capacity * 2), need)
    return out, need.astype(jnp.int32)


# two independent odd bases for the 64-bit-budget polynomial pair
_HB1 = 0x85EBCA6B
_HB2 = 0xC2B2AE35


def _window_hashes(grid: jax.Array, lenpos: jax.Array, W: int):
    """Per-CELL polynomial hashes of the token starting at each byte:
    h(cell) = sum_{d < len} (byte[d]+1) * B^d  (mod 2^32), for two
    independent odd bases — 24 constant-shift multiply-adds over the
    grid, no scans, no gathers.  Valid where is_start; garbage elsewhere
    (harmless — non-start cells never ride the slot sorts)."""
    cap, L = grid.shape
    padg = jnp.pad(grid, ((0, 0), (0, W))).astype(jnp.uint32)
    h1 = jnp.zeros((cap, L), jnp.uint32)
    h2 = jnp.zeros((cap, L), jnp.uint32)
    p1 = 1
    p2 = 1
    for d in range(W):
        b = padg[:, d:L + d] + jnp.uint32(1)
        m = d < lenpos
        h1 = h1 + jnp.where(m, b * jnp.uint32(p1), 0)
        h2 = h2 + jnp.where(m, b * jnp.uint32(p2), 0)
        p1 = (p1 * _HB1) & 0xFFFFFFFF
        p2 = (p2 * _HB2) & 0xFFFFFFFF
    # fold the length (cheap extra discrimination for truncated tokens)
    h2 = h2 ^ (lenpos.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    return h1, h2


def tokenize_group_count(batch: Batch, column: str, out_capacity: int,
                         vocab_capacity: int, count_name: str,
                         max_token_len: int = 24,
                         delims: bytes = b" \t\r\n.,;:!?\"'()[]{}<>",
                         lower: bool = False,
                         max_tokens_per_row: int | None = None
                         ) -> Tuple[Batch, jax.Array]:
    """Fused SelectMany(split) -> GroupBy(token) -> Count.

    Equivalent to split_tokens (+ lower_ascii) + group_aggregate count,
    but tokens are hashed IN PLACE (_window_hashes) and the windowed
    byte extraction — the dominant tokenizer cost, ~10 ns per gathered
    word — runs only for ``vocab_capacity`` group REPRESENTATIVES.
    Returns (groups batch [vocab_capacity] with columns (column,
    count_name), need) — need covers token overflow, per-row overflow,
    AND vocabulary overflow; the executor's scale-retry fixes all three.

    Grouping is by the 64-bit polynomial hash pair without byte
    verification — the same 2^-64 collision budget every hash-path
    group in kernels.py documents (_hash_sort_segments).

    Reference role: the WordCount map vertex — SelectMany + hash GroupBy
    + combiner fused in one pass (samples/WordCount.cs.pp,
    DryadLinqVertex.cs:510 GroupBy family).
    """
    from dryad_tpu.ops.kernels import (_lane_differs, _segment_flags,
                                       _sort_carrying)

    col: StringColumn = batch.columns[column]
    cap, L = col.capacity, col.max_len
    grid, is_start, lenpos, tok_cnt_row = _token_grid(
        batch, column, delims, max_token_len, lower=lower)
    h1g, h2g = _window_hashes(grid, lenpos, max_token_len)

    rowbase = (jnp.arange(cap, dtype=jnp.uint32) * jnp.uint32(L))[:, None]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    abs_pos = jnp.broadcast_to(rowbase + pos.astype(jnp.uint32), (cap, L))
    one_lane = _one_lane_ok(cap, L, max_token_len)
    extra = [h1g, h2g] + _poslen_lanes(abs_pos, lenpos, one_lane)
    slots, num_tokens, over_row = _token_slots(
        is_start, extra, tok_cnt_row, cap, L, out_capacity,
        max_tokens_per_row)

    # group the token stream by hash pair: ONE unstable sort carrying the
    # packed position, boundary flags, counts by index difference on the
    # densified end rows (the kernels.py boundary-carry recipe)
    t = jnp.arange(out_capacity, dtype=jnp.int32)
    n_tok = jnp.minimum(num_tokens, out_capacity)
    tvalid = t < n_tok
    big = jnp.uint32(0xFFFFFFFF)
    h1 = jnp.where(tvalid, slots[0], big)
    h2 = jnp.where(tvalid, slots[1], big)
    carry = slots[2:]
    (sh1, sh2), scarry = _sort_carrying([h1, h2], carry, out_capacity,
                                        stable=False)
    _is_s, is_end, num_groups = _segment_flags(
        _lane_differs(sh1, sh2), n_tok)
    dkeys, dl = _sort_carrying(
        [(~is_end).astype(jnp.uint32), t.astype(jnp.uint32)],
        list(scarry), out_capacity, stable=False)
    didx = dkeys[1].astype(jnp.int32)
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), didx[:-1]])
    cnt_g = didx - prev

    # representative byte extraction at VOCABULARY size only
    V = vocab_capacity
    gv = jnp.arange(V, dtype=jnp.int32) < jnp.minimum(num_groups, V)

    def _v(a):
        return a[:V] if a.shape[0] >= V else jnp.concatenate(
            [a, jnp.zeros((V - a.shape[0],), a.dtype)])

    start_pos, tok_len = _poslen_decode([_v(a) for a in dl], one_lane, gv)
    tok_bytes = _extract_bytes(grid.reshape(-1), start_pos, tok_len,
                               V, max_token_len)
    counts = jnp.where(gv, _v(cnt_g), 0)
    out = Batch({column: StringColumn(tok_bytes, tok_len),
                 count_name: counts},
                jnp.minimum(num_groups, V))
    need = jnp.where(num_tokens > out_capacity, num_tokens, 0)
    # ceil-factor FIRST: num_groups * out_capacity overflows int32 in
    # exactly the regime where this branch fires — and even the factored
    # product can wrap for extreme group counts, so the multiply is
    # clamped to int32 max (a saturated NEED still tells the caller "far
    # too small"; a wrapped NEGATIVE need would read as "fits")
    imax = jnp.int32(jnp.iinfo(jnp.int32).max)
    factor = -(-num_groups // V)
    vocab_need = jnp.where(factor > imax // out_capacity, imax,
                           factor * out_capacity)
    need = jnp.where(num_groups > V, jnp.maximum(need, vocab_need), need)
    need = jnp.where(over_row, jnp.maximum(need, out_capacity * 2), need)
    return out, need.astype(jnp.int32)
