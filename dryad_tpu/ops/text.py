"""Vectorized text ops: tokenization (the WordCount SelectMany kernel).

The reference's WordCount does ``SelectMany(line => line.Split(' '))``
(reference samples/WordCount.cs.pp) with per-record C# string ops.  On TPU we
tokenize a whole batch of lines in one fused program: flatten all line bytes
into one stream (row boundaries act as delimiters), mark token starts with
elementwise compares, place tokens with a prefix-sum + scatter, and slice
token bytes with a windowed gather.  No per-row loop, no dynamic shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dryad_tpu.data.columnar import Batch, StringColumn

__all__ = ["split_tokens", "lower_ascii"]


def lower_ascii(col: StringColumn) -> StringColumn:
    d = col.data
    is_upper = (d >= ord("A")) & (d <= ord("Z"))
    return StringColumn(jnp.where(is_upper, d + 32, d), col.lengths)


def _is_delim(b: jax.Array, delims: bytes) -> jax.Array:
    m = jnp.zeros(b.shape, jnp.bool_)
    for ch in delims:
        m = m | (b == ch)
    return m


def split_tokens(batch: Batch, column: str, out_capacity: int,
                 max_token_len: int = 24,
                 delims: bytes = b" \t\r\n.,;:!?\"'()[]{}<>") -> Batch:
    """Split a string column into a batch of tokens (one row per token).

    Returns ``(tokens_batch, overflow)``: the batch has a single string
    column named ``column``; tokens longer than ``max_token_len`` are
    truncated (semantic); ``overflow`` is True when tokens beyond
    ``out_capacity`` were dropped (a capacity-planning failure — the
    executor retries the stage with scaled capacity).
    """
    col: StringColumn = batch.columns[column]
    cap, L = col.capacity, col.max_len
    valid_row = batch.valid_mask()

    # flatten to one byte stream; bytes past each row's length and rows past
    # count are forced to delimiter (0x20) so they never join tokens
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_row = (pos < col.lengths[:, None]) & valid_row[:, None]
    flat = jnp.where(in_row, col.data, ord(" ")).reshape(-1)  # [cap*L]
    N = cap * L

    nondelim = ~_is_delim(flat, delims)
    prev_nondelim = jnp.concatenate([jnp.zeros((1,), jnp.bool_), nondelim[:-1]])
    # row starts break tokens even without explicit delimiters because each
    # row's tail is padded with spaces; first byte of stream handled by prev=0
    is_start = nondelim & ~prev_nondelim

    # start positions, compaction by STABLE SORT instead of scatter: the
    # t-th token's start is the t-th True in is_start, so a stable argsort
    # of ~is_start lists start positions in order (TPU scatters serialize;
    # sorts ride the vector units — measured ~2.5x faster at 100M bytes)
    num_tokens = is_start.sum(dtype=jnp.int32)
    start_idx = jnp.argsort(~is_start, stable=True).astype(jnp.int32)
    if N >= out_capacity:
        start_pos = start_idx[:out_capacity]
    else:  # fewer byte positions than token slots: pad (masked later)
        start_pos = jnp.concatenate(
            [start_idx, jnp.zeros((out_capacity - N,), jnp.int32)])

    # token length = distance from each position to the next delimiter,
    # via a single reverse cummin primitive (a custom-combine
    # associative_scan here compiles pathologically at scale on TPU)
    delim_pos = jnp.where(~nondelim, jnp.arange(N, dtype=jnp.int32), N)
    next_delim = jnp.flip(jax.lax.cummin(jnp.flip(delim_pos)))

    tok_valid = jnp.arange(out_capacity, dtype=jnp.int32) < jnp.minimum(
        num_tokens, out_capacity)
    tok_len = jnp.where(
        tok_valid,
        jnp.minimum(jnp.take(next_delim, start_pos) - start_pos,
                    max_token_len), 0)

    # token bytes via PACKED u32 gather + byte realignment: gathering one
    # u32 word moves 4 bytes, so a max_token_len window needs len/4 + 1
    # word fetches instead of len byte fetches (the windowed byte gather
    # was the tokenizer's dominant cost).  Little-endian bitcast: byte i
    # of a word occupies bits [8i, 8i+8), so >> (8*s) realigns a window
    # starting at sub-offset s.
    nw = -(-max_token_len // 4) + 1
    pad4 = (-N) % 4
    flat4 = jnp.concatenate([flat, jnp.zeros((pad4,), flat.dtype)]) \
        if pad4 else flat
    n_words = (N + pad4) // 4
    words = jax.lax.bitcast_convert_type(flat4.reshape(-1, 4), jnp.uint32)
    base = start_pos >> 2
    sub = (start_pos & 3).astype(jnp.uint32)[:, None]
    widx = jnp.clip(base[:, None] + jnp.arange(nw, dtype=jnp.int32)[None, :],
                    0, n_words - 1)
    toku32 = jnp.take(words, widx)                      # [T, nw]
    sh = 8 * sub
    lo = toku32[:, :nw - 1] >> sh
    hi = toku32[:, 1:nw] << ((jnp.uint32(32) - sh) & jnp.uint32(31))
    outw = jnp.where(sub == 0, toku32[:, :nw - 1], lo | hi)
    tok_bytes = jax.lax.bitcast_convert_type(outw, jnp.uint8) \
        .reshape(out_capacity, (nw - 1) * 4)[:, :max_token_len]
    w = jnp.arange(max_token_len, dtype=jnp.int32)[None, :]
    tok_bytes = jnp.where(w < tok_len[:, None], tok_bytes, 0)

    out = Batch({column: StringColumn(tok_bytes, tok_len)},
                jnp.minimum(num_tokens, out_capacity))
    # second return is the NEED channel: 0 = fits, else the actual row
    # requirement — lets the executor right-size the retry in one shot
    # (the dynamic-manager size-feedback idea, DrDynamicDistributor.cpp:388)
    need = jnp.where(num_tokens > out_capacity, num_tokens, 0)
    return out, need.astype(jnp.int32)
