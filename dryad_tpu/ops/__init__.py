from dryad_tpu.ops import hashing, kernels, text  # noqa: F401
