"""Hand-written pallas TPU kernels for the data-plane hot spots where the
XLA lowering measurably leaves bandwidth on the table.

This is the TPU-native answer to the reference's hand-tuned native
byte-pump (DryadVertex record/channel plumbing,
channelbuffernativewriter.cpp:1-2773, recorditem.cpp:1-1140): the
reference hand-rolls buffer management because its CPUs need it; on TPU
the XLA sort/fusion machinery already runs the comparison-network paths
at VPU speed (measured 3.9 ps/row/stage, benchmarks/pallas_probe.py), so
pallas is reserved for the primitives XLA lowers badly:

  * ``hist_buckets`` — bucket-count histogram.  XLA's bincount lowers to
    sort+segment machinery (measured 18.3 ms for 2M keys); the pallas
    kernel broadcast-compares each tile against the bucket iota along the
    (free) leading axis and accumulates per-lane partial counts in VMEM —
    0.26 ms for 2M keys, 72x.  Feeds exchange slot sizing (exact first
    waves) and the OOC bucket scatter.
  * ``prefix_sum`` — 1-D inclusive scan.  XLA's cumsum is a log-depth
    pass chain over HBM (0.54 ms / 500k f32); the pallas kernel is ONE
    streamed pass with an SMEM carry between sequential grid steps
    (in-VMEM Hillis-Steele per tile) — 0.12 ms / 512k, 4.5x.  Feeds the
    boundary-carry group aggregation (ops/kernels.group_aggregate).
  * ``slot_expand`` / ``slot_compact`` — exchange pack/unpack.  The
    send-side slot expansion (first min(count, C) rows of each
    destination run -> the [D, C] slot grid) and the receive-side slot
    compaction (valid prefix of each source block -> dense rows) were
    XLA random gathers over scatter-shaped index math (~10.7 ns/row x
    packed words).  Each destination run / source block is CONTIGUOUS
    in the dest-sorted (resp. received) buffer, so both kernels are D
    dynamic-offset block DMAs — sequential-bandwidth copies the DMA
    engine runs at HBM rate, not the gather unit's per-row cost.
    Feeds parallel/shuffle._exchange_one_axis (every hash/range
    repartition wave).

Probe provenance (real v5e, fetch-fenced slopes — benchmarks/pallas_probe
reproduces): designs that LOST to XLA and were therefore not shipped:
per-tile permutation-matmul compaction peaked at 0.45 G rows/s vs the
XLA sort-based compact's 0.86 G rows/s (the [T,T] one-hot build costs T
compares/row); bitonic pallas sorts matched XLA's network (~4 ps/row/
stage, VPU-bound) with no algorithmic headroom because the chip has no
scatter unit and random gathers run ~10.7 ns/row — the same verdict held
for a pallas MULTI-KEY bitonic sort (the comparator is wider, the
network identical), so multi-key sort speedups ship as the XLA-level
runtime key-lane fusion in ops/kernels.sort_by_columns instead; a
per-row-DMA join gather (one async copy per matched right row, probe +
verify + gather fused per tile) bottomed out at the DMA issue rate
(descriptor cost >> 20-byte payload, ~3x WORSE than the batched XLA
gather), so the join probe fusion also ships at the XLA level
(ops/kernels.hash_join packed single-gather + rank-fused compaction)
and the exchange keeps its DMAs BLOCK-sized (slot_expand above).

Gating: compiled kernels on TPU backends; ``interpret=True`` under
``force_interpret()`` (tests exercise the kernel logic on CPU); plain
XLA fallbacks otherwise, so every caller works on any backend.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["hist_buckets", "prefix_sum", "prefix_sum2",
           "slot_expand", "slot_compact",
           "pallas_active", "force_interpret"]

_FORCE_INTERPRET = False


@contextlib.contextmanager
def force_interpret():
    """Run the pallas kernels in interpreter mode (any backend) — used by
    the CPU test suite to exercise the real kernel bodies."""
    global _FORCE_INTERPRET
    prev = _FORCE_INTERPRET
    _FORCE_INTERPRET = True
    try:
        yield
    finally:
        _FORCE_INTERPRET = prev


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def pallas_active() -> Optional[str]:
    """None (use XLA fallback), "compiled", or "interpret"."""
    if os.environ.get("DRYAD_NO_PALLAS"):
        return None
    if _FORCE_INTERPRET:
        return "interpret"
    if _on_tpu():
        return "compiled"
    return None


def _pad_to(x: jax.Array, mult: int) -> jax.Array:
    n = x.shape[0]
    rem = (-n) % mult
    return jnp.pad(x, (0, rem)) if rem else x


# ---------------------------------------------------------------------------
# histogram

_HIST_R = 128            # tile rows of 128 lanes -> 16k elements per step
_HIST_MAX_B = 512        # acc is [B, 128] i32 in VMEM (256 KB at 512)


def _hist_kernel_body(B: int, R: int):
    import jax.experimental.pallas as pl

    def kern(x_ref, o_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)
        x = x_ref[:]                                        # [R, 128] i32
        # bucket ids along the LEADING axis: broadcasting x there is free
        # (no lane<->sublane relayout), and the [B, R, 128] compare is
        # pure VPU work summed immediately down to [B, 128]
        iota = jax.lax.broadcasted_iota(jnp.int32, (B, 1, 1), 0)
        m = x[None, :, :] == iota
        o_ref[:] = o_ref[:] + jnp.sum(m, axis=1, dtype=jnp.int32)

    return kern


def hist_buckets(bid: jax.Array, n_buckets: int) -> jax.Array:
    """Counts of each bucket id in [0, n_buckets); other values (e.g. an
    invalid-row sentinel of ``n_buckets``) are ignored.  bid: i32 [n].

    Replaces jnp.bincount on the exchange/OOC paths (which XLA lowers to
    sort+segment machinery — measured 72x slower at 2M keys)."""
    mode = pallas_active()
    if mode is None or n_buckets > _HIST_MAX_B:
        oob = jnp.where(bid < 0, n_buckets, jnp.minimum(bid, n_buckets))
        return jnp.bincount(oob, length=n_buckets + 1)[:n_buckets]
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = bid.shape[0]
    tile = _HIST_R * 128
    x = _pad_to(bid.astype(jnp.int32), tile)
    # pad rows fall outside [0, B) only if the caller's ids stay inside;
    # shift everything by +1 so the 0-pad never counts
    x = jnp.where(jnp.arange(x.shape[0]) < n, x + 1, 0)
    B = n_buckets + 1
    grid = x.shape[0] // tile
    acc = pl.pallas_call(
        _hist_kernel_body(B, _HIST_R),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_HIST_R, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((B, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.int32),
        interpret=(mode == "interpret"),
    )(x.reshape(-1, 128))
    return jnp.sum(acc, axis=1)[1:]


# ---------------------------------------------------------------------------
# prefix sum

_SCAN_R = 256            # 32k elements per grid step


def _scan_kernel_body(R: int, dt):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_ref, o_ref, carry):
        @pl.when(pl.program_id(0) == 0)
        def _():
            carry[0] = jnp.zeros((), dt)
        t = x_ref[:]                                        # [R, 128]
        zero = jnp.zeros((), dt)
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, 128), 1)
        d = 1
        while d < 128:          # Hillis-Steele within each row's lanes
            t = t + jnp.where(lane >= d, pltpu.roll(t, d, 1), zero)
            d *= 2
        row_tot = t[:, 127:128]                             # [R, 1]
        sub = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
        base = row_tot
        d = 1
        while d < R:            # prefix over the row totals (sublanes)
            base = base + jnp.where(sub >= d, pltpu.roll(base, d, 0), zero)
            d *= 2
        o_ref[:] = t + (base - row_tot) + carry[0]
        carry[0] = carry[0] + base[R - 1, 0]

    return kern


def _dd_add(hi1, lo1, hi2, lo2):
    """Double-single (compensated) f32 add via Knuth TwoSum + Dekker
    renormalization — the ONE implementation both the pallas kernel body
    and the XLA fallback scan use (drift here silently changes error
    bounds)."""
    s = hi1 + hi2
    bb = s - hi1
    err = (hi1 - (s - bb)) + (hi2 - bb)
    lo = lo1 + lo2 + err
    hi_n = s + lo
    lo_n = lo - (hi_n - s)
    return hi_n, lo_n


def _scan2_kernel_body(R: int):
    """Compensated (double-single f32) scan: every partial prefix is an
    unevaluated (hi, lo) pair combined with TwoSum, so the running error
    stays ~eps^2 x prefix instead of eps x prefix.  This is what makes
    the boundary-carry group aggregation's adjacent-difference sums safe
    for f32: the per-group error is bounded near ulp(group_sum), not
    ulp(global_prefix) (the accuracy cliff a plain cumsum would have)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    add2 = _dd_add

    def kern(x_ref, hi_ref, lo_ref, carry):
        @pl.when(pl.program_id(0) == 0)
        def _():
            carry[0] = jnp.zeros((), jnp.float32)
            carry[1] = jnp.zeros((), jnp.float32)
        hi = x_ref[:]                                       # [R, 128]
        lo = jnp.zeros_like(hi)
        zero = jnp.zeros((), jnp.float32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, 128), 1)
        d = 1
        while d < 128:
            m = lane >= d
            hi, lo = add2(hi, lo,
                          jnp.where(m, pltpu.roll(hi, d, 1), zero),
                          jnp.where(m, pltpu.roll(lo, d, 1), zero))
            d *= 2
        rt_hi, rt_lo = hi[:, 127:128], lo[:, 127:128]
        sub = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
        b_hi, b_lo = rt_hi, rt_lo
        d = 1
        while d < R:
            m = sub >= d
            b_hi, b_lo = add2(b_hi, b_lo,
                              jnp.where(m, pltpu.roll(b_hi, d, 0), zero),
                              jnp.where(m, pltpu.roll(b_lo, d, 0), zero))
            d *= 2
        e_hi, e_lo = add2(b_hi, b_lo, -rt_hi, -rt_lo)       # exclusive
        o_hi, o_lo = add2(hi, lo, e_hi, e_lo)
        o_hi, o_lo = add2(o_hi, o_lo, carry[0], carry[1])
        hi_ref[:] = o_hi
        lo_ref[:] = o_lo
        c_hi, c_lo = add2(b_hi[R - 1, 0], b_lo[R - 1, 0],
                          carry[0], carry[1])
        carry[0] = c_hi
        carry[1] = c_lo

    return kern


def prefix_sum(x: jax.Array) -> jax.Array:
    """Inclusive 1-D prefix sum (f32/i32/u32) — one streamed pass with an
    SMEM carry across sequential grid steps, vs XLA cumsum's log-depth
    HBM pass chain (measured 4.5x at 512k f32).  For f32, see
    prefix_sum2 — the compensated variant group sums should use."""
    mode = pallas_active()
    if mode is None:
        return jnp.cumsum(x)
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x.shape[0]
    dt = x.dtype
    tile = _SCAN_R * 128
    xp = _pad_to(x, tile)
    grid = xp.shape[0] // tile
    y = pl.pallas_call(
        _scan_kernel_body(_SCAN_R, dt),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_SCAN_R, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_SCAN_R, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0] // 128, 128), dt),
        scratch_shapes=[pltpu.SMEM((1,), dt)],
        interpret=(mode == "interpret"),
    )(xp.reshape(-1, 128))
    return y.reshape(-1)[:n]


def prefix_sum2(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Compensated f32 inclusive prefix sum: returns an unevaluated
    (hi, lo) pair per prefix (hi + lo = the prefix to ~2x f32 precision).
    Consumers differencing adjacent prefixes (group sums) difference BOTH
    lanes: (hi_b - hi_a) + (lo_b - lo_a) has error near ulp of the
    difference itself — the plain-cumsum error was proportional to the
    GLOBAL prefix magnitude, unbounded relative to a small group's sum.

    Fallback (no pallas): jnp.cumsum of f64 when x64 is enabled, else a
    Dekker two-float running pair via associative_scan."""
    mode = pallas_active()
    if mode is None:
        if jax.config.jax_enable_x64:
            c = jnp.cumsum(x.astype(jnp.float64))
            hi = c.astype(jnp.float32)
            lo = (c - hi.astype(jnp.float64)).astype(jnp.float32)
            return hi, lo

        def comb(a, b):
            return _dd_add(a[0], a[1], b[0], b[1])

        return jax.lax.associative_scan(
            comb, (x, jnp.zeros_like(x)))
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x.shape[0]
    tile = _SCAN_R * 128
    xp = _pad_to(x, tile)
    grid = xp.shape[0] // tile
    hi, lo = pl.pallas_call(
        _scan2_kernel_body(_SCAN_R),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_SCAN_R, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((_SCAN_R, 128), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)] * 2,
        out_shape=[jax.ShapeDtypeStruct((xp.shape[0] // 128, 128),
                                        jnp.float32)] * 2,
        scratch_shapes=[pltpu.SMEM((2,), jnp.float32)],
        interpret=(mode == "interpret"),
    )(xp.reshape(-1, 128))
    return hi.reshape(-1)[:n], lo.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# exchange pack/unpack (slot expansion / slot compaction)
#
# Both sides of a repartition move CONTIGUOUS row runs: after the dest
# sort, destination d's rows occupy [offsets[d], offsets[d]+counts[d]);
# after the all_to_all, source block s's valid rows are the prefix of
# slot block [s*C, (s+1)*C).  The XLA lowering expressed both moves as
# random gathers over scatter-shaped index math (clip(offsets[d]+j) /
# argsort(~valid)), paying the per-row gather cost for what is really D
# block copies.  The kernels below issue ONE dynamic-offset DMA per
# destination/source block — the DMA engine streams each run at copy
# bandwidth and handles arbitrary (non-tile-aligned) row offsets, which
# is exactly what VMEM-resident vector code cannot do cheaply on the
# lane-padded [rows, W] layout.

# block DMAs below this many rows pay more descriptor cost than they
# move; the XLA gather is better there (and in the degenerate D=1 case)
_SLOT_MIN_C = 8


def _expand_kernel_body(C: int, cap: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(offs_ref, x_ref, o_ref, sem):
        d = pl.program_id(0)
        # x_ref is the C-row-padded source, so a run starting anywhere
        # in [0, cap] always has C readable rows — no down-clamp that
        # would shift the block off its run (slots past the run's count
        # read pad garbage the receiver masks via send_counts)
        start = jnp.clip(offs_ref[d], 0, cap)
        dma = pltpu.make_async_copy(
            x_ref.at[pl.ds(start, C), :], o_ref, sem)
        dma.start()
        dma.wait()

    return kern


def slot_expand(words: jax.Array, offsets: jax.Array, C: int) -> jax.Array:
    """Send-slot expansion: ``words`` is the dest-sorted packed row matrix
    [cap, W] u32; destination d's rows start at ``offsets[d]`` (i32 [D]).
    Returns the [D*C, W] send buffer whose block d holds rows
    offsets[d] .. offsets[d]+C (clamped to the array; slots past the
    run's count are garbage the receiver masks via send_counts).

    One dynamic-offset block DMA per destination vs the XLA fallback's
    D*C-row random gather."""
    D = offsets.shape[0]
    cap, W = words.shape
    mode = pallas_active()
    if mode is None or C < _SLOT_MIN_C or D < 2 or cap < C:
        d_idx = jnp.repeat(jnp.arange(D, dtype=jnp.int32), C)
        j_idx = jnp.tile(jnp.arange(C, dtype=jnp.int32), D)
        src = jnp.clip(jnp.take(offsets, d_idx) + j_idx, 0, cap - 1)
        return jnp.take(words, src, axis=0)
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # C pad rows guarantee every run's block DMA [offs, offs+C) stays in
    # bounds WITHOUT clamping the start (a down-clamp would shift the
    # block off its run and ship another destination's rows)
    xp = jnp.concatenate([words, jnp.zeros((C, W), words.dtype)])
    return pl.pallas_call(
        _expand_kernel_body(C, cap),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(D,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec((C, W), lambda d, offs: (d, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA],
        ),
        out_shape=jax.ShapeDtypeStruct((D * C, W), words.dtype),
        interpret=(mode == "interpret"),
    )(offsets.astype(jnp.int32), xp)


def _compact_kernel_body(C: int, out_rows: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(starts_ref, zeros_ref, x_ref, o_ref, sem):
        del zeros_ref   # aliased into o_ref: the zero seed
        s = pl.program_id(0)
        # o_ref is C-row-padded, so any cursor in [0, out_rows] has C
        # writable rows — no down-clamp (that would land this block's
        # valid prefix at the wrong offset AND overwrite earlier valid
        # rows).  Blocks wholly past out_rows write only the pad
        # (truncation); the caller slices the pad off.
        dst = jnp.clip(starts_ref[s], 0, out_rows)
        dma = pltpu.make_async_copy(
            x_ref.at[pl.ds(s * C, C), :],
            o_ref.at[pl.ds(dst, C), :], sem)
        dma.start()
        dma.wait()

    return kern


def slot_compact(words: jax.Array, counts: jax.Array, C: int,
                 out_rows: int) -> jax.Array:
    """Receive-slot compaction: ``words`` is the received slot buffer
    [D*C, W] u32 where source block s's valid rows are the prefix
    ``counts[s]`` (i32 [D], <= C) of rows [s*C, (s+1)*C).  Returns
    [out_rows, W] with the valid rows dense at the front (block s
    writes its full C rows at the running cursor and block s+1's write
    overlaps the tail garbage; the sequential grid makes the last
    writer deterministic.  Rows past the total hold the last block's
    deterministic tail, then the zero seed — unspecified-padding rows
    by the Batch contract, like the fallback's dropped-slot rows).

    One dynamic-offset block DMA per source block vs the XLA fallback's
    stable valid-sort + full gather."""
    S, W = words.shape
    D = counts.shape[0]
    counts = jnp.minimum(counts.astype(jnp.int32), C)
    starts = jnp.cumsum(counts) - counts   # exclusive prefix
    mode = pallas_active()
    if (mode is None or C < _SLOT_MIN_C or D < 2 or S != D * C
            or out_rows < C):
        idx = jnp.arange(S, dtype=jnp.int32)
        rvalid = (idx % C) < jnp.take(counts, idx // C)
        # fallback mirrors the pre-kernel lowering: stable valid-first
        # sort of the row ids, then one packed gather
        perm = jnp.argsort(~rvalid, stable=True)
        g = jnp.take(words, perm[:out_rows], axis=0) if S >= out_rows \
            else jnp.pad(jnp.take(words, perm, axis=0),
                         ((0, out_rows - S), (0, 0)))
        total = rvalid.sum(dtype=jnp.int32)
        gmask = jnp.arange(out_rows, dtype=jnp.int32) < total
        return jnp.where(gmask[:, None], g, 0)
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # C pad rows let every block write its full C rows at the exact
    # running cursor (no down-clamp); the pad absorbs the last blocks'
    # tail garbage and truncated rows, and is sliced off below
    zeros = jnp.zeros((out_rows + C, W), words.dtype)
    out = pl.pallas_call(
        _compact_kernel_body(C, out_rows),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(D,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                      pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA],
        ),
        out_shape=jax.ShapeDtypeStruct((out_rows + C, W), words.dtype),
        # zero-seeded output (aliased operand): padding rows past the
        # total stay deterministically 0, matching the XLA fallback
        input_output_aliases={1: 0},
        interpret=(mode == "interpret"),
    )(starts, zeros, words)
    return out[:out_rows]
