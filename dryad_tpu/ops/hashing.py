"""Vectorized 64-bit hashing of record keys (as two uint32 lanes).

Role of reference LinqToDryad/Hash64.cs (the hash behind HashPartition,
DryadLinqQueryable.cs:275) — but vectorized over a whole Batch so the TPU
computes every row's hash in one fused XLA op.  TPUs have no fast uint64, so
a 64-bit hash is carried as an ``(hi, lo)`` pair of uint32 arrays; arithmetic
wraps mod 2**32, which is exactly what uint32 ops give us.

Strings hash via a masked weighted byte dot-product (MXU-friendly); ints via
splitmix-style avalanche mixing.  All constants are fixed, so hashes are
deterministic across runs — required for replay-based fault tolerance
(SURVEY.md §7 "Determinism for replay").
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.data.columnar import Batch, StringColumn

__all__ = ["hash_column", "hash_columns", "hash_batch_keys"]

_U32 = jnp.uint32

# Deterministic odd weights for byte dot-product hashing (fixed seed).
# Built lazily: a module-level jnp.asarray would initialize the XLA backend
# at import, which breaks worker processes (jax.distributed.initialize must
# run first) and forces devices onto the pure-control-plane driver.
_MAX_HASH_LEN = 512


def _byte_weights():
    # NUMPY values (not jnp): a device array built lazily inside a trace
    # would cache that trace's tracer and leak it into later programs
    global _BYTE_W
    try:
        return _BYTE_W
    except NameError:
        rng = np.random.RandomState(0xD47AD)
        _BYTE_W = (rng.randint(0, 2**31, _MAX_HASH_LEN)
                   .astype(np.uint32) * 2 + 1,
                   rng.randint(0, 2**31, _MAX_HASH_LEN)
                   .astype(np.uint32) * 2 + 1)
        return _BYTE_W


def _mix32(x: jax.Array, c1: int, c2: int) -> jax.Array:
    """xorshift-multiply avalanche (murmur3 finalizer shape)."""
    x = x.astype(_U32)
    x = x ^ (x >> 16)
    x = x * _U32(c1)
    x = x ^ (x >> 13)
    x = x * _U32(c2)
    x = x ^ (x >> 16)
    return x


def _combine(h: Tuple[jax.Array, jax.Array],
             g: Tuple[jax.Array, jax.Array]) -> Tuple[jax.Array, jax.Array]:
    """Combine two 64-bit lane-pair hashes (boost::hash_combine style)."""
    hi = _mix32(h[0] ^ (g[0] + _U32(0x9E3779B9) + (h[0] << 6) + (h[0] >> 2)),
                0x85EBCA6B, 0xC2B2AE35)
    lo = _mix32(h[1] ^ (g[1] + _U32(0x9E3779B9) + (h[1] << 6) + (h[1] >> 2)),
                0xCC9E2D51, 0x1B873593)
    return hi, lo


def _hash_dense(col: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Hash a dense [n] or [n, k] numeric column to (hi, lo) uint32 pairs."""
    if jnp.issubdtype(col.dtype, jnp.floating):
        # Canonicalize -0.0 == 0.0, then hash the bit pattern.
        col = jnp.where(col == 0, jnp.zeros_like(col), col)
        col = col.astype(jnp.float32)
        bits = jax.lax.bitcast_convert_type(col, jnp.uint32)
    elif col.dtype == jnp.bool_:
        bits = col.astype(_U32)
    elif col.dtype.itemsize > 4:
        # 64-bit ints: hash both 32-bit halves so values differing only in
        # the high word don't collide.
        lo32 = col.astype(_U32)
        hi32 = (col >> 32).astype(_U32)
        bits = jnp.stack([hi32, lo32], axis=-1) if col.ndim == 1 else \
            jnp.concatenate([hi32, lo32], axis=-1)
    else:
        bits = col.astype(_U32)
    if bits.ndim == 1:
        bits = bits[:, None]
    hi = jnp.zeros(bits.shape[0], _U32)
    lo = jnp.zeros(bits.shape[0], _U32)
    for j in range(bits.shape[1]):
        hi, lo = _combine((hi, lo), (_mix32(bits[:, j], 0x85EBCA6B, 0xC2B2AE35),
                                     _mix32(bits[:, j], 0xCC9E2D51, 0x1B873593)))
    return hi, lo


def _hash_string(col: StringColumn) -> Tuple[jax.Array, jax.Array]:
    """Masked weighted byte sum — one [n, L] x [L] product per lane."""
    L = col.max_len
    if L > _MAX_HASH_LEN:
        raise ValueError(f"string max_len {L} > hashable {_MAX_HASH_LEN}")
    mask = (jnp.arange(L, dtype=jnp.int32)[None, :] < col.lengths[:, None])
    b = jnp.where(mask, col.data, 0).astype(_U32)
    # (b+1) so that a 0x00 byte differs from padding; wrapping uint32 dot.
    w1, w2 = _byte_weights()
    hi = ((b + mask.astype(_U32)) * w1[:L][None, :]).sum(axis=1, dtype=_U32)
    lo = ((b + mask.astype(_U32)) * w2[:L][None, :]).sum(axis=1, dtype=_U32)
    lenmix = (_mix32(col.lengths, 0x85EBCA6B, 0xC2B2AE35),
              _mix32(col.lengths, 0xCC9E2D51, 0x1B873593))
    return _combine((_mix32(hi, 0xCC9E2D51, 0x85EBCA6B),
                     _mix32(lo, 0x1B873593, 0xC2B2AE35)), lenmix)


def hash_column(col) -> Tuple[jax.Array, jax.Array]:
    if isinstance(col, StringColumn):
        return _hash_string(col)
    return _hash_dense(col)


def hash_columns(cols: Sequence) -> Tuple[jax.Array, jax.Array]:
    """Combined hash of several columns (row-wise)."""
    assert cols
    h = hash_column(cols[0])
    for c in cols[1:]:
        h = _combine(h, hash_column(c))
    return h


def hash_batch_keys(batch: Batch, key_names: Sequence[str]) -> Tuple[jax.Array, jax.Array]:
    return hash_columns([batch.columns[k] for k in key_names])
