"""Per-partition operator kernels over columnar Batches.

These are the record-streaming operator implementations of the reference's
vertex runtime (LinqToDryad/DryadLinqVertex.cs:51 — Where/Select/GroupBy/
Join/sorts/partitioners), re-designed for XLA: every kernel is a pure,
shape-static function on ``Batch`` pytrees, so a fused pipeline of them jits
into ONE XLA program per stage (the reference gets the same effect from
supernode pipelining + subgraphvertex.cpp fused processes; we get it from the
compiler).

Key idioms:
  * validity is a prefix: ``count`` valid rows then padding;
  * compaction (filter) = stable argsort of the drop-mask;
  * group-by = 64-bit key hash -> lexsort -> segment boundaries -> segment
    reductions (sort-based, like the reference's hash/merge GroupBy but
    tensorized);
  * join = sort the right side by key hash, binary-search candidate ranges,
    expand by prefix-sum offsets, then verify real key equality.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from dryad_tpu.data.columnar import Batch, StringColumn
from dryad_tpu.ops.hashing import hash_batch_keys

__all__ = [
    "compact", "filter_rows", "sort_by_columns", "group_aggregate",
    "group_decompose_partial", "group_decompose_merge",
    "group_decompose_local", "distinct",
    "group_top_k", "group_rank_select", "group_regroup_apply",
    "scalar_aggregate", "hash_join", "semi_anti_join",
    "concat2", "take", "AGG_KINDS",
]

AGG_KINDS = ("sum", "count", "min", "max", "mean", "any", "all")


def searchsorted_small(bounds: jax.Array, q: jax.Array,
                       side: str = "left") -> jax.Array:
    """searchsorted against a SMALL sorted array (partition bounds, bucket
    splitters).  jnp.searchsorted's default 'scan' method lowers to a
    while loop of random gathers — measured ~180 ms per 1M queries on TPU
    — while 'compare_all' fuses into |bounds| vectorized compares
    (~free for |bounds| <= a few thousand)."""
    return jnp.searchsorted(bounds, q, side=side, method="compare_all")


def searchsorted_big(table: jax.Array, q: jax.Array,
                     side: str = "left") -> jax.Array:
    """searchsorted against a LARGE sorted array (join candidate ranges).
    'sort' method = one variadic device sort of (table ++ queries) —
    O((n+m) log^2) vectorized passes instead of the scan method's
    log(n) rounds of random gathers (TPU random gathers run ~9 ns/row;
    sorts ride the vector units)."""
    return jnp.searchsorted(table, q, side=side, method="sort")


# ---------------------------------------------------------------------------
# packed row transport: u32 word lanes carried as sort VALUE operands
#
# TPU random gathers cost ~9 ns/row and scatters serialize, while the
# variadic sort network streams its value operands with vector-unit
# memory access — measured 3.5x faster to CARRY a packed 20-byte payload
# through lax.sort than to lexsort indices and gather the columns
# (benchmarks/prim_probe.py).  So every argsort+gather pair below is
# expressed as ONE stable lax.sort over (key lanes..., packed words...).


def _pack_columns_u32(cols: Dict[str, Any]) -> Tuple[List[jax.Array], List]:
    """Columns -> list of uint32 word lanes [cap] + a reassembly spec."""
    lanes: List[jax.Array] = []
    spec: List[Tuple] = []
    for name in cols:
        v = cols[name]
        if isinstance(v, StringColumn):
            L = v.max_len
            L4 = -(-L // 4) * 4
            d = jnp.pad(v.data, ((0, 0), (0, L4 - L))) if L4 != L else v.data
            w = jax.lax.bitcast_convert_type(
                d.reshape(d.shape[0], L4 // 4, 4), jnp.uint32)
            k = w.shape[1]
            lanes.extend(w[:, j] for j in range(k))
            lanes.append(v.lengths.astype(jnp.uint32))
            spec.append((name, "str", L, k + 1))
        else:
            tail = v.shape[1:]
            flat = v.reshape(v.shape[0], -1) if tail else v[:, None]
            if flat.dtype.itemsize == 4:
                w = jax.lax.bitcast_convert_type(flat, jnp.uint32)
            elif flat.dtype.itemsize == 8:
                w = jax.lax.bitcast_convert_type(flat, jnp.uint32)
                w = w.reshape(w.shape[0], -1)
            elif flat.dtype.itemsize == 2:
                # f16/bf16/i16/u16: BIT-level widening (a numeric astype
                # would truncate half-precision fractions)
                w = jax.lax.bitcast_convert_type(
                    flat, jnp.uint16).astype(jnp.uint32)
            else:  # bool / u8 / i8 widen losslessly (mod-256 roundtrip)
                w = flat.astype(jnp.uint32)
            k = w.shape[1]
            lanes.extend(w[:, j] for j in range(k))
            spec.append((name, "dense", (v.dtype, tail), k))
    return lanes, spec


def _unpack_columns_u32(lanes: List[jax.Array], spec: List) -> Dict[str, Any]:
    cols: Dict[str, Any] = {}
    i = 0
    for name, kind, meta, k in spec:
        w = lanes[i:i + k]
        i += k
        if kind == "str":
            L = meta
            data4 = jax.lax.bitcast_convert_type(
                jnp.stack(w[:-1], axis=1), jnp.uint8)
            data = data4.reshape(data4.shape[0], -1)[:, :L]
            cols[name] = StringColumn(data, w[-1].astype(jnp.int32))
        else:
            dtype, tail = meta
            if dtype.itemsize == 4:
                flat = jax.lax.bitcast_convert_type(
                    jnp.stack(w, axis=1), dtype)
            elif dtype.itemsize == 8:
                flat = jax.lax.bitcast_convert_type(
                    jnp.stack(w, axis=1).reshape(w[0].shape[0], -1, 2),
                    dtype)
            elif dtype.itemsize == 2:
                flat = jax.lax.bitcast_convert_type(
                    jnp.stack(w, axis=1).astype(jnp.uint16), dtype)
            else:
                flat = jnp.stack(w, axis=1).astype(dtype)
            cols[name] = flat.reshape((flat.shape[0],) + tail) if tail \
                else flat[:, 0]
    return cols



def _lane_differs(*lanes: jax.Array) -> jax.Array:
    """Per-row "key differs from previous row" mask over SORTED key lanes
    (row 0 always True) — the input _segment_flags expects.  The single
    home of the adjacent-compare; every segment sorter and the
    boundary-carry aggregator call it."""
    d = None
    for l in lanes:
        dl = l[1:] != l[:-1]
        d = dl if d is None else (d | dl)
    return jnp.concatenate([jnp.ones((1,), jnp.bool_), d])


def _sentinel_fold(hi: jax.Array, lo: jax.Array, valid: jax.Array):
    """Fold invalid rows to the all-ones 64-bit hash sentinel so they
    sort last without an extra invalid lane (collision budget documented
    on _hash_sort_segments)."""
    big = jnp.uint32(0xFFFFFFFF)
    return jnp.where(valid, hi, big), jnp.where(valid, lo, big)


def _dense_key_lane(kcol) -> jax.Array:
    """Order lane of a dense-fast GROUPING key.  Grouping equality
    canonicalizes signed zero (-0.0 == +0.0, matching hashing._hash_dense
    and the shuffle partitioner); the order-transform lane would
    otherwise split them.  Shared by both group_aggregate lowerings."""
    if jnp.issubdtype(kcol.dtype, jnp.floating):
        kcol = jnp.where(kcol == 0, jnp.zeros((), kcol.dtype), kcol)
    return _dense_sort_lanes(kcol, False)[0]


def _dense_fast_key(batch: Batch, key_names: Sequence[str]) -> bool:
    """Single <=32-bit 1-D dense key: group by its EXACT order lane (no
    hashing, rebuilt from the sorted lane) — shared predicate of the
    grouping kernels."""
    if len(key_names) != 1:
        return False
    kcol0 = batch.columns[key_names[0]]
    return (_lanes_reconstructible(kcol0)
            and not isinstance(kcol0, StringColumn)
            and len(_dense_sort_lanes(kcol0, False)) == 1)


def _segment_flags(differs: jax.Array, n_valid):
    """Shared boundary derivation for the segment sorters: given the
    per-row "key differs from previous row" mask over SORTED rows (row 0
    always True), mark each segment's first/last row among the valid
    prefix.  The single home of this subtle logic — both the hash and the
    dense-key sorters call it."""
    cap = differs.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    svalid = idx < n_valid
    is_start = svalid & differs
    nxt_start = jnp.concatenate([is_start[1:], jnp.ones((1,), jnp.bool_)])
    is_end = svalid & (nxt_start | (idx + 1 == n_valid))
    num_groups = is_start.sum(dtype=jnp.int32)
    return is_start, is_end, num_groups


def _sort_segments_carry(hi: jax.Array, lo: jax.Array, valid: jax.Array,
                         n_valid, value_lanes, stable: bool = True):
    """Value-carry hash segmentation: ONE stable variadic sort groups rows
    by the 64-bit hash (invalid rows fold to the all-ones sentinel and
    sort last — same collision budget as _hash_sort_segments), carrying
    ``value_lanes`` as sort value operands.  Returns (sorted value lanes,
    is_start, is_end, num_groups); is_start/is_end mark each hash
    segment's first/last SORTED row among the valid prefix.  The single
    home of this subtle boundary logic — group_aggregate, distinct, and
    _hash_membership all call it.

    ``stable=False`` drops the in-segment order guarantee (XLA's stable
    sort costs ~2x the unstable one, measured) — safe only when nothing
    downstream observes the order of rows WITHIN a hash segment."""
    cap = hi.shape[0]
    hi_s, lo_s = _sentinel_fold(hi, lo, valid)
    (shi, slo), sorted_vals = _sort_carrying([hi_s, lo_s], value_lanes,
                                             cap, stable=stable)
    is_start, is_end, num_groups = _segment_flags(
        _lane_differs(shi, slo), n_valid)
    return sorted_vals, is_start, is_end, num_groups


def _sort_segments_dense(key_lane: jax.Array, valid: jax.Array, n_valid,
                         value_lanes):
    """Dense-key segmentation: like _sort_segments_carry but grouping by a
    single order-transformed u32 lane holding the EXACT key (no hash, no
    collision budget).  An explicit invalid flag is the most significant
    sort key (a real key may legitimately hit the all-ones lane value, so
    the sentinel fold used for 64-bit hashes is not sound here).  The sort
    is UNSTABLE: in-segment value order is not observed by any caller
    (aggregates are commutative; representatives only read key columns,
    which are equal within a segment).  Returns (sorted key lane, sorted
    value lanes, is_start, is_end, num_groups)."""
    cap = key_lane.shape[0]
    inv = (~valid).astype(jnp.uint32)
    (sinv, skey), sorted_vals = _sort_carrying(
        [inv, key_lane], value_lanes, cap, stable=False)
    is_start, is_end, num_groups = _segment_flags(
        _lane_differs(skey), n_valid)
    return skey, sorted_vals, is_start, is_end, num_groups


# multi-key sorts with exactly two u32 key lanes runtime-fuse them into
# ONE lane when the measured lane spans allow (span_a * span_b <= 2^32);
# both lowerings live in one lax.cond, so the gate bounds the doubled
# sort-program size (XLA unrolls sort networks — see _VALOPS_MAX_ELEMS)
_SORT_FUSE_MAX_CAP = 1 << 21

# value-carry beats lexsort+gather until the packed row is so wide that
# carrying it through every compare-exchange pass costs more than one
# ~9 ns/row random gather (measured crossover ~32 words = 128 B/row)
_VALOPS_MAX_WORDS = 32
# ...and until the PROGRAM gets too big: XLA:TPU unrolls sort networks,
# so executable size scales ~log^2(n) x operands (measured 53 MB for an
# 8-operand sort at 250k rows) — huge caps with many carried words make
# remote compiles take minutes and binaries enormous.  Above this
# cap x operand budget, reorder via the 3-operand index sort + ONE
# packed gather instead (slower on-device at huge n, but compilable).
_VALOPS_MAX_ELEMS = 48 << 20


def _carry_fits(cap: int, n_key_lanes: int, n_val_lanes: int) -> bool:
    return (n_val_lanes <= _VALOPS_MAX_WORDS
            and cap * (n_key_lanes + n_val_lanes) <= _VALOPS_MAX_ELEMS)


def _sort_carrying(key_lanes, value_lanes, cap: int, stable: bool = True):
    """Sort by uint32 ``key_lanes`` (stable by default) returning the value
    lanes in sorted order — value-carry when the program-size budget
    allows, else index sort + one packed gather (see _VALOPS_MAX_ELEMS)."""
    value_lanes = list(value_lanes)
    if _carry_fits(cap, len(key_lanes), len(value_lanes)):
        out = jax.lax.sort(tuple(key_lanes) + tuple(value_lanes),
                           num_keys=len(key_lanes), is_stable=stable)
        return list(out[:len(key_lanes)]), list(out[len(key_lanes):])
    out = jax.lax.sort(tuple(key_lanes)
                       + (jnp.arange(cap, dtype=jnp.int32),),
                       num_keys=len(key_lanes), is_stable=True)
    order = out[len(key_lanes)]
    if not value_lanes:
        return list(out[:len(key_lanes)]), []
    words = jnp.stack(value_lanes, axis=1)
    g = jnp.take(words, order, axis=0)
    return (list(out[:len(key_lanes)]),
            [g[:, j] for j in range(len(value_lanes))])


def _sort_fused2(lanes: List[jax.Array], packed: List[jax.Array],
                 cap: int):
    """Runtime key-lane fusion for 2-key-lane sorts (multi-key sort key
    packing): when the VALID rows' lane spans satisfy
    span_a * span_b <= 2^32, the two lex lanes collapse into ONE fused
    lane ``(la - la_min) * span_b + (lb - lb_min)`` — the sort network's
    cost is linear in operands (measured, see sort_by_columns), so the
    fused program runs one comparator lane where the general one runs
    two.  The spans are runtime values, so the choice is a lax.cond
    between the two lowerings (the _group_aggregate_smallkey pattern);
    wide-span inputs pay two tiny reductions and ride the general path.
    ``lanes`` is [invalid, la, lb]; returns the same
    ([sinv, sla, slb], svals) structure either way (the fused branch
    rebuilds the sorted lanes from the fused lane — exact for valid
    rows; invalid rows' lanes are garbage both ways and every caller
    masks them)."""
    inv, la, lb = lanes
    valid = inv == 0
    big = jnp.uint32(0xFFFFFFFF)
    zero = jnp.uint32(0)
    la_min = jnp.min(jnp.where(valid, la, big))
    la_max = jnp.max(jnp.where(valid, la, zero))
    lb_min = jnp.min(jnp.where(valid, lb, big))
    lb_max = jnp.max(jnp.where(valid, lb, zero))
    any_valid = valid.any()
    span_a = la_max - la_min + 1
    span_b = lb_max - lb_min + 1
    # fused max = span_a*span_b - 1 must fit u32; the conservative test
    # span_a <= big // span_b never wraps (off by < span_b rows)
    ok = (any_valid & (la_max >= la_min) & (lb_max >= lb_min)
          & (span_a != 0) & (span_b != 0)
          & (span_a <= big // jnp.maximum(span_b, 1)))

    def fused(args):
        inv, la, lb, packed = args
        f = (la - la_min) * span_b + (lb - lb_min)
        (sinv, sf), svals = _sort_carrying([inv, f], list(packed), cap)
        sla = sf // span_b + la_min
        slb = sf % span_b + lb_min
        return [sinv, sla, slb], list(svals)

    def general(args):
        inv, la, lb, packed = args
        skeys, svals = _sort_carrying([inv, la, lb], list(packed), cap)
        return list(skeys), list(svals)

    return jax.lax.cond(ok, fused, general, (inv, la, lb, tuple(packed)))


def permute_by_sort(batch: Batch, key_lanes: Sequence[jax.Array],
                    count=None, stable: bool = True) -> Batch:
    """Sort the batch's rows by the given uint32 key lanes (most
    significant first; stable by default), moving ALL columns as packed
    value operands of one variadic lax.sort — zero random gathers.
    Falls back to lexsort+single-packed-gather for very wide rows."""
    lanes, spec = _pack_columns_u32(dict(batch.columns))
    new_count = batch.count if count is None else count
    _, svals = _sort_carrying(list(key_lanes), lanes, batch.capacity,
                              stable=stable)
    return Batch(_unpack_columns_u32(svals, spec), new_count)


# ---------------------------------------------------------------------------
# filtering / compaction


def compact(batch: Batch, keep: jax.Array) -> Batch:
    """Move rows where ``keep`` (and valid) to the front, preserving order.

    Rank-fused UNSTABLE value-carry sort: the row index rides as a
    second sort KEY, so (drop, index) is a total order — the unstable
    network produces exactly the stable compaction without paying XLA's
    stable-sort machinery (measured ~2x on the same operand set; the
    index operand replaces the iota a stable sort materializes
    internally anyway).  ``DRYAD_NO_SORT_OPT=1`` restores the stable
    1-key form (A/B lever for benchmarks/pallas_probe provenance)."""
    keep = keep & batch.valid_mask()
    n_keep = keep.sum(dtype=jnp.int32)
    if os.environ.get("DRYAD_NO_SORT_OPT"):
        return permute_by_sort(batch, ((~keep).astype(jnp.uint32),),
                               count=n_keep)
    iota = jnp.arange(batch.capacity, dtype=jnp.uint32)
    return permute_by_sort(batch, ((~keep).astype(jnp.uint32), iota),
                           count=n_keep, stable=False)


def filter_rows(batch: Batch, predicate) -> Batch:
    """predicate: dict[str, Column] -> bool[capacity]."""
    keep = predicate(batch.columns)
    return compact(batch, keep)


def take(batch: Batch, n) -> Batch:
    return batch.with_count(jnp.minimum(batch.count, jnp.asarray(n, jnp.int32)))


# ---------------------------------------------------------------------------
# sorting


def _dense_sort_lanes(col: jax.Array, descending: bool) -> List[jax.Array]:
    """Represent a dense column as a list of uint32 sort lanes (most
    significant first) whose unsigned lex order == the column's order."""
    if jnp.issubdtype(col.dtype, jnp.floating):
        f = col.astype(jnp.float32)
        bits = jax.lax.bitcast_convert_type(f, jnp.uint32)
        # flip: negative floats reverse order; standard total-order trick
        sign = (bits >> 31).astype(jnp.uint32)
        bits = jnp.where(sign == 1, ~bits, bits | jnp.uint32(0x80000000))
        lanes = [bits]
    elif col.dtype in (jnp.int64, jnp.uint64):
        u = col.astype(jnp.int64)
        hi = (u >> 32).astype(jnp.uint32)
        if col.dtype == jnp.int64:
            hi = hi ^ jnp.uint32(0x80000000)
        lo = u.astype(jnp.uint32)
        lanes = [hi, lo]
    elif jnp.issubdtype(col.dtype, jnp.signedinteger):
        lanes = [col.astype(jnp.uint32) ^ jnp.uint32(0x80000000)]
    elif col.dtype == jnp.bool_:
        lanes = [col.astype(jnp.uint32)]
    else:
        lanes = [col.astype(jnp.uint32)]
    if descending:
        lanes = [~l for l in lanes]
    return lanes


def _string_sort_lanes(col: StringColumn, descending: bool) -> List[jax.Array]:
    """Lexicographic byte order as packed uint32 lanes (4 bytes per lane).

    Shorter strings sort first among equal prefixes because padding packs
    as 0x00 bytes with the length as tiebreak.  When the last lane has at
    least two spare pad bytes, the length (u16) FOLDS into them — one
    fewer lexsort pass (every lexsort lane is a full stable device sort,
    so a 10-byte TeraSort key drops from 4 sort passes to 3).  Mirrored
    EXACTLY by exec/ooc._host_sort_lanes.
    """
    L = col.max_len
    mask = (jnp.arange(L, dtype=jnp.int32)[None, :] < col.lengths[:, None])
    b = jnp.where(mask, col.data, 0).astype(jnp.uint32)
    pad = (-L) % 4
    lens = col.lengths.astype(jnp.uint32)
    fold_len = pad >= 2 and L <= 0xFFFF
    if fold_len:
        cols = [b, (lens >> 8)[:, None], (lens & 0xFF)[:, None]]
        if pad == 3:
            cols.append(jnp.zeros((b.shape[0], 1), jnp.uint32))
        b = jnp.concatenate(cols, axis=1)
    elif pad:
        b = jnp.pad(b, ((0, 0), (0, pad)))
    b4 = b.reshape(b.shape[0], -1, 4)
    lanes = list(jnp.moveaxis(
        (b4[..., 0] << 24) | (b4[..., 1] << 16) | (b4[..., 2] << 8) | b4[..., 3],
        -1, 0))
    if not fold_len:
        lanes.append(lens)
    if descending:
        lanes = [~l for l in lanes]
    return lanes


def sort_lanes_for(col, descending: bool = False) -> List[jax.Array]:
    if isinstance(col, StringColumn):
        return _string_sort_lanes(col, descending)
    return _dense_sort_lanes(col, descending)


def _lanes_reconstructible(col) -> bool:
    """Can this column be rebuilt exactly from its sort lanes?  True for
    strings (byte lanes + length, fold or no fold) and for 1-D dense
    <=32-bit columns (the lane transforms are bijections).  64-bit ints
    are excluded: without jax x64 their lane build already degrades, so
    they keep riding the packed value path."""
    if isinstance(col, StringColumn):
        return True
    if col.ndim != 1:
        return False
    if col.dtype in (jnp.int64, jnp.uint64, jnp.float64):
        return False
    if col.dtype in (jnp.float16, jnp.bfloat16):
        # the float lane goes through a NUMERIC f32 cast, which
        # canonicalizes NaN payloads — not bit-injective, so half floats
        # keep riding the bit-exact packed value path (same hazard the
        # _pack_columns_u32 widening comment documents)
        return False
    return True


def _dense_lanes_invert(lanes: List[jax.Array], dtype, descending: bool
                        ) -> jax.Array:
    """Inverse of _dense_sort_lanes for the reconstructible dtypes."""
    ls = [~l for l in lanes] if descending else list(lanes)
    b = ls[0]
    if jnp.issubdtype(dtype, jnp.floating):
        # forward: neg -> ~bits, pos -> bits | 0x80000000
        neg = (b >> 31) == 0
        bits = jnp.where(neg, ~b, b ^ jnp.uint32(0x80000000))
        f = jax.lax.bitcast_convert_type(bits, jnp.float32)
        return f.astype(dtype)
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return (b ^ jnp.uint32(0x80000000)).astype(dtype)
    if dtype == jnp.bool_:
        return b != 0
    return b.astype(dtype)


def _string_lanes_invert(lanes: List[jax.Array], max_len: int,
                         descending: bool) -> StringColumn:
    """Inverse of _string_sort_lanes (fold and no-fold layouts)."""
    ls = [~l for l in lanes] if descending else list(lanes)
    L = max_len
    pad = (-L) % 4
    fold_len = pad >= 2 and L <= 0xFFFF
    if fold_len:
        byte_lanes = ls
    else:
        byte_lanes, lens_lane = ls[:-1], ls[-1]
    w = jnp.stack(byte_lanes, axis=1)                      # [cap, nl] u32
    b4 = jnp.stack([(w >> 24) & 0xFF, (w >> 16) & 0xFF,
                    (w >> 8) & 0xFF, w & 0xFF], axis=2)    # [cap, nl, 4]
    flat = b4.reshape(w.shape[0], -1)
    data = flat[:, :L].astype(jnp.uint8)
    if fold_len:
        lens = ((flat[:, L] << 8) | flat[:, L + 1]).astype(jnp.int32)
    else:
        lens = lens_lane.astype(jnp.int32)
    # canonicalize: forward lanes zero bytes past the length, and invalid
    # rows may hold sentinel lanes — clamp + remask below in the caller
    return StringColumn(data, lens)


def sort_by_columns(batch: Batch, keys: Sequence[Tuple[str, bool]]) -> Batch:
    """Sort valid rows by the given (column, descending) keys; padding stays
    at the end.  Stable.

    The key columns are NOT carried as packed value operands when their
    sort lanes already determine them (strings and 1-D dense <=32-bit
    columns — the lane transforms are bijections): they are rebuilt from
    the SORTED key lanes instead.  For the TeraSort shape (10-byte string
    key + i32 payload) this halves the variadic sort from 8 operands
    (3 key lanes + 5 packed) to 4 (3 key lanes + payload), and the sort
    network's cost is linear in operands (measured ~2x end-to-end).
    Two-key-lane sorts additionally RUNTIME-fuse their lanes into one
    when the measured spans fit 32 bits (_sort_fused2 — multi-key key
    packing; e.g. two small-span ints, or an i64 whose values span
    < 2^32), dropping another comparator lane.
    Reference role: the vertex sorter reads each record once
    (DryadVertex/.../recorditem.cpp:1-1140); carrying a second copy of the
    key bytes through every compare-exchange pass has no analogue there.
    """
    lanes: List[jax.Array] = []
    recon: Dict[str, Tuple[int, int, bool]] = {}
    for name, desc in keys:
        col = batch.columns[name]
        ls = sort_lanes_for(col, desc)
        if name not in recon and _lanes_reconstructible(col):
            recon[name] = (len(lanes), len(ls), desc)
        lanes.extend(ls)
    invalid = ~batch.valid_mask()
    col0 = batch.columns[keys[0][0]]
    if (len(keys) == 1 and not keys[0][1]
            and isinstance(col0, StringColumn)
            and (-col0.max_len) % 4 >= 2 and col0.max_len <= 0xFFFF):
        # single ascending folded-length string key: a VALID row's last
        # lane is strictly below 0xFFFFFFFF (its length bytes are
        # <= max_len < 0xFFFF), so setting every lane to all-ones for
        # invalid rows sorts them last EXACTLY — one fewer lexsort pass
        # (each pass is a full stable device sort; this is the TeraSort
        # shape)
        big = jnp.uint32(0xFFFFFFFF)
        lanes = [jnp.where(invalid, big, l) for l in lanes]
        base = 0
    else:
        # general case: explicit invalid flag as the most significant key
        lanes = [invalid.astype(jnp.uint32)] + lanes
        base = 1
    carry_cols = {k: v for k, v in batch.columns.items() if k not in recon}
    packed, spec = _pack_columns_u32(carry_cols)
    from dryad_tpu.ops.pallas_kernels import pallas_active
    if (base == 1 and len(lanes) == 3
            and batch.capacity <= _SORT_FUSE_MAX_CAP
            and pallas_active() is not None
            and not os.environ.get("DRYAD_NO_SORT_OPT")):
        # multi-key sort key packing: two key lanes runtime-fuse into
        # one when the measured spans allow (see _sort_fused2).  The
        # comparator-lane cost model is the TPU sort network's (cost
        # linear in operands); on cpu the fusion measured a wash
        # (BENCH_kernels r06), so it rides the same backend tier as the
        # pallas kernels.
        skeys, svals = _sort_fused2(lanes, packed, batch.capacity)
    else:
        skeys, svals = _sort_carrying(lanes, packed, batch.capacity)
    cols = _unpack_columns_u32(svals, spec)
    valid_sorted = jnp.arange(batch.capacity, dtype=jnp.int32) < batch.count
    for name, (off, cnt, desc) in recon.items():
        kl = skeys[base + off: base + off + cnt]
        col = batch.columns[name]
        if isinstance(col, StringColumn):
            newcol = _string_lanes_invert(kl, col.max_len, desc)
        else:
            newcol = _dense_lanes_invert(kl, col.dtype, desc)
        # padding rows may hold sentinel lanes — zero them (canonical form)
        cols[name] = _mask_rows(newcol, valid_sorted)
    return Batch(cols, batch.count)


# ---------------------------------------------------------------------------
# group-by (sort + segment reduce)


def _hash_sort_segments(hi: jax.Array, lo: jax.Array, valid: jax.Array,
                        extra_lanes: Tuple[jax.Array, ...] = ()):
    """Shared segment machinery: sort rows by 64-bit hash (invalid last),
    label equal-hash runs among valid rows as segments.  ``extra_lanes``
    are uint32 lanes LEAST significant first, ordering rows WITHIN a key
    segment (the group-contents family sorts segments by a value column).

    Returns (order, seg, is_start, num_groups); seg for invalid rows is n
    (out of range — dropped by segment reductions).

    Grouping is by the full 64-bit key hash (both uint32 lanes) without
    true-key verification: two distinct keys colliding in all 64 bits would
    be merged.  P(any collision) ~ n^2/2^64 per partition — negligible at
    per-partition sizes (1e-9 even for 100M-row partitions).

    Invalid rows sort last by FOLDING the all-ones sentinel into the hash
    lanes instead of adding an invalid lane — one fewer lexsort pass on
    every group/distinct/semi-join (each pass is a full stable device
    sort).  A valid row whose 64-bit hash is exactly all-ones would sort
    among the padding and drop — P ~ n/2^64, strictly smaller than the
    collision-merge budget above.
    """
    n = hi.shape[0]
    hi, lo = _sentinel_fold(hi, lo, valid)
    order = jnp.lexsort(tuple(extra_lanes) + (lo, hi))
    shi, slo = jnp.take(hi, order), jnp.take(lo, order)
    svalid = jnp.take(valid, order)
    differs = _lane_differs(shi, slo)
    is_start = svalid & differs
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    seg = jnp.where(svalid, seg, n)
    num_groups = is_start.sum(dtype=jnp.int32)
    return order, seg, is_start, num_groups


def _group_segments(batch: Batch, key_names: Sequence[str]):
    """Sort batch by key hash; return (sorted batch, seg_id, is_start,
    num_groups).  See _hash_sort_segments for collision semantics."""
    hi, lo = hash_batch_keys(batch, key_names)
    order, seg, is_start, num_groups = _hash_sort_segments(
        hi, lo, batch.valid_mask())
    return batch.gather(order), seg, is_start, num_groups


def _first_row_per_segment(is_start: jax.Array,
                           num_groups: jax.Array) -> jax.Array:
    """Index of the first (sorted) row of each segment; 0 past num_groups.
    Scatter-free: the g-th True in ``is_start`` is segment g's first row
    (TPU scatters serialize; the bool argsort rides the vector units)."""
    cap = is_start.shape[0]
    start_pos = jnp.argsort(~is_start, stable=True).astype(jnp.int32)
    return jnp.where(jnp.arange(cap) < num_groups, start_pos, 0)


def _segment_bounds(is_start: jax.Array, num_groups: jax.Array,
                    n_valid: jax.Array):
    """(start_pos, end_excl) per segment slot, scatter-free.

    Rows are segment-sorted (valid first), so the g-th True in ``is_start``
    is segment g's first row: a stable argsort of ``~is_start`` lists those
    positions in order — one cheap bool sort instead of a segment_min
    SCATTER (TPU scatters serialize; sorts ride the vector units)."""
    cap = is_start.shape[0]
    start_pos = jnp.argsort(~is_start, stable=True).astype(jnp.int32)
    idx = jnp.arange(cap, dtype=jnp.int32)
    nxt = jnp.roll(start_pos, -1)
    end_excl = jnp.where(idx + 1 < num_groups, nxt, n_valid)
    return start_pos, end_excl


def _seg_sum_sorted(v: jax.Array, start_pos, end_excl, num_groups,
                    n_valid) -> jax.Array:
    """Segment sums over segment-sorted rows via cumsum boundary
    differences — no scatter.  Exact for integer dtypes (two's-complement
    wraparound cancels in the difference); float32 sums trade the
    per-segment accumulation order for a global prefix (documented on
    group_aggregate)."""
    cap = v.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    mask = (idx < n_valid).reshape((cap,) + (1,) * (v.ndim - 1))
    c = jnp.cumsum(jnp.where(mask, v, 0), axis=0)
    top = jnp.take(c, jnp.clip(end_excl - 1, 0, cap - 1), axis=0)
    bot_i = start_pos - 1
    bot = jnp.take(c, jnp.clip(bot_i, 0, cap - 1), axis=0)
    bot = jnp.where((bot_i >= 0).reshape((cap,) + (1,) * (v.ndim - 1)),
                    bot, 0)
    out = top - bot
    gmask = (idx < num_groups).reshape((cap,) + (1,) * (v.ndim - 1))
    return jnp.where(gmask, out, 0)


def _neutral_for(kind: str, dtype):
    if kind in ("sum", "count"):
        return 0
    if kind == "min":
        return jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating) \
            else jnp.iinfo(dtype).max
    if kind == "max":
        return jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating) \
            else jnp.iinfo(dtype).min
    raise ValueError(kind)


def _boundary_eligible(batch: Batch, aggs) -> Tuple[bool, str | None]:
    """Can this agg set run on the boundary-carry path?  Returns
    (ok, the single min/max order column or None).  Requirements: sum/
    mean/any/all columns are 1-D 4-byte dense (native prefix_sum dtypes);
    all min/max aggregates share ONE 1-D single-lane reconstructible
    column (it rides as a sort key; its extremes then sit at segment
    boundaries).  Everything else falls back to the segmented-scan path."""
    minmax: set = set()
    for _out, (kind, vname) in aggs.items():
        if kind == "count":
            continue
        col = batch.columns[vname]
        if isinstance(col, StringColumn) or col.ndim != 1:
            return False, None
        if kind in ("sum", "mean"):
            if col.dtype.itemsize != 4:
                return False, None
        elif kind in ("min", "max"):
            if not _lanes_reconstructible(col) \
                    or len(_dense_sort_lanes(col, False)) != 1:
                return False, None
            minmax.add(vname)
        elif kind in ("any", "all"):
            pass
        else:
            return False, None
    if len(minmax) > 1:
        return False, None
    return True, (next(iter(minmax)) if minmax else None)


def _shift_fwd(a: jax.Array, fill) -> jax.Array:
    """[fill, a[0], ..., a[-2]] — previous-row view on dense outputs."""
    return jnp.concatenate([jnp.full((1,), fill, a.dtype), a[:-1]])


def group_aggregate(batch: Batch, key_names: Sequence[str],
                    aggs: Dict[str, Tuple[str, str | None]]) -> Batch:
    """GroupBy + decomposable aggregation.

    aggs: out_name -> (kind, value_column | None).  Kinds: sum, count, min,
    max, mean, any, all.  Output batch has the key columns (one representative
    row per group) plus one column per aggregate; count = number of groups.

    This is the map-side combine of the reference's IDecomposable protocol
    (reference LinqToDryad/IDecomposable.cs:34): all kinds here are
    associative, so re-applying the same kernel after a shuffle (with sum for
    count/mean-parts) merges partial aggregates — that is how the distributed
    GroupBy works (planner splits it into local combine -> shuffle -> merge).

    Lowering: small-span integer keys take the one-hot MXU path (a
    runtime span check, _group_aggregate_smallkey); then the
    boundary-carry path when the agg set allows it; else the
    segmented-scan path (_group_aggregate_scan).

    NaN note: the boundary path ranks float min/max by the total order
    -NaN < -inf < ... < +inf < +NaN (the IEEE totalOrder the sort lanes
    induce — and the comparer order the reference's LINQ Min/Max uses),
    while the scan path's jnp.minimum/maximum PROPAGATE any NaN to both
    extremes.  Groups containing NaN can therefore answer differently
    across the two lowerings; all other inputs agree exactly.
    """
    ok, minmax_col = _boundary_eligible(batch, aggs)
    if ok:
        fallback = lambda b: _group_aggregate_boundary(  # noqa: E731
            b, key_names, aggs, minmax_col)
    else:
        fallback = lambda b: _group_aggregate_scan(  # noqa: E731
            b, key_names, aggs)
    if _matmul_group_eligible(batch, key_names, aggs):
        return _group_aggregate_smallkey(batch, key_names, aggs, fallback)
    return fallback(batch)


_SMALLKEY_SLOTS = 512      # one-hot width: span <= this rides the MXU
_SMALLKEY_CHUNK = 16384    # rows per accumulation step (bounds the
                           # materialized [chunk, slots] one-hot to 32 MB)


def _matmul_group_eligible(batch: Batch, key_names, aggs) -> bool:
    """Static half of the MXU group gate: single integer dense key,
    sums/means over float columns only (f32 accumulation is exact for
    counts below 2^24 but not for wide integers), partition small enough
    that counts stay exact."""
    if not _dense_fast_key(batch, key_names):
        return False
    kd = batch.columns[key_names[0]].dtype
    if not jnp.issubdtype(kd, jnp.integer):
        return False
    if batch.capacity >= (1 << 24):
        return False
    for _out, (kind, vname) in aggs.items():
        if kind == "count":
            continue
        if kind not in ("sum", "mean"):
            return False
        col = batch.columns[vname]
        if isinstance(col, StringColumn) or \
                not jnp.issubdtype(col.dtype, jnp.floating) or \
                col.dtype.itemsize != 4:
            return False
    return True


def _group_aggregate_smallkey(batch: Batch, key_names: Sequence[str],
                              aggs: Dict[str, Tuple[str, str | None]],
                              fallback) -> Batch:
    """One-hot MXU group aggregation for small-span integer keys.

    The sort-based lowerings pay ~log^2(n) compare-exchange stages per
    row; when the key span fits ``_SMALLKEY_SLOTS``, per-group sums are
    ONE matmul against the one-hot slot matrix — the systolic array does
    the scatter-add the chip has no scatter unit for (k-means recenter,
    reference role: the broadcast/aggregation ML loops of BASELINE
    config 5).  The span is a runtime property, so the choice is a
    lax.cond against the sort fallback: wide-key batches pay one extra
    min/max reduction, nothing else.
    """
    kcol = batch.columns[key_names[0]]
    cap = batch.capacity
    valid = batch.valid_mask()
    n_valid = batch.count
    S = _SMALLKEY_SLOTS
    kmin = jnp.min(jnp.where(valid, kcol, jnp.iinfo(kcol.dtype).max))
    kmax = jnp.max(jnp.where(valid, kcol, jnp.iinfo(kcol.dtype).min))
    # i32 wraparound on huge true spans lands negative -> fallback
    span = kmax - kmin + 1
    use = (n_valid > 0) & (kmax >= kmin) & (span > 0) & (span <= S)

    def mm_branch(b: Batch) -> Batch:
        k = b.columns[key_names[0]]
        slot = jnp.clip((k - kmin).astype(jnp.int32), 0, S - 1)
        slot = jnp.where(valid, slot, S)          # padding matches nothing
        vals: Dict[str, jax.Array] = {}
        shapes: Dict[str, Tuple] = {}
        for _o, (kind, vname) in aggs.items():
            if kind != "count" and vname not in vals:
                v = b.columns[vname]
                shapes[vname] = v.shape[1:]
                # padding rows hold unspecified bytes (inf/NaN included);
                # a zero one-hot row does NOT neutralize them in the
                # contraction (0 * NaN = NaN) — zero the values themselves
                v = _mask_rows(v, valid)
                vals[vname] = v.reshape(cap, -1)
        names = list(vals)
        m_tot = sum(vals[n].shape[1] for n in names) if names else 0
        pad = (-cap) % _SMALLKEY_CHUNK
        nb = (cap + pad) // _SMALLKEY_CHUNK
        slot_p = jnp.pad(slot, (0, pad), constant_values=S) \
            .reshape(nb, _SMALLKEY_CHUNK)
        if names:
            vcat = jnp.concatenate([vals[n] for n in names], axis=1)
            vcat = jnp.pad(vcat, ((0, pad), (0, 0))) \
                .reshape(nb, _SMALLKEY_CHUNK, m_tot)

        def step(acc, xs):
            cnt_acc, sum_acc = acc
            sl = xs[0]
            oh = (sl[:, None] ==
                  jnp.arange(S, dtype=jnp.int32)[None, :]) \
                .astype(jnp.float32)                      # [chunk, S]
            cnt_acc = cnt_acc + jnp.sum(oh, axis=0)
            if names:
                sum_acc = sum_acc + jax.lax.dot_general(
                    oh, xs[1], (((0,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST)  # [S, m]
            return (cnt_acc, sum_acc), None

        init = (jnp.zeros((S,), jnp.float32),
                jnp.zeros((S, max(m_tot, 1)), jnp.float32))
        (cnts, sums), _ = jax.lax.scan(
            step, init, (slot_p, vcat) if names else (slot_p,))
        nonempty = cnts > 0
        num_groups = nonempty.sum(dtype=jnp.int32)
        order = jnp.argsort(~nonempty, stable=True)       # [S], tiny
        rank = jnp.arange(S, dtype=jnp.int32)
        gvalid_s = rank < num_groups

        def place(a_s):
            """[S, ...] slot-ordered -> [cap, ...] group-compacted."""
            g = jnp.take(a_s, order, axis=0)
            g = _mask_rows(g, gvalid_s)
            if cap >= S:
                padw = ((0, cap - S),) + ((0, 0),) * (g.ndim - 1)
                return jnp.pad(g, padw)
            return g[:cap]

        out_cols: Dict[str, Any] = {}
        out_cols[key_names[0]] = place(
            (kmin + rank).astype(kcol.dtype))
        cnt_g = place(cnts).astype(jnp.int32)
        off = 0
        col_sums: Dict[str, jax.Array] = {}
        for n in names:
            m = vals[n].shape[1]
            col_sums[n] = place(sums[:, off:off + m]) \
                .reshape((cap,) + shapes[n])
            off += m
        for out_name, (kind, vname) in aggs.items():
            if kind == "count":
                out_cols[out_name] = cnt_g
            elif kind == "sum":
                out_cols[out_name] = col_sums[vname]
            else:  # mean
                c = jnp.maximum(cnt_g, 1).reshape(
                    (cap,) + (1,) * len(shapes[vname]))
                out_cols[out_name] = col_sums[vname] / c.astype(jnp.float32)
        return Batch(out_cols, num_groups)

    return jax.lax.cond(use, mm_branch, fallback, batch)


def _group_aggregate_boundary(batch: Batch, key_names: Sequence[str],
                              aggs: Dict[str, Tuple[str, str | None]],
                              minmax_col: str | None) -> Batch:
    """Boundary-carry group aggregation — scan-free.

    The round-4 profile (scratch probes, re-runnable via
    benchmarks/pallas_probe.py methodology) showed the segmented-scan
    lowering spending only 0.11 ms of its 2.55 ms in the segment sort at
    500k rows: the associative scans (0.80 ms, log-depth HBM passes) and
    the densify sort's carried aggregate lanes dominated.  This path
    removes the scans entirely:

      * the min/max order column rides as an extra SORT KEY, so each
        segment's min sits at its first row and its max at its last —
        no scan, and the column is rebuilt from its own sorted lane;
      * sums ride as ONE global prefix_sum (pallas streaming scan,
        ops/pallas_kernels — 4.5x XLA's cumsum); per-group sums are then
        ADJACENT DIFFERENCES of the csum lane on the DENSE output rows
        (integer-exact; f32 inherits the global-prefix cancellation
        bound documented on _seg_sum_sorted);
      * counts are adjacent differences of the carried row index —
        segments tile the valid prefix, so end_idx[g] - end_idx[g-1] is
        exactly group g's size;
      * group g's MIN is the order lane of the row AFTER segment g-1's
        end — carried as a shifted lane and read off the previous dense
        row (group 0 reads sorted row 0).

    One unstable segment sort + one stable boundary densify + one
    streamed prefix pass — nothing else touches HBM.
    """
    valid = batch.valid_mask()
    cap = batch.capacity
    n_valid = batch.count
    idx = jnp.arange(cap, dtype=jnp.int32)

    kcol0 = batch.columns[key_names[0]]
    dense_fast = _dense_fast_key(batch, key_names)

    # --- sort keys: grouping lanes (+ the min/max order lane) ----------
    if dense_fast:
        klane = _dense_key_lane(kcol0)
        key_lanes = [(~valid).astype(jnp.uint32), klane]
        n_group_lanes = 2
    else:
        hi, lo = hash_batch_keys(batch, key_names)
        hi_s, lo_s = _sentinel_fold(hi, lo, valid)
        key_lanes = [hi_s, lo_s]
        n_group_lanes = 2
    if minmax_col is not None:
        key_lanes.append(_dense_sort_lanes(batch.columns[minmax_col],
                                           False)[0])

    # --- carries: native-dtype lanes for each summed column ------------
    def _as_u32(a):
        return jax.lax.bitcast_convert_type(a, jnp.uint32) \
            if a.dtype != jnp.uint32 else a

    sum_cols: Dict[str, jax.Array] = {}     # cumsum inputs, native dtype
    for _out, (kind, vname) in aggs.items():
        if kind in ("sum", "mean") and vname not in sum_cols:
            sum_cols[vname] = batch.columns[vname]
        elif kind in ("any", "all"):
            ik = "#i:" + vname
            if ik not in sum_cols:
                sum_cols[ik] = batch.columns[vname].astype(jnp.int32)
    # the min/max column's order lane already determines its values
    # (bijection), so when it is ALSO summed it does not ride as a carry:
    # the sorted column is rebuilt from the sorted key lane instead —
    # one fewer sort operand (sort cost is linear in operands, measured)
    rebuild_sum = (minmax_col is not None and minmax_col in sum_cols)
    carry = [_as_u32(v) for name, v in sum_cols.items()
             if not (rebuild_sum and name == minmax_col)]
    if dense_fast:
        pack_spec = None
    else:
        kp, pack_spec = _pack_columns_u32(
            {k: batch.columns[k] for k in key_names})
        carry = kp + carry

    skeys, scarry = _sort_carrying(key_lanes, carry, cap, stable=False)
    if dense_fast:
        skey = skeys[1]
        differs = _lane_differs(skey)
    else:
        differs = _lane_differs(skeys[0], skeys[1])
    _is_start, is_end, num_groups = _segment_flags(differs, n_valid)
    svord = skeys[n_group_lanes] if minmax_col is not None else None

    # --- streamed prefix sums over the sorted value lanes ---------------
    # f32 prefixes are COMPENSATED (hi, lo) pairs: the adjacent-difference
    # group sums below would otherwise carry error proportional to the
    # GLOBAL prefix magnitude — unbounded relative to a small group's own
    # sum (pallas_kernels.prefix_sum2).  Integer prefixes are exact under
    # modular wraparound and ride the plain scan.
    from dryad_tpu.ops.pallas_kernels import prefix_sum, prefix_sum2
    n_pack = 0 if dense_fast else sum(s[3] for s in pack_spec)
    svalid = idx < n_valid
    csums: Dict[str, Tuple[jax.Array, ...]] = {}
    j = 0
    for name, v in sum_cols.items():
        if rebuild_sum and name == minmax_col:
            sv = _dense_lanes_invert([svord], v.dtype, False)
        else:
            sv = scarry[n_pack + j]
            j += 1
            if v.dtype != jnp.uint32:
                sv = jax.lax.bitcast_convert_type(sv, v.dtype)
        masked = jnp.where(svalid, sv, jnp.zeros((), v.dtype))
        if v.dtype == jnp.float32:
            csums[name] = prefix_sum2(masked)
        else:
            csums[name] = (prefix_sum(masked),)

    # --- densify segment-END rows to the front (group order) ------------
    dlanes: List[jax.Array] = []
    if dense_fast:
        dlanes.append(skey)
    else:
        dlanes.extend(scarry[:n_pack])
    if minmax_col is not None:
        dlanes.append(svord)
        # order-lane of the row after each end = next segment's min
        dlanes.append(jnp.concatenate([svord[1:], svord[-1:]]))
    cs_off: Dict[str, int] = {}
    for name in sum_cols:
        cs_off[name] = len(dlanes)
        dlanes.extend(_as_u32(lane) for lane in csums[name])
    # UNSTABLE 2-key sort: the row index is both the order tiebreak
    # (so end-rows keep group order deterministically) and the count
    # payload — one operand doing double duty vs a stable 1-key sort
    # (XLA's stable sort pays for an internal iota anyway, measured)
    dkeys, dl = _sort_carrying(
        [(~is_end).astype(jnp.uint32), idx.astype(jnp.uint32)],
        dlanes, cap, stable=False)
    didx_lane = dkeys[1]

    gmask = idx < num_groups
    out_cols: Dict[str, Any] = {}
    if dense_fast:
        out_cols[key_names[0]] = _mask_rows(
            _dense_lanes_invert([dl[0]], kcol0.dtype, False), gmask)
        p = 1
    else:
        kcols = _unpack_columns_u32(dl[:n_pack], pack_spec)
        for k in key_names:
            out_cols[k] = _mask_rows(kcols[k], gmask)
        p = n_pack
    if minmax_col is not None:
        mm_dtype = batch.columns[minmax_col].dtype
        vmax = _dense_lanes_invert([dl[p]], mm_dtype, False)
        minfeed = _shift_fwd(dl[p + 1], 0)
        vmin = _dense_lanes_invert([minfeed], mm_dtype, False)
        # group 0's min = the very first sorted row's order lane
        v0 = _dense_lanes_invert([svord[0:1]], mm_dtype, False)[0]
        vmin = jnp.where(idx == 0, v0, vmin)
        p += 2
    dcs: Dict[str, jax.Array] = {}
    for name, v in sum_cols.items():
        o = cs_off[name]
        c = dl[o]
        if v.dtype != jnp.uint32:
            c = jax.lax.bitcast_convert_type(c, v.dtype)
        if v.dtype == jnp.float32:
            clo = jax.lax.bitcast_convert_type(dl[o + 1], jnp.float32)
            # difference BOTH compensated lanes: error ~ ulp(group sum)
            dcs[name] = ((c - _shift_fwd(c, 0))
                         + (clo - _shift_fwd(clo, 0)))
        else:
            dcs[name] = c - _shift_fwd(c, 0)
    didx = didx_lane.astype(jnp.int32)
    cnt_g = didx - _shift_fwd(didx, -1)

    for out_name, (kind, vname) in aggs.items():
        if kind == "count":
            o = cnt_g
        elif kind == "sum":
            o = dcs[vname]
        elif kind == "mean":
            s = dcs[vname]
            c = jnp.maximum(cnt_g, 1)
            o = s / c.astype(s.dtype) \
                if jnp.issubdtype(s.dtype, jnp.floating) \
                else s.astype(jnp.float32) / c
        elif kind == "min":
            o = vmin
        elif kind == "max":
            o = vmax
        elif kind == "any":
            o = dcs["#i:" + vname] > 0
        elif kind == "all":
            o = dcs["#i:" + vname] == cnt_g
        out_cols[out_name] = _mask_rows(o, gmask)
    return Batch(out_cols, num_groups)


def _group_aggregate_scan(batch: Batch, key_names: Sequence[str],
                          aggs: Dict[str, Tuple[str, str | None]]) -> Batch:
    """Segmented-scan group aggregation — the general path (2-D value
    columns, 8-byte sums, string or multi-column min/max)."""
    # Scatter- and gather-free lowering (TPU: scatters serialize, random
    # gathers cost ~9 ns/row): ONE variadic sort carries the agg value
    # columns as packed words alongside the grouping lanes; segmented
    # associative scans produce running reduces whose per-group totals sit
    # at each segment's LAST row; a second value-carry sort on the is_end
    # flag densifies those rows to the front in group order.
    #
    # Dense-key fast path: a single <=32-bit dense key groups by its EXACT
    # order lane — no hashing (exact, no 64-bit collision budget), the key
    # column rides as one raw lane and is rebuilt from the sorted lane,
    # and the segment sort runs UNSTABLE (measured ~2x cheaper; nothing
    # observes in-segment value order).
    valid = batch.valid_mask()
    cap = batch.capacity
    n_valid = batch.count
    idx = jnp.arange(cap, dtype=jnp.int32)

    kcol0 = batch.columns[key_names[0]]
    dense_fast = _dense_fast_key(batch, key_names)

    needed_vals = list(dict.fromkeys(
        v for _, v in aggs.values() if v and v not in
        (key_names if dense_fast else ())))
    if dense_fast:
        needed = needed_vals
    else:
        needed = list(dict.fromkeys(list(key_names) + needed_vals))
    lanes, spec = _pack_columns_u32({k: batch.columns[k] for k in needed})
    if dense_fast:
        key_lane = _dense_key_lane(kcol0)
        skey, slanes, is_start, is_end, num_groups = _sort_segments_dense(
            key_lane, valid, n_valid, lanes)
    else:
        hi, lo = hash_batch_keys(batch, key_names)
        skey = None
        slanes, is_start, is_end, num_groups = _sort_segments_carry(
            hi, lo, valid, n_valid, lanes, stable=False)
    scols = _unpack_columns_u32(slanes, spec)
    if dense_fast and key_names[0] in (v for _, v in aggs.values() if v):
        # the key column doubles as an agg value (e.g. count over key):
        # rebuild its sorted version from the key lane
        scols[key_names[0]] = _dense_lanes_invert([skey], kcol0.dtype,
                                                  False)

    # every aggregate's running reduce rides ONE fused segmented scan
    # (shared log(cap) passes + boundary carry — the scans dominate this
    # kernel's device time at millions of rows, measured ~2 ms per extra
    # scan at 2M)
    scan_in: List[Tuple[jax.Array, Any]] = [
        ((idx < n_valid).astype(jnp.int32), jnp.add)]   # run_cnt
    slots: Dict[Tuple[str, str | None], int] = {}

    def _slot(kind, vname, arr, op):
        k = (kind, vname)
        if k not in slots:
            slots[k] = len(scan_in)
            scan_in.append((arr, op))
        return slots[k]

    for out_name, (kind, vname) in aggs.items():
        if kind == "count":
            continue
        if kind in ("sum", "mean"):
            _slot("sum", vname, scols[vname], jnp.add)
        elif kind == "min":
            _slot("min", vname, scols[vname], jnp.minimum)
        elif kind == "max":
            _slot("max", vname, scols[vname], jnp.maximum)
        elif kind in ("any", "all"):
            _slot("isum", vname, scols[vname].astype(jnp.int32), jnp.add)
        else:
            raise ValueError(f"unknown aggregate kind {kind}")
    scanned = _seg_scan_multi(scan_in, is_start)
    run_cnt = scanned[0]

    dense_in: Dict[str, Any] = ({} if dense_fast
                                else {k: scols[k] for k in key_names})
    for out_name, (kind, vname) in aggs.items():
        if kind == "count":
            o = run_cnt
        elif kind in ("sum", "mean"):
            s = scanned[slots[("sum", vname)]]
            if kind == "sum":
                o = s
            else:
                c = jnp.maximum(run_cnt, 1).reshape(
                    (cap,) + (1,) * (s.ndim - 1))
                o = s / c.astype(s.dtype) \
                    if jnp.issubdtype(s.dtype, jnp.floating) \
                    else s.astype(jnp.float32) / c
        elif kind == "min":
            o = scanned[slots[("min", vname)]]
        elif kind == "max":
            o = scanned[slots[("max", vname)]]
        elif kind == "any":
            o = scanned[slots[("isum", vname)]] > 0
        elif kind == "all":
            o = scanned[slots[("isum", vname)]] == run_cnt
        else:
            raise ValueError(f"unknown aggregate kind {kind}")
        dense_in[out_name] = o

    lanes2, spec2 = _pack_columns_u32(dense_in)
    if dense_fast:
        lanes2 = [skey] + lanes2
    _, svals2 = _sort_carrying([(~is_end).astype(jnp.uint32)], lanes2, cap)
    if dense_fast:
        skey2, svals2 = svals2[0], svals2[1:]
    dcols = _unpack_columns_u32(svals2, spec2)
    gmask = idx < num_groups
    out_cols = {name: _mask_rows(v, gmask) for name, v in dcols.items()}
    if dense_fast:
        out_cols[key_names[0]] = _mask_rows(
            _dense_lanes_invert([skey2], kcol0.dtype, False), gmask)
    return Batch(out_cols, num_groups)


def _mask_rows(col, keep: jax.Array):
    """Zero rows where ``keep`` is False (strings get zero data+length)."""
    if isinstance(col, StringColumn):
        m2 = keep.reshape(-1, 1)
        return StringColumn(jnp.where(m2, col.data, 0),
                            jnp.where(keep, col.lengths, 0))
    m = keep.reshape(keep.shape + (1,) * (col.ndim - 1))
    return jnp.where(m, col, 0)


# ---------------------------------------------------------------------------
# user-defined decomposable aggregation (IDecomposable parity)


def _segmented_merge(seg: jax.Array, states, merge_fn):
    """Reduce an arbitrary associative ``merge_fn`` over each segment.

    TPU-idiomatic segmented reduction: a single ``associative_scan`` over
    rows carrying (segment id, state); the combine keeps the right operand
    where segments differ, so each segment's LAST row ends up holding the
    full segment reduction.  This is what lets *user-defined* aggregations
    (reference IDecomposable.cs:34 Accumulate/RecursiveAccumulate) run as
    one fused XLA op instead of a per-group loop.
    """

    def combine(a, b):
        sa, va = a
        sb, vb = b
        same = sa == sb

        def pick(x, y):
            m = same.reshape(same.shape + (1,) * (x.ndim - 1))
            return jnp.where(m, x, y)

        merged = merge_fn(va, vb)
        out = jax.tree.map(pick, merged, vb)
        return sb, out

    _, scanned = jax.lax.associative_scan(combine, (seg, states))
    return scanned


def _last_row_per_segment(is_start: jax.Array, num_groups: jax.Array,
                          n_valid: jax.Array) -> jax.Array:
    """Index of the last (sorted) row of each segment; 0 past num_groups.
    Scatter-free via _segment_bounds (XLA CSE merges the bool argsort
    with _first_row_per_segment's when both are used)."""
    cap = is_start.shape[0]
    _, end_excl = _segment_bounds(is_start, num_groups, n_valid)
    return jnp.where(jnp.arange(cap) < num_groups,
                     jnp.maximum(end_excl - 1, 0), 0)


def _seg_scan_multi(vals_ops, is_start: jax.Array):
    """Running segment reduces for SEVERAL (value, op) pairs in ONE
    associative scan: the log(cap) passes and the boundary-flag carry are
    shared instead of paid per aggregate (measured: the scans, not the
    sorts, dominate group_aggregate at millions of rows — five separate
    scans re-stream the array five times)."""

    def comb(a, b):
        fa, va = a[0], a[1:]
        fb, vb = b[0], b[1:]
        out = []
        for (xa, xb, (_, op)) in zip(va, vb, vals_ops):
            m = fb.reshape(fb.shape + (1,) * (xa.ndim - 1))
            out.append(jnp.where(m, xb, op(xa, xb)))
        return (fa | fb,) + tuple(out)

    res = jax.lax.associative_scan(
        comb, (is_start,) + tuple(v for v, _ in vals_ops))
    return list(res[1:])


def _seg_scan_reduce(v: jax.Array, is_start: jax.Array, op,
                     reverse: bool = False) -> jax.Array:
    """Per-row running ``op``-reduce within each segment (rows in sorted
    segment order, ``is_start`` marking segment firsts).  One segmented
    associative_scan — log(cap) vectorized passes, NO scatter (TPU
    scatters serialize; measured ~25 ms per 4M rows vs ~1 ms for scans).
    The per-segment total sits at the segment's last row (first row with
    ``reverse=True``, whose boundary flags must mark segment ENDS).  Float
    accumulation order is the scan's balanced tree — no cross-segment
    cancellation (unlike a global-prefix difference), bounded rounding
    like numpy's pairwise sums."""

    def comb(a, b):
        va, fa = a
        vb, fb = b
        m = fb.reshape(fb.shape + (1,) * (va.ndim - 1))
        return jnp.where(m, vb, op(va, vb)), fa | fb

    out, _ = jax.lax.associative_scan(comb, (v, is_start), reverse=reverse)
    return out


def _hash_membership(hi: jax.Array, lo: jax.Array, flag: jax.Array,
                     valid: jax.Array) -> jax.Array:
    """bool [n] in ORIGINAL row order: does the row's 64-bit-hash segment
    contain a flagged row?  Scatter- and gather-free: one value-carry sort
    groups hashes (carrying the flag and the original position), forward +
    reverse segmented max-scans spread each segment's answer to every row,
    and a second 1-key sort on the carried position restores original
    order (the inverse-permutation-as-sort trick — TPU scatters
    serialize)."""
    n = hi.shape[0]
    iota = jnp.arange(n, dtype=jnp.uint32)
    # NOTE: valid rows sort as a prefix ONLY when valid is itself a
    # prefix mask; callers concatenate whole-batch valid prefixes, and
    # _sort_segments_carry's sentinel fold sorts the invalid rows last
    # regardless, so is_start/is_end stay correct
    (sflag, siota), is_start, is_end, _ng = _sort_segments_carry(
        hi, lo, valid, valid.sum(dtype=jnp.int32),
        (flag.astype(jnp.uint32), iota), stable=False)
    fwd = _seg_scan_reduce(sflag, is_start, jnp.maximum)
    bwd = _seg_scan_reduce(sflag, is_end, jnp.maximum, reverse=True)
    tot = jnp.maximum(fwd, bwd)
    # both sorts run unstable: the carried iota is a total key, so the
    # restore sort is deterministic regardless, and the first sort's
    # in-segment order is erased by the max-scans
    _, member = jax.lax.sort((siota, tot), num_keys=1, is_stable=False)
    return member > 0


def _group_states(batch: Batch, key_names: Sequence[str],
                  decs: Dict[str, Tuple], state_box: Dict):
    """Shared seed+segmented-merge machinery: returns (key out_cols,
    out -> per-group merged state pytree, num_groups, valid_rows mask)."""
    sb, seg, is_start, num_groups = _group_segments(batch, key_names)
    cap = batch.capacity

    out_cols = {}
    rep = sb.gather(_first_row_per_segment(is_start, num_groups))
    for k in key_names:
        out_cols[k] = rep.columns[k]

    last = _last_row_per_segment(is_start, num_groups, batch.count)
    valid_rows = jnp.arange(cap) < num_groups
    merged_states = {}
    for out_name, (seed, merge_fn, _fin) in decs.items():
        states = seed(dict(sb.columns))
        state_box[out_name] = jax.tree.structure(states)
        scanned = _segmented_merge(seg, states, merge_fn)
        merged_states[out_name] = jax.tree.map(
            lambda l: jnp.take(l, last, axis=0), scanned)
    return out_cols, merged_states, num_groups, valid_rows


def _emit_finalized(out_cols, out_name, fin, merged, valid_rows):
    val = fin(merged) if fin is not None else merged
    named = val if isinstance(val, dict) else {out_name: val}
    for cname, v in named.items():
        m = valid_rows.reshape(valid_rows.shape + (1,) * (v.ndim - 1))
        out_cols[cname] = jnp.where(m, v, 0)


def resolve_dec_spec(spec):
    """Dec spec -> (seed, merge, finalize) callables.  Specs are either a
    plan.expr.Decomposable (user-defined; ships by fn_table registration)
    or a ("__builtin__", kind, col) tag rebuilt here on the executing side
    (keeps plans serializable — runtime/shiplan.py)."""
    if isinstance(spec, tuple) and len(spec) == 3 and \
            spec[0] == "__builtin__":
        from dryad_tpu.plan.planner import _builtin_as_decomposable
        d = _builtin_as_decomposable(spec[1], spec[2])
        return (d.seed, d.merge, d.finalize)
    if hasattr(spec, "seed"):
        return (spec.seed, spec.merge, spec.finalize)
    return spec  # already a triple (direct kernel callers)


def _resolve_decs(decs):
    return {k: resolve_dec_spec(v) for k, v in decs.items()}


def group_decompose_partial(batch: Batch, key_names: Sequence[str],
                            decs: Dict[str, Tuple], state_box: Dict
                            ) -> Batch:
    """Map-side combine for user-defined decomposable aggregates.

    decs: out_name -> dec spec (see resolve_dec_spec).  ``seed(columns)``
    maps the row columns to a state pytree (vectorized over rows);
    ``merge(a, b)`` is the associative combine.  Output: key columns + the
    flattened state leaves as columns ``{out}@{i}``; the treedefs are
    published into ``state_box`` for the merge/finalize stage
    (reference IDecomposable.cs:34 Initialize/Seed/Accumulate).
    """
    decs = _resolve_decs(decs)
    out_cols, merged_states, num_groups, valid_rows = _group_states(
        batch, key_names, decs, state_box)
    for out_name, merged in merged_states.items():
        for i, leaf in enumerate(jax.tree.leaves(merged)):
            m = valid_rows.reshape(valid_rows.shape + (1,) * (leaf.ndim - 1))
            out_cols[f"{out_name}@{i}"] = jnp.where(m, leaf, 0)
    return Batch(out_cols, num_groups)


def group_decompose_local(batch: Batch, key_names: Sequence[str],
                          decs: Dict[str, Tuple], state_box: Dict) -> Batch:
    """Single-pass decomposable GroupBy (co-located input): seed + merge +
    FinalReduce in one fused kernel."""
    decs = _resolve_decs(decs)
    out_cols, merged_states, num_groups, valid_rows = _group_states(
        batch, key_names, decs, state_box)
    for out_name, merged in merged_states.items():
        fin = decs[out_name][2]
        _emit_finalized(out_cols, out_name, fin, merged, valid_rows)
    return Batch(out_cols, num_groups)


def group_decompose_merge(batch: Batch, key_names: Sequence[str],
                          decs: Dict[str, Tuple], state_box: Dict,
                          finalize: bool) -> Batch:
    """Reduce-side merge of partial states (columns ``{out}@{i}``), plus
    FinalReduce when ``finalize`` (reference IDecomposable.cs:34
    RecursiveAccumulate/FinalReduce)."""
    decs = _resolve_decs(decs)
    sb, seg, is_start, num_groups = _group_segments(batch, key_names)
    cap = batch.capacity

    out_cols = {}
    rep = sb.gather(_first_row_per_segment(is_start, num_groups))
    for k in key_names:
        out_cols[k] = rep.columns[k]

    last = _last_row_per_segment(is_start, num_groups, batch.count)
    valid_rows = jnp.arange(cap) < num_groups
    for out_name, (_seed, merge_fn, fin) in decs.items():
        treedef = state_box[out_name]
        n_leaves = treedef.num_leaves
        leaves = [sb.columns[f"{out_name}@{i}"] for i in range(n_leaves)]
        states = jax.tree.unflatten(treedef, leaves)
        scanned = _segmented_merge(seg, states, merge_fn)
        merged = jax.tree.map(
            lambda l: jnp.take(l, last, axis=0), scanned)
        if finalize:
            _emit_finalized(out_cols, out_name, fin, merged, valid_rows)
        else:
            for i, leaf in enumerate(jax.tree.leaves(merged)):
                m = valid_rows.reshape(
                    valid_rows.shape + (1,) * (leaf.ndim - 1))
                out_cols[f"{out_name}@{i}"] = jnp.where(m, leaf, 0)
    return Batch(out_cols, num_groups)


# ---------------------------------------------------------------------------
# group CONTENTS (per-group apply / top-k / rank select)
#
# The reference's GroupBy materializes each key's element sequence and runs
# ANY result selector over it (DryadLinqVertex.cs:510-753 — hash/sort
# GroupBy yielding IGrouping to user code).  The TPU-native forms below keep
# everything shape-static: rows are sorted into key segments and either
# (a) trimmed per segment by rank (top-k / rank select — O(cap) memory), or
# (b) regrouped into a dense [max_groups, group_capacity] layout and handed
# to a user fn vmapped over groups (the general result-selector path).


def _segments_by_keys_and_lanes(batch: Batch, key_names: Sequence[str],
                                extra_lanes: Tuple[jax.Array, ...]):
    """Sort rows by (key hash, extra ordering lanes), label equal-hash runs
    as segments — _hash_sort_segments with within-segment value order."""
    hi, lo = hash_batch_keys(batch, key_names)
    return _hash_sort_segments(hi, lo, batch.valid_mask(), extra_lanes)


def group_top_k(batch: Batch, key_names: Sequence[str], k: int, by: str,
                descending: bool = True) -> Batch:
    """Per-group top-k rows by the ``by`` column (all columns kept).

    O(cap) memory: rows are sorted by (key hash, by-value), and each
    segment keeps its first k rows — no dense regrouping.  Ties keep
    original row order (both sorts are stable).  Output fits the input
    capacity by construction (no overflow channel needed).
    Reference: a per-group result selector taking the k largest
    (DryadLinqVertex.cs:510-753 GroupBy family)."""
    lanes = sort_lanes_for(batch.columns[by], descending)
    order, seg, is_start, num_groups = _segments_by_keys_and_lanes(
        batch, key_names, tuple(reversed(lanes)))
    cap = batch.capacity
    sb = batch.gather(order)
    start_pos, _ = _segment_bounds(is_start, num_groups, batch.count)
    idx = jnp.arange(cap, dtype=jnp.int32)
    rel = idx - jnp.take(start_pos, jnp.clip(seg, 0, cap - 1))
    keep = (idx < batch.count) & (rel < k)
    return compact(sb, keep)


def group_rank_select(batch: Batch, key_names: Sequence[str], by: str,
                      rank: str = "median", out: str | None = None) -> Batch:
    """One row per group: the group's element at a sorted rank of ``by``.

    rank="median" picks the LOWER median (element (n-1)//2 of the
    ascending ``by`` order — exact an element of the group, unlike
    numpy's interpolated even-size median); "min"/"max" pick the ends.
    Output columns: the key columns + ``out`` (default: the ``by`` name)
    holding the selected value."""
    lanes = sort_lanes_for(batch.columns[by], False)
    order, seg, is_start, num_groups = _segments_by_keys_and_lanes(
        batch, key_names, tuple(reversed(lanes)))
    cap = batch.capacity
    sb = batch.gather(order)
    start_pos, end_excl = _segment_bounds(is_start, num_groups, batch.count)
    sizes = end_excl - start_pos
    if rank == "median":
        pos = start_pos + (sizes - 1) // 2
    elif rank == "min":
        pos = start_pos
    elif rank == "max":
        pos = end_excl - 1
    else:
        raise ValueError(f"unknown rank {rank!r}")
    gvalid = jnp.arange(cap, dtype=jnp.int32) < num_groups
    sel = jnp.where(gvalid, jnp.clip(pos, 0, cap - 1), 0)
    rep = sb.gather(jnp.where(gvalid, start_pos, 0))
    out_cols: Dict[str, Any] = {}
    for kname in key_names:
        out_cols[kname] = rep.columns[kname]
    v = sb.columns[by]
    oname = out or by
    if isinstance(v, StringColumn):
        out_cols[oname] = v.gather(sel)
    else:
        out_cols[oname] = jnp.take(v, sel, axis=0)
    return Batch(out_cols, num_groups)


def group_regroup_apply(batch: Batch, key_names: Sequence[str], fn,
                        max_groups: int, group_capacity: int,
                        out_rows: int, out_capacity: int):
    """The general per-group result selector: regroup rows into a dense
    [max_groups, group_capacity] layout and vmap ``fn`` over groups.

    ``fn(cols, count) -> (out_cols, mask)``: cols are ONE group's columns
    ([group_capacity, ...] arrays / StringColumns; rows >= count are
    unspecified), out_cols are [out_rows, ...], mask is [out_rows] bool.
    Group key columns are attached to the output automatically (one value
    per group, broadcast over its emitted rows) unless fn emits a column
    of the same name.  Outputs of all groups are flattened and compacted
    into ``out_capacity`` rows.

    Returns (batch, num_groups, max_group_size, total_out_rows) — the
    three measured requirements; the executor converts any that exceed
    its static bound into a right-sized retry (measured-need feedback,
    DrDynamicDistributor.cpp:388 role).

    Memory note: the dense regroup materializes
    max_groups x group_capacity cells per column — size the two knobs for
    the workload (the price of giving user code a whole materialized
    group on a tensor machine; reference streams IGroupings instead,
    DryadLinqVertex.cs:510)."""
    sb, seg, is_start, num_groups = _group_segments(batch, key_names)
    cap = batch.capacity
    start_pos, end_excl = _segment_bounds(is_start, num_groups, batch.count)
    idx = jnp.arange(cap, dtype=jnp.int32)
    sizes = jnp.where(idx < num_groups, end_excl - start_pos, 0)
    max_size = jnp.max(sizes).astype(jnp.int32)

    # a partition cannot hold more groups (or a larger group) than rows
    G, C, R = min(max_groups, cap), min(group_capacity, cap), out_rows
    gstart = start_pos[:G]
    gsizes = jnp.minimum(sizes[:G], C)  # clamp: oversize triggers retry
    gvalid = jnp.arange(G, dtype=jnp.int32) < num_groups
    gidx = jnp.clip(gstart[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :],
                    0, cap - 1)  # [G, C]
    group_cols: Dict[str, Any] = {}
    for kname, v in sb.columns.items():
        if isinstance(v, StringColumn):
            group_cols[kname] = StringColumn(
                jnp.take(v.data, gidx, axis=0),
                jnp.take(v.lengths, gidx, axis=0))
        else:
            group_cols[kname] = jnp.take(v, gidx, axis=0)

    out_cols, mask = jax.vmap(fn)(group_cols, gsizes)  # [G, R, ...], [G, R]
    mask = mask & gvalid[:, None]

    rep = sb.gather(jnp.where(gvalid, gstart, 0))  # [G] key rows
    full: Dict[str, Any] = {}
    for kname in key_names:
        if kname in out_cols:
            continue
        v = rep.columns[kname]
        if isinstance(v, StringColumn):
            full[kname] = StringColumn(
                jnp.broadcast_to(v.data[:, None, :], (G, R, v.max_len)),
                jnp.broadcast_to(v.lengths[:, None], (G, R)))
        else:
            full[kname] = jnp.broadcast_to(
                v[:, None], (G, R) + v.shape[1:])
    full.update(out_cols)

    flat_mask = mask.reshape(-1)
    total = flat_mask.sum(dtype=jnp.int32)
    perm = jnp.argsort(~flat_mask, stable=True)[:out_capacity]
    cols: Dict[str, Any] = {}
    for kname, v in full.items():
        if isinstance(v, StringColumn):
            data = v.data.reshape((G * R,) + v.data.shape[2:])
            lens = v.lengths.reshape(-1)
            cols[kname] = StringColumn(jnp.take(data, perm, axis=0),
                                       jnp.take(lens, perm))
        else:
            flat = v.reshape((G * R,) + v.shape[2:])
            cols[kname] = jnp.take(flat, perm, axis=0)
    out = Batch(cols, jnp.minimum(total, out_capacity))
    return out, num_groups, max_size, total


def distinct(batch: Batch, key_names: Sequence[str] | None = None) -> Batch:
    """One representative row per distinct key (all columns kept).

    Gather-free: value-carry sort by hash, then a second value-carry sort
    on the is_start flag densifies each segment's first row to the front
    in group order (see the packed-row transport note above)."""
    keys = list(key_names) if key_names else sorted(batch.names)
    hi, lo = hash_batch_keys(batch, keys)
    cap = batch.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    lanes, spec = _pack_columns_u32(dict(batch.columns))
    slanes, is_start, _is_end, num_groups = _sort_segments_carry(
        hi, lo, batch.valid_mask(), batch.count, lanes)
    _, svals2 = _sort_carrying([(~is_start).astype(jnp.uint32)], slanes,
                               cap)
    cols = _unpack_columns_u32(svals2, spec)
    gmask = idx < num_groups
    return Batch({k: _mask_rows(v, gmask) for k, v in cols.items()},
                 num_groups)


# ---------------------------------------------------------------------------
# whole-batch (scalar) aggregation


def scalar_aggregate(batch: Batch,
                     aggs: Dict[str, Tuple[str, str | None]]) -> Dict[str, jax.Array]:
    """Masked full-batch reductions: out_name -> (kind, value_column|None)."""
    valid = batch.valid_mask()
    out = {}
    for out_name, (kind, vname) in aggs.items():
        if kind == "count":
            out[out_name] = batch.count
            continue
        v = batch.columns[vname]
        if kind in ("sum", "mean"):
            vm = jnp.where(valid, v, 0)
            s = vm.sum(axis=0)
            if kind == "sum":
                out[out_name] = s
            else:
                c = jnp.maximum(batch.count, 1)
                out[out_name] = s / c if jnp.issubdtype(s.dtype, jnp.floating) \
                    else s.astype(jnp.float32) / c
        elif kind == "min":
            out[out_name] = jnp.where(valid, v, _neutral_for("min", v.dtype)).min(axis=0)
        elif kind == "max":
            out[out_name] = jnp.where(valid, v, _neutral_for("max", v.dtype)).max(axis=0)
        elif kind == "any":
            out[out_name] = (jnp.where(valid, v, False)).any(axis=0)
        elif kind == "all":
            out[out_name] = (jnp.where(valid, v, True)).all(axis=0)
        else:
            raise ValueError(kind)
    return out


# ---------------------------------------------------------------------------
# join


def _keys_equal(a: Batch, a_idx, a_names, b: Batch, b_idx, b_names) -> jax.Array:
    eq = jnp.ones(a_idx.shape, jnp.bool_)
    for an, bn in zip(a_names, b_names):
        ca, cb = a.columns[an], b.columns[bn]
        if isinstance(ca, StringColumn):
            la = jnp.take(ca.lengths, a_idx)
            lb = jnp.take(cb.lengths, b_idx)
            da = jnp.take(ca.data, a_idx, axis=0)
            db = jnp.take(cb.data, b_idx, axis=0)
            L = min(ca.max_len, cb.max_len)
            pos = jnp.arange(L, dtype=jnp.int32)[None, :]
            m = pos < la[:, None]
            beq = jnp.where(m, da[:, :L] == db[:, :L], True).all(axis=1)
            # if max_lens differ, longer-side extra bytes imply inequality via length
            eq = eq & (la == lb) & beq
        else:
            eq = eq & (jnp.take(ca, a_idx, axis=0) == jnp.take(cb, b_idx, axis=0))
    return eq


def _packed_gather(cols: Dict[str, Any], idx: jax.Array) -> Dict[str, Any]:
    """Gather rows of several columns with ONE fused word-matrix gather:
    pack the columns to u32 lanes, take the stacked [cap, W] matrix
    once, unpack.  TPU random gathers pay a per-ROW cost (~10.7 ns
    measured, benchmarks/pallas_probe), so fetching each output row's
    whole packed payload in one gather beats one gather per column —
    the join probe's dominant cost (probe + verify + gather fuse into
    one program around this).  The per-row-cost model is TPU-specific:
    on cpu the stack/unpack copies made the packed form ~2x SLOWER
    (BENCH_kernels r06 join_gather at 262k rows), so other backends
    keep one take per column — the same backend tier gating the pallas
    kernels (force_interpret() routes tests through the packed form)."""
    from dryad_tpu.ops.pallas_kernels import pallas_active
    if pallas_active() is None:
        out: Dict[str, Any] = {}
        for k, v in cols.items():
            out[k] = v.gather(idx) if isinstance(v, StringColumn) \
                else jnp.take(v, idx, axis=0)
        return out
    lanes, spec = _pack_columns_u32(cols)
    if not lanes:
        return {}
    w = jnp.stack(lanes, axis=1)
    g = jnp.take(w, idx, axis=0)
    return _unpack_columns_u32([g[:, j] for j in range(len(lanes))], spec)


def _join_out_names(left: Batch, right: Batch, right_keys, suffix: str):
    """Output column name plan shared by both join lowerings (the
    lax.cond pair must produce identical pytrees)."""
    names = list(left.names)
    rkeyset = set(right_keys)
    rmap = []
    for k in right.names:
        if k in rkeyset:
            continue
        name = k if k not in names else k + suffix
        rmap.append((k, name))
        names.append(name)
    return rmap


def _lookup_join(left: Batch, right: Batch, left_keys: Sequence[str],
                 right_keys: Sequence[str], out_capacity: int,
                 suffix: str, how: str) -> Tuple[Batch, jax.Array]:
    """Gather-free join for a UNIQUE-keyed right side (lookup/dimension
    table — the PageRank ranks join, the star-schema shape).

    The general hash_join materializes every output column by random
    gather (~10.7 ns/row x columns x out_capacity, measured — the
    dominant join cost).  With at most ONE right row per key, each left
    row is its own output row, so the join is a merge: sort the union of
    both sides by 64-bit key hash with rights first in each run, forward-
    fill the right payload by segmented max (a single fused multi-scan —
    at most one right per segment, everything else contributes zero), and
    compact the left rows.  Zero gathers.

    Match verification: when the two sides' key columns pack to the SAME
    u32 lane layout (same dtype / string max_len — the common case), the
    right row's packed key lanes ride the fill and each left row
    byte-compares them against its own carried key lanes, so a 64-bit
    hash collision is caught exactly like the general kernel's
    _keys_equal.  When the layouts differ (e.g. joining an i32 key to an
    i64 key column), verification falls back to the 64-bit hash pair
    itself — the same ~n^2/2^64 budget every hash group documents.  The
    caller-facing ``right_unique`` path also RUNTIME-verifies right-side
    uniqueness and falls back to the general kernel on duplicates
    (covering hash-collision-induced apparent duplicates).
    """
    lhi, llo = hash_batch_keys(left, left_keys)
    rhi, rlo = hash_batch_keys(right, right_keys)
    lvalid = left.valid_mask()
    rvalid = right.valid_mask()
    lhi, llo = _sentinel_fold(lhi, llo, lvalid)
    rhi, rlo = _sentinel_fold(rhi, rlo, rvalid)
    cl, cr = left.capacity, right.capacity
    n = cl + cr

    hi = jnp.concatenate([lhi, rhi])
    lo = jnp.concatenate([llo, rlo])
    # rights sort BEFORE lefts within a key run, so a forward fill sees
    # the payload
    side = jnp.concatenate([jnp.ones((cl,), jnp.uint32),
                            jnp.zeros((cr,), jnp.uint32)])

    lpack, lspec = _pack_columns_u32(dict(left.columns))
    rmap = _join_out_names(left, right, right_keys, suffix)
    rpack, rspec = _pack_columns_u32(
        {name: right.columns[k] for k, name in rmap})
    # byte verification (carried packed key lanes): only when both
    # sides' key columns pack identically — offsets of the left key
    # lanes within lpack, and the right keys packed under the left
    # names so the specs are directly comparable
    loff: Dict[str, Tuple[int, Tuple]] = {}
    off = 0
    for entry in lspec:
        loff[entry[0]] = (off, entry[1:])
        off += entry[3]
    vpack: List[jax.Array] = []
    lkey_lane_idx: List[int] = []
    vlanes, vspec = _pack_columns_u32(
        {ln: right.columns[rn]
         for ln, rn in zip(left_keys, right_keys)})
    verify = (len(set(left_keys)) == len(left_keys)
              and len(vspec) == len(left_keys)
              and all(ln in loff and loff[ln][1] == entry[1:]
                      for ln, entry in zip(left_keys, vspec)))
    if verify:
        vpack = vlanes
        for ln, entry in zip(left_keys, vspec):
            o = loff[ln][0]
            lkey_lane_idx.extend(range(o, o + entry[3]))
    nv = len(vpack)
    zl = jnp.zeros((cr,), jnp.uint32)
    zr = jnp.zeros((cl,), jnp.uint32)
    lanes = [jnp.concatenate([l, zl]) for l in lpack]
    nr = len(rpack)
    lanes += [jnp.concatenate([zr, r]) for r in rpack]
    lanes.append(jnp.concatenate([zr, rvalid.astype(jnp.uint32)]))
    lanes += [jnp.concatenate([zr, v]) for v in vpack]

    skeys, sl = _sort_carrying([hi, lo, side], lanes, n, stable=False)
    shi, slo, sside = skeys
    n_valid = left.count + right.count
    is_start, _is_end, _ng = _segment_flags(
        _lane_differs(shi, slo), n_valid)

    # forward-fill the right payload + presence (+ the verify key lanes)
    # within each key segment: one fused multi-scan of max ops (<=1
    # right per segment, zeros elsewhere, so max IS the fill)
    fill_in = [(sl[len(lpack) + j], jnp.maximum)
               for j in range(nr + 1 + nv)]
    filled = _seg_scan_multi(fill_in, is_start) if fill_in else []
    present = filled[nr] > 0
    if verify:
        # byte-equality of the filled right key lanes vs each left
        # row's own carried key lanes — exact collision rejection
        eq = jnp.ones((n,), jnp.bool_)
        for j, li in enumerate(lkey_lane_idx):
            eq = eq & (filled[nr + 1 + j] == sl[li])
        present = present & eq

    idx = jnp.arange(n, dtype=jnp.int32)
    is_left = (sside == 1) & (idx < n_valid)
    keep = is_left & present if how == "inner" else is_left
    total = keep.sum(dtype=jnp.int32)

    out_lanes = list(sl[:len(lpack)])
    for j in range(nr):
        # unmatched (or collision-rejected) left rows zero-fill the
        # right columns (how="left")
        out_lanes.append(jnp.where(present, filled[j], 0))
    _, dl = _sort_carrying([(~keep).astype(jnp.uint32)], out_lanes, n)

    def _fit(a):
        return a[:out_capacity] if n >= out_capacity else jnp.concatenate(
            [a, jnp.zeros((out_capacity - n,), a.dtype)])

    dl = [_fit(a) for a in dl]
    cols = _unpack_columns_u32(dl[:len(lpack)], lspec)
    rcols = _unpack_columns_u32(dl[len(lpack):], rspec)
    cols.update(rcols)
    cnt = jnp.minimum(total, out_capacity)
    gmask = jnp.arange(out_capacity) < cnt
    cols = {k: _mask_rows(v, gmask) for k, v in cols.items()}
    need = jnp.where(total > out_capacity, total, 0).astype(jnp.int32)
    return Batch(cols, cnt), need


def hash_join(left: Batch, right: Batch, left_keys: Sequence[str],
              right_keys: Sequence[str], out_capacity: int,
              suffix: str = "_r", how: str = "inner",
              right_unique: bool = False) -> Tuple[Batch, jax.Array]:
    """Equi-join; output columns = left columns + right non-key columns
    (right name suffixed on collision).  Returns ``(batch, overflow)``.

    ``how="left"``: left rows without a match emit ONE row with the right
    columns zero-filled (the GroupJoin empty-group case — reference
    DryadLinqQueryable GroupJoin; pair with a count aggregate to
    distinguish empty groups).  A left row whose only hash candidates are
    64-bit-collision false positives could be misclassified as matched-less
    output being dropped — probability ~2^-32 per pair, same collision
    budget documented on group_by.

    ``how="right"``: mirrored — right rows without a match emit ONE row
    with the LEFT non-key columns zero-filled and the left key columns
    taken from the right keys.  ``how="full"`` combines both.  Unmatched
    right rows are appended after the matched output (reference right/full
    outer join lowering, DryadLinqQueryable.cs:3639-area operator family).

    Output capacity is the static ``out_capacity``.  ``overflow`` is a
    conservative bool: True whenever the number of *candidate* pairs (hash
    matches before real-key verification) exceeds ``out_capacity`` — in that
    case true matches may have been dropped and the caller should re-run with
    a larger capacity.  It can be a false alarm when hash collisions inflate
    the candidate count, which is rare and only costs a re-plan.

    Reference semantics: DryadLinqVertex hash join (DryadLinqVertex.cs:942).

    ``right_unique=True`` (inner/left only) declares the right side a
    lookup table: after a cheap runtime duplicate check on the right's
    64-bit hashes, the gather-free merge-fill path (_lookup_join) runs;
    duplicates (or hash collisions that look like them) fall back to this
    general kernel inside the same compiled program (lax.cond).
    """
    if right_unique and how in ("inner", "left"):
        rhi0, rlo0 = hash_batch_keys(right, right_keys)
        rv = right.valid_mask()
        rhi0, rlo0 = _sentinel_fold(rhi0, rlo0, rv)
        shi0, slo0 = jax.lax.sort((rhi0, rlo0), num_keys=2,
                                  is_stable=False)
        dup = jnp.any((shi0[1:] == shi0[:-1]) & (slo0[1:] == slo0[:-1])
                      & (jnp.arange(1, right.capacity) < right.count))
        return jax.lax.cond(
            ~dup,
            lambda lr: _lookup_join(lr[0], lr[1], left_keys, right_keys,
                                    out_capacity, suffix, how),
            lambda lr: hash_join(lr[0], lr[1], left_keys, right_keys,
                                 out_capacity, suffix, how),
            (left, right))
    # TPUs have no fast uint64, so candidate ranges are found on a single
    # 32-bit hash lane; real-key verification below removes the (rare)
    # collision-induced false candidates.  (A collision only widens a
    # candidate range, never loses a match.)
    lhi, llo = hash_batch_keys(left, left_keys)
    rhi, rlo = hash_batch_keys(right, right_keys)
    lh = lhi ^ (llo * jnp.uint32(0x9E3779B9))
    rh = rhi ^ (rlo * jnp.uint32(0x9E3779B9))
    rvalid = right.valid_mask()
    lvalid = left.valid_mask()

    # sort right by hash, invalid last.  The sorted batch is never
    # materialized: every sorted-row access composes the permutation
    # (order) with its index — one full-batch gather saved per join.
    # (invalid, rh, iota) rides ONE unstable 3-key sort: the iota is
    # both the tiebreak (deterministic candidate order) and the
    # permutation payload — the same operand set lexsort's stable
    # machinery pays for, without the stability passes.
    _, _, order = jax.lax.sort(
        ((~rvalid).astype(jnp.uint32), rh,
         jnp.arange(right.capacity, dtype=jnp.int32)),
        num_keys=3, is_stable=False)
    rkey = jnp.take(rh, order)
    # mark invalid rows with sentinel max keys so searchsorted excludes them;
    # valid rows hashing to the sentinel just become extra candidates.
    pos = jnp.arange(right.capacity)
    rkey = jnp.where(pos < right.count, rkey, jnp.uint32(0xFFFFFFFF))

    start = searchsorted_big(rkey, lh, side="left")
    stop = searchsorted_big(rkey, lh, side="right")
    mult = jnp.where(lvalid, stop - start, 0)
    if how not in ("inner", "left", "right", "full"):
        raise ValueError(f"unknown join how={how!r}")
    left_synth = how in ("left", "full")
    if left_synth:
        # unmatched left rows still occupy one output slot (synthetic)
        synth_row = lvalid & (mult == 0)
        mult = jnp.where(synth_row, 1, mult)

    # output slot -> (left row, right row) via prefix sums
    cum = jnp.cumsum(mult)
    total = cum[-1]
    t = jnp.arange(out_capacity, dtype=jnp.int32)
    lid = searchsorted_big(cum, t, side="right").astype(jnp.int32)
    lid_c = jnp.minimum(lid, left.capacity - 1)
    base = cum[lid_c] - mult[lid_c]
    rid = (jnp.take(start, lid_c) + (t - base)).astype(jnp.int32)
    rid = jnp.clip(rid, 0, right.capacity - 1)
    slot_valid = t < total

    # verify true key equality (hash collisions) then compact; also exclude
    # candidates that landed in the right-side padding region, whose contents
    # are unspecified and may hold stale real keys
    rid_abs = jnp.take(order, rid)   # sorted position -> original row
    eq = _keys_equal(left, lid_c, left_keys, right, rid_abs, right_keys)
    keep_match = slot_valid & eq & (rid < right.count)
    keep = keep_match
    if left_synth:
        synth_slot = slot_valid & jnp.take(synth_row, lid_c)
        keep = keep | synth_slot

    # one packed gather per side (probe + verify + gather fused around
    # it — see _packed_gather) instead of one random gather per column
    out_cols = _packed_gather(dict(left.columns), lid_c)
    rkeyset = set(right_keys)
    rpayload = {}
    for k, v in right.columns.items():
        if k in rkeyset:
            continue
        name = k if k not in out_cols else k + suffix
        rpayload[name] = v
    for name, g in _packed_gather(rpayload, rid_abs).items():
        if left_synth:
            # unmatched left rows zero-fill the right columns
            g = _mask_rows(g, ~synth_slot)
        out_cols[name] = g
    # compaction by value-carry sort, not argsort+gather: the full-batch
    # gather alone measured ~22 ms at 400k rows x 5 columns
    joined = Batch(out_cols, jnp.asarray(out_capacity, jnp.int32))
    out = compact(joined, keep)
    # conservative: candidate pairs dropped for capacity might have been real.
    # NEED channel: 0 = fits, else actual candidate-pair count so the
    # executor can right-size the retry in one shot
    need = jnp.where(total > out_capacity, total, 0).astype(jnp.int32)
    if how in ("right", "full"):
        # right rows whose segment produced no VERIFIED match get one
        # synthetic output row each, appended after the matched rows.  A
        # match dropped only by capacity overflow marks its right row
        # matched=False, inflating u — harmless: need already forces a
        # right-sized retry in that case.
        matched = jnp.zeros((right.capacity,), jnp.int32).at[rid_abs].max(
            keep_match.astype(jnp.int32))
        unmatched = right.valid_mask() & (matched == 0)
        ru = compact(right, unmatched)
        u = ru.count
        key_map = dict(zip(left_keys, right_keys))
        synth_cols: Dict[str, Any] = {}
        for k, v in left.columns.items():
            if k in key_map:
                rv = ru.columns[key_map[k]]
                if isinstance(v, StringColumn):
                    # keep the right key's full width — concat2 pads
                    # mismatched string widths (truncating here would
                    # corrupt unmatched right keys longer than the left
                    # column's max_len)
                    synth_cols[k] = rv
                else:
                    synth_cols[k] = rv.astype(v.dtype)
            elif isinstance(v, StringColumn):
                synth_cols[k] = StringColumn(
                    jnp.zeros((right.capacity, v.max_len), jnp.uint8),
                    jnp.zeros((right.capacity,), jnp.int32))
            else:
                synth_cols[k] = jnp.zeros((right.capacity,) + v.shape[1:],
                                          v.dtype)
        for k, v in ru.columns.items():
            if k in rkeyset:
                continue
            name = k if k not in synth_cols else k + suffix
            synth_cols[name] = v
        merged = concat2(out, Batch(synth_cols, u))
        out = merged.gather(
            jnp.arange(out_capacity, dtype=jnp.int32),
            count=jnp.minimum(merged.count, out_capacity))
        need = jnp.where(total + u > out_capacity, total + u,
                         need).astype(jnp.int32)
    return out, need


def flat_map_expand(batch: Batch, fn, out_capacity: int
                    ) -> Tuple[Batch, jax.Array]:
    """Generic SelectMany: ``fn(cols) -> (out_cols, mask)`` where each output
    column is [cap, m, ...] and mask is [cap, m]; flattens row-major and
    compacts into ``out_capacity`` rows.  Returns (batch, overflow)."""
    out_cols, mask = fn(dict(batch.columns))
    mask = mask & batch.valid_mask()[:, None]
    cap, m = mask.shape
    flat_mask = mask.reshape(-1)
    total = flat_mask.sum(dtype=jnp.int32)
    perm = jnp.argsort(~flat_mask, stable=True)[:out_capacity]
    cols = {}
    for k, v in out_cols.items():
        if isinstance(v, StringColumn):
            data = v.data.reshape((cap * m,) + v.data.shape[2:])
            lens = v.lengths.reshape(-1)
            cols[k] = StringColumn(jnp.take(data, perm, axis=0),
                                   jnp.take(lens, perm))
        else:
            flat = v.reshape((cap * m,) + v.shape[2:])
            cols[k] = jnp.take(flat, perm, axis=0)
    out = Batch(cols, jnp.minimum(total, out_capacity))
    need = jnp.where(total > out_capacity, total, 0)
    return out, need.astype(jnp.int32)


def zip2(a: Batch, b: Batch, suffix: str = "_r") -> Batch:
    """Positional pairing within a partition; shorter-side count (LINQ Zip).
    Capacity = min of the two capacities."""
    cap = min(a.capacity, b.capacity)

    def trim(v):
        return jax.tree.map(lambda x: x[:cap] if x.ndim else x, v)

    cols = {}
    for k, v in a.columns.items():
        cols[k] = trim(v)
    for k, v in b.columns.items():
        name = k if k not in cols else k + suffix
        cols[name] = trim(v)
    return Batch(cols, jnp.minimum(a.count, b.count))


def right_match_mask(left: Batch, right: Batch, left_keys: Sequence[str],
                     right_keys: Sequence[str]) -> jax.Array:
    """bool [right.capacity]: right rows whose 64-bit key hash appears
    among left's VALID rows (the cross-chunk matched-right tracking that
    streamed right/full outer joins need; same hash-membership collision
    budget as semi_anti_join)."""
    lhi, llo = hash_batch_keys(left, left_keys)
    rhi, rlo = hash_batch_keys(right, right_keys)
    lvalid = left.valid_mask()
    rvalid = right.valid_mask()
    hi = jnp.concatenate([rhi, lhi])
    lo = jnp.concatenate([rlo, llo])
    is_left = jnp.concatenate([jnp.zeros(right.capacity, jnp.int32),
                               lvalid.astype(jnp.int32)])
    valid = jnp.concatenate([rvalid, lvalid])
    member = _hash_membership(hi, lo, is_left, valid)
    return member[:right.capacity] & rvalid


def semi_anti_join(left: Batch, right: Batch, left_keys: Sequence[str],
                   right_keys: Sequence[str], anti: bool = False) -> Batch:
    """Keep left rows whose key does (semi) / does not (anti) appear in right.

    Exact membership on the full 64-bit hash pair via a merged sort: right
    hashes are flagged, the union is sorted, and a per-segment max of the
    flag tells each left row whether its segment contains a right row.
    Reference semantics: Intersect/Except building blocks
    (DryadLinqVertex set ops)."""
    lhi, llo = hash_batch_keys(left, left_keys)
    rhi, rlo = hash_batch_keys(right, right_keys)
    lvalid = left.valid_mask()
    rvalid = right.valid_mask()
    hi = jnp.concatenate([lhi, rhi])
    lo = jnp.concatenate([llo, rlo])
    is_right = jnp.concatenate([jnp.zeros(left.capacity, jnp.int32),
                                rvalid.astype(jnp.int32)])
    valid = jnp.concatenate([lvalid, rvalid])
    member = _hash_membership(hi, lo, is_right, valid)
    lmember = member[:left.capacity]
    keep = lvalid & (~lmember if anti else lmember)
    return compact(left, keep)


# ---------------------------------------------------------------------------
# concat


def concat2(a: Batch, b: Batch) -> Batch:
    """Device-side concat: valid rows of ``a`` then valid rows of ``b``."""
    ca, cb = a.capacity, b.capacity
    out_cap = ca + cb
    i = jnp.arange(out_cap, dtype=jnp.int32)
    from_a = i < a.count
    src = jnp.where(from_a, jnp.minimum(i, ca - 1),
                    jnp.minimum(ca + (i - a.count), out_cap - 1))
    cols = {}
    for k in a.names:
        va, vb = a.columns[k], b.columns[k]
        if isinstance(va, StringColumn):
            L = max(va.max_len, vb.max_len)
            da = jnp.pad(va.data, ((0, 0), (0, L - va.max_len)))
            db = jnp.pad(vb.data, ((0, 0), (0, L - vb.max_len)))
            data = jnp.concatenate([da, db], axis=0)
            lens = jnp.concatenate([va.lengths, vb.lengths])
            cols[k] = StringColumn(jnp.take(data, src, axis=0),
                                   jnp.take(lens, src))
        else:
            cols[k] = jnp.take(jnp.concatenate([va, vb], axis=0), src, axis=0)
    return Batch(cols, a.count + b.count)


def mean_finalize_columns(cols: dict, mean_cols: Sequence[str]) -> dict:
    """Finalize decomposed means: replace {m}__sum/{m}__cnt partial columns
    with their quotient (the FinalReduce step of the builtin Average
    decomposition, IDecomposable.cs:34 / _decompose_aggs)."""
    out = dict(cols)
    for m in mean_cols:
        s = out.pop(m + "__sum")
        c = out.pop(m + "__cnt")
        cf = jnp.maximum(c, 1).reshape(c.shape + (1,) * (s.ndim - 1))
        out[m] = s / cf.astype(s.dtype) \
            if jnp.issubdtype(s.dtype, jnp.floating) \
            else s.astype(jnp.float32) / cf
    return out
