"""Cluster backend seam + factory registry.

The reference separates the GM engine from any concrete scheduler behind
`ICluster`/`IScheduler` with a name-keyed factory registry
(ClusterInterface/Interfaces.cs:324,491,545) — the same scheduler code
serves local spawns and YARN containers.  This module is that seam for
dryad_tpu: everything driver-side (Context submission, TaskFarm,
streamed plans) against :class:`ClusterBackend`, and new
deployment targets (a GKE pod launcher, an SSH multi-host launcher)
register themselves by name without touching the core.

``runtime.LocalCluster`` is the built-in "local" backend: real OS worker
processes under jax.distributed on one box — the reference's
LocalJobSubmission topology, and the SAME worker code that deploys one
per TPU host on a real pod.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Optional

__all__ = ["ClusterBackend", "register_cluster", "make_cluster",
           "cluster_backends"]


class ClusterBackend(abc.ABC):
    """The driver-side contract every cluster implementation provides.

    Gang jobs (SPMD plans, streamed wave jobs) broadcast to the fixed
    gang; farm tasks may additionally use elastic members.  See
    LocalCluster for reference semantics of each operation."""

    n_processes: int
    event_log: Optional[Callable[[dict], None]]

    @property
    @abc.abstractmethod
    def nparts(self) -> int:
        """Total data partitions the gang serves (devices across it)."""

    @abc.abstractmethod
    def alive(self) -> bool:
        """True when the full gang is connected and running."""

    @abc.abstractmethod
    def restart(self) -> None:
        """Tear down and re-form the gang (resident state is lost)."""

    @abc.abstractmethod
    def shutdown(self) -> None:
        """Stop all workers and release resources."""

    @abc.abstractmethod
    def next_job_id(self) -> int:
        """Monotonic job tag; workers echo it so schedulers can discard
        stale replies."""

    @abc.abstractmethod
    def execute(self, plan_json: str, source_specs: Dict[str, Any],
                **kw) -> Dict[str, Any]:
        """Run one gang SPMD plan; returns worker 0's reply (collected
        tables merged from per-worker parts)."""

    # -- task-farm surface (per-task scheduling over gang + elastic) -------

    @property
    @abc.abstractmethod
    def sockets(self) -> Dict[int, Any]:
        """pid -> control socket for every CONNECTED worker (gang and
        elastic) — the farm's dispatch/ping surface."""

    @abc.abstractmethod
    def worker_procs(self) -> Dict[int, Any]:
        """pid -> OS process handle for every task-capable worker (the
        farm's liveness poll)."""

    @abc.abstractmethod
    def recv_frames(self, pid: int, job: int):
        """One non-blocking drain of pid's socket: (replies_for_job,
        alive)."""

    @abc.abstractmethod
    def retire_worker(self, pid: int) -> None:
        """Remove one wedged worker from scheduling (sever its socket)."""

    @abc.abstractmethod
    def log_tails(self) -> str:
        """Recent worker log excerpts for failure diagnostics."""


# -- factory registry (Interfaces.cs:545 Factory.Register parity) -----------

_FACTORIES: Dict[str, Callable[..., "ClusterBackend"]] = {}


def register_cluster(name: str, factory: Callable[..., "ClusterBackend"]
                     ) -> None:
    """Register/replace a cluster backend under ``name``."""
    _FACTORIES[name.lower()] = factory


def cluster_backends() -> list:
    return sorted(_FACTORIES)


def make_cluster(name: str = "local", **kw) -> "ClusterBackend":
    """Instantiate a registered backend: ``make_cluster("local",
    n_processes=4)``."""
    fn = _FACTORIES.get(name.lower())
    if fn is None:
        raise KeyError(
            f"no cluster backend {name!r} registered (known: "
            f"{cluster_backends()}); register one with "
            f"runtime.interfaces.register_cluster")
    return fn(**kw)
