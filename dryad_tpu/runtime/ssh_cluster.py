"""SSH submission backend: bootstrap one worker per remote host.

The second REAL deployment target behind the ClusterBackend seam
(VERDICT r3 item 5; the reference ships two — local processes and YARN —
behind one interface: LinqToDryad/LocalJobSubmission.cs:35,
YarnJobSubmission.cs:38, with Peloponnese staging resources and launching
the process groups, PeloponneseJobSubmission.cs:111-147).

What it does, per host:
  1. STAGES the code: tars the installed ``dryad_tpu`` package on the
     driver and unpacks it into a per-job remote directory over the remote
     shell's stdin (the resource-staging role of
     PeloponneseJobSubmission.cs:111 — no shared filesystem assumed);
  2. launches ``python -m dryad_tpu.runtime.worker`` with the
     DISTRIBUTED addresses: jax.distributed coordinator = host 0, control
     socket = the driver (reachable address, not loopback);
  3. the generic control plane (runtime/cluster.py: gang formation,
     failure detection via the local ssh client process, job submission,
     restart, farm dispatch) runs unchanged on top.

The remote-shell TRANSPORT is pluggable: ``rsh(host, command) -> argv``
defaults to ``ssh -o BatchMode=yes <host> <command>``.  Tests inject a
local subprocess transport (``bash -c``) — no sshd in CI — which still
exercises the full orchestration: staging, addressing, bootstrap,
gang SPMD execution, teardown.  Register/lookup: ``make_cluster("ssh",
hosts=[...])``.
"""

from __future__ import annotations

import io
import os
import shlex
import socket
import subprocess
import tarfile
from typing import Callable, List, Optional, Sequence

from dryad_tpu.runtime.cluster import LocalCluster, WorkerFailure

__all__ = ["SshCluster", "default_rsh"]


def default_rsh(host: str, command: str) -> List[str]:
    """ssh argv for one remote shell command (BatchMode: never prompt).
    ``accept-new`` pins host keys on first contact instead of disabling
    verification outright (a silently-MITMed transport would hand the
    attacker the staged control secret — ADVICE r4)."""
    return ["ssh", "-o", "BatchMode=yes",
            "-o", "StrictHostKeyChecking=accept-new", host, command]


def _route_source_addr(target: str) -> str:
    """The local interface address that routes toward ``target`` (UDP
    connect sends no packets).  Falls back to the hostname's resolution,
    then loopback — the HMAC handshake still guards whatever we bind."""
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((target, 9))
            return probe.getsockname()[0]
        finally:
            probe.close()
    except OSError:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _package_tar() -> bytes:
    """One tar.gz of the installed dryad_tpu package (the staged
    'wheel')."""
    import dryad_tpu

    pkg_dir = os.path.dirname(os.path.abspath(dryad_tpu.__file__))
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        tf.add(pkg_dir, arcname="dryad_tpu",
               filter=lambda ti: None if "__pycache__" in ti.name else ti)
    return buf.getvalue()


class SshCluster(LocalCluster):
    """One gang worker per entry of ``hosts`` (repeat a host for multiple
    workers on it), launched over a remote shell.

    Parameters beyond LocalCluster's: ``hosts`` (remote targets, e.g.
    ["10.0.0.4", "10.0.0.5"]); ``driver_host`` (address remote workers
    can reach THIS process at — required unless every host is local);
    ``python`` (remote interpreter); ``remote_root`` (staging directory,
    default per-job under /tmp); ``stage_code`` (False = assume
    dryad_tpu importable remotely); ``platform`` ("default" uses each
    host's accelerators — one worker per TPU host; "cpu" forces virtual
    CPU devices, the test topology); ``rsh`` (transport, see module
    docstring)."""

    def __init__(self, hosts: Sequence[str],
                 devices_per_process: int = 1,
                 driver_host: Optional[str] = None,
                 python: str = "python3",
                 remote_root: Optional[str] = None,
                 stage_code: bool = True,
                 platform: str = "default",
                 coordinator_host: Optional[str] = None,
                 remote_pythonpath: Sequence[str] = (),
                 rsh: Callable[[str, str], List[str]] = default_rsh,
                 **kw):
        self.hosts = list(hosts)
        if not self.hosts:
            raise ValueError("SshCluster needs at least one host")
        self.driver_host = driver_host or socket.gethostname()
        # bind the control listener to the SPECIFIC address workers dial,
        # never 0.0.0.0: even with the HMAC handshake in front of the
        # pickle decoder there is no reason to expose the port on every
        # interface (ADVICE r4 high).  When driver_host was given
        # explicitly, its resolution IS the reachable address; the
        # hostname default instead uses a route probe toward the first
        # worker host (local resolution of one's own hostname is a
        # loopback alias like 127.0.1.1 on Debian-style /etc/hosts, which
        # remote workers cannot reach).
        if driver_host:
            try:
                self._bind_host = socket.gethostbyname(driver_host)
            except OSError as e:
                raise ValueError(
                    f"driver_host {driver_host!r} does not resolve to a "
                    f"bindable address: {e}") from e
        else:
            # advertise the probed IP literal too: remote resolution of
            # the driver's bare hostname may differ from the interface
            # that actually routes to the workers
            self._bind_host = _route_source_addr(list(hosts)[0])
            self.driver_host = self._bind_host
        # jax.distributed coordinator lives in worker 0's process — its
        # HOST by default; overridable (test transports run every
        # "remote" worker locally)
        self.coordinator_host = coordinator_host or list(hosts)[0]
        self.python = python
        self.remote_root = remote_root or f"/tmp/dryad-ssh-{os.getpid()}"
        self.stage_code = stage_code
        self.platform = platform
        # extra remote sys.path entries (user fn modules on the hosts)
        self.remote_pythonpath = list(remote_pythonpath)
        self._rsh = rsh
        self._staged: set = set()
        self._tar: Optional[bytes] = None
        super().__init__(n_processes=len(self.hosts),
                         devices_per_process=devices_per_process, **kw)

    def worker_hosts(self):
        """pid -> remote host: gang workers map onto their ssh target,
        elastic joiners (add_worker, local) onto this machine — the map
        block->host locality hints resolve against (runtime/farm.py;
        Interfaces.cs:98-152 affinity role)."""
        import socket as _socket
        local = _socket.gethostname()
        return {pid: (self.hosts[pid] if pid < len(self.hosts) else local)
                for pid in self._socks}

    # -- staging (PeloponneseJobSubmission.cs:111-147 role) ----------------

    def _stage(self, host: str) -> None:
        if host in self._staged:
            return
        if not self.stage_code:
            # no code to ship, but the control secret still travels by
            # file — the only channel that keeps it off command lines
            self._stage_secret(host)
            self._staged.add(host)
            return
        if self._tar is None:
            self._tar = _package_tar()
        cmd = (f"mkdir -p {shlex.quote(self.remote_root)} && "
               f"tar xzf - -C {shlex.quote(self.remote_root)}")
        p = subprocess.run(self._rsh(host, cmd), input=self._tar,
                           capture_output=True, timeout=120)
        if p.returncode != 0:
            raise WorkerFailure(
                f"staging to {host} failed (rc={p.returncode}): "
                f"{p.stderr.decode(errors='replace')[-500:]}")
        self._stage_secret(host)
        self._staged.add(host)

    def _stage_secret(self, host: str) -> None:
        """Write the per-cluster control secret to a 0600 remote file over
        the remote shell's STDIN — never on a command line (visible in ps)
        and never in the launch environment prefix (part of the ssh
        command string).  Workers read it via DRYAD_CONTROL_SECRET_FILE
        and answer the driver's HMAC challenge with it
        (protocol.server_authenticate)."""
        path = self._secret_path()
        cmd = (f"umask 077 && mkdir -p {shlex.quote(self.remote_root)} && "
               f"cat > {shlex.quote(path)}")
        p = subprocess.run(self._rsh(host, cmd),
                           input=self._secret.hex().encode(),
                           capture_output=True, timeout=60)
        if p.returncode != 0:
            raise WorkerFailure(
                f"secret staging to {host} failed (rc={p.returncode}): "
                f"{p.stderr.decode(errors='replace')[-500:]}")

    def _secret_path(self) -> str:
        return os.path.join(self.remote_root, ".control-secret")

    # -- spawn (one remote worker per host entry) --------------------------

    def _spawn_worker(self, pid: int, coord_port: int | None,
                      control_port: int,
                      standalone: bool = False) -> subprocess.Popen:
        host = self.hosts[pid % len(self.hosts)]
        self._stage(host)
        coord_host = self.coordinator_host
        envs = {
            "DRYAD_WORKER_ID": str(pid),
            "DRYAD_CONTROL_SECRET_FILE": self._secret_path(),
        }
        if self.platform == "cpu":
            envs["JAX_PLATFORMS"] = "cpu"
        pypath = ([self.remote_root] if self.stage_code else []) \
            + self.remote_pythonpath
        if pypath:
            envs["PYTHONPATH"] = os.pathsep.join(pypath)
        env_prefix = " ".join(f"{k}={shlex.quote(v)}"
                              for k, v in envs.items())
        args = [self.python, "-m", "dryad_tpu.runtime.worker",
                "--coordinator",
                f"{coord_host}:{coord_port if coord_port else 0}",
                "--control", f"{self.driver_host}:{control_port}",
                "--num-processes", str(self.n_processes),
                "--process-id", str(pid),
                "--devices-per-process", str(self.devices_per_process),
                "--platform", self.platform]
        if standalone:
            args.append("--standalone")
        for m in self.fn_modules:
            args += ["--fn-module", m]
        command = "env " + env_prefix + " " + \
            " ".join(shlex.quote(a) for a in args)
        log = open(os.path.join(self.log_dir, f"worker-{pid}.log"), "ab")
        proc = subprocess.Popen(self._rsh(host, command), stdout=log,
                                stderr=subprocess.STDOUT,
                                stdin=subprocess.DEVNULL)
        log.close()
        return proc


def _register() -> None:
    from dryad_tpu.runtime.interfaces import register_cluster

    register_cluster("ssh", SshCluster)


_register()
