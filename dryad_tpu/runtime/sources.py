"""Deferred source descriptions for cluster execution.

In single-process mode a Context constructor places data on the mesh
immediately; in cluster mode the driver owns no devices, so a source is a
SPEC — "these columns", "this text file", "this store path" — shipped with
the plan and materialized by every worker identically (the reference's
data-provider model: the plan names input partition files, vertices read
them; DataProvider.cs, DrPartitionFile.cpp:607)."""

from __future__ import annotations

from typing import Any, Dict, Mapping

__all__ = ["DeferredSource", "columns_spec", "text_spec", "store_spec",
           "preferred_worker_for_partitions", "locality_hints_for_store",
           "farm_store_tasks", "build_source", "count_lines",
           "MissingResidentToken"]


class MissingResidentToken(KeyError):
    """A plan referenced a cluster-resident token this worker doesn't hold
    (the gang restarted since it was cached).  Carries the token as
    STRUCTURED data: the worker copies ``.token`` into its error reply's
    ``missing_token`` field, and the driver's resident-healing
    (api/dataset.py _lost_resident_token) keys off that field — never off
    the message text (ADVICE r3)."""

    def __init__(self, token: str):
        super().__init__(
            f"resident token {token!r} not present on this worker — the "
            f"gang restarted since it was cached; re-run the producing "
            f"query")
        self.token = token

    def __str__(self) -> str:  # KeyError quotes its arg; keep the prose
        return self.args[0]


class DeferredSource:
    """Planner-visible stand-in for source data (exposes ``.capacity`` the
    way PData does, plan/planner.py:228)."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec

    @property
    def capacity(self) -> int:
        return self.spec["capacity"]


def _block_capacity(n: int, nparts: int) -> int:
    """Per-partition capacity of block partitioning — must match
    exec.data._block_slices (max block = ceil split)."""
    base, rem = divmod(n, nparts)
    return max(1, base + (1 if rem else 0))


def count_lines(buf: bytes) -> int:
    """Line count matching native.pack_lines splitting (split on \\n, a
    trailing unterminated line counts)."""
    n = buf.count(b"\n")
    if buf and not buf.endswith(b"\n"):
        n += 1
    return n


def count_lines_file(path: str, chunk: int = 1 << 22) -> int:
    """Streaming line count — the driver never holds the file in memory
    (it only needs the capacity estimate; workers read the data)."""
    n = 0
    last = b""
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            n += b.count(b"\n")
            last = b
    if last and not last.endswith(b"\n"):
        n += 1
    return n


def columns_spec(columns: Mapping[str, Any], nparts: int,
                 capacity: int | None = None,
                 str_max_len: int = 64) -> Dict[str, Any]:
    n = 0
    for v in columns.values():
        n = len(v)
        break
    return {"kind": "columns", "columns": dict(columns),
            "capacity": capacity or _block_capacity(n, nparts),
            "str_max_len": str_max_len}


def text_spec(path, nparts: int, column: str = "line",
              max_line_len: int = 256) -> Dict[str, Any]:
    """``path``: one file path or a list of file paths (already expanded by
    io.providers.expand_paths; workers read them from the shared fs)."""
    paths = [path] if isinstance(path, str) else list(path)
    n = sum(count_lines_file(p) for p in paths)
    # "rows" is the EXACT line count (the capacity computation already
    # pays for it) — the static cost analyzer seeds its row intervals
    # from it (analysis/cost.py source seeding)
    return {"kind": "text", "paths": paths, "column": column,
            "max_line_len": max_line_len, "rows": n,
            "capacity": _block_capacity(n, nparts)}


def store_spec(path: str, nparts: int, meta: Dict[str, Any],
               capacity: int | None = None,
               partitions: list | None = None,
               preferred_worker: int | None = None,
               preferred_hosts: list | None = None) -> Dict[str, Any]:
    """``partitions`` restricts to the listed store partitions — the
    per-task input granularity for farming a big store (one task per
    partition group, DrPartitionFile.cpp:607 role).  ``preferred_worker``
    (a worker pid) and ``preferred_hosts`` (machine names holding the
    partitions' blocks, e.g. from hdfs GETFILEBLOCKLOCATIONS via
    ``locality_hints_for_store``) are soft locality hints the task farm
    honors when a matching worker is available (the reference's weighted
    affinity lists from block locations,
    ClusterInterface/Interfaces.cs:98-152)."""
    counts = meta.get("counts", [])
    if partitions is not None:
        counts = [counts[p] for p in partitions]
    if partitions is None and meta["npartitions"] == nparts:
        cap = capacity or max(int(meta.get("capacity", 0)),
                              max(counts or [0]), 1)
    else:
        cap = capacity or _block_capacity(sum(counts), nparts)
    # manifest statistics ride the spec: exact rows + the store schema
    # let the static cost analyzer predict this source's device bytes
    # before a single partition file is opened (analysis/cost.py)
    return {"kind": "store", "path": path, "capacity": cap,
            "partitions": partitions,
            "rows": int(sum(counts)) if counts else None,
            "schema": meta.get("schema"),
            "preferred_worker": preferred_worker,
            "preferred_hosts": (list(preferred_hosts)
                                if preferred_hosts else None)}


def farm_store_tasks(path: str, src_key: str, nparts_local: int,
                     meta: Dict[str, Any] | None = None,
                     group_size: int = 1,
                     n_processes: int | None = None) -> list:
    """Per-task source specs for farming a partitioned store over a
    TaskFarm: one task per ``group_size`` store partitions (the
    reference's one-vertex-per-partition-file model,
    DrPartitionFile.cpp:607), each spec carrying the best available
    locality hint — block->host hints for ``hdfs://`` stores
    (GETFILEBLOCKLOCATIONS via ``locality_hints_for_store``), writer
    affinity for local parallel-output stores (pass ``n_processes``).
    This is the production entry of the locality chain:
    ``TaskFarm(cl).run(plan_json, farm_store_tasks(...))``.

    ``src_key`` is the plan's source binding key (from
    shiplan.serialize_for_cluster); ``nparts_local`` the per-worker
    partition count (cluster.devices_per_process for local worker
    meshes)."""
    import concurrent.futures

    from dryad_tpu.io.store import store_meta
    meta = meta or store_meta(path)
    nparts = meta["npartitions"]
    groups = [list(range(i, min(i + group_size, nparts)))
              for i in range(0, nparts, group_size)]
    # hint lookups hit the namenode once per partition — prefetch all
    # groups concurrently so a 1000-partition store's farm setup isn't
    # serialized on HTTP round trips
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(8, max(len(groups), 1))) as pool:
        all_hints = list(pool.map(
            lambda g: locality_hints_for_store(path, g, meta), groups))
    tasks = []
    for g, hosts in zip(groups, all_hints):
        w = (preferred_worker_for_partitions(g, nparts, n_processes)
             if n_processes else None)
        tasks.append({src_key: store_spec(
            path, nparts_local, meta, partitions=g,
            preferred_worker=w, preferred_hosts=hosts or None)})
    return tasks


def locality_hints_for_store(path: str, partitions,
                             meta: Dict[str, Any] | None = None
                             ) -> list:
    """Block->host locality hints for the given store partitions, for
    ``store_spec(..., preferred_hosts=)``.  Real for ``hdfs://`` stores
    (GETFILEBLOCKLOCATIONS block->host metadata, DrHdfsClient.cpp role);
    empty for stores without host-addressed blocks (local fs, s3) —
    locality is always a HINT, never a requirement."""
    if path.startswith("hdfs://"):
        from dryad_tpu.io.webhdfs import hdfs_preferred_hosts
        return hdfs_preferred_hosts(path, partitions)
    return []


def preferred_worker_for_partitions(partitions, npartitions: int,
                                    n_processes: int) -> int | None:
    """The worker that WROTE (and likely page-caches / locally holds) the
    given store partitions under the parallel-output layout: worker w
    writes partitions [w*dpp, (w+1)*dpp).  Returns the majority holder,
    or None when the layout doesn't divide evenly."""
    if n_processes <= 1 or npartitions % n_processes:
        return None
    dpp = npartitions // n_processes
    owners = [p // dpp for p in partitions]
    if not owners:
        return None
    return max(set(owners), key=owners.count)


def build_source(spec: Dict[str, Any], mesh, resident=None):
    """Materialize a source spec as sharded PData — runs on EVERY process
    (array creation fills only local addressable shards; no collective).

    ``resident`` is the worker's token -> PData cache: loop-carried /
    cached intermediates stay CLUSTER-RESIDENT and the plan ships only a
    token, never the table (the reference's cluster-resident temp outputs
    read in place, GraphManager/vertex/DrVertex.h:325-351)."""
    kind = spec["kind"]
    if kind == "resident":
        tok = spec["token"]
        if resident is None or tok not in resident:
            raise MissingResidentToken(tok)
        return resident[tok]
    if kind == "columns":
        from dryad_tpu.exec.data import pdata_from_host
        return pdata_from_host(spec["columns"], mesh,
                               capacity=spec["capacity"],
                               str_max_len=spec["str_max_len"])
    if kind == "text":
        from dryad_tpu.exec.data import pdata_from_packed_strings
        from dryad_tpu.io.providers import read_text_files
        paths = spec.get("paths") or [spec["path"]]
        data, lens, _ = read_text_files(paths, spec["max_line_len"])
        return pdata_from_packed_strings(data, lens, mesh,
                                         column=spec["column"],
                                         capacity=spec["capacity"])
    if kind == "store":
        from dryad_tpu.io.store import read_store
        return read_store(spec["path"], mesh, capacity=spec["capacity"],
                          partitions=spec.get("partitions"))
    raise ValueError(f"unknown source kind {kind!r}")
