"""Driver-side cluster control plane: spawn workers, submit plans, detect
process failure, restart.

The counterpart of the reference's LocalJobSubmission
(LinqToDryad/LocalJobSubmission.cs:97-302 — real GM + real worker processes
on one box, its default test topology) plus the GM's process-failure
reaction (DrVertex ReactToFailedVertex): here a dead worker is detected via
its exited process / closed control socket; the whole gang is torn down
(SPMD stages are gang-scheduled — one lost process stalls every collective)
and the job is replayed on a fresh gang, sources being re-readable by
construction (the lineage argument, SURVEY.md §3.5)."""

from __future__ import annotations

import os
import select
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

from dryad_tpu.runtime import protocol

__all__ = ["LocalCluster", "WorkerFailure", "ClusterJobError"]


class WorkerFailure(RuntimeError):
    """A worker process died or stopped responding mid-job."""


class ClusterJobError(RuntimeError):
    """The job itself raised on a worker (plan/UDF/capacity error)."""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalCluster:
    """N worker processes × D virtual devices each, on this machine.

    The same control plane works for real multi-host TPU: workers would run
    one per host with real local chips (jax.distributed over the pod), the
    driver anywhere reachable.  ``fn_modules`` are imported by workers to
    resolve plan callables (FN_TABLE exports + module:qualname refs)."""

    @classmethod
    def from_config(cls, config, **kw) -> "LocalCluster":
        """Build from JobConfig cluster_* knobs (overridable via kw)."""
        base = dict(n_processes=config.cluster_processes,
                    devices_per_process=config.cluster_devices_per_process,
                    fn_modules=tuple(config.cluster_fn_modules),
                    startup_timeout=config.cluster_startup_timeout_s)
        base.update(kw)
        return cls(**base)

    def __init__(self, n_processes: int = 2, devices_per_process: int = 2,
                 fn_modules: tuple = (), startup_timeout: float = 180.0,
                 event_log: Optional[Callable[[dict], None]] = None,
                 log_dir: Optional[str] = None):
        self.n_processes = n_processes
        self.devices_per_process = devices_per_process
        self.fn_modules = list(fn_modules)
        self.startup_timeout = startup_timeout
        self.event_log = event_log
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="dryad-cluster-")
        self._procs: List[subprocess.Popen] = []
        self._socks: Dict[int, socket.socket] = {}
        self._listener: Optional[socket.socket] = None
        # monotonic job id: every submission is tagged, workers echo it, and
        # schedulers discard stale replies (a finished job may leave an
        # ignored-duplicate reply in flight — see runtime/farm.py)
        self._job_seq = 0
        self._start()

    def next_job_id(self) -> int:
        self._job_seq += 1
        return self._job_seq

    @property
    def nparts(self) -> int:
        return self.n_processes * self.devices_per_process

    # -- lifecycle ---------------------------------------------------------

    def _start(self) -> None:
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(self.n_processes)
        control_port = self._listener.getsockname()[1]
        coord_port = _free_port()

        env = dict(os.environ)
        env["XLA_FLAGS"] = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        env["JAX_PLATFORMS"] = "cpu"
        # workers must import dryad_tpu regardless of their cwd — ship the
        # package location (and the driver's sys.path additions) explicitly
        import dryad_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(dryad_tpu.__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH")
                          else []))

        for pid in range(self.n_processes):
            cmd = [sys.executable, "-m", "dryad_tpu.runtime.worker",
                   "--coordinator", f"127.0.0.1:{coord_port}",
                   "--control", f"127.0.0.1:{control_port}",
                   "--num-processes", str(self.n_processes),
                   "--process-id", str(pid),
                   "--devices-per-process", str(self.devices_per_process),
                   "--platform", "cpu"]
            for m in self.fn_modules:
                cmd += ["--fn-module", m]
            log = open(os.path.join(self.log_dir, f"worker-{pid}.log"), "ab")
            self._procs.append(subprocess.Popen(
                cmd, env=env, stdout=log, stderr=subprocess.STDOUT))
            log.close()

        deadline = time.time() + self.startup_timeout
        self._listener.settimeout(1.0)
        while len(self._socks) < self.n_processes:
            if time.time() > deadline:
                self._kill_all()
                raise WorkerFailure(
                    f"only {len(self._socks)}/{self.n_processes} workers "
                    f"connected within {self.startup_timeout}s"
                    + self._log_tails())
            self._check_deaths(during_startup=True)
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            hello = protocol.recv_msg(conn)
            conn.setblocking(False)
            self._socks[hello["hello"]] = conn

    def _check_deaths(self, during_startup: bool = False) -> None:
        for pid, proc in enumerate(self._procs):
            if proc.poll() is not None:
                self._kill_all()
                raise WorkerFailure(
                    f"worker {pid} exited with rc={proc.returncode}"
                    + ("" if during_startup else " mid-job")
                    + self._log_tails())

    def _log_tails(self, n: int = 2000) -> str:
        out = []
        for pid in range(self.n_processes):
            p = os.path.join(self.log_dir, f"worker-{pid}.log")
            try:
                with open(p, "rb") as f:
                    f.seek(max(0, os.path.getsize(p) - n))
                    tail = f.read().decode(errors="replace")
                if tail.strip():
                    out.append(f"\n--- worker {pid} log tail ---\n{tail}")
            except OSError:
                pass
        return "".join(out)

    def _kill_all(self) -> None:
        for proc in self._procs:
            if proc.poll() is None:
                proc.kill()
        for proc in self._procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._procs, self._socks = [], {}
        if self._listener is not None:
            self._listener.close()
            self._listener = None

    def alive(self) -> bool:
        return (len(self._socks) == self.n_processes
                and all(p.poll() is None for p in self._procs))

    def restart(self) -> None:
        self._kill_all()
        self._start()

    def shutdown(self) -> None:
        for s in self._socks.values():
            try:
                protocol.send_msg(s, {"cmd": "stop"})
            except OSError:
                pass
        time.sleep(0.2)
        self._kill_all()

    def __enter__(self) -> "LocalCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- job submission ----------------------------------------------------

    def execute(self, plan_json: str,
                source_specs: Dict[str, Dict[str, Any]],
                collect: bool = True, store_path: Optional[str] = None,
                store_partitioning: Optional[Dict[str, Any]] = None,
                config=None,
                timeout: float = 600.0) -> Optional[Dict[str, Any]]:
        """Submit one job to the gang; returns worker 0's host table.
        ``config`` (a JobConfig) rides the pickle control message so the
        driver's executor knobs apply on the workers."""
        if not self.alive():
            self.restart()
        job = self.next_job_id()
        msg = {"cmd": "run", "plan": plan_json, "sources": source_specs,
               "collect": collect, "store_path": store_path,
               "store_partitioning": store_partitioning, "job": job,
               "config": config}
        for s in self._socks.values():
            s.setblocking(True)
            protocol.send_msg(s, msg)
            s.setblocking(False)

        replies: Dict[int, dict] = {}
        pending = set(self._socks)
        deadline = time.time() + timeout
        # buffered receive state per worker
        bufs: Dict[int, bytearray] = {pid: bytearray() for pid in pending}
        while pending:
            if time.time() > deadline:
                self._kill_all()
                raise WorkerFailure(
                    f"job timed out after {timeout}s; workers "
                    f"{sorted(pending)} never replied" + self._log_tails())
            try:
                self._check_deaths()
            except WorkerFailure:
                raise
            socks = {self._socks[pid]: pid for pid in pending}
            ready, _, _ = select.select(list(socks), [], [], 0.25)
            for s in ready:
                pid = socks[s]
                try:
                    chunk = s.recv(1 << 20)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    chunk = b""
                if not chunk:
                    self._kill_all()
                    raise WorkerFailure(
                        f"worker {pid} closed its control connection "
                        f"mid-job" + self._log_tails())
                bufs[pid].extend(chunk)
                while True:
                    reply = _try_decode(bufs[pid])
                    if reply is None:
                        break
                    if reply.get("job") != job:   # stale prior-job frame
                        continue
                    replies[pid] = reply
                    pending.discard(pid)

            # a worker that errored before entering a collective leaves the
            # rest blocked forever — once any error reply arrives, give the
            # stragglers a short grace then tear the gang down
            errs = [r for r in replies.values() if not r.get("ok")]
            if errs and pending:
                grace = time.time() + 5.0
                while pending and time.time() < grace:
                    ready, _, _ = select.select(
                        [self._socks[p] for p in pending], [], [], 0.25)
                    for s in ready:
                        pid = {self._socks[p]: p for p in pending}[s]
                        try:
                            chunk = s.recv(1 << 20)
                        except (BlockingIOError, InterruptedError):
                            continue
                        except OSError:
                            chunk = b""
                        if chunk:
                            bufs[pid].extend(chunk)
                            while True:
                                r = _try_decode(bufs[pid])
                                if r is None:
                                    break
                                if r.get("job") != job:
                                    continue
                                replies[pid] = r
                                pending.discard(pid)
                        else:
                            pending.discard(pid)
                break

        errs = {pid: r["error"] for pid, r in replies.items()
                if not r.get("ok")}
        if errs:
            self._kill_all()  # gang state is unknown after an error
            first = min(errs)
            raise ClusterJobError(
                f"job failed on worker(s) {sorted(errs)}; worker {first} "
                f"error:\n{errs[first]}")

        if self.event_log is not None and 0 in replies:
            for e in replies[0].get("events", []):
                self.event_log(dict(e, worker=0))
        return replies.get(0, {}).get("table")


def _try_decode(buf: bytearray):
    """Decode one length-prefixed frame from ``buf`` if complete."""
    import pickle
    import struct
    if len(buf) < 8:
        return None
    (n,) = struct.unpack_from("<Q", buf, 0)
    if len(buf) < 8 + n:
        return None
    obj = pickle.loads(bytes(buf[8:8 + n]))
    del buf[:8 + n]
    return obj
