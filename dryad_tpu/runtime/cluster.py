"""Driver-side cluster control plane: spawn workers, submit plans, detect
process failure, restart.

The counterpart of the reference's LocalJobSubmission
(LinqToDryad/LocalJobSubmission.cs:97-302 — real GM + real worker processes
on one box, its default test topology) plus the GM's process-failure
reaction (DrVertex ReactToFailedVertex): here a dead worker is detected via
its exited process / closed control socket; the whole gang is torn down
(SPMD stages are gang-scheduled — one lost process stalls every collective)
and the job is replayed on a fresh gang, sources being re-readable by
construction (the lineage argument, SURVEY.md §3.5)."""

from __future__ import annotations

import os
import select
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

from dryad_tpu.runtime import protocol
from dryad_tpu.runtime.interfaces import ClusterBackend

__all__ = ["LocalCluster", "WorkerFailure", "ClusterJobError"]


class WorkerFailure(RuntimeError):
    """A worker process died or stopped responding mid-job."""


class ClusterJobError(RuntimeError):
    """The job itself raised on a worker (plan/UDF/capacity error).
    ``missing_token`` carries a lost cluster-resident token when that is
    the cause (structured, from the worker's reply — the driver's healing
    path reads this attribute, never the message text)."""

    def __init__(self, msg: str, missing_token=None):
        super().__init__(msg)
        self.missing_token = missing_token


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalCluster(ClusterBackend):
    """N worker processes × D virtual devices each, on this machine — the
    built-in "local" ClusterBackend (runtime/interfaces.py seam).

    The same control plane works for real multi-host TPU: workers would run
    one per host with real local chips (jax.distributed over the pod), the
    driver anywhere reachable.  ``fn_modules`` are imported by workers to
    resolve plan callables (FN_TABLE exports + module:qualname refs)."""

    @classmethod
    def from_config(cls, config, **kw) -> "LocalCluster":
        """Build from JobConfig cluster_* knobs (overridable via kw)."""
        base = dict(n_processes=config.cluster_processes,
                    devices_per_process=config.cluster_devices_per_process,
                    fn_modules=tuple(config.cluster_fn_modules),
                    startup_timeout=config.cluster_startup_timeout_s)
        base.update(kw)
        return cls(**base)

    def __init__(self, n_processes: int = 2, devices_per_process: int = 2,
                 fn_modules: tuple = (), startup_timeout: float = 180.0,
                 event_log: Optional[Callable[[dict], None]] = None,
                 log_dir: Optional[str] = None):
        self.n_processes = n_processes
        self.devices_per_process = devices_per_process
        self.fn_modules = list(fn_modules)
        self.startup_timeout = startup_timeout
        self.event_log = event_log
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="dryad-cluster-")
        # per-cluster control-plane secret: every accepted connection must
        # answer an HMAC challenge BEFORE any pickle is decoded (pickle
        # executes code on load; see protocol.server_authenticate).  Local
        # workers get it via their process environment; remote backends
        # stage it as a 0600 file (never on a command line).
        import secrets as _secrets
        self._secret: Optional[bytes] = _secrets.token_bytes(32)
        self._procs: List[subprocess.Popen] = []
        self._socks: Dict[int, socket.socket] = {}
        # elastic (standalone) workers joined mid-life: control-plane
        # only — they serve farm tasks but never gang SPMD jobs
        # (reference dynamic registration, LocalScheduler/Queues.cs:104)
        self._elastic: set = set()
        self._elastic_procs: Dict[int, subprocess.Popen] = {}
        # monotonic: a dropped member's pid is never reused (reuse would
        # overwrite a LIVE worker's socket/process entries)
        self._elastic_seq = 0
        # per-worker receive buffers persist ACROSS jobs (cleared only on
        # restart): a speculated task's losing duplicate reply may arrive
        # after the farm returns, possibly split across recv() calls — a
        # call-local buffer would discard the partial prefix and leave the
        # next job decoding from mid-frame
        self._bufs: Dict[int, bytearray] = {}
        self._listener: Optional[socket.socket] = None
        # monotonic job id: every submission is tagged, workers echo it, and
        # schedulers discard stale replies (a finished job may leave an
        # ignored-duplicate reply in flight — see runtime/farm.py)
        self._job_seq = 0
        # resident tokens queued for release (owning Dataset/Context was
        # dropped); lives on the CLUSTER — Contexts come and go while the
        # gang holds the device memory — and piggybacks on every job
        self.pending_release: List[str] = []
        self._start()

    def next_job_id(self) -> int:
        self._job_seq += 1
        return self._job_seq

    def _emit(self, event: dict) -> None:
        """Structured failure/lifecycle events into the driver's event
        stream (the Calypso reporter feed the diagnosis view renders —
        JobBrowser/Diagnosis.cs:929 role)."""
        if self.event_log is not None:
            try:
                self.event_log(event)
            except Exception:
                pass

    @property
    def nparts(self) -> int:
        return self.n_processes * self.devices_per_process

    # -- lifecycle ---------------------------------------------------------

    # control-listener bind address: loopback for the local backend;
    # remote submission backends (runtime/ssh_cluster.py) bind all
    # interfaces and advertise a reachable driver host
    _bind_host = "127.0.0.1"

    def _start(self) -> None:
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self._bind_host, 0))
        self._listener.listen(self.n_processes)
        control_port = self._listener.getsockname()[1]
        coord_port = _free_port()

        for pid in range(self.n_processes):
            self._procs.append(self._spawn_worker(pid, coord_port,
                                                  control_port))

        deadline = time.time() + self.startup_timeout
        self._listener.settimeout(1.0)
        while len(self._socks) < self.n_processes:
            if time.time() > deadline:
                self._kill_all()
                raise WorkerFailure(
                    f"only {len(self._socks)}/{self.n_processes} workers "
                    f"connected within {self.startup_timeout}s"
                    + self._log_tails())
            self._check_deaths(during_startup=True)
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            if not protocol.server_authenticate(conn, self._secret):
                conn.close()   # wrong secret / not our worker: reject
                continue
            hello = protocol.recv_msg(conn)
            conn.setblocking(False)
            self._socks[hello["hello"]] = conn
            self._bufs[hello["hello"]] = bytearray()

    def _spawn_worker(self, pid: int, coord_port: int | None,
                      control_port: int,
                      standalone: bool = False) -> subprocess.Popen:
        env = dict(os.environ)
        env["XLA_FLAGS"] = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        env["JAX_PLATFORMS"] = "cpu"
        # workers must import dryad_tpu regardless of their cwd — ship the
        # package location (and the driver's sys.path additions) explicitly
        import dryad_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(dryad_tpu.__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH")
                          else []))
        if self._secret is not None:
            # in-memory env dict of a direct child: not visible on any
            # command line (unlike the ssh backend, which stages a file)
            env["DRYAD_CONTROL_SECRET"] = self._secret.hex()
        cmd = [sys.executable, "-m", "dryad_tpu.runtime.worker",
               "--coordinator",
               f"127.0.0.1:{coord_port if coord_port else 0}",
               "--control", f"127.0.0.1:{control_port}",
               "--num-processes", str(self.n_processes),
               "--process-id", str(pid),
               "--devices-per-process", str(self.devices_per_process),
               "--platform", "cpu"]
        if standalone:
            cmd.append("--standalone")
        for m in self.fn_modules:
            cmd += ["--fn-module", m]
        log = open(os.path.join(self.log_dir, f"worker-{pid}.log"), "ab")
        proc = subprocess.Popen(cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
        log.close()
        return proc

    def add_worker(self, timeout: float = 120.0) -> int:
        """Register one ELASTIC worker mid-life (the reference's dynamic
        computer registration, LocalScheduler/Queues.cs:104-137): a
        standalone process outside the jax.distributed gang that serves
        independently schedulable farm tasks on its own local devices.
        Gang SPMD jobs ignore it.  Returns the new worker's pid."""
        if not self.alive():
            self.restart()   # also recreates the listener after teardown
        pid = self.n_processes + self._elastic_seq
        self._elastic_seq += 1
        control_port = self._listener.getsockname()[1]
        proc = self._spawn_worker(pid, None, control_port, standalone=True)
        deadline = time.time() + timeout
        self._listener.settimeout(1.0)
        try:
            while True:
                if time.time() > deadline:
                    raise WorkerFailure(
                        f"elastic worker {pid} did not connect within "
                        f"{timeout}s" + self._log_tails())
                if proc.poll() is not None:
                    raise WorkerFailure(
                        f"elastic worker {pid} exited rc={proc.returncode}"
                        + self._log_tails())
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                if not protocol.server_authenticate(conn, self._secret):
                    conn.close()
                    continue
                hello = protocol.recv_msg(conn)
                conn.setblocking(False)
                hp = hello["hello"]
                self._socks[hp] = conn
                self._bufs[hp] = bytearray()
                self._elastic.add(hp)
                # register the process only once it is CONNECTED: a
                # failed join must not leave a phantom in worker_procs()
                # (the farm would count its death toward "all workers
                # died") or an orphan running process
                self._elastic_procs[hp] = proc
                return hp
        except BaseException:
            if proc.poll() is None:
                proc.kill()
            raise

    def gang_pids(self):
        return [p for p in self._socks if p not in self._elastic]

    # public ClusterBackend aliases of the farm-facing surface
    @property
    def sockets(self) -> Dict[int, socket.socket]:
        return self._socks

    def recv_frames(self, pid: int, job: int):
        return self._recv_frames(pid, job)

    def recv_frames_any(self, pid: int):
        """One non-blocking drain of ``pid``'s socket returning EVERY
        complete frame regardless of job tag: the multi-tenant service
        loop (dryad_tpu/service) multiplexes many concurrent jobs over
        one fleet and routes each frame to its job's driver state by the
        frame's ``protocol.JOB_ID`` tag itself.  Same ``(frames, alive)``
        contract as :meth:`recv_frames`."""
        got = self._drain_socket(pid)
        if got is not True:
            return [], got is False       # None = dead, False = no data
        out: List[dict] = []
        try:
            while True:
                r = _try_decode(self._bufs[pid])
                if r is None:
                    break
                out.append(r)
        except WorkerFailure:
            # a desynced stream poisons only THIS worker for the service
            # loop (it owns per-worker reaction); report it dead
            return out, False
        return out, True

    def log_tails(self) -> str:
        return self._log_tails()

    def _drop_elastic(self, pid: int) -> None:
        """Remove one dead/unresponsive ELASTIC worker — optional members
        never take the gang down with them."""
        s = self._socks.pop(pid, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        self._bufs.pop(pid, None)
        self._elastic.discard(pid)
        proc = self._elastic_procs.pop(pid, None)
        if proc is not None and proc.poll() is None:
            proc.kill()

    def worker_procs(self) -> Dict[int, subprocess.Popen]:
        """pid -> process for EVERY task-capable worker (gang + elastic)."""
        out = {pid: proc for pid, proc in enumerate(self._procs)}
        out.update(self._elastic_procs)
        return out

    def worker_hosts(self) -> Dict[int, str]:
        """pid -> machine name, for block->host locality hints (the
        reference's computer table feeding affinity resolution,
        Interfaces.cs:98-152).  Every LocalCluster worker runs on this
        machine; SshCluster overrides with the per-worker remote host."""
        import socket as _socket
        host = _socket.gethostname()
        return {pid: host for pid in self._socks}

    def _check_deaths(self, during_startup: bool = False) -> None:
        for pid, proc in enumerate(self._procs):
            if proc.poll() is not None:
                self._emit({"event": "worker_failed", "worker": pid,
                            "error": f"process exited with "
                                     f"rc={proc.returncode}"
                                     + ("" if during_startup
                                        else " mid-job"),
                            "log_tails": self._log_tails(800)})
                self._kill_all()
                raise WorkerFailure(
                    f"worker {pid} exited with rc={proc.returncode}"
                    + ("" if during_startup else " mid-job")
                    + self._log_tails())

    def _log_tails(self, n: int = 2000) -> str:
        out = []
        for pid in (list(range(self.n_processes))
                    + sorted(self._elastic_procs)):
            p = os.path.join(self.log_dir, f"worker-{pid}.log")
            try:
                with open(p, "rb") as f:
                    f.seek(max(0, os.path.getsize(p) - n))
                    tail = f.read().decode(errors="replace")
                if tail.strip():
                    out.append(f"\n--- worker {pid} log tail ---\n{tail}")
            except OSError:
                pass
        return "".join(out)

    def _kill_all(self) -> None:
        everyone = list(self._procs) + list(self._elastic_procs.values())
        for proc in everyone:
            if proc.poll() is None:
                proc.kill()
        for proc in everyone:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._procs, self._socks, self._bufs = [], {}, {}
        self._elastic, self._elastic_procs = set(), {}
        if self._listener is not None:
            self._listener.close()
            self._listener = None

    def alive(self) -> bool:
        return (len(self.gang_pids()) == self.n_processes
                and all(p.poll() is None for p in self._procs))

    def restart(self) -> None:
        self._kill_all()
        # fresh processes hold no residents; queued releases are moot
        del self.pending_release[:]
        self._start()

    def shutdown(self) -> None:
        for s in self._socks.values():
            try:
                protocol.send_msg(s, {"cmd": "stop"})
            except OSError:
                pass
        time.sleep(0.2)
        self._kill_all()

    def __enter__(self) -> "LocalCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def __del__(self):
        # a dropped cluster must not leak worker processes: workers linger
        # on a severed control socket (by design, see retire_worker), so
        # the driver-side GC is the line of defense for abandoned clusters
        try:
            self._kill_all()
        except Exception:
            pass

    def _drain_socket(self, pid: int) -> Optional[bool]:
        """One non-blocking recv into ``pid``'s frame buffer (the step
        shared by :meth:`_recv_frames` and :meth:`recv_frames_any` —
        only the decode policy differs between them).  True = bytes
        buffered, False = nothing to read right now, None = socket
        closed/broken (the caller treats the worker as dead)."""
        s = self._socks.get(pid)
        if s is None:
            return None
        try:
            chunk = s.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            return None
        if not chunk:
            return None
        self._bufs[pid].extend(chunk)
        return True

    def _recv_frames(self, pid: int, job: int):
        """One non-blocking drain of ``pid``'s socket: returns
        ``(replies_for_job, alive)``.  ``alive=False`` means the socket is
        closed/broken — the caller picks the site-appropriate reaction
        (gang teardown, grace-period skip, or farm reassignment)."""
        got = self._drain_socket(pid)
        if got is not True:
            return [], got is False
        return self._decode_job_frames(pid, job), True

    def _decode_job_frames(self, pid: int, job: int) -> List[dict]:
        """Decode every complete frame buffered for ``pid``, returning the
        ones tagged with ``job`` (stale prior-job frames — e.g. a losing
        speculative duplicate's late reply — are discarded).  A corrupt
        frame tears the whole gang down (the stream is desynced)."""
        out: List[dict] = []
        try:
            while True:
                r = _try_decode(self._bufs[pid])
                if r is None:
                    break
                if r.get("job") != job:
                    continue
                out.append(r)
        except WorkerFailure:
            self._kill_all()
            raise
        return out

    def wait_quiescent(self, timeout: float = 60.0) -> None:
        """Block until every worker answers a fresh ping — i.e. has drained
        all previously queued work (a losing speculative duplicate from a
        prior farm run, for example).  Useful before timing-sensitive
        submissions."""
        job = self.next_job_id()
        for pid, s in list(self._socks.items()):
            try:
                s.setblocking(True)
                protocol.send_msg(s, {"cmd": "ping", "job": job})
                s.setblocking(False)
            except OSError:
                if pid in self._elastic:
                    # a dead OPTIONAL member never takes the gang down
                    self._drop_elastic(pid)
                    continue
                self._kill_all()
                raise WorkerFailure(
                    f"worker {pid} unreachable during quiescence ping"
                    + self._log_tails())
        pending = set(self._socks)
        deadline = time.time() + timeout
        while pending:
            if time.time() > deadline:
                if pending <= self._elastic:
                    # only optional members are silent: drop them
                    for pid in list(pending):
                        self._drop_elastic(pid)
                    return
                raise WorkerFailure(
                    f"workers {sorted(pending)} not quiescent after "
                    f"{timeout}s" + self._log_tails())
            socks = {self._socks[p]: p for p in pending}
            ready, _, _ = select.select(list(socks), [], [], 0.25)
            for s in ready:
                pid = socks[s]
                frames, ok = self._recv_frames(pid, job)
                if not ok:
                    if pid in self._elastic:
                        self._drop_elastic(pid)
                        pending.discard(pid)
                        continue
                    self._kill_all()
                    raise WorkerFailure(
                        f"worker {pid} closed its control connection"
                        + self._log_tails())
                for r in frames:
                    if "pong" in r:
                        pending.discard(pid)

    def retire_worker(self, pid: int) -> None:
        """Remove one worker from the gang by severing its control socket
        (the reference abandons the vertex on timeout,
        ReactToFailedVertex).  The process is deliberately NOT killed:
        killing any jax.distributed client (coordinator or not) risks a
        heartbeat-failure cascade through the surviving workers mid-farm.
        A retired worker notices the severed socket and lingers quietly
        (runtime/worker.py) until the next gang restart kills it; severing
        alone already prevents a half-written reply from wedging the next
        job's blocking send.  The cluster is no longer ``alive()``
        afterwards, so the next gang job triggers a full restart."""
        s = self._socks.pop(pid, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        self._bufs.pop(pid, None)

    # -- job submission ----------------------------------------------------

    def execute(self, plan_json: str,
                source_specs: Dict[str, Dict[str, Any]],
                collect: bool = True, store_path: Optional[str] = None,
                store_partitioning: Optional[Dict[str, Any]] = None,
                config=None, timeout: float = 600.0,
                keep_token: Optional[str] = None,
                release: tuple = (),
                store_compression: Optional[str] = None) -> Dict[str, Any]:
        """Submit one job to the gang; returns worker 0's full reply (its
        host table under "table", plus resident-cache metadata).
        ``config`` (a JobConfig) rides the pickle control message so the
        driver's executor knobs apply on the workers.  ``keep_token``
        caches the result cluster-resident; ``release`` piggybacks token
        drops."""
        from dryad_tpu.obs import trace
        if not self.alive():
            self.restart()
        job = self.next_job_id()
        queued = self.pending_release[:]
        del self.pending_release[:len(queued)]
        hb_every = getattr(config, "gang_heartbeat_s", 2.0) if config \
            else 2.0
        # the driver's job span: its context rides the envelope so every
        # worker's run/stage/io spans parent-link here (protocol.TRACE_CTX);
        # the sink inherits the attached EventLog's level — and with NO
        # log attached, level 0: no consumer means zero span work, and
        # no trace_ctx means the workers skip theirs too
        with trace.span(f"job {job}", "job",
                        sink=trace.leveled(
                            self._emit,
                            getattr(self.event_log, "level", None)
                            if self.event_log is not None else 0),
                        job=job) as jsp:
            msg = protocol.attach_trace(
                {"cmd": "run", "plan": plan_json, "sources": source_specs,
                 "collect": collect, "store_path": store_path,
                 "store_partitioning": store_partitioning, "job": job,
                 "config": config, "keep_token": keep_token,
                 "release": list(release) + queued,
                 "store_compression": store_compression,
                 "hb_every": hb_every}, trace.ctx_of(jsp))
            for pid in self.gang_pids():
                s = self._socks[pid]
                s.setblocking(True)
                protocol.send_msg(s, msg)
                s.setblocking(False)

            replies = self._gather_job_replies(job, timeout, "job",
                                               config=config)

        if self.event_log is not None and 0 in replies:
            for e in replies[0].get("events", []):
                self.event_log(dict(e, worker=0))
        reply0 = dict(replies.get(0, {}))
        # same gate as the workers (any truthy non-"count" collect ships
        # table parts) — an identity check would silently discard them
        if collect and collect != "count" and any(
                "table_part" in r for r in replies.values()):
            # parallel collect: merge per-worker parts in pid order
            # (= partition order); gather all parts per column first so
            # each column is ONE extend/concatenate, not W re-copies
            import numpy as _np
            parts_by_col: Dict[str, list] = {}
            for pid in sorted(replies):
                part = replies[pid].get("table_part")
                if not part:
                    continue
                for k, v in part.items():
                    parts_by_col.setdefault(k, []).append(v)
            merged: Dict[str, Any] = {}
            for k, parts in parts_by_col.items():
                if isinstance(parts[0], list):
                    merged[k] = [x for p in parts for x in p]
                else:
                    merged[k] = (parts[0] if len(parts) == 1
                                 else _np.concatenate(parts))
            reply0["table"] = merged
        return reply0

    def _gather_job_replies(self, job: int, timeout: float,
                            what: str, config=None) -> Dict[int, dict]:
        """Collect one reply per worker for ``job`` (shared by execute and
        streamed runs).  On any error reply, stragglers get a 5s grace
        drain (so co-errors reach the diagnosis) and the gang is torn
        down; on success every worker's reply is returned.  Elastic
        workers never receive gang jobs and are not awaited.

        STRAGGLER/WEDGE WATCHDOG (DrVertex.h:195 / DrStageStatistics.cpp
        role for a gang that cannot duplicate one member): workers
        heartbeat while executing; a worker silent past the heartbeat
        timeout — or one that misses the post-first-reply margin — is
        declared wedged, the gang is torn down, and the tagged
        WorkerFailure lets the driver REPLAY the deterministic job on a
        fresh gang instead of hanging every collective to the hard
        timeout."""
        hb_every = getattr(config, "gang_heartbeat_s", 2.0) \
            if config else 2.0
        hb_timeout = getattr(config, "gang_heartbeat_timeout_s", 60.0) \
            if config else 60.0
        rel = getattr(config, "gang_straggler_rel_margin", 1.0) \
            if config else 1.0
        abs_m = getattr(config, "gang_straggler_abs_margin_s", 15.0) \
            if config else 15.0
        replies: Dict[int, dict] = {}
        pending = set(self.gang_pids())
        t0 = time.time()
        deadline = t0 + timeout
        first_reply_at: Optional[float] = None
        last_seen: Dict[int, float] = {p: t0 for p in pending}

        def _wedged(pids, why: str):
            self._emit({"event": "worker_wedged", "workers": sorted(pids),
                        "why": why, "what": what,
                        "log_tails": self._log_tails(800)})
            self._kill_all()
            raise WorkerFailure(
                f"{what}: workers {sorted(pids)} {why} — declared wedged; "
                f"gang torn down for replay" + self._log_tails())

        while pending:
            now = time.time()
            if now > deadline:
                self._kill_all()
                raise WorkerFailure(
                    f"{what} timed out after {timeout}s; workers "
                    f"{sorted(pending)} never replied" + self._log_tails())
            if hb_every > 0:
                silent = [p for p in pending
                          if now - last_seen[p] > hb_timeout]
                if silent:
                    _wedged(silent, f"sent no heartbeat for "
                                    f">{hb_timeout:g}s")
            if hb_every > 0 and first_reply_at is not None:
                margin = max(rel * (first_reply_at - t0), abs_m)
                if now > first_reply_at + margin:
                    # the heartbeat distinguishes BUSY from FROZEN: past
                    # the margin, only workers whose heartbeats have ALSO
                    # stopped are wedged.  A worker still beating is slow
                    # but alive (deterministic skew — e.g. one member
                    # writing far larger partitions) and keeps running
                    # until gang_heartbeat_timeout_s or the job deadline;
                    # declaring it wedged would fail the identical replay
                    # too (ADVICE r4).
                    hb_stale = max(3 * hb_every, 10.0)
                    frozen = [p for p in pending
                              if now - last_seen[p] > hb_stale]
                    if frozen:
                        _wedged(frozen,
                                f"missed the straggler margin "
                                f"({margin:.1f}s after the first reply) "
                                f"with heartbeats stopped >{hb_stale:g}s")
            self._check_deaths()
            socks = {self._socks[pid]: pid for pid in pending}
            ready, _, _ = select.select(list(socks), [], [], 0.25)
            for s in ready:
                pid = socks[s]
                frames, ok = self._recv_frames(pid, job)
                if not ok:
                    self._kill_all()
                    raise WorkerFailure(
                        f"worker {pid} closed its control connection "
                        f"mid-{what}" + self._log_tails())
                if frames:
                    last_seen[pid] = time.time()
                for reply in frames:
                    if "hb" in reply:      # liveness only, not a reply
                        continue
                    replies[pid] = reply
                    pending.discard(pid)
                    if first_reply_at is None:
                        first_reply_at = time.time()

            # a worker that errored before entering a collective leaves the
            # rest blocked forever — once any error reply arrives, give the
            # stragglers a short grace then tear the gang down
            errs = [r for r in replies.values() if not r.get("ok")]
            if errs and pending:
                grace = time.time() + 5.0
                while pending and time.time() < grace:
                    ready, _, _ = select.select(
                        [self._socks[p] for p in pending], [], [], 0.25)
                    for s in ready:
                        pid = {self._socks[p]: p for p in pending}[s]
                        frames, ok = self._recv_frames(pid, job)
                        if not ok:
                            pending.discard(pid)
                            continue
                        for r in frames:
                            if "hb" in r:   # liveness frame, not a reply
                                continue
                            replies[pid] = r
                            pending.discard(pid)
                break

        errs = {pid: r["error"] for pid, r in replies.items()
                if not r.get("ok")}
        if errs:
            self._emit({"event": "job_failed", "what": what,
                        "workers": sorted(errs),
                        "error": errs[min(errs)],
                        "log_tails": self._log_tails(800)})
            bpath = self._persist_forensics(replies, sorted(errs), config)
            self._kill_all()  # gang state is unknown after an error
            first = min(errs)
            # ANY failing worker's lost-resident tag makes the job
            # healable (a peer may fail differently, e.g. a collective
            # abort after the tagged worker raised)
            tok = next((replies[p].get("missing_token")
                        for p in sorted(errs)
                        if replies[p].get("missing_token") is not None),
                       None)
            raise ClusterJobError(
                f"{what} failed on worker(s) {sorted(errs)}; worker "
                f"{first} error:\n{errs[first]}"
                + (f"\nforensics bundle: {bpath}\n"
                   f"  reproduce locally: python -m dryad_tpu.obs "
                   f"replay {bpath}" if bpath else ""),
                missing_token=tok)
        return replies

    def _persist_forensics(self, replies: Dict[int, dict], err_pids,
                           config) -> Optional[str]:
        """Persist the FIRST failing worker's flight-recorder bundle
        (the raised error quotes that worker; peers usually fail as
        collective aborts of the same root cause).  Best-effort; the
        placement/breadcrumb logic is shared with the task farm
        (obs/flight.persist_reply_forensics)."""
        from dryad_tpu.obs import flight
        for pid in err_pids:
            path = flight.persist_reply_forensics(
                replies[pid], config, self.event_log, self._emit)
            if path:
                return path
        return None


def _try_decode(buf: bytearray):
    """Decode one buffered frame (protocol.try_decode), mapping framing
    corruption to WorkerFailure — the caller tears the gang down
    (_decode_job_frames)."""
    try:
        return protocol.try_decode(buf)
    except protocol.FrameError as e:
        raise WorkerFailure(str(e))
