"""Length-prefixed message framing for the driver<->worker control plane.

The role of the reference's vertex command protocol (SURVEY.md §2.2 "vertex
commands", ProcessService HTTP endpoints): a tiny, explicit wire format —
8-byte little-endian length + pickled payload.  Pickle executes arbitrary
code on load, so every control connection must FIRST pass the shared-secret
HMAC challenge below before a single pickled byte is decoded: the driver
generates a per-cluster 256-bit secret, hands it to the workers it spawns
out-of-band (process environment locally; a 0600-mode staged file over the
remote shell for SSH deployments — never on a command line), and rejects
any peer that cannot MAC its nonce.  This is what makes binding the
listener on a non-loopback interface sound (runtime/ssh_cluster.py);
the reference's GM<->daemon channel relies on the cluster security domain
the same way (ProcessService authenticates callers via the cluster's
credentials).
"""

from __future__ import annotations

import hmac
import os
import pickle
import socket
import struct
from typing import Any, Optional

_MAGIC = b"DRYD"
_ACK = b"OK01"

# -- trace-context propagation ----------------------------------------------
# Every job/task envelope may carry a TRACE_CTX field: the submitting
# driver span's {"trace": trace_id, "parent": span_id}, adopted by the
# worker for the execution's duration (obs/trace.tracing) so worker-side
# task/stage/io spans parent-link into the driver's trace across the
# process boundary (the Dapper propagation model; the reference's
# Calypso stream carries no causality — SURVEY.md §5 gap).
TRACE_CTX = "trace_ctx"


def attach_trace(msg: dict, ctx) -> dict:
    """Attach a wire trace context to an outgoing envelope (no-op when
    tracing is off and ``ctx`` is None)."""
    if ctx:
        msg[TRACE_CTX] = ctx
    return msg


def extract_trace(msg: dict):
    """Worker side: the envelope's trace context, if any (validated to a
    plain dict — the field rides the pickle channel but is inert data)."""
    ctx = msg.get(TRACE_CTX)
    return ctx if isinstance(ctx, dict) else None


# -- job namespacing ---------------------------------------------------------
# Every job/task envelope and every reply carries a JOB tag: workers echo
# it verbatim, schedulers discard stale frames by it (runtime/cluster.py
# _decode_job_frames), and the multi-tenant service daemon routes frames
# from MANY concurrent jobs sharing one fleet back to the right per-job
# driver state by it (dryad_tpu/service).  One constant + two helpers so
# every attach/read site names the same field.
JOB_ID = "job"


def attach_job(msg: dict, job) -> dict:
    """Tag an outgoing envelope with its job id (in place; returns msg)."""
    msg[JOB_ID] = job
    return msg


def extract_job(msg: dict):
    """The envelope/reply's job tag, or None."""
    return msg.get(JOB_ID)


# -- failure forensics -------------------------------------------------------
# A failing worker's error reply may carry a FORENSICS field: the flight
# recorder's self-contained bundle (obs/flight.py — task envelope, input
# digests, exception, recent-event ring) for driver-side persistence and
# `python -m dryad_tpu.obs replay` local reproduction.
FORENSICS = "forensics"


def attach_forensics(reply: dict, bundle) -> dict:
    """Attach a forensics bundle to an error reply (no-op on None)."""
    if bundle:
        reply[FORENSICS] = bundle
    return reply


def extract_forensics(reply: dict):
    """Driver side: the reply's forensics bundle, if it carries a valid
    one (obs/flight.py magic key — anything else is ignored)."""
    b = reply.get(FORENSICS)
    return b if isinstance(b, dict) and b.get("dryad_forensics") else None


class AuthError(RuntimeError):
    """Control-plane handshake failed (wrong secret or not our protocol)."""


def server_authenticate(conn: socket.socket, secret: Optional[bytes],
                        timeout: float = 10.0) -> bool:
    """Challenge an accepted control connection BEFORE any unpickling.

    Sends a random nonce, requires HMAC-SHA256(secret, nonce) back, acks.
    Returns False (caller closes the socket) on mismatch, timeout, or a
    peer that does not speak the handshake.  ``secret=None`` (explicitly
    configured trust, e.g. single-machine loopback tests) skips the
    challenge."""
    if secret is None:
        return True
    nonce = os.urandom(16)
    prev = conn.gettimeout()
    try:
        conn.settimeout(timeout)
        conn.sendall(_MAGIC + nonce)
        mac = _recv_exact(conn, 32)
        want = hmac.new(secret, nonce, "sha256").digest()
        if not hmac.compare_digest(want, mac):
            return False
        conn.sendall(_ACK)
        return True
    except (OSError, EOFError):
        return False
    finally:
        try:
            conn.settimeout(prev)
        except OSError:
            pass


def client_authenticate(sock: socket.socket, secret: Optional[bytes]
                        ) -> None:
    """Answer the driver's HMAC challenge (worker side); raises AuthError
    on a protocol mismatch or rejected MAC."""
    if secret is None:
        return
    hdr = _recv_exact(sock, len(_MAGIC) + 16)
    if hdr[:len(_MAGIC)] != _MAGIC:
        raise AuthError("control peer did not send an auth challenge")
    sock.sendall(hmac.new(secret, hdr[len(_MAGIC):], "sha256").digest())
    if _recv_exact(sock, len(_ACK)) != _ACK:
        raise AuthError("driver rejected control-plane credentials")


def load_secret_from_env() -> Optional[bytes]:
    """Worker-side secret source: DRYAD_CONTROL_SECRET (hex, set in the
    spawned process environment by the local backend) or
    DRYAD_CONTROL_SECRET_FILE (path to a 0600 staged file, SSH backend)."""
    h = os.environ.get("DRYAD_CONTROL_SECRET")
    if h:
        return bytes.fromhex(h.strip())
    p = os.environ.get("DRYAD_CONTROL_SECRET_FILE")
    if p:
        with open(p) as f:
            return bytes.fromhex(f.read().strip())
    return None

_LEN = struct.Struct("<Q")
# control messages are plans + host source columns; cap frames at 4 GiB to
# fail fast on corruption rather than allocating garbage lengths
_MAX_FRAME = 4 << 30


def send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise EOFError("peer closed control connection")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise EOFError(f"oversized control frame ({n} bytes)")
    return pickle.loads(_recv_exact(sock, n))


class FrameError(RuntimeError):
    """The byte stream is desynced from the framing — unrecoverable."""


def try_decode(buf: bytearray) -> Any:
    """Decode one frame from an accumulation buffer if complete, else None.

    The non-blocking sibling of recv_msg — ONE place owns the wire format.
    A length beyond _MAX_FRAME or an undecodable payload means the stream
    lost framing; the poisoned bytes are dropped (so a persistent buffer
    cannot re-raise on the next decode) and FrameError is raised."""
    if len(buf) < _LEN.size:
        return None
    (n,) = _LEN.unpack_from(buf, 0)
    if n > _MAX_FRAME:
        del buf[:]
        raise FrameError(f"corrupt control frame: length {n} exceeds "
                         f"{_MAX_FRAME} byte cap")
    if len(buf) < _LEN.size + n:
        return None
    try:
        obj = pickle.loads(bytes(buf[_LEN.size:_LEN.size + n]))
    except Exception as e:
        del buf[:]
        raise FrameError(f"corrupt control frame payload: {e!r}")
    del buf[:_LEN.size + n]
    return obj
