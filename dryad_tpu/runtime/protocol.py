"""Length-prefixed message framing for the driver<->worker control plane.

The role of the reference's vertex command protocol (SURVEY.md §2.2 "vertex
commands", ProcessService HTTP endpoints): a tiny, explicit wire format —
8-byte little-endian length + pickled payload.  Pickle is acceptable here
because both ends are processes WE spawned on the same machine from the
same codebase (a trusted local control plane, like the reference's
GM<->daemon channel inside one cluster security domain); nothing in this
module ever listens on a non-loopback interface.
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any

_LEN = struct.Struct("<Q")
# control messages are plans + host source columns; cap frames at 4 GiB to
# fail fast on corruption rather than allocating garbage lengths
_MAX_FRAME = 4 << 30


def send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise EOFError("peer closed control connection")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise EOFError(f"oversized control frame ({n} bytes)")
    return pickle.loads(_recv_exact(sock, n))


class FrameError(RuntimeError):
    """The byte stream is desynced from the framing — unrecoverable."""


def try_decode(buf: bytearray) -> Any:
    """Decode one frame from an accumulation buffer if complete, else None.

    The non-blocking sibling of recv_msg — ONE place owns the wire format.
    A length beyond _MAX_FRAME or an undecodable payload means the stream
    lost framing; the poisoned bytes are dropped (so a persistent buffer
    cannot re-raise on the next decode) and FrameError is raised."""
    if len(buf) < _LEN.size:
        return None
    (n,) = _LEN.unpack_from(buf, 0)
    if n > _MAX_FRAME:
        del buf[:]
        raise FrameError(f"corrupt control frame: length {n} exceeds "
                         f"{_MAX_FRAME} byte cap")
    if len(buf) < _LEN.size + n:
        return None
    try:
        obj = pickle.loads(bytes(buf[_LEN.size:_LEN.size + n]))
    except Exception as e:
        del buf[:]
        raise FrameError(f"corrupt control frame payload: {e!r}")
    del buf[:_LEN.size + n]
    return obj
