"""Shared host<->mesh plumbing for streamed gang execution.

Helpers used by runtime/stream_plan.py (the planned streamed runner) and
runtime/exec_common.py (parallel collect / parallel store output):
per-process host allgather, wave placement onto the global mesh, local
shard readback, parallel partition writes with process-0 metadata commit,
and range-bounds sampling (DryadLinqSampler.cs:42 role).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["StreamJobError", "local_batch_chunks"]

_SAMPLES_PER_CHUNK = 512
_MAX_SAMPLES = 8192


class StreamJobError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# host <-> mesh plumbing (worker side)


def _host_allgather(arr: np.ndarray, mesh) -> np.ndarray:
    """Per-process host array [k, ...] -> [nprocs, k, ...] everywhere.
    Single collective over the dcn axis; nprocs=1 short-circuits."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    nprocs = jax.process_count()
    if nprocs == 1:
        return arr[None]
    from dryad_tpu.parallel.mesh import HOST_AXIS
    gshape = (nprocs,) + arr.shape
    sh = NamedSharding(mesh, P(HOST_AXIS))

    def cb(idx):
        return arr[None]

    garr = jax.make_array_from_callback(gshape, sh, cb)
    rep = jax.jit(lambda x: x,
                  out_shardings=NamedSharding(mesh, P()))(garr)
    return np.asarray(rep)


def _split_local(chunk, schema, dpp: int, chunk_rows: int):
    """Block-split one host chunk across the process's dpp local devices;
    returns (cols [dpp, chunk_rows, ...] zero-padded, counts [dpp])."""
    n = chunk.n if chunk is not None else 0
    base, rem = divmod(n, dpp)
    sizes = [base + (1 if d < rem else 0) for d in range(dpp)]
    offs = np.cumsum([0] + sizes)
    cols: Dict[str, Any] = {}
    for k, spec in schema.items():
        if spec["kind"] == "str":
            L = spec["max_len"]
            sd = np.zeros((dpp, chunk_rows, L), np.uint8)
            sl = np.zeros((dpp, chunk_rows), np.int32)
            if n:
                d, l = chunk.cols[k]
                for p in range(dpp):
                    sd[p, :sizes[p]] = d[offs[p]:offs[p + 1]]
                    sl[p, :sizes[p]] = l[offs[p]:offs[p + 1]]
            cols[k] = (sd, sl)
        else:
            dt = np.dtype(spec["dtype"])
            tail = tuple(spec.get("shape", ()))
            sa = np.zeros((dpp, chunk_rows) + tail, dt)
            if n:
                v = chunk.cols[k]
                for p in range(dpp):
                    sa[p, :sizes[p]] = v[offs[p]:offs[p + 1]]
            cols[k] = sa
    return cols, np.asarray(sizes, np.int32)


def _put_wave(chunk, schema, chunk_rows: int, mesh):
    """Place one process-local chunk onto the GLOBAL mesh batch
    [P_total, chunk_rows, ...]: each process fills only its own device
    rows (make_array_from_callback touches addressable shards only)."""
    import jax
    from dryad_tpu.data.columnar import Batch, StringColumn
    from dryad_tpu.parallel.mesh import batch_sharding

    P_total = mesh.devices.size
    nprocs = jax.process_count()
    dpp = P_total // nprocs
    start = jax.process_index() * dpp
    local_cols, local_counts = _split_local(chunk, schema, dpp, chunk_rows)
    sharding = batch_sharding(mesh)

    def put(local):
        gshape = (P_total,) + local.shape[1:]

        def cb(idx):
            s = idx[0]
            return local[s.start - start: s.stop - start]

        return jax.make_array_from_callback(gshape, sharding, cb)

    cols: Dict[str, Any] = {}
    for k, spec in schema.items():
        if spec["kind"] == "str":
            d, l = local_cols[k]
            cols[k] = StringColumn(put(d), put(l))
        else:
            cols[k] = put(local_cols[k])
    return Batch(cols, put(local_counts))


def local_batch_chunks(local) -> Tuple[Dict[str, Any], List[Any]]:
    """Split a host-side local Batch [dpp, cap, ...] (from
    _read_local_shards) into per-device TRIMMED HChunks plus their schema
    — the one conversion between sharded batches and host chunk rows
    (used by wave draining and the parallel store writers)."""
    from dryad_tpu.data.columnar import StringColumn
    from dryad_tpu.exec.ooc import HChunk

    counts = np.asarray(local.count)
    dpp = counts.shape[0]
    schema: Dict[str, Any] = {}
    for k, v in local.columns.items():
        if isinstance(v, StringColumn):
            schema[k] = {"kind": "str",
                         "max_len": int(np.asarray(v.data).shape[2])}
        else:
            a = np.asarray(v)
            schema[k] = {"kind": "dense", "dtype": a.dtype.name,
                         "shape": list(a.shape[2:])}
    chunks: List[Any] = []
    for d in range(dpp):
        n = int(counts[d])
        cols: Dict[str, Any] = {}
        for k, v in local.columns.items():
            if isinstance(v, StringColumn):
                cols[k] = (np.asarray(v.data)[d][:n],
                           np.asarray(v.lengths)[d][:n])
            else:
                cols[k] = np.asarray(v)[d][:n]
        chunks.append(HChunk(cols, n))
    return schema, chunks


def _read_local_shards(tree, start: int, dpp: int):
    """Pull a mesh-sharded pytree's LOCAL partitions to host:
    leaf [P, ...] -> np [dpp, ...] (this process's rows only)."""
    import jax

    def read(arr):
        parts: List[Any] = [None] * dpp
        for sh in arr.addressable_shards:
            g = sh.index[0].start if isinstance(sh.index[0], slice) else 0
            if start <= g < start + dpp:
                parts[g - start] = np.asarray(sh.data)[0]
        return np.stack(parts)

    return jax.tree.map(read, tree)


# ---------------------------------------------------------------------------
# wave programs


def _squeeze(b):
    import jax
    return jax.tree.map(lambda x: x[0], b)


def _expand(b):
    import jax
    return jax.tree.map(lambda x: x[None], b)


# ---------------------------------------------------------------------------
# parallel store output (each worker writes its own partitions)


def _write_partitions(out_path: str, schema, part_chunks, part_ids,
                      mesh, chunk_rows: int,
                      partitioning: Optional[Dict[str, Any]] = None,
                      compression: Optional[str] = None,
                      capacity: Optional[int] = None):
    """Every process writes its own partition files under out.tmp; counts
    and checksums are allgathered; process 0 merges meta.json and commits
    the rename (parallel output — DrOutputVertex per-vertex writers,
    DrVertex.h:325-351 — instead of funneling through one process).
    Checksums cover the UNCOMPRESSED segments (store read contract).

    ``hdfs://`` targets write the same way — every worker uploads ITS
    OWN partitions through the WebHDFS adapter into the shared temp
    directory, process 0 commits meta + the (atomic) HDFS rename — the
    reference's per-vertex HDFS output writers (DrHdfsClient.cpp write
    side, channelbufferhdfs.cpp)."""
    import jax
    from dryad_tpu import native
    from dryad_tpu.exec import ooc

    if compression not in (None, "gzip"):
        raise StreamJobError(f"unknown compression {compression!r}")
    hdfs = out_path.startswith("hdfs://")
    if out_path.startswith("s3://"):
        raise StreamJobError(
            "cluster parallel output to s3:// is not supported (no "
            "atomic multi-object commit across writers); use a shared "
            "filesystem or hdfs:// target")
    from dryad_tpu.io.store import chunk_segments, segments_blob
    if hdfs:
        from dryad_tpu.io.webhdfs import hdfs_client, hdfs_part_path
        hc, hpath = hdfs_client(out_path)
        hpath = hpath.rstrip("/")
        tmp = hpath + ".tmp"
    else:
        tmp = out_path + ".tmp"
    # clear any stale temp dir from a crashed previous job BEFORE anyone
    # uploads, behind a barrier — a leftover part-NNNNN.bin from a dead
    # run with more partitions would otherwise ride the rename into the
    # committed store.  Process 0 clears; the allgather is the fence.
    if jax.process_index() == 0:
        if hdfs:
            hc.delete(tmp, recursive=True)
        elif os.path.exists(tmp):
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
    _host_allgather(np.zeros((1,), np.int32), mesh)
    if hdfs:
        hc.mkdirs(tmp)   # idempotent; every writer may race to create it
    else:
        os.makedirs(tmp, exist_ok=True)
    my_counts: List[int] = []
    my_sums: List[int] = []
    for g, chunks in zip(part_ids, part_chunks):
        merged = ooc._concat_hchunks(schema, list(chunks))
        segs = chunk_segments(schema, merged.cols)
        if hdfs:
            hc.create(hdfs_part_path(tmp, g),
                      segments_blob(segs, compression))
        else:
            native.write_files([os.path.join(tmp, f"part-{g:05d}.bin")],
                               [segs], compress=(compression == "gzip"))
        my_counts.append(merged.n)
        my_sums.append(native.checksum_segments(segs))

    # allgather (counts, checksums) — doubles as the write barrier.
    # uint32 lanes only: jax without x64 silently truncates 64-bit arrays,
    # so the fnv64 checksum rides as (hi, lo) words
    sums = np.asarray(my_sums, np.uint64)
    arr = np.stack([np.asarray(my_counts, np.uint32),
                    (sums >> np.uint64(32)).astype(np.uint32),
                    sums.astype(np.uint32)], axis=1)
    allinfo = _host_allgather(arr, mesh)  # [nprocs, dpp, 3]
    if jax.process_index() == 0:
        from dryad_tpu.io.store import build_meta
        flat = allinfo.reshape(-1, 3).astype(np.uint64)
        counts = [int(x) for x in flat[:, 0]]
        checksums = ["%016x" % int((h << np.uint64(32)) | l)
                     for h, l in zip(flat[:, 1], flat[:, 2])]
        store_schema = {}
        for k, spec in schema.items():
            if spec["kind"] == "str":
                store_schema[k] = {"kind": "str",
                                   "max_len": spec["max_len"]}
            else:
                store_schema[k] = {"kind": "dense", "dtype": spec["dtype"],
                                   "shape": list(spec.get("shape", ()))}
        meta = build_meta(store_schema, counts, checksums,
                          partitioning=partitioning,
                          compression=compression, capacity=capacity)
        if hdfs:
            hc.create(tmp + "/meta.json",
                      json.dumps(meta, indent=1).encode())
            hc.delete(hpath, recursive=True)
            hc.rename(tmp, hpath)
        else:
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f, indent=1)
            if os.path.exists(out_path):
                import shutil
                shutil.rmtree(out_path)
            os.rename(tmp, out_path)
    # post-commit barrier so no worker reports success (or starts the next
    # job's waves) before the rename happened
    _host_allgather(np.zeros((1,), np.int32), mesh)


# ---------------------------------------------------------------------------
# terminals


def _sample_pass(cs, key: Optional[str]):
    """One full pass over the local stream: (lane samples, chunk count,
    row count).  Samples empty when key is None."""
    from dryad_tpu.exec import ooc

    samples: List[np.ndarray] = []
    nchunks = 0
    rows = 0
    for chunk in cs:
        nchunks += 1
        rows += chunk.n
        if key is None or chunk.n == 0:
            continue
        spec = cs.schema[key]
        take = min(chunk.n, _SAMPLES_PER_CHUNK)
        idx = np.linspace(0, chunk.n - 1, take).astype(np.int64)
        col = chunk.cols[key]
        if spec["kind"] == "str":
            lane = ooc._host_sort_lanes(spec, (col[0][idx], col[1][idx]))[0]
        else:
            lane = ooc._host_sort_lanes(spec, col[idx])[0]
        samples.append(lane)
    s = (np.concatenate(samples) if samples
         else np.zeros((0,), np.uint32))
    if len(s) > _MAX_SAMPLES:
        s = s[np.linspace(0, len(s) - 1, _MAX_SAMPLES).astype(np.int64)]
    return s, nchunks, rows


def _gathered_bounds(samples: np.ndarray, mesh, n_buckets: int
                     ) -> np.ndarray:
    """Allgather per-process samples and cut global quantile bounds —
    the distributed form of the reference's sampling stage
    (DryadLinqSampler.cs:42 + DrDynamicRangeDistributor.h:23)."""
    from dryad_tpu.exec import ooc

    padded = np.zeros((_MAX_SAMPLES,), np.uint32)
    padded[:len(samples)] = samples
    meta = np.asarray([len(samples)], np.uint32)
    all_s = _host_allgather(padded, mesh)     # [nprocs, SMAX]
    all_n = _host_allgather(meta, mesh)       # [nprocs, 1]
    merged = np.concatenate([all_s[p, :int(all_n[p, 0])]
                             for p in range(all_s.shape[0])])
    return ooc._bounds_from_samples(merged, n_buckets)
