"""Streamed (out-of-core) jobs over the multi-process worker gang.

VERDICT r2 item 2: compose the per-host OOC chunk streams with the sharded
exchanges.  Every worker streams ITS OWN subset of the store's partitions
in fixed-capacity chunks; the gang advances in lockstep through chunk
WAVES, each wave running ONE jitted shard_map exchange over the full
(dcn, dp) mesh (partial-aggregate-then-hash for group-by, sampled range
scatter for sort); received rows spill into per-device host bucket stores
between waves; after the last wave each worker finishes its buckets
locally (recursive external sort / aggregate merge) and writes its own
output partitions in parallel — process 0 only merges the metadata.

This is the reference's architecture made SPMD: every vertex
simultaneously streams disk channels AND participates in the cross-machine
shuffle (SURVEY.md §2.8), with device working set O(chunk_rows) per chip
regardless of total data size — the 1 TB TeraSort north star shape
(BASELINE.md config 2) on a real pod.

Mirrored determinism contract (runtime/exec_common.py): all processes
derive the same wave count, the same range bounds, and the same retry
decisions (exchange needs are pmax'd across the mesh inside the program),
so the only cross-process coupling is the collectives themselves.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dryad_tpu.plan.stages import StageOp

__all__ = ["build_stream_spec", "execute_stream_job", "StreamJobError"]

_SAMPLES_PER_CHUNK = 512
_MAX_SAMPLES = 8192


class StreamJobError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# spec building (driver side)


def build_stream_spec(path: str, chunk_rows: int, ops: List[StageOp],
                      terminal: Dict[str, Any],
                      fn_table: Optional[Dict[str, Any]] = None
                      ) -> Tuple[str, str]:
    """Serialize a streamed cluster job: (spec_json, fake_plan_json for
    worker fn-table resolution).  Ops must be chunk-local (the shuffle is
    the terminal's wave exchange, not a plan exchange).  A group
    terminal's aggregates (builtin tags AND user Decomposables) ride as
    an op-encoded param so callable refs ship like any UDF."""
    from dryad_tpu.plan.serialize import _op_to_json
    from dryad_tpu.plan.stages import Stage, StageGraph
    from dryad_tpu.runtime.shiplan import _collect_refs

    terminal = dict(terminal)
    ship_ops = list(ops)
    if terminal.get("kind") == "group":
        agg_op = StageOp("__terminal_aggs__",
                         {"aggs": dict(terminal.pop("aggs"))})
        ship_ops.append(agg_op)
    graph = StageGraph([Stage(id=0, legs=[], body=ship_ops)], 0)
    user_names = {id(v): k for k, v in (fn_table or {}).items()}
    fn_names = _collect_refs(graph, user_names)
    shared: Dict[int, int] = {}
    ops_json = [_op_to_json(o, fn_names, shared) for o in ops]
    body_json = list(ops_json)
    if terminal.get("kind") == "group":
        terminal["aggs_op"] = _op_to_json(agg_op, fn_names, shared)
        body_json.append(terminal["aggs_op"])
    plan_json = json.dumps({"version": 1, "stages": [
        {"id": 0, "label": "stream", "legs": [], "body": body_json}],
        "out_stage": 0})
    spec = {"source": {"path": path, "chunk_rows": chunk_rows},
            "ops": ops_json, "terminal": terminal}
    return json.dumps(spec), plan_json


# ---------------------------------------------------------------------------
# driver-side lazy wrapper


class ClusterStream:
    """Streamed dataset over a cluster Context — the restricted surface
    that composes per-worker chunk streams with mesh exchanges.  Chunk-
    local operators (select/where/split_words/flat_map) accumulate; the
    terminals (count, order_by().to_store(), group_by().collect()/
    .to_store()) submit ONE streamed SPMD job to the gang.  UDFs must be
    importable or fn_table-registered, as with any cluster plan."""

    def __init__(self, ctx, path: str, chunk_rows: int,
                 ops: Optional[List[StageOp]] = None):
        self._ctx = ctx
        self._path = path
        self._chunk_rows = chunk_rows
        self._ops = list(ops or [])

    def _with(self, op: StageOp) -> "ClusterStream":
        return ClusterStream(self._ctx, self._path, self._chunk_rows,
                             self._ops + [op])

    def select(self, fn, label: str = "select") -> "ClusterStream":
        return self._with(StageOp("fn", {"fn": fn, "label": label}))

    def where(self, fn, label: str = "where") -> "ClusterStream":
        return self._with(StageOp("filter", {"fn": fn, "label": label}))

    def split_words(self, column: str, out_capacity: int,
                    max_token_len: int | None = None,
                    delims: bytes | None = None,
                    lower: bool = False) -> "ClusterStream":
        cfg = self._ctx.config
        return self._with(StageOp("flat_tokens", {
            "column": column, "out_capacity": out_capacity,
            "max_token_len": max_token_len or cfg.token_max_len,
            "delims": delims or cfg.token_delims, "lower": lower}))

    def flat_map(self, fn, out_capacity: int,
                 label: str = "flat_map") -> "ClusterStream":
        return self._with(StageOp("flat_map", {
            "fn": fn, "out_capacity": out_capacity, "label": label}))

    # -- terminals ---------------------------------------------------------

    def _submit(self, terminal: Dict[str, Any]) -> Dict[int, Any]:
        spec_json, plan_json = build_stream_spec(
            self._path, self._chunk_rows, self._ops, terminal,
            self._ctx.fn_table)
        return self._ctx.cluster.execute_stream(
            spec_json, plan_json, config=self._ctx.config,
            timeout=self._ctx.config.cluster_job_timeout_s)

    def count(self) -> int:
        parts = self._submit({"kind": "count"})
        return sum(r["count"] for r in parts.values())

    def order_by(self, keys) -> "_SortedClusterStream":
        return _SortedClusterStream(self, [(k, bool(d)) for k, d in keys])

    def group_by(self, keys, aggs) -> "_GroupedClusterStream":
        """Builtin (kind, column) aggregates AND user Decomposables.  A
        Decomposable must be REGISTERED by name (Context(fn_table=...) on
        the driver + --fn-module FN_TABLE on the workers) — instances
        carry no importable qualname, same constraint as the in-memory
        cluster path.  Malformed specs fail HERE, before submission."""
        from dryad_tpu.ops.kernels import AGG_KINDS
        from dryad_tpu.plan.expr import Decomposable
        for name, spec in aggs.items():
            if isinstance(spec, Decomposable):
                continue
            if (isinstance(spec, tuple) and len(spec) == 2
                    and spec[0] in AGG_KINDS):
                continue
            raise StreamJobError(
                f"agg {name!r}: expected a (kind, column) tuple with kind "
                f"in {AGG_KINDS} or a Decomposable, got {spec!r}")
        return _GroupedClusterStream(self, list(keys), dict(aggs))


class _SortedClusterStream:
    def __init__(self, base: ClusterStream, keys):
        self._base = base
        self._keys = keys

    def to_store(self, path: str) -> None:
        self._base._submit({"kind": "sort",
                            "keys": [list(k) for k in self._keys],
                            "out": path})


class _GroupedClusterStream:
    def __init__(self, base: ClusterStream, keys, aggs):
        self._base = base
        self._keys = keys
        self._aggs = aggs

    def to_store(self, path: str) -> None:
        self._base._submit({"kind": "group", "keys": self._keys,
                            "aggs": self._aggs, "out": path})

    def collect(self) -> Dict[str, Any]:
        parts = self._base._submit({"kind": "group", "keys": self._keys,
                                    "aggs": self._aggs, "out": None})
        tables = [parts[pid]["table_part"] for pid in sorted(parts)]
        tables = [t for t in tables if t is not None]
        out: Dict[str, Any] = {}
        for t in tables:
            for k, v in t.items():
                if k not in out:
                    out[k] = v
                elif isinstance(v, list):
                    out[k] = list(out[k]) + list(v)
                else:
                    out[k] = np.concatenate([out[k], v])
        return out


# ---------------------------------------------------------------------------
# host <-> mesh plumbing (worker side)


def _host_allgather(arr: np.ndarray, mesh) -> np.ndarray:
    """Per-process host array [k, ...] -> [nprocs, k, ...] everywhere.
    Single collective over the dcn axis; nprocs=1 short-circuits."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    nprocs = jax.process_count()
    if nprocs == 1:
        return arr[None]
    from dryad_tpu.parallel.mesh import HOST_AXIS
    gshape = (nprocs,) + arr.shape
    sh = NamedSharding(mesh, P(HOST_AXIS))

    def cb(idx):
        return arr[None]

    garr = jax.make_array_from_callback(gshape, sh, cb)
    rep = jax.jit(lambda x: x,
                  out_shardings=NamedSharding(mesh, P()))(garr)
    return np.asarray(rep)


def _split_local(chunk, schema, dpp: int, chunk_rows: int):
    """Block-split one host chunk across the process's dpp local devices;
    returns (cols [dpp, chunk_rows, ...] zero-padded, counts [dpp])."""
    n = chunk.n if chunk is not None else 0
    base, rem = divmod(n, dpp)
    sizes = [base + (1 if d < rem else 0) for d in range(dpp)]
    offs = np.cumsum([0] + sizes)
    cols: Dict[str, Any] = {}
    for k, spec in schema.items():
        if spec["kind"] == "str":
            L = spec["max_len"]
            sd = np.zeros((dpp, chunk_rows, L), np.uint8)
            sl = np.zeros((dpp, chunk_rows), np.int32)
            if n:
                d, l = chunk.cols[k]
                for p in range(dpp):
                    sd[p, :sizes[p]] = d[offs[p]:offs[p + 1]]
                    sl[p, :sizes[p]] = l[offs[p]:offs[p + 1]]
            cols[k] = (sd, sl)
        else:
            dt = np.dtype(spec["dtype"])
            tail = tuple(spec.get("shape", ()))
            sa = np.zeros((dpp, chunk_rows) + tail, dt)
            if n:
                v = chunk.cols[k]
                for p in range(dpp):
                    sa[p, :sizes[p]] = v[offs[p]:offs[p + 1]]
            cols[k] = sa
    return cols, np.asarray(sizes, np.int32)


def _put_wave(chunk, schema, chunk_rows: int, mesh):
    """Place one process-local chunk onto the GLOBAL mesh batch
    [P_total, chunk_rows, ...]: each process fills only its own device
    rows (make_array_from_callback touches addressable shards only)."""
    import jax
    from dryad_tpu.data.columnar import Batch, StringColumn
    from dryad_tpu.parallel.mesh import batch_sharding

    P_total = mesh.devices.size
    nprocs = jax.process_count()
    dpp = P_total // nprocs
    start = jax.process_index() * dpp
    local_cols, local_counts = _split_local(chunk, schema, dpp, chunk_rows)
    sharding = batch_sharding(mesh)

    def put(local):
        gshape = (P_total,) + local.shape[1:]

        def cb(idx):
            s = idx[0]
            return local[s.start - start: s.stop - start]

        return jax.make_array_from_callback(gshape, sharding, cb)

    cols: Dict[str, Any] = {}
    for k, spec in schema.items():
        if spec["kind"] == "str":
            d, l = local_cols[k]
            cols[k] = StringColumn(put(d), put(l))
        else:
            cols[k] = put(local_cols[k])
    return Batch(cols, put(local_counts))


def local_batch_chunks(local) -> Tuple[Dict[str, Any], List[Any]]:
    """Split a host-side local Batch [dpp, cap, ...] (from
    _read_local_shards) into per-device TRIMMED HChunks plus their schema
    — the one conversion between sharded batches and host chunk rows
    (used by wave draining and the parallel store writers)."""
    from dryad_tpu.data.columnar import StringColumn
    from dryad_tpu.exec.ooc import HChunk

    counts = np.asarray(local.count)
    dpp = counts.shape[0]
    schema: Dict[str, Any] = {}
    for k, v in local.columns.items():
        if isinstance(v, StringColumn):
            schema[k] = {"kind": "str",
                         "max_len": int(np.asarray(v.data).shape[2])}
        else:
            a = np.asarray(v)
            schema[k] = {"kind": "dense", "dtype": a.dtype.name,
                         "shape": list(a.shape[2:])}
    chunks: List[Any] = []
    for d in range(dpp):
        n = int(counts[d])
        cols: Dict[str, Any] = {}
        for k, v in local.columns.items():
            if isinstance(v, StringColumn):
                cols[k] = (np.asarray(v.data)[d][:n],
                           np.asarray(v.lengths)[d][:n])
            else:
                cols[k] = np.asarray(v)[d][:n]
        chunks.append(HChunk(cols, n))
    return schema, chunks


def _read_local_shards(tree, start: int, dpp: int):
    """Pull a mesh-sharded pytree's LOCAL partitions to host:
    leaf [P, ...] -> np [dpp, ...] (this process's rows only)."""
    import jax

    def read(arr):
        parts: List[Any] = [None] * dpp
        for sh in arr.addressable_shards:
            g = sh.index[0].start if isinstance(sh.index[0], slice) else 0
            if start <= g < start + dpp:
                parts[g - start] = np.asarray(sh.data)[0]
        return np.stack(parts)

    return jax.tree.map(read, tree)


# ---------------------------------------------------------------------------
# wave programs


def _squeeze(b):
    import jax
    return jax.tree.map(lambda x: x[0], b)


def _expand(b):
    import jax
    return jax.tree.map(lambda x: x[None], b)


def _build_wave_fn(mesh, kind: str, params: Dict[str, Any], chunk_rows: int,
                   scale: int, slack: int):
    """One jitted shard_map program for a chunk wave: (optional local
    partial aggregation) + global exchange.  Need channels are pmax'd by
    the exchange itself, so every process reads identical retry info."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dryad_tpu.ops import kernels
    from dryad_tpu.parallel import shuffle

    axes = tuple(mesh.axis_names)
    cap = chunk_rows * scale

    def per_shard(batch, bounds):
        b = _squeeze(batch)
        if kind == "range":
            out, nr, nsl = shuffle.range_exchange(
                b, params["key"], bounds, cap,
                descending=params["descending"], send_slack=slack,
                axes=axes)
        elif kind == "group":
            if "decs" in params:
                pb = kernels.group_decompose_partial(
                    b, params["keys"], params["decs"], params["box"])
            else:
                pb = kernels.group_aggregate(b, params["keys"],
                                             params["partial"])
            out, nr, nsl = shuffle.hash_exchange(pb, params["keys"], cap,
                                                 send_slack=slack,
                                                 axes=axes)
        else:
            raise ValueError(kind)
        need_scale = (-(-nr // jnp.int32(chunk_rows))).astype(jnp.int32)
        info = jnp.stack([need_scale, jnp.asarray(nsl, jnp.int32),
                          out.count.astype(jnp.int32)])
        return _expand(out), info[None]

    in_specs = (P(axes), P())
    fn = jax.shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                       out_specs=(P(axes), P(axes)), check_vma=False)
    return jax.jit(fn)


def _run_waves(cs, schema, mesh, kind: str, params: Dict[str, Any],
               chunk_rows: int, config, bounds_arr):
    """Advance the gang through lockstep chunk waves until every process's
    stream is exhausted (a tiny per-wave continuation allgather keeps the
    SPMD collective counts identical WITHOUT a counting pre-pass over the
    data); append each wave's received rows to per-local-device bucket
    stores (compacting group partials whenever a bucket exceeds the chunk
    capacity — the streaming aggregation-tree role).  Returns (bucket
    store, its row schema)."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.exec import ooc
    from dryad_tpu.ops import kernels

    nprocs = jax.process_count()
    dpp = mesh.devices.size // nprocs
    start = jax.process_index() * dpp

    # bucket store schema = the EXCHANGED row schema (partial rows for
    # group) — probe with an empty chunk through the local part (for
    # user decomposables this also fills the treedef box before any
    # merge traces)
    compact_fn = None
    if kind == "group":
        if "decs" in params:
            pfn = (lambda b: kernels.group_decompose_partial(
                b, params["keys"], params["decs"], params["box"]))
        else:
            pfn = (lambda b: kernels.group_aggregate(
                b, params["keys"], params["partial"]))
        probe = ooc._batch_to_chunk(jax.jit(pfn)(
            ooc._chunk_to_batch(ooc.HChunk.empty_like(schema), 1)))
        out_schema = ooc.chunk_schema(probe)
        # merging partials is the associative combine; finalization
        # (mean quotient / FinalReduce) happens only at the end
        compact_fn = jax.jit(params["merge_fn"])
    else:
        out_schema = schema

    # sort buckets hold the worker's ENTIRE received key range across all
    # waves — they must spill to disk (the host-side bucket spill of the
    # composition contract), or a 1 TB sort OOMs every worker.  Group
    # buckets stay in RAM: compaction bounds them at one row per distinct
    # key (<= chunk_rows).
    spill = None
    if kind == "range":
        import tempfile
        spill = tempfile.mkdtemp(prefix="wave-buckets-")
    store = ooc._BucketStore(out_schema, dpp, spill_dir=spill)

    def compact_bucket(d: int) -> None:
        # merge accumulated partials down to one row per distinct key;
        # pow2 device capacity bounds the number of retraces.  RAM-only
        # buckets by construction (spill is never enabled for group).
        assert store.spill_dir is None
        merged = ooc._concat_hchunks(out_schema, store.fragments(d))
        capm = 1
        while capm < max(merged.n, 1):
            capm *= 2
        out = ooc._batch_to_chunk(compact_fn(
            ooc._chunk_to_batch(merged, capm)))
        if out.n > chunk_rows:
            raise StreamJobError(
                f"device bucket {start + d} holds {out.n} distinct groups "
                f"> chunk capacity {chunk_rows}; raise chunk_rows")
        store._ram[d] = [out]

    fns: Dict[Tuple[int, int], Any] = {}
    slack = config.initial_send_slack
    scale = 1
    jbounds = jnp.asarray(bounds_arr)

    it = iter(cs)
    w = 0
    while True:
        chunk = next(it, None)
        live = _host_allgather(
            np.asarray([1 if chunk is not None else 0], np.int32), mesh)
        if int(live.sum()) == 0:
            break
        w += 1
        for attempt in range(config.max_capacity_retries + 1):
            key = (scale, slack)
            fn = fns.get(key)
            if fn is None:
                fn = fns[key] = _build_wave_fn(mesh, kind, params,
                                               chunk_rows, scale, slack)
            garr = _put_wave(chunk, schema, chunk_rows, mesh)
            out, info = fn(garr, jbounds)
            local_info = _read_local_shards(info, start, dpp)  # [dpp, 3]
            need_scale = int(local_info[:, 0].max())
            need_slack = int(local_info[:, 1].max())
            if need_scale == 0 and need_slack == 0:
                break
            # mirrored right-sizing (info is pmax'd mesh-wide: every
            # process sees the same values and retries identically)
            scale = max(scale, need_scale)
            slack = max(slack, min(need_slack, mesh.devices.size))
        else:
            raise StreamJobError(
                f"wave {w}: exchange still overflowing after "
                f"{config.max_capacity_retries} retries (scale={scale})")
        local = _read_local_shards(out, start, dpp)
        _, wave_chunks = local_batch_chunks(local)
        for d, hc in enumerate(wave_chunks):
            if hc.n == 0:
                continue
            store.append(d, hc)
            if compact_fn is not None and store.rows(d) > chunk_rows:
                compact_bucket(d)
    return store, out_schema


# ---------------------------------------------------------------------------
# parallel store output (each worker writes its own partitions)


def _write_partitions(out_path: str, schema, part_chunks, part_ids,
                      mesh, chunk_rows: int,
                      partitioning: Optional[Dict[str, Any]] = None,
                      compression: Optional[str] = None,
                      capacity: Optional[int] = None):
    """Every process writes its own partition files under out.tmp; counts
    and checksums are allgathered; process 0 merges meta.json and commits
    the rename (parallel output — DrOutputVertex per-vertex writers,
    DrVertex.h:325-351 — instead of funneling through one process).
    Checksums cover the UNCOMPRESSED segments (store read contract)."""
    import jax
    from dryad_tpu import native
    from dryad_tpu.exec import ooc

    if compression not in (None, "gzip"):
        raise StreamJobError(f"unknown compression {compression!r}")
    tmp = out_path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    my_counts: List[int] = []
    my_sums: List[int] = []
    for g, chunks in zip(part_ids, part_chunks):
        merged = ooc._concat_hchunks(schema, list(chunks))
        segs: List[np.ndarray] = []
        for k in sorted(schema):
            v = merged.cols[k]
            if schema[k]["kind"] == "str":
                segs.append(np.ascontiguousarray(v[0]))
                segs.append(np.ascontiguousarray(v[1]))
            else:
                segs.append(np.ascontiguousarray(v))
        native.write_files([os.path.join(tmp, f"part-{g:05d}.bin")],
                           [segs], compress=(compression == "gzip"))
        my_counts.append(merged.n)
        my_sums.append(native.checksum_segments(segs))

    # allgather (counts, checksums) — doubles as the write barrier.
    # uint32 lanes only: jax without x64 silently truncates 64-bit arrays,
    # so the fnv64 checksum rides as (hi, lo) words
    sums = np.asarray(my_sums, np.uint64)
    arr = np.stack([np.asarray(my_counts, np.uint32),
                    (sums >> np.uint64(32)).astype(np.uint32),
                    sums.astype(np.uint32)], axis=1)
    allinfo = _host_allgather(arr, mesh)  # [nprocs, dpp, 3]
    if jax.process_index() == 0:
        from dryad_tpu.io.store import build_meta
        flat = allinfo.reshape(-1, 3).astype(np.uint64)
        counts = [int(x) for x in flat[:, 0]]
        checksums = ["%016x" % int((h << np.uint64(32)) | l)
                     for h, l in zip(flat[:, 1], flat[:, 2])]
        store_schema = {}
        for k, spec in schema.items():
            if spec["kind"] == "str":
                store_schema[k] = {"kind": "str",
                                   "max_len": spec["max_len"]}
            else:
                store_schema[k] = {"kind": "dense", "dtype": spec["dtype"],
                                   "shape": list(spec.get("shape", ()))}
        meta = build_meta(store_schema, counts, checksums,
                          partitioning=partitioning,
                          compression=compression, capacity=capacity)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        if os.path.exists(out_path):
            import shutil
            shutil.rmtree(out_path)
        os.rename(tmp, out_path)
    # post-commit barrier so no worker reports success (or starts the next
    # job's waves) before the rename happened
    _host_allgather(np.zeros((1,), np.int32), mesh)


# ---------------------------------------------------------------------------
# terminals


def _sample_pass(cs, key: Optional[str]):
    """One full pass over the local stream: (lane samples, chunk count,
    row count).  Samples empty when key is None."""
    from dryad_tpu.exec import ooc

    samples: List[np.ndarray] = []
    nchunks = 0
    rows = 0
    for chunk in cs:
        nchunks += 1
        rows += chunk.n
        if key is None or chunk.n == 0:
            continue
        spec = cs.schema[key]
        take = min(chunk.n, _SAMPLES_PER_CHUNK)
        idx = np.linspace(0, chunk.n - 1, take).astype(np.int64)
        col = chunk.cols[key]
        if spec["kind"] == "str":
            lane = ooc._host_sort_lanes(spec, (col[0][idx], col[1][idx]))[0]
        else:
            lane = ooc._host_sort_lanes(spec, col[idx])[0]
        samples.append(lane)
    s = (np.concatenate(samples) if samples
         else np.zeros((0,), np.uint32))
    if len(s) > _MAX_SAMPLES:
        s = s[np.linspace(0, len(s) - 1, _MAX_SAMPLES).astype(np.int64)]
    return s, nchunks, rows


def _gathered_bounds(samples: np.ndarray, mesh, n_buckets: int
                     ) -> np.ndarray:
    """Allgather per-process samples and cut global quantile bounds —
    the distributed form of the reference's sampling stage
    (DryadLinqSampler.cs:42 + DrDynamicRangeDistributor.h:23)."""
    from dryad_tpu.exec import ooc

    padded = np.zeros((_MAX_SAMPLES,), np.uint32)
    padded[:len(samples)] = samples
    meta = np.asarray([len(samples)], np.uint32)
    all_s = _host_allgather(padded, mesh)     # [nprocs, SMAX]
    all_n = _host_allgather(meta, mesh)       # [nprocs, 1]
    merged = np.concatenate([all_s[p, :int(all_n[p, 0])]
                             for p in range(all_s.shape[0])])
    return ooc._bounds_from_samples(merged, n_buckets)


def _finish_sort(store, schema, keys, chunk_rows: int, mesh,
                 out_path: str, term):
    """Per-device buckets -> fully sorted partitions, written in parallel.
    Output partition order equals global sort order (range buckets are
    laid out in mesh partition order by the exchange)."""
    import jax
    from dryad_tpu.exec import ooc

    nprocs = jax.process_count()
    dpp = mesh.devices.size // nprocs
    start = jax.process_index() * dpp
    sort_fn = ooc._make_sort_fn(tuple(tuple(k) for k in keys))
    part_chunks = []
    for d in range(dpp):
        frags = store.fragments(d)
        part_chunks.append(list(ooc._sorted_bucket_chunks(
            schema, frags, [tuple(k) for k in keys], chunk_rows, sort_fn)))
    part_ids = list(range(start, start + dpp))
    # ascending sorts leave partitions in range order; a descending
    # primary cannot claim ascending range partitioning (plan/planner.py
    # OrderBy semantics)
    part = ({"kind": "range", "keys": [keys[0][0]]}
            if not keys[0][1] else {"kind": "none"})
    _write_partitions(out_path, schema, part_chunks, part_ids, mesh,
                      chunk_rows, partitioning=part)


def _finish_group(store, pschema, chunk_rows: int, mesh, term, final_fn):
    """Finalize each device bucket's accumulated partials (associative
    merge + FinalReduce / mean quotient via ``final_fn``), then either
    write partitions in parallel or return the local host table part
    (driver concatenates parts in pid order)."""
    import jax

    from dryad_tpu.exec import ooc

    nprocs = jax.process_count()
    dpp = mesh.devices.size // nprocs
    start = jax.process_index() * dpp
    keys = list(term["keys"])
    fin = jax.jit(final_fn)

    # final output schema, probed on an empty partial batch
    fin_schema = ooc.chunk_schema(ooc._batch_to_chunk(fin(
        ooc._chunk_to_batch(ooc.HChunk.empty_like(pschema), 1))))

    finals: List[List[Any]] = []
    for d in range(dpp):
        frags = store.fragments(d)
        if not frags:
            finals.append([])
            continue
        merged = ooc._concat_hchunks(pschema, frags)
        capm = 1
        while capm < max(merged.n, 1):
            capm *= 2
        finals.append([ooc._batch_to_chunk(fin(
            ooc._chunk_to_batch(merged, capm)))])

    if term.get("out") is not None:
        _write_partitions(term["out"], fin_schema, finals,
                          list(range(start, start + dpp)), mesh,
                          chunk_rows,
                          partitioning={"kind": "hash", "keys": keys})
        return None
    # collect: return this worker's part as a host table
    from dryad_tpu.exec.stream_exec import chunks_to_table
    flat = [c for lst in finals for c in lst]
    cs = ooc.ChunkSource(lambda: iter(flat), fin_schema, chunk_rows)
    return chunks_to_table(cs)


# ---------------------------------------------------------------------------
# worker entry


def execute_stream_job(spec_json: str, fn_table, mesh, config):
    """Run one streamed job SPMD on this worker; returns the worker's
    reply payload (merged by the driver)."""
    import jax

    from dryad_tpu.exec import ooc
    from dryad_tpu.exec.stream_exec import (_LOCAL_KINDS, _stream_local)
    from dryad_tpu.io.store import store_meta
    from dryad_tpu.plan.serialize import _op_from_json

    spec = json.loads(spec_json)
    path = spec["source"]["path"]
    chunk_rows = spec["source"]["chunk_rows"]
    me, nprocs = jax.process_index(), jax.process_count()

    meta = store_meta(path)
    parts = [p for p in range(meta["npartitions"]) if p % nprocs == me]
    cs = ooc.ChunkSource.from_store(path, chunk_rows, partitions=parts)

    shared: Dict[int, dict] = {}
    ops = [_op_from_json(o, fn_table, shared) for o in spec["ops"]]
    bad = [o.kind for o in ops if o.kind not in _LOCAL_KINDS]
    if bad:
        raise StreamJobError(
            f"streamed cluster jobs support chunk-local ops only; got "
            f"{bad}")
    if ops:
        cs = _stream_local(cs, ops, config)
    schema = cs.schema
    chunk_rows = cs.chunk_rows  # local ops may change the chunk bound

    term = spec["terminal"]
    kind = term["kind"]
    if kind == "count":
        return {"count": sum(c.n for c in cs)}

    if kind == "sort":
        keys = [(k, bool(d)) for k, d in term["keys"]]
        key0, desc0 = keys[0]
        samples, _, _ = _sample_pass(cs, key0)
        bounds = _gathered_bounds(samples, mesh, mesh.devices.size)
        store, _ = _run_waves(cs, schema, mesh, "range",
                              {"key": key0, "descending": desc0},
                              chunk_rows, config, bounds)
        try:
            _finish_sort(store, schema, keys, chunk_rows, mesh,
                         term["out"], term)
        finally:
            store.close()
            if store.spill_dir:
                import shutil
                shutil.rmtree(store.spill_dir, ignore_errors=True)
        return {"stored": term["out"]}

    if kind == "group":
        from dryad_tpu.plan.planner import (_decompose_aggs,
                                            _has_user_decs,
                                            _normalize_decs)
        keys = list(term["keys"])
        aggs = _op_from_json(term["aggs_op"], fn_table,
                             shared).params["aggs"]
        if _has_user_decs(aggs):
            # user Decomposables ride the waves as flattened partial
            # states (seed+merge in the wave program, merge compaction
            # between waves, FinalReduce per bucket —
            # IDecomposable.cs:34 over the cluster)
            decs = _normalize_decs(aggs)
            box: Dict[str, Any] = {}
            from dryad_tpu.ops import kernels as K
            merge_fn = (lambda b: K.group_decompose_merge(
                b, keys, decs, box, False))
            final_fn = (lambda b: K.group_decompose_merge(
                b, keys, decs, box, True))
            params = {"keys": keys, "decs": decs, "box": box,
                      "merge_fn": merge_fn}
        else:
            partial, final, mean_cols = _decompose_aggs(dict(aggs))

            from dryad_tpu.data.columnar import Batch as _B
            from dryad_tpu.ops import kernels as K

            def merge_fn(b):
                return K.group_aggregate(b, keys, final)

            def final_fn(b):
                m = K.group_aggregate(b, keys, final)
                return _B(K.mean_finalize_columns(dict(m.columns),
                                                  mean_cols), m.count)

            params = {"keys": keys, "partial": partial,
                      "merge_fn": merge_fn}
        # no pre-pass: the per-wave continuation flag drives the loop, so
        # group-by reads and computes the data exactly once
        store, pschema = _run_waves(cs, schema, mesh, "group", params,
                                    chunk_rows, config,
                                    np.zeros((0,), np.uint32))
        table = _finish_group(store, pschema, chunk_rows, mesh, term,
                              final_fn)
        if term.get("out") is not None:
            return {"stored": term["out"]}
        return {"table_part": table}

    raise StreamJobError(f"unknown streamed terminal {kind!r}")
