"""Plan shipping: turn a planned StageGraph into (JSON plan, source specs,
callable references) that worker processes can rebuild.

The reference names vertex entry points `assembly!class.method` in its XML
plan (QueryParser.cs:100) — the same idea here: a UDF crossing the process
boundary must be IMPORTABLE (``module:qualname``), or pre-registered by
name in the Context's ``fn_table`` and exported by a worker ``--fn-module``
(a module defining ``FN_TABLE``).  Lambdas/closures cannot ship — exactly
the reference's serializable-expression constraint.
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Callable, Dict, Iterable, Tuple

from dryad_tpu.analysis.diagnostics import DiagnosticError
from dryad_tpu.plan.serialize import graph_to_json, import_ref, ship_ref_of
from dryad_tpu.plan.stages import StageGraph
from dryad_tpu.runtime.sources import DeferredSource

__all__ = ["PlanShipError", "serialize_for_cluster", "resolve_fn_table",
           "register_fn_table"]


class PlanShipError(DiagnosticError):
    """Shipping-contract violation.  Carries the stable diagnostic code
    of the dryad_tpu/analysis rule that catches the same condition
    pre-submit (DTA014/015/016; DTA905 is worker-side deploy-only)."""


# process-global shipping names (merged UNDER Context(fn_table=...)):
# a convenience registry so library code can pre-register its UDFs once
_GLOBAL_FN_TABLE: Dict[str, Any] = {}


def register_fn_table(table: Dict[str, Any]) -> None:
    """Register callables/Decomposables by shipping name for every later
    ``serialize_for_cluster`` in this process.  Workers must still export
    the same names from a ``--fn-module`` FN_TABLE."""
    _GLOBAL_FN_TABLE.update(table)


# the one importability check (moved to plan/serialize.import_ref so the
# serializer's shippable-value protocol shares it); kept under the old
# name for its existing importers (analysis/udf_lint)
_import_ref = import_ref


# serializer-ephemeral params (rebuilt on the executing side) need no refs
_EPHEMERAL_PARAMS = {"box"}


def _collect_refs(graph: StageGraph,
                  user_names: Dict[int, str]) -> Dict[int, str]:
    """id(value) -> shipping name for every non-JSON value reachable from
    op params (recursing into dicts/tuples — e.g. user Decomposables
    inside a group's ``decs`` dict)."""
    fn_names: Dict[int, str] = {}

    def visit(v: Any, op, pname: str) -> None:
        if isinstance(v, (str, int, float, bool, bytes, type(None))):
            return
        if id(v) in user_names:
            fn_names[id(v)] = user_names[id(v)]
            return
        if ship_ref_of(v) is not None:
            # shippable-value protocol (plan/serialize.ship_ref_of):
            # serializes as data, needs no shipping name
            return
        if callable(v):
            ref = _import_ref(v)
            if ref is None:
                code_obj = getattr(v, "__code__", None)
                defined = (f", defined at {code_obj.co_filename}:"
                           f"{code_obj.co_firstlineno}"
                           if code_obj is not None else "")
                raise PlanShipError(
                    f"op {op.kind!r} param {pname!r}: callable "
                    f"{getattr(v, '__qualname__', v)!r}{defined} is not "
                    f"importable (lambda/closure?) — move it to module "
                    f"level, or register it by name via "
                    f"runtime.shiplan.register_fn_table({{name: fn}}) / "
                    f"Context(fn_table=...) and export it from a worker "
                    f"--fn-module FN_TABLE",
                    code="DTA014", span=op.span)
            fn_names[id(v)] = ref
            return
        if isinstance(v, (tuple, list)):
            for x in v:
                visit(x, op, pname)
            return
        if isinstance(v, dict):
            for x in v.values():
                visit(x, op, pname)
            return
        raise PlanShipError(
            f"op {op.kind!r} param {pname!r} ({type(v).__name__}) is "
            f"not serializable for cluster execution — register it by "
            f"name via runtime.shiplan.register_fn_table({{name: value}}) "
            f"/ Context(fn_table=...) and export it from a worker "
            f"--fn-module FN_TABLE",
            code="DTA016", span=op.span)

    for st in graph.stages:
        ops = [o for leg in st.legs for o in leg.ops] + list(st.body)
        for op in ops:
            for k, v in op.params.items():
                if k in _EPHEMERAL_PARAMS:
                    continue
                visit(v, op, k)
    return fn_names


def serialize_for_cluster(graph: StageGraph,
                          user_fn_table: Dict[str, Any] | None = None
                          ) -> Tuple[str, Dict[str, Dict[str, Any]]]:
    """Returns (plan_json, source_specs keyed "sid:leg")."""
    merged = dict(_GLOBAL_FN_TABLE)
    merged.update(user_fn_table or {})
    user_names = {id(v): k for k, v in merged.items()}
    fn_names = _collect_refs(graph, user_names)
    plan_json = graph_to_json(graph, fn_names)
    specs: Dict[str, Dict[str, Any]] = {}
    for st in graph.stages:
        for li, leg in enumerate(st.legs):
            if isinstance(leg.src, tuple) and leg.src[0] == "source":
                v = leg.src[1]
                if not isinstance(v, DeferredSource):
                    span = next((o.span for o in leg.ops
                                 if o.span is not None), None)
                    raise PlanShipError(
                        "cluster execution needs deferred sources — create "
                        "datasets through a Context constructed with "
                        "cluster=...", code="DTA015", span=span)
                specs[f"{st.id}:{li}"] = v.spec
    return plan_json, specs


def _scan_names(plan_json: str) -> Iterable[str]:
    def walk(v):
        if isinstance(v, dict):
            if "__fn__" in v:
                yield v["__fn__"]
            if "__opaque__" in v:
                yield v["__opaque__"]
            for x in v.values():
                yield from walk(x)
        elif isinstance(v, list):
            for x in v:
                yield from walk(x)

    d = json.loads(plan_json)
    for st in d["stages"]:
        ops = [o for leg in st["legs"] for o in leg["ops"]] + st["body"]
        for op in ops:
            yield from walk(op["params"])


def resolve_fn_table(plan_json: str,
                     fn_modules: Iterable[str] = ()) -> Dict[str, Callable]:
    """Worker-side: resolve every callable name the plan references."""
    table: Dict[str, Any] = {}
    for m in fn_modules:
        mod = importlib.import_module(m)
        table.update(getattr(mod, "FN_TABLE", {}))
    for name in _scan_names(plan_json):
        if name in table:
            continue
        if ":" in name:
            mod_name, qual = name.split(":", 1)
            obj: Any = importlib.import_module(mod_name)
            for part in qual.split("."):
                obj = getattr(obj, part)
            table[name] = obj
        else:
            raise PlanShipError(
                f"plan references {name!r} but no --fn-module exports it",
                code="DTA905")
    return table
