"""Plan shipping: turn a planned StageGraph into (JSON plan, source specs,
callable references) that worker processes can rebuild.

The reference names vertex entry points `assembly!class.method` in its XML
plan (QueryParser.cs:100) — the same idea here: a UDF crossing the process
boundary must be IMPORTABLE (``module:qualname``), or pre-registered by
name in the Context's ``fn_table`` and exported by a worker ``--fn-module``
(a module defining ``FN_TABLE``).  Lambdas/closures cannot ship — exactly
the reference's serializable-expression constraint.
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Callable, Dict, Iterable, Tuple

from dryad_tpu.plan.serialize import graph_to_json
from dryad_tpu.plan.stages import StageGraph
from dryad_tpu.runtime.sources import DeferredSource

__all__ = ["PlanShipError", "serialize_for_cluster", "resolve_fn_table"]


class PlanShipError(RuntimeError):
    pass


def _json_ok(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except TypeError:
        return False


def _import_ref(fn: Callable) -> str | None:
    """``module:qualname`` if re-importing it yields the same object."""
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", None)
    if not mod or not qual or "<" in qual:
        return None
    try:
        obj: Any = importlib.import_module(mod)
        for part in qual.split("."):
            obj = getattr(obj, part)
    except (ImportError, AttributeError):
        return None
    return f"{mod}:{qual}" if obj is fn else None


def _collect_refs(graph: StageGraph,
                  user_names: Dict[int, str]) -> Dict[int, str]:
    """id(value) -> shipping name for every non-JSON op param."""
    fn_names: Dict[int, str] = {}
    for st in graph.stages:
        ops = [o for leg in st.legs for o in leg.ops] + list(st.body)
        for op in ops:
            for k, v in op.params.items():
                if isinstance(v, (str, int, float, bool, bytes,
                                  type(None))):
                    continue
                if id(v) in user_names:
                    fn_names[id(v)] = user_names[id(v)]
                    continue
                if callable(v):
                    ref = _import_ref(v)
                    if ref is None:
                        raise PlanShipError(
                            f"op {op.kind!r} param {k!r}: callable "
                            f"{getattr(v, '__qualname__', v)!r} is not "
                            f"importable (lambda/closure?) — move it to "
                            f"module level, or register it by name in "
                            f"Context(fn_table=...) and export it from a "
                            f"worker --fn-module FN_TABLE")
                    fn_names[id(v)] = ref
                    continue
                if _json_ok(v) or (isinstance(v, (tuple, list, dict))
                                   and _json_ok_structure(v)):
                    continue
                raise PlanShipError(
                    f"op {op.kind!r} param {k!r} ({type(v).__name__}) is "
                    f"not serializable for cluster execution — register "
                    f"it by name in Context(fn_table=...) and export it "
                    f"from a worker --fn-module FN_TABLE")
    return fn_names


def _json_ok_structure(v: Any) -> bool:
    """Matches the value shapes plan.serialize._op_to_json round-trips
    (scalars, bytes, nested tuples/lists, dicts of those)."""
    if isinstance(v, (tuple, list)):
        return all(_json_ok_structure(x) for x in v)
    if isinstance(v, dict):
        return all(_json_ok_structure(x) for x in v.values())
    return isinstance(v, (str, int, float, bool, bytes, type(None)))


def serialize_for_cluster(graph: StageGraph,
                          user_fn_table: Dict[str, Any] | None = None
                          ) -> Tuple[str, Dict[str, Dict[str, Any]]]:
    """Returns (plan_json, source_specs keyed "sid:leg")."""
    user_names = {id(v): k for k, v in (user_fn_table or {}).items()}
    fn_names = _collect_refs(graph, user_names)
    plan_json = graph_to_json(graph, fn_names)
    specs: Dict[str, Dict[str, Any]] = {}
    for st in graph.stages:
        for li, leg in enumerate(st.legs):
            if isinstance(leg.src, tuple) and leg.src[0] == "source":
                v = leg.src[1]
                if not isinstance(v, DeferredSource):
                    raise PlanShipError(
                        "cluster execution needs deferred sources — create "
                        "datasets through a Context constructed with "
                        "cluster=...")
                specs[f"{st.id}:{li}"] = v.spec
    return plan_json, specs


def _scan_names(plan_json: str) -> Iterable[str]:
    d = json.loads(plan_json)
    for st in d["stages"]:
        ops = [o for leg in st["legs"] for o in leg["ops"]] + st["body"]
        for op in ops:
            for v in op["params"].values():
                if isinstance(v, dict) and "__fn__" in v:
                    yield v["__fn__"]
                if isinstance(v, dict) and "__opaque__" in v:
                    yield v["__opaque__"]


def resolve_fn_table(plan_json: str,
                     fn_modules: Iterable[str] = ()) -> Dict[str, Callable]:
    """Worker-side: resolve every callable name the plan references."""
    table: Dict[str, Any] = {}
    for m in fn_modules:
        mod = importlib.import_module(m)
        table.update(getattr(mod, "FN_TABLE", {}))
    for name in _scan_names(plan_json):
        if name in table:
            continue
        if ":" in name:
            mod_name, qual = name.split(":", 1)
            obj: Any = importlib.import_module(mod_name)
            for part in qual.split("."):
                obj = getattr(obj, part)
            table[name] = obj
        else:
            raise PlanShipError(
                f"plan references {name!r} but no --fn-module exports it")
    return table
