"""Multi-process runtime: job submission, per-node worker services, and the
driver-side cluster control plane.

The counterpart of the reference's layers 2/4/5 (SURVEY.md §1): job
submission (LinqToDryad/LocalJobSubmission.cs:97-302), the cluster
interface (ClusterInterface/Interfaces.cs:324,491), and the per-node daemon
(ProcessService/ProcessService.cs:389).  TPU-native shape: the driver is a
pure control plane (it owns no devices); N worker processes form a
jax.distributed job whose global mesh carries the data plane — collectives
over the cross-process axis are the DCN transport the reference implements
with its TCP channel fabric.
"""

from dryad_tpu.runtime.cluster import (ClusterJobError, LocalCluster,
                                       WorkerFailure)
from dryad_tpu.runtime.interfaces import (ClusterBackend, cluster_backends,
                                          make_cluster, register_cluster)
from dryad_tpu.runtime.sources import DeferredSource

# the built-in backends register here (Interfaces.cs:545 role):
# "local" = worker processes on this box; "ssh" = one worker per remote
# host over a remote shell, code staged per job (runtime/ssh_cluster.py)
register_cluster("local", LocalCluster)
from dryad_tpu.runtime.ssh_cluster import SshCluster  # noqa: E402  (registers "ssh")

__all__ = ["LocalCluster", "SshCluster", "WorkerFailure",
           "ClusterJobError", "DeferredSource", "ClusterBackend",
           "register_cluster", "make_cluster", "cluster_backends"]
