"""Streamed (>HBM) execution of PLANNED StageGraphs over the worker gang.

VERDICT r3 item 3: the cluster streamed path used to be a hand-mirrored
mini-API (ClusterStream) accepting only chunk-local ops + three terminals —
every new operator needed a third implementation.  This module replaces it:
plain Dataset plans (the SAME planner lowering the in-memory cluster path
uses, exchanges included) execute over per-device chunk streams:

* each mesh device streams its own subset of the source store's
  partitions (partition p -> device p mod P);
* a leg's trailing chunk-local (and partial-safe: group/distinct) ops fuse
  INTO the jitted wave program; whole-stream leg ops (take/skip/row_index/
  sort/...) apply per-device through exec/stream_exec's machinery first;
* a leg's exchange runs as lockstep chunk WAVES over the mesh (hash /
  range / broadcast — including the hierarchical per-axis hops), received
  rows spilling into per-device bucket stores between waves;
* stage BODY ops then run per device over its bucket stream through the
  single-partition streamed executor — joins materialize their
  (bucket-aligned) right side exactly like the one-process path;
* terminals reuse the parallel collect / parallel store writers; loop
  state (do_while) materializes cluster-resident under keep_token.

The reference's channels stream every operator identically
(DryadVertex/.../channelinterface.h:212 makes no operator distinction);
this gives the TPU gang the same property through ONE lowering.

Mirrored-determinism contract as runtime/exec_common.py: every process
derives the same wave count (a tiny continuation allgather), the same
bounds, and the same retry decisions (needs are pmax'd in-program).
"""

from __future__ import annotations

import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dryad_tpu.analysis.diagnostics import DiagnosticError
from dryad_tpu.plan.stages import Exchange, Stage, StageOp

__all__ = ["execute_stream_plan", "has_stream_sources", "StreamPlanError"]


class StreamPlanError(DiagnosticError):
    """Streamed-plan contract violation.  Every raise carries the stable
    diagnostic code of the dryad_tpu/analysis rule that catches the same
    condition pre-submit (DTA002/003), or a DTA9xx runtime-only code
    for data-dependent overflows and internal invariants — see
    analysis/diagnostics.CODES; tests/test_analysis.py asserts the
    mapping has no drift."""


# leg-op kinds safe to apply PER CHUNK inside the wave program: chunk-local
# ops, plus partial aggregations whose merge happens post-exchange
_WAVE_FUSABLE = {"fn", "filter", "mean_fin", "flat_tokens", "flat_map",
                 "apply", "recap", "group", "dgroup_partial",
                 "dgroup_local", "distinct"}

# whole-group kinds (group_apply/group_rank) stream through
# exec/ooc.streaming_group_whole — post-exchange bucket streams are
# key-aligned, so each device materializes complete groups; zip pairs
# per-device streams positionally (the in-memory executor's
# per-partition zip semantics); global take coordinates across the gang
# through one mirrored host allgather (_global_take).  Nothing is
# unsupported here anymore (channelinterface.h:212 — reference channels
# stream EVERY operator).
_UNSUPPORTED: Dict[str, str] = {}


class _StreamSpec:
    """Planner/graph-visible marker for a streamed store source."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec

    @property
    def capacity(self) -> int:
        return self.spec["chunk_rows"]


def has_stream_sources(source_specs: Dict[str, Dict[str, Any]]) -> bool:
    return any(s.get("kind") == "store_stream"
               for s in source_specs.values())


# ---------------------------------------------------------------------------
# per-stage results: one re-iterable ChunkSource per LOCAL device


class _DevStreams:
    def __init__(self, streams: List[Any]):
        self.streams = streams  # [dpp] ChunkSources, device-aligned

    @property
    def schema(self):
        return self.streams[0].schema

    @property
    def chunk_rows(self):
        return self.streams[0].chunk_rows


def _source_streams(spec: Dict[str, Any], mesh, config) -> _DevStreams:
    """Store partitions -> per-local-device chunk streams (partition p is
    served by global device p mod P; device-aligned so output partition
    ids line up with bucket ids)."""
    import jax

    from dryad_tpu.exec import ooc
    from dryad_tpu.io.store import store_meta

    path = spec["path"]
    chunk_rows = spec["chunk_rows"]
    P = mesh.devices.size
    nprocs = jax.process_count()
    dpp = P // nprocs
    start = jax.process_index() * dpp
    meta = store_meta(path)
    streams = []
    for d in range(dpp):
        g = start + d
        parts = [p for p in range(meta["npartitions"]) if p % P == g]
        streams.append(ooc.ChunkSource.from_store(path, chunk_rows,
                                                  partitions=parts))
    return _DevStreams(streams)


def _resident_streams(pd, mesh, config) -> _DevStreams:
    """Device-resident PData -> per-device host chunk streams (loop state
    and other in-HBM inputs joining a streamed plan)."""
    import jax

    from dryad_tpu.exec.ooc import ChunkSource
    from dryad_tpu.runtime.stream_cluster import (_read_local_shards,
                                                  local_batch_chunks)

    nprocs = jax.process_count()
    dpp = pd.nparts // nprocs
    start = jax.process_index() * dpp
    local = _read_local_shards(pd.batch, start, dpp)
    schema, chunks = local_batch_chunks(local)
    cap = max(pd.capacity, 1)
    return _DevStreams([
        ChunkSource((lambda c=c: iter([c])), schema, cap) for c in chunks])


# ---------------------------------------------------------------------------
# wave exchange


def _wave_chunk_op(b, op: StageOp, scale: int):
    """One wave-fusable op applied to a per-device chunk batch."""
    import jax.numpy as jnp

    from dryad_tpu.exec import stream_exec
    from dryad_tpu.ops import kernels

    k, p = op.kind, op.params
    no = jnp.zeros((), jnp.int32)
    if k in stream_exec._LOCAL_KINDS:
        return stream_exec._local_op(b, op, scale)
    if k == "group":
        return kernels.group_aggregate(b, list(p["keys"]),
                                       dict(p["aggs"])), no
    if k == "dgroup_partial":
        return kernels.group_decompose_partial(
            b, list(p["keys"]), p["decs"], p["box"]), no
    if k == "dgroup_local":
        return kernels.group_decompose_local(
            b, list(p["keys"]), p["decs"], p["box"]), no
    if k == "distinct":
        return kernels.distinct(b, list(p["keys"]) or None), no
    raise StreamPlanError(f"op {k!r} cannot ride a wave program",
                          code="DTA901", span=op.span)


def _build_wave_fn(mesh, leg_ops: List[StageOp], ex: Exchange,
                   chunk_rows: int, scale: int, slack: int,
                   slot_rows: int | None = None):
    """One jitted shard_map program: per-chunk leg ops + the leg's
    exchange; need channels pmax'd in-program (mirrored retries)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dryad_tpu.parallel import shuffle
    from dryad_tpu.runtime.stream_cluster import _expand, _squeeze

    axes = tuple(mesh.axis_names)
    out_cap = max(1, ex.out_capacity) * scale

    def per_shard(batch, bounds):
        b = _squeeze(batch)
        need_local = jnp.zeros((), jnp.int32)
        for op in leg_ops:
            b, need = _wave_chunk_op(b, op, scale)
            need_local = jnp.maximum(need_local, need)
        if ex.kind == "hash":
            out, nr, nsl, slot = shuffle.hash_exchange(
                b, list(ex.keys), out_cap, send_slack=slack, axes=axes,
                axis=ex.axis, slot_rows=slot_rows)
        elif ex.kind == "range":
            out, nr, nsl, slot = shuffle.range_exchange(
                b, ex.keys[0], bounds, out_cap,
                descending=ex.descending, send_slack=slack, axes=axes,
                slot_rows=slot_rows)
        elif ex.kind == "broadcast":
            out, nr, nsl = shuffle.broadcast_gather(b, out_cap, axes=axes)
            slot = jnp.zeros((), jnp.int32)
        else:
            raise StreamPlanError(f"exchange kind {ex.kind!r}",
                                  code="DTA902")
        exch_scale = (-(-nr // jnp.int32(max(1, ex.out_capacity)))
                      ).astype(jnp.int32)
        need_scale = jnp.maximum(need_local, exch_scale)
        need_scale = jax.lax.pmax(need_scale, axes)
        info = jnp.stack([need_scale, jnp.asarray(nsl, jnp.int32),
                          out.count.astype(jnp.int32),
                          jnp.asarray(slot, jnp.int32)])
        return _expand(out), info[None]

    in_specs = (P(axes), P())
    fn = jax.shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                       out_specs=(P(axes), P(axes)), check_vma=False)
    return jax.jit(fn)


def _compact_fn_for(stage: Stage):
    """Associative bucket-compaction callable from the stage's FIRST body
    group op (merging already-merged partials is sound: the merge specs
    are associative — sum of sums, min of mins, decomposable merge)."""
    from dryad_tpu.ops import kernels

    for op in stage.body:
        if op.kind == "group":
            keys, aggs = list(op.params["keys"]), dict(op.params["aggs"])
            return lambda b: kernels.group_aggregate(b, keys, aggs)
        if op.kind == "dgroup_merge":
            keys = list(op.params["keys"])
            decs, box = op.params["decs"], op.params["box"]
            return lambda b: kernels.group_decompose_merge(
                b, keys, decs, box, False)
        if op.kind == "distinct":
            keys = list(op.params["keys"]) or None
            return lambda b: kernels.distinct(b, keys)
    return None


def _run_leg_waves(dev: _DevStreams, leg_ops: List[StageOp], ex: Exchange,
                   mesh, config, bounds_arr, compact_fn, job_root: str,
                   stats=None) -> _DevStreams:
    """Lockstep chunk waves for one leg's exchange; returns per-device
    bucket streams holding ALL received rows (spilled to disk for
    unbounded kinds, RAM + compaction for group partials)."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.exec import ooc
    from dryad_tpu.exec.ooc import ChunkSource
    from dryad_tpu.runtime.stream_cluster import (_host_allgather,
                                                  _read_local_shards,
                                                  local_batch_chunks)

    nprocs = jax.process_count()
    dpp = mesh.devices.size // nprocs
    start = jax.process_index() * dpp
    chunk_rows = dev.chunk_rows
    schema = dev.schema

    # bucket schema = the EXCHANGED row schema: probe the wave ops over an
    # empty chunk (also fills decomposable treedef boxes pre-merge)
    probe_b = ooc._chunk_to_batch(ooc.HChunk.empty_like(schema), 1)
    for op in leg_ops:
        probe_b, _ = _wave_chunk_op(probe_b, op, 1)
    out_schema = ooc.chunk_schema(ooc._batch_to_chunk(probe_b))

    spill = None if compact_fn is not None else \
        tempfile.mkdtemp(prefix="wave-", dir=job_root)
    store = ooc._BucketStore(out_schema, dpp, spill_dir=spill)
    out_cap = max(1, ex.out_capacity)

    def compact_bucket(d: int) -> None:
        merged = ooc._concat_hchunks(out_schema, store.fragments(d))
        capm = 1
        while capm < max(merged.n, 1):
            capm *= 2
        out = ooc._batch_to_chunk(jax.jit(compact_fn)(
            ooc._chunk_to_batch(merged, capm)))
        if out.n > out_cap:
            raise StreamPlanError(
                f"bucket {start + d} holds {out.n} distinct groups > "
                f"exchange capacity {out_cap}; raise chunk_rows",
                code="DTA903")
        store._ram[d] = [out]

    fns: Dict[Tuple, Any] = {}
    slack = config.initial_send_slack
    scale = 1
    # measured send-slot right-sizing (DrDynamicDistributor.cpp:388 role):
    # wave 1 ships the structural slack and MEASURES the real per-slot
    # need; later waves ship exact slots (quantized to 16 rows to bound
    # recompiles) — wire bytes converge to ~useful bytes
    slot_rows: Optional[int] = None
    jbounds = jnp.asarray(bounds_arr)
    # prefetch: the NEXT wave's chunk reads/unpacks overlap the current
    # wave's collective (exec/ooc.prefetch_iter, per-device threads)
    its = [ooc.prefetch_iter(iter(cs), config.ooc_prefetch_depth, stats)
           for cs in dev.streams]
    while True:
        chunks = [next(it, None) for it in its]
        live = _host_allgather(
            np.asarray([sum(c is not None for c in chunks)], np.int32),
            mesh)
        if int(live.sum()) == 0:
            break
        for attempt in range(config.max_capacity_retries + 1):
            key = (scale, slack, slot_rows)
            fn = fns.get(key)
            if fn is None:
                fn = fns[key] = _build_wave_fn(mesh, leg_ops, ex,
                                               chunk_rows, scale, slack,
                                               slot_rows=slot_rows)
            garr = _put_aligned(chunks, schema, chunk_rows, mesh)
            out, info = fn(garr, jbounds)
            local_info = _read_local_shards(info, start, dpp)
            need_scale = int(local_info[:, 0].max())
            need_slack = int(local_info[:, 1].max())
            slot_used = int(local_info[:, 3].max())
            if need_scale == 0 and need_slack == 0:
                if ex.kind != "broadcast":
                    # steady-state exact slots for the NEXT wave (never
                    # below this wave's measured need)
                    q = max(16, -(-slot_used // 16) * 16)
                    slot_rows = max(slot_rows or 0, q)
                break
            scale = max(scale, need_scale)
            if slot_rows is not None:
                # measured mode overflowed (data drifted): resize from
                # the fresh measurement
                slot_rows = max(16, -(-slot_used // 16) * 16)
            else:
                slack = max(slack, min(need_slack, mesh.devices.size))
        else:
            raise StreamPlanError(
                "wave exchange still overflowing after "
                f"{config.max_capacity_retries} retries (scale={scale})",
                code="DTA904")
        local = _read_local_shards(out, start, dpp)
        _, wave_chunks = local_batch_chunks(local)
        for d, hc in enumerate(wave_chunks):
            if hc.n == 0:
                continue
            store.append(d, hc)
            if compact_fn is not None and store.rows(d) > out_cap:
                compact_bucket(d)
    # waves done: release the spill WRITE handles (fragments() reads by
    # name) — a long-lived worker running many streamed jobs must not
    # accumulate open fds
    store.close()

    def bucket_source(d: int) -> ChunkSource:
        # capacity-retried waves may have delivered fragments larger than
        # the declared bound — re-slice so downstream chunk programs keep
        # their static shapes
        bound = max(out_cap, chunk_rows)

        def it():
            for frag in store.fragments(d):
                for s in range(0, max(frag.n, 1), bound):
                    e = min(s + bound, frag.n)
                    if e > s:
                        yield ooc._slice_hchunk(frag, s, e)
        return ChunkSource(it, out_schema, bound)

    return _DevStreams([bucket_source(d) for d in range(dpp)])


def _put_aligned(chunks, schema, chunk_rows: int, mesh):
    """Per-device host chunks -> one global mesh batch [P, chunk_rows]
    (each process fills only its own device rows)."""
    import jax

    from dryad_tpu.data.columnar import Batch, StringColumn
    from dryad_tpu.parallel.mesh import batch_sharding

    P_total = mesh.devices.size
    nprocs = jax.process_count()
    dpp = P_total // nprocs
    start = jax.process_index() * dpp
    sharding = batch_sharding(mesh)

    local_cols: Dict[str, Any] = {}
    counts = np.asarray([c.n if c is not None else 0 for c in chunks],
                        np.int32)
    for k, spec in schema.items():
        if spec["kind"] == "str":
            L = spec["max_len"]
            sd = np.zeros((dpp, chunk_rows, L), np.uint8)
            sl = np.zeros((dpp, chunk_rows), np.int32)
            for d, c in enumerate(chunks):
                if c is not None and c.n:
                    dat, ln = c.cols[k]
                    sd[d, :c.n] = dat
                    sl[d, :c.n] = ln
            local_cols[k] = (sd, sl)
        else:
            dt = np.dtype(spec["dtype"])
            tail = tuple(spec.get("shape", ()))
            sa = np.zeros((dpp, chunk_rows) + tail, dt)
            for d, c in enumerate(chunks):
                if c is not None and c.n:
                    sa[d, :c.n] = c.cols[k]
            local_cols[k] = sa

    def put(local):
        gshape = (P_total,) + local.shape[1:]

        def cb(idx):
            s = idx[0]
            return local[s.start - start: s.stop - start]

        return jax.make_array_from_callback(gshape, sharding, cb)

    cols: Dict[str, Any] = {}
    for k, spec in schema.items():
        if spec["kind"] == "str":
            d, l = local_cols[k]
            cols[k] = StringColumn(put(d), put(l))
        else:
            cols[k] = put(local_cols[k])
    return Batch(cols, put(counts))


# ---------------------------------------------------------------------------
# leg / body streaming through the single-partition machinery


def _global_take(dev: _DevStreams, n: int, mesh) -> _DevStreams:
    """Global take over cluster streams — a REAL lowering (this used to
    be a typed DTA001 error).  Every device drains AT MOST n rows from
    its stream (the pull stops early, upstream chunks past the bound
    are never fetched); ONE mirrored host allgather of the per-device
    prefix counts then assigns device d exactly
    ``clip(n - rows_before_d, 0, local)`` rows in DEVICE-MAJOR order —
    the same order streamed ``collect()``/``to_store`` emit rows, so
    ``take(n)`` is precisely the head of the streamed output (and after
    a range-exchanged ``order_by``, the exact global top-n).  The kept
    rows are materialized on host, bounded by n per device."""
    import jax

    from dryad_tpu.exec.ooc import ChunkSource, _slice_hchunk
    from dryad_tpu.runtime.stream_cluster import _host_allgather

    dpp = len(dev.streams)
    start = jax.process_index() * dpp
    schema, chunk_rows = dev.schema, dev.chunk_rows
    frags_per_dev: List[List[Any]] = []
    counts: List[int] = []
    for cs in dev.streams:
        frags: List[Any] = []
        got = 0
        for c in cs:
            if c.n == 0:
                continue
            take = min(c.n, n - got)
            frags.append(c if take == c.n else _slice_hchunk(c, 0, take))
            got += take
            if got >= n:
                break           # stop BEFORE pulling another chunk
        frags_per_dev.append(frags)
        counts.append(got)
    allc = _host_allgather(np.asarray(counts, np.int32), mesh
                           ).reshape(-1)          # [P] device-major
    outs: List[Any] = []
    for d, frags in enumerate(frags_per_dev):
        before = int(allc[: start + d].sum())
        keep = max(0, min(n - before, counts[d]))
        kept: List[Any] = []
        acc = 0
        for c in frags:
            if acc >= keep:
                break
            t = min(c.n, keep - acc)
            kept.append(c if t == c.n else _slice_hchunk(c, 0, t))
            acc += t
        outs.append(ChunkSource(lambda ks=tuple(kept): iter(ks),
                                schema, chunk_rows))
    return _DevStreams(outs)


def _apply_leg_ops(dev: _DevStreams, ops: List[StageOp], config, job_root,
                   mesh, stats=None) -> _DevStreams:
    """Leg ops with whole-stream semantics over a stage input's
    per-device streams: chunk-local runs and per-partition globals apply
    per device through exec/stream_exec; a GLOBAL take coordinates
    across the gang eagerly (mirrored — every process walks the same
    stages in the same order, so the allgather lines up)."""
    from dryad_tpu.exec import stream_exec

    for kind, payload in stream_exec._split_leg_ops(list(ops)):
        if kind == "local":
            dev = _DevStreams([
                stream_exec._stream_local(cs, payload, config,
                                          stats=stats)
                for cs in dev.streams])
            continue
        if payload.kind in _UNSUPPORTED:
            raise StreamPlanError(
                f"op {payload.kind!r} is not supported over cluster "
                f"streams: {_UNSUPPORTED[payload.kind]}",
                code="DTA003", span=payload.span)
        if payload.kind == "take" and payload.params.get("global"):
            dev = _global_take(dev, payload.params["n"], mesh)
            continue
        dev = _DevStreams([
            stream_exec._stream_global(cs, payload, config, job_root,
                                       stats=stats)
            for cs in dev.streams])
    return dev


def _run_body(legs_out: List[_DevStreams], body: List[StageOp], config,
              job_root, mesh, stats=None) -> _DevStreams:
    """Stage body over (bucket-aligned) per-device streams; per-device
    ops stream independently, a global take coordinates via
    ``_global_take``."""
    from dryad_tpu.exec import stream_exec

    dpp = len(legs_out[0].streams)
    cur = legs_out[0]
    rest = list(legs_out[1:])
    for op in body:
        if op.kind in ("join", "apply2", "semi_anti"):
            r = rest.pop(0)
            outs = []
            for d in range(dpp):
                right_b, right_h = stream_exec._materialize_small(
                    r.streams[d], config, "right/build")
                outs.append(stream_exec._stream_local(
                    cur.streams[d], [], config, extra_right=right_b,
                    right_chunk=right_h, body_op=op, stats=stats))
            cur = _DevStreams(outs)
        elif op.kind == "concat":
            r = rest.pop(0)
            cur = _DevStreams([
                stream_exec._concat_sources(cur.streams[d], r.streams[d])
                for d in range(dpp)])
        elif op.kind == "zip":
            r = rest.pop(0)
            cur = _DevStreams([
                stream_exec._zip_sources(cur.streams[d], r.streams[d],
                                         op.params.get("suffix", "_r"))
                for d in range(dpp)])
        elif op.kind in _UNSUPPORTED:
            raise StreamPlanError(
                f"op {op.kind!r} is not supported over cluster "
                f"streams: {_UNSUPPORTED[op.kind]}",
                code="DTA003", span=op.span)
        elif op.kind == "take" and op.params.get("global"):
            cur = _global_take(cur, op.params["n"], mesh)
        elif op.kind in stream_exec._STREAM_KINDS \
                or op.kind == "dgroup_merge":
            cur = _DevStreams([
                _body_stream_global(cur.streams[d], op, config, job_root)
                for d in range(dpp)])
        elif op.kind in stream_exec._LOCAL_KINDS:
            cur = _DevStreams([
                stream_exec._stream_local(cur.streams[d], [op], config,
                                          stats=stats)
                for d in range(dpp)])
        else:
            raise StreamPlanError(
                f"op {op.kind!r} unsupported over cluster streams",
                code="DTA003", span=op.span)
    return cur


def _body_stream_global(cs, op: StageOp, config, job_root):
    from dryad_tpu.exec import stream_exec

    if op.kind == "dgroup_merge":
        # decomposable reduce-side merge over the bucket stream: merge
        # partial-state rows, finalizing per the op
        import jax

        from dryad_tpu.exec import ooc
        from dryad_tpu.ops import kernels

        keys = list(op.params["keys"])
        decs, box = op.params["decs"], op.params["box"]
        final = op.params["finalize"]

        def run(b):
            return kernels.group_decompose_merge(b, keys, decs, box, final)

        def it():
            frags = list(cs)
            merged = ooc._concat_hchunks(cs.schema, frags)
            capm = 1
            while capm < max(merged.n, 1):
                capm *= 2
            out = ooc._batch_to_chunk(jax.jit(run)(
                ooc._chunk_to_batch(merged, capm)))
            yield out

        probe = ooc._batch_to_chunk(jax.jit(run)(
            ooc._chunk_to_batch(ooc.HChunk.empty_like(cs.schema), 1)))
        return ooc.ChunkSource(it, ooc.chunk_schema(probe), cs.chunk_rows)
    return stream_exec._stream_global(cs, op, config, job_root)


# ---------------------------------------------------------------------------
# the runner


def execute_stream_plan(plan_json: str, fn_table, source_specs, mesh,
                        event_log=None, store_path: Optional[str] = None,
                        store_partitioning: Optional[Dict[str, Any]] = None,
                        collect: Any = True, config=None,
                        keep_token: Optional[str] = None,
                        release: tuple = (),
                        store_compression: Optional[str] = None):
    """Streamed counterpart of runtime/exec_common.execute_plan: same
    submission contract ((table, extras) back to the worker loop), plan
    executed as chunk waves + per-device bucket streams."""
    import jax

    from dryad_tpu.exec import ooc
    from dryad_tpu.exec.stream_exec import chunks_to_table
    from dryad_tpu.plan.serialize import graph_from_json
    from dryad_tpu.runtime import exec_common
    from dryad_tpu.runtime.stream_cluster import (_gathered_bounds,
                                                  _host_allgather,
                                                  _sample_pass,
                                                  _write_partitions)
    from dryad_tpu.utils.config import JobConfig

    config = config or JobConfig()
    ev = event_log or (lambda e: None)
    for tok in release:
        exec_common._RESIDENT.pop(tok, None)

    sources: Dict[str, Any] = {}
    for key, spec in source_specs.items():
        if spec.get("kind") == "store_stream":
            sources[key] = _StreamSpec(spec)
        elif spec.get("kind") == "resident":
            tok = spec["token"]
            from dryad_tpu.runtime.sources import MissingResidentToken
            if tok not in exec_common._RESIDENT:
                raise MissingResidentToken(tok)
            sources[key] = exec_common._RESIDENT[tok]
        else:
            from dryad_tpu.runtime.sources import build_source
            sources[key] = build_source(spec, mesh,
                                        resident=exec_common._RESIDENT)
    graph = graph_from_json(plan_json, fn_table=fn_table, sources=sources)

    nprocs = jax.process_count()
    dpp = mesh.devices.size // nprocs
    start = jax.process_index() * dpp
    job_root = tempfile.mkdtemp(prefix="dryad-splan-")

    def as_dev_streams(x) -> _DevStreams:
        if isinstance(x, _DevStreams):
            return x
        if isinstance(x, _StreamSpec):
            return _source_streams(x.spec, mesh, config)
        # device-resident PData (loop state, columns, stores)
        return _resident_streams(x, mesh, config)

    import time

    results: Dict[int, _DevStreams] = {}
    stage_stats: List[Tuple[int, Any, Dict[str, Any]]] = []
    for st in graph.topo_order():
        t0 = time.time()
        # per-stage prefetch accounting: stalls measured while this
        # stage's waves/legs drain surface on its stream_stage_done
        stats = ooc.PrefetchStats()
        legs_out: List[_DevStreams] = []
        for leg in st.legs:
            if isinstance(leg.src, int):
                src = results[leg.src]
            elif leg.src[0] == "source":
                src = as_dev_streams(leg.src[1])
            else:
                raise StreamPlanError(
                    "placeholders are not supported in streamed cluster "
                    "plans (do_while ships loop state as residents)",
                    code="DTA002")
            src = as_dev_streams(src)
            if leg.exchange is None:
                legs_out.append(_apply_leg_ops(src, list(leg.ops),
                                               config, job_root, mesh,
                                               stats=stats))
                continue
            # split leg ops: whole-stream prefix runs host-side per
            # device; the trailing wave-fusable suffix rides the program
            ops = list(leg.ops)
            cut = len(ops)
            while cut > 0 and ops[cut - 1].kind in _WAVE_FUSABLE:
                cut -= 1
            pre, fus = ops[:cut], ops[cut:]
            pre_dev = src
            if pre:
                pre_dev = _apply_leg_ops(src, pre, config, job_root,
                                         mesh, stats=stats)
            bounds = np.zeros((0,), np.uint32)
            if leg.exchange.kind == "range":
                # sampled global quantile bounds (DryadLinqSampler.cs:42
                # role) from the exchange's own input streams
                samples = []
                for cs in pre_dev.streams:
                    s, _, _ = _sample_pass(cs, leg.exchange.bounds_key
                                           or leg.exchange.keys[0])
                    samples.append(s)
                merged = (np.concatenate(samples) if samples
                          else np.zeros((0,), np.uint32))
                from dryad_tpu.runtime.stream_cluster import _MAX_SAMPLES
                if len(merged) > _MAX_SAMPLES:
                    merged = merged[np.linspace(
                        0, len(merged) - 1,
                        _MAX_SAMPLES).astype(np.int64)]
                bounds = _gathered_bounds(merged, mesh,
                                          mesh.devices.size)
            compact = _compact_fn_for(st) if any(
                o.kind in ("group", "dgroup_partial", "dgroup_local")
                for o in fus) else None
            legs_out.append(_run_leg_waves(pre_dev, fus, leg.exchange,
                                           mesh, config, bounds, compact,
                                           job_root, stats=stats))
        out = _run_body(legs_out, list(st.body), config, job_root, mesh,
                        stats=stats)
        results[st.id] = out
        snap = stats.snapshot()
        ev({"event": "stream_stage_done", "stage": st.id,
            "label": st.label, "wall_s": round(time.time() - t0, 4),
            "prefetch_stalls": snap["stalls"],
            "prefetch_stall_s": snap["stall_s"]})
        if snap["stalls"]:
            ev({"event": "prefetch_stall", "stage": st.id, **snap})
        # exchange-free stages compose LAZY streams: their prefetchers
        # stall later, when the final drain (or a downstream stage's
        # waves) actually pulls — keep the stats object so those late
        # stalls can be reported after the drain instead of lost
        stage_stats.append((st.id, stats, snap))

    final = results[graph.out_stage]
    extras: Dict[str, Any] = {}

    drained: Optional[List[List[Any]]] = None

    def drain() -> List[List[Any]]:
        nonlocal drained
        if drained is None:
            drained = [list(cs) for cs in final.streams]
        return drained

    if keep_token is not None:
        # materialize the (small: loop state / cached) result as gang-
        # resident PData with MIRRORED capacity (allgathered max)
        from dryad_tpu.exec.data import PData

        chunks = [ooc._concat_hchunks(final.schema, frags)
                  for frags in drain()]
        local_max = max([c.n for c in chunks] + [1])
        gmax = int(_host_allgather(
            np.asarray([local_max], np.int32), mesh).max())
        capm = 1
        while capm < gmax:
            capm *= 2
        batch = _put_aligned(chunks, final.schema, capm, mesh)
        pd = PData(batch, mesh.devices.size)
        exec_common._RESIDENT[keep_token] = pd
        extras["resident_capacity"] = pd.capacity

    table = None
    if collect == "count":
        # >HBM row counts exceed int32, and jax without x64 silently
        # truncates int64 arrays — ship (hi, lo) uint32 lanes
        local = sum(c.n for frags in drain() for c in frags)
        arr = np.asarray([[local >> 32, local & 0xFFFFFFFF]], np.uint32)
        allc = _host_allgather(arr, mesh).astype(np.uint64)
        table = int(sum((int(h) << 32) | int(l)
                        for h, l in allc.reshape(-1, 2)))
    elif collect:
        merged: List[Any] = [c for frags in drain() for c in frags]
        cs = ooc.ChunkSource(lambda: iter(merged), final.schema,
                             max(final.chunk_rows, 1))
        table = chunks_to_table(cs)
    if store_path is not None:
        part_chunks = drain()
        part_ids = list(range(start, start + dpp))
        _write_partitions(store_path, final.schema, part_chunks, part_ids,
                          mesh, final.chunk_rows,
                          partitioning=store_partitioning,
                          compression=store_compression,
                          capacity=final.chunk_rows)

    # late stalls: every consumer path above has drained by now — emit
    # the per-stage delta beyond what the stage's own stream_stage_done
    # already carried (obs/analyze folds prefetch_stall events into the
    # report TOTALS only, so this cannot double-count stage rows)
    for sid, stats, snap in stage_stats:
        late = stats.snapshot()
        d_stalls = late["stalls"] - snap["stalls"]
        if d_stalls > 0:
            ev({"event": "prefetch_stall", "stage": sid,
                "stalls": d_stalls,
                "stall_s": round(late["stall_s"] - snap["stall_s"], 6),
                "chunks": late["chunks"], "late": True})

    import shutil
    shutil.rmtree(job_root, ignore_errors=True)
    return table, extras
