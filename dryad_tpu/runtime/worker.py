"""Per-node worker service: ``python -m dryad_tpu.runtime.worker``.

The counterpart of the reference's per-node daemon
(ProcessService/ProcessService.cs:389 — a process the submission layer
starts on every machine, which then executes vertex commands from the GM).
Here each worker joins a jax.distributed job (gloo on CPU, ICI/DCN on real
TPU pods), connects back to the driver's control socket, and executes
submitted plans SPMD until told to stop."""

from __future__ import annotations

import argparse
import os
import socket
import traceback


def _configure_jax(platform: str, devices_per_process: int) -> None:
    if platform != "cpu":
        # real accelerators: leave the backend choice to the environment
        # (one worker per TPU host; local chips are the "dp" axis)
        return
    flags = os.environ.get("XLA_FLAGS", "")
    kept = [f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(
        f"--xla_force_host_platform_device_count={devices_per_process}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    os.environ["JAX_PLATFORMS"] = "cpu"


def _tag_missing_token(reply: dict, exc: BaseException) -> None:
    """Copy a MissingResidentToken's token into the error reply as
    STRUCTURED data (the driver's resident healing keys off this field,
    not the traceback text — ADVICE r3)."""
    from dryad_tpu.runtime.sources import MissingResidentToken
    if isinstance(exc, MissingResidentToken):
        reply["missing_token"] = exc.token


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--control", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--devices-per-process", type=int, default=1)
    ap.add_argument("--fn-module", action="append", default=[])
    ap.add_argument("--platform", default="default",
                    help="'cpu' forces N virtual CPU devices (local test "
                         "topology); 'default' uses the environment's "
                         "backend (real TPU hosts)")
    ap.add_argument("--standalone", action="store_true",
                    help="elastic (control-plane-only) worker: no "
                         "jax.distributed membership — serves farm tasks "
                         "on its local devices, refuses gang SPMD jobs "
                         "(reference dynamic computer registration, "
                         "LocalScheduler/Queues.cs:104-137)")
    args = ap.parse_args(argv)

    _configure_jax(args.platform, args.devices_per_process)
    # worker identity for subsystems outside jax.distributed (standalone
    # elastic workers have process_count==1 — profiler traces etc. still
    # need per-worker attribution)
    os.environ["DRYAD_WORKER_ID"] = str(args.process_id)
    import jax
    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if not args.standalone:
        jax.distributed.initialize(coordinator_address=args.coordinator,
                                   num_processes=args.num_processes,
                                   process_id=args.process_id)

    from dryad_tpu.parallel.mesh import make_mesh
    from dryad_tpu.runtime import protocol
    # cross-process boundary = the "dcn" axis; in-process devices = "dp"
    mesh = make_mesh(hosts=args.num_processes
                     if args.num_processes > 1 and not args.standalone
                     else None)

    # snapshot the spawning driver's pid NOW — by the time a severed socket
    # is observed the kernel may already have reparented us, and a late
    # getppid() would capture pid 1 and linger forever
    parent_pid = os.getppid()
    host, port = args.control.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)))
    # answer the driver's HMAC challenge BEFORE any pickled traffic; the
    # secret arrives out-of-band (env for local spawns, a 0600 staged
    # file for ssh — see protocol.load_secret_from_env)
    protocol.client_authenticate(sock, protocol.load_secret_from_env())
    import threading
    send_lock = threading.Lock()   # reply thread + heartbeat thread
    protocol.send_msg(sock, {"hello": args.process_id,
                             "devices": jax.device_count()})

    def _send_reply(obj) -> bool:
        """Send a control reply; on a severed socket (the driver retired
        this worker, runtime/cluster.py retire_worker) return False
        instead of crashing the process."""
        try:
            with send_lock:
                protocol.send_msg(sock, obj)
            return True
        except OSError:
            return False

    def _heartbeat(job, interval: float, stop: "threading.Event"):
        """Progress frames while a gang job executes: the driver's
        straggler watchdog (runtime/cluster.py) distinguishes a WEDGED
        worker (frozen process — heartbeats stop) from a busy one
        (heartbeats flow even while blocked in a collective, since this
        thread runs regardless).  Reference role: vertex status updates
        feeding DrStageStatistics (DrVertex.h:195 duplicate-on-slow)."""
        while not stop.wait(interval):
            if not _send_reply({"hb": args.process_id, "job": job}):
                return

    lost_control = False
    while True:
        try:
            msg = protocol.recv_msg(sock)
        except (EOFError, OSError):
            lost_control = True
            break
        cmd = msg.get("cmd")
        if cmd == "stop":
            _send_reply({"bye": args.process_id})
            break
        if cmd == "ping":
            # echo the job tag: a pong proves the worker has DRAINED all
            # prior work queued on its socket (the farm's idle gate)
            if not _send_reply({"pong": args.process_id,
                                "job": msg.get("job")}):
                lost_control = True
                break
            continue
        if cmd == "run_task":
            # independent per-partition task on the LOCAL device mesh (no
            # cross-process collectives) — the freely duplicable /
            # reassignable unit of the task farm (runtime/farm.py;
            # reference DrVertex::RequestDuplicate)
            import time as _time

            from dryad_tpu.obs import flight as _flight
            from dryad_tpu.obs import profile as _profile
            from dryad_tpu.obs import trace as _trace

            reply = {"ok": True, "pid": args.process_id,
                     "task": msg.get("task"), "job": msg.get("job")}
            events: list = []

            def _ev(e, _events=events):
                # stamp the emission time HERE: the driver only forwards
                # these after the reply, and a late setdefault would skew
                # every viewer/Gantt timestamp by the task wall
                e = dict(e, ts=round(_time.time(), 4))
                _events.append(e)
                # the flight ring keeps recent events across TASKS, so
                # a later failure's forensics bundle carries the lead-up
                _flight.record(e)

            # adopt the driver's trace context for this task only: our
            # task/stage/io spans parent-link into the dispatch span
            # riding the envelope (protocol.TRACE_CTX).  The SUBMITTING
            # DRIVER decides tracing for the whole job — trace_ctx
            # presence carries its verdict, so an untraced driver costs
            # zero span work here too; the resource sampler follows the
            # same verdict (plus its own JobConfig.resource_sample_s
            # gate)
            _tctx = protocol.extract_trace(msg)
            _evs = _trace.leveled(_ev, 2 if _tctx is not None else 0)
            _sampler = _profile.start(
                _ev if _tctx is not None else None,
                getattr(msg.get("config"), "resource_sample_s", 0.0)
                or 0.0,
                worker_pid=args.process_id, task=msg.get("task"))
            try:
                with _trace.tracing(_evs, _tctx), \
                        _trace.span(f"task {msg.get('task')}", "task",
                                    task=msg.get("task"),
                                    job=msg.get("job"),
                                    worker_pid=args.process_id):
                    if msg.get("delay_s"):
                        _time.sleep(msg["delay_s"])
                    from dryad_tpu.exec.data import (
                        maybe_shrink_for_collect, pdata_to_host)
                    from dryad_tpu.exec.executor import Executor
                    from dryad_tpu.plan.serialize import graph_from_json
                    from dryad_tpu.runtime.shiplan import resolve_fn_table
                    from dryad_tpu.runtime.sources import build_source
                    global _LOCAL
                    try:
                        local_mesh, local_ex = _LOCAL
                    except NameError:
                        local_mesh = make_mesh(devices=jax.local_devices())
                        local_ex = Executor(local_mesh)
                        # a farm task is one slice of the driver's job,
                        # not a job: its Run must not emit job_done
                        # (exec/recovery.py) or dryad_jobs_total would
                        # count every task
                        local_ex._emit_job_done = False
                        _LOCAL = (local_mesh, local_ex)
                    cfg = msg.get("config")
                    local_ex.apply_config(cfg)
                    local_ex._event = _evs
                    fn_table = resolve_fn_table(msg["plan"],
                                                args.fn_module)
                    sources = {key: build_source(spec, local_mesh)
                               for key, spec in msg["sources"].items()}
                    graph = graph_from_json(msg["plan"],
                                            fn_table=fn_table,
                                            sources=sources)
                    pd = local_ex.run(graph)
                    # adaptive rewrites applied inside this task's run
                    # (JobConfig.adaptive rides the shipped config);
                    # the farm folds the count into task_done
                    _rw = getattr(local_ex, "_last_run_rewrites", 0)
                    if _rw:
                        reply["rewrites"] = _rw
                    reply["table"] = pdata_to_host(
                        maybe_shrink_for_collect(pd, config=cfg))
            except Exception as e:
                reply = {"ok": False, "pid": args.process_id,
                         "task": msg.get("task"), "job": msg.get("job"),
                         "error": traceback.format_exc()}
                # ship the flight recorder's forensics bundle with the
                # error: the driver persists it and `python -m
                # dryad_tpu.obs replay` reproduces this failure locally.
                # Best-effort — forensics must never mask the error.
                try:
                    protocol.attach_forensics(
                        reply, _flight.capture_bundle(
                            msg, e, kind="task",
                            worker=args.process_id,
                            fn_modules=args.fn_module, events=events))
                except Exception:
                    pass
            finally:
                _profile.stop(_sampler)
            reply["events"] = events
            if not _send_reply(reply):
                lost_control = True
                break
            continue
        if args.standalone and cmd == "run":
            # gang SPMD jobs need jax.distributed membership, which a
            # mid-life joiner cannot acquire without a gang restart —
            # elastic workers serve independently schedulable farm tasks
            if not _send_reply({"ok": False, "pid": args.process_id,
                                "job": msg.get("job"),
                                "error": "standalone (elastic) worker "
                                         "cannot join gang SPMD jobs"}):
                lost_control = True
                break
            continue
        if cmd == "run":
            import time as _time

            from dryad_tpu.obs import flight as _flight
            from dryad_tpu.obs import profile as _profile
            from dryad_tpu.obs import trace as _trace

            events: list = []

            def _ev(e, _events=events):
                # emission-time stamp (see run_task): forwarded events
                # must carry the time they happened, not arrival time
                e = dict(e, ts=round(_time.time(), 4))
                _events.append(e)
                _flight.record(e)

            reply: dict = {"ok": True, "pid": args.process_id,
                           "job": msg.get("job")}
            hb_stop = threading.Event()
            hb_every = float(msg.get("hb_every") or 0)
            hb_thread = None
            if hb_every > 0:
                hb_thread = threading.Thread(
                    target=_heartbeat,
                    args=(msg.get("job"), hb_every, hb_stop), daemon=True)
                hb_thread.start()
            # trace_ctx presence = the driver's tracing verdict (see
            # run_task); the resource sampler follows it too
            _tctx = protocol.extract_trace(msg)
            _evs = _trace.leveled(_ev, 2 if _tctx is not None else 0)
            _sampler = _profile.start(
                _ev if _tctx is not None else None,
                getattr(msg.get("config"), "resource_sample_s", 0.0)
                or 0.0,
                worker_pid=args.process_id, job=msg.get("job"))
            try:
                from dryad_tpu.runtime.exec_common import execute_plan
                from dryad_tpu.runtime.shiplan import resolve_fn_table
                fn_table = resolve_fn_table(msg["plan"], args.fn_module)
                collect = msg.get("collect", True)
                with _trace.tracing(_evs, _tctx):
                    table, extras = execute_plan(
                        msg["plan"], fn_table, msg["sources"], mesh,
                        event_log=_evs,
                        store_path=msg.get("store_path"),
                        store_partitioning=msg.get("store_partitioning"),
                        collect=collect, config=msg.get("config"),
                        keep_token=msg.get("keep_token"),
                        release=tuple(msg.get("release") or ()),
                        store_compression=msg.get("store_compression"))
                reply.update(extras)
                if collect == "count":
                    if args.process_id == 0:
                        reply["table"] = table
                elif collect:
                    # every worker ships ITS partitions' rows (parallel
                    # collect); the driver concatenates parts in pid order
                    reply["table_part"] = table
                # test hook ("pid:seconds"): delay ONE worker's reply
                # while its heartbeats keep flowing — how the watchdog
                # tests exercise the busy-vs-frozen distinction (a slow
                # member must NOT be declared wedged while demonstrably
                # alive)
                _spec = os.environ.get("DRYAD_TEST_REPLY_DELAY", "")
                if _spec:
                    _pid, _, _secs = _spec.partition(":")
                    if int(_pid) == args.process_id:
                        import time as _t
                        _t.sleep(float(_secs))
            except Exception as e:
                reply = {"ok": False, "pid": args.process_id,
                         "job": msg.get("job"),
                         "error": traceback.format_exc()}
                _tag_missing_token(reply, e)
                try:
                    protocol.attach_forensics(
                        reply, _flight.capture_bundle(
                            msg, e, kind="job",
                            worker=args.process_id,
                            fn_modules=args.fn_module, events=events))
                except Exception:
                    pass
            finally:
                _profile.stop(_sampler)
                hb_stop.set()
                if hb_thread is not None:
                    hb_thread.join(timeout=5)
            reply["events"] = events
            if not _send_reply(reply):
                lost_control = True
                break
            continue
        if not _send_reply({"ok": False, "pid": args.process_id,
                            "error": f"unknown command {cmd!r}"}):
            lost_control = True
            break
    sock.close()
    if lost_control:
        # the driver retired us (severed socket) but the gang is still
        # running: exiting now would kill our jax.distributed client (and,
        # for process 0, the coordinator itself), cascading heartbeat
        # failures through the surviving workers mid-farm.  Linger until
        # the driver's gang restart kills us — or until we are orphaned.
        import time as _time
        while os.getppid() == parent_pid:
            _time.sleep(1.0)
        return 0
    if not args.standalone:
        jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
