"""Per-node worker service: ``python -m dryad_tpu.runtime.worker``.

The counterpart of the reference's per-node daemon
(ProcessService/ProcessService.cs:389 — a process the submission layer
starts on every machine, which then executes vertex commands from the GM).
Here each worker joins a jax.distributed job (gloo on CPU, ICI/DCN on real
TPU pods), connects back to the driver's control socket, and executes
submitted plans SPMD until told to stop."""

from __future__ import annotations

import argparse
import os
import socket
import traceback


def _configure_jax(platform: str, devices_per_process: int) -> None:
    if platform != "cpu":
        # real accelerators: leave the backend choice to the environment
        # (one worker per TPU host; local chips are the "dp" axis)
        return
    flags = os.environ.get("XLA_FLAGS", "")
    kept = [f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(
        f"--xla_force_host_platform_device_count={devices_per_process}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    os.environ["JAX_PLATFORMS"] = "cpu"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--control", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--devices-per-process", type=int, default=1)
    ap.add_argument("--fn-module", action="append", default=[])
    ap.add_argument("--platform", default="default",
                    help="'cpu' forces N virtual CPU devices (local test "
                         "topology); 'default' uses the environment's "
                         "backend (real TPU hosts)")
    args = ap.parse_args(argv)

    _configure_jax(args.platform, args.devices_per_process)
    import jax
    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coordinator,
                               num_processes=args.num_processes,
                               process_id=args.process_id)

    from dryad_tpu.parallel.mesh import make_mesh
    from dryad_tpu.runtime import protocol
    # cross-process boundary = the "dcn" axis; in-process devices = "dp"
    mesh = make_mesh(hosts=args.num_processes
                     if args.num_processes > 1 else None)

    host, port = args.control.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)))
    protocol.send_msg(sock, {"hello": args.process_id,
                             "devices": jax.device_count()})

    while True:
        try:
            msg = protocol.recv_msg(sock)
        except EOFError:
            break
        cmd = msg.get("cmd")
        if cmd == "stop":
            protocol.send_msg(sock, {"bye": args.process_id})
            break
        if cmd == "ping":
            protocol.send_msg(sock, {"pong": args.process_id})
            continue
        if cmd == "run_task":
            # independent per-partition task on the LOCAL device mesh (no
            # cross-process collectives) — the freely duplicable /
            # reassignable unit of the task farm (runtime/farm.py;
            # reference DrVertex::RequestDuplicate)
            import time as _time

            reply = {"ok": True, "pid": args.process_id,
                     "task": msg.get("task"), "job": msg.get("job")}
            try:
                if msg.get("delay_s"):
                    _time.sleep(msg["delay_s"])
                from dryad_tpu.exec.data import (maybe_shrink_for_collect,
                                                 pdata_to_host)
                from dryad_tpu.exec.executor import Executor
                from dryad_tpu.plan.serialize import graph_from_json
                from dryad_tpu.runtime.shiplan import resolve_fn_table
                from dryad_tpu.runtime.sources import build_source
                global _LOCAL
                try:
                    local_mesh, local_ex = _LOCAL
                except NameError:
                    local_mesh = make_mesh(devices=jax.local_devices())
                    local_ex = Executor(local_mesh)
                    _LOCAL = (local_mesh, local_ex)
                fn_table = resolve_fn_table(msg["plan"], args.fn_module)
                sources = {key: build_source(spec, local_mesh)
                           for key, spec in msg["sources"].items()}
                graph = graph_from_json(msg["plan"], fn_table=fn_table,
                                        sources=sources)
                pd = local_ex.run(graph)
                reply["table"] = pdata_to_host(
                    maybe_shrink_for_collect(pd))
            except Exception:
                reply = {"ok": False, "pid": args.process_id,
                         "task": msg.get("task"), "job": msg.get("job"),
                         "error": traceback.format_exc()}
            protocol.send_msg(sock, reply)
            continue
        if cmd == "run":
            events: list = []
            reply: dict = {"ok": True, "pid": args.process_id,
                           "job": msg.get("job")}
            try:
                from dryad_tpu.runtime.exec_common import execute_plan
                from dryad_tpu.runtime.shiplan import resolve_fn_table
                fn_table = resolve_fn_table(msg["plan"], args.fn_module)
                collect = msg.get("collect", True)
                table = execute_plan(
                    msg["plan"], fn_table, msg["sources"], mesh,
                    event_log=events.append,
                    store_path=msg.get("store_path"),
                    store_partitioning=msg.get("store_partitioning"),
                    collect=collect, config=msg.get("config"))
                if args.process_id == 0 and collect:
                    reply["table"] = table
            except Exception:
                reply = {"ok": False, "pid": args.process_id,
                         "job": msg.get("job"),
                         "error": traceback.format_exc()}
            reply["events"] = events
            protocol.send_msg(sock, reply)
            continue
        protocol.send_msg(sock, {"ok": False, "pid": args.process_id,
                                 "error": f"unknown command {cmd!r}"})
    sock.close()
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
