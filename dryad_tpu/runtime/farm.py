"""Task-farm scheduling with straggler speculation.

The counterpart of the reference's per-vertex scheduling + speculative
duplication: DrStageStatistics fits robust completion statistics and
requests duplicates for outliers (DrStageStatistics.cpp:403-534, capped
at 20% duplication), DrVertex::RequestDuplicate reruns the vertex
elsewhere, first finisher wins, and a failed machine only costs the
vertices that ran there (ReactToFailedVertex).

Gang-SPMD stages cannot speculate one shard (every collective is a
barrier), so speculation lives where tasks ARE independent: map-style
per-partition tasks farmed over the worker processes.  Each task runs on
one worker's LOCAL device mesh (no cross-process collectives), so tasks
are freely duplicable, reassignable, and survive the loss of any worker
without a gang restart.
"""

from __future__ import annotations

import select
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

from dryad_tpu.runtime import protocol
from dryad_tpu.runtime.cluster import WorkerFailure

__all__ = ["TaskFarm", "FarmError"]


class FarmError(RuntimeError):
    pass


def _norm_host(h: str) -> str:
    """Case-insensitive, FQDN-insensitive host matching: block reports
    may say ``dn0.cluster.local`` while the worker registered ``dn0``.
    IP addresses keep all their dots (stripping ``10.0.0.4`` to ``10``
    would collide every same-subnet worker)."""
    h = h.strip().lower()
    first = h.split(".", 1)[0]
    return h if first.isdigit() else first


class _Task:
    __slots__ = ("idx", "sources", "runs", "delays", "result", "duplicated",
                 "pref", "spans", "dup_pid")

    def __init__(self, idx: int, sources: Dict[str, Dict[str, Any]],
                 host_pids: Dict[str, set]):
        self.idx = idx
        self.sources = sources
        self.runs: Dict[int, float] = {}   # worker -> dispatch time
        self.delays: Dict[int, float] = {}  # worker -> commanded test delay
        self.spans: Dict[int, Any] = {}    # worker -> open dispatch span
        self.result: Optional[Dict[str, Any]] = None
        self.duplicated = False
        self.dup_pid: Optional[int] = None   # the speculative copy's pid
        # soft locality hints from the task's source specs: an explicit
        # worker pid (the worker that wrote/holds the store partitions)
        # and/or block-holding HOST names (hdfs GETFILEBLOCKLOCATIONS
        # metadata) resolved to worker pids through the cluster's
        # worker->host map (Interfaces.cs:98-152 affinity-list role).
        # Unknown hosts resolve to nothing — a hint can never make a
        # task undispatchable.
        self.pref: set = set()
        for s in sources.values():
            if not isinstance(s, dict):
                continue
            if s.get("preferred_worker") is not None:
                self.pref.add(s["preferred_worker"])
            for h in (s.get("preferred_hosts") or ()):
                self.pref |= host_pids.get(_norm_host(h), set())


class TaskFarm:
    """Farm one plan over many independent per-task sources.

    ``run(plan_json, per_task_sources)`` executes the SAME plan once per
    task, each with its own source bindings, and returns the per-task host
    tables in task order.  Straggler speculation: once ``min_samples``
    tasks have completed, a running task whose elapsed time exceeds
    median + max(sigma * 1.4826 * MAD, rel_margin * median, abs_margin)
    is duplicated onto an idle worker (at most ``duplication_budget`` of
    the task count, the reference's 20% cap); the first finisher wins.
    A dead worker's in-flight tasks are reassigned, not failed.
    """

    def __init__(self, cluster, duplication_budget: Optional[float] = None,
                 outlier_sigma: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 rel_margin: Optional[float] = None,
                 abs_margin_s: Optional[float] = None,
                 config=None,
                 delay_hook: Optional[Callable[[int, int], float]] = None,
                 worker_hosts: Optional[Dict[int, str]] = None,
                 job_label: Optional[str] = None):
        from dryad_tpu.utils.config import JobConfig
        cfg = config or JobConfig()
        self.config = cfg
        self.cluster = cluster
        # per-job metric namespacing (obs/metrics.PER_JOB_FAMILIES): when
        # the caller names the job (the service daemon always does), the
        # queue-depth gauge and task histogram carry a job label so
        # concurrent jobs' scrapes never merge; unset = the historical
        # unlabeled families
        self.job_label = job_label
        self._job_labels = ({"job": job_label} if job_label is not None
                            else {})
        self.duplication_budget = (
            duplication_budget if duplication_budget is not None
            else (cfg.speculation_duplication_budget
                  if cfg.speculation_enabled else 0.0))
        self.outlier_sigma = (outlier_sigma if outlier_sigma is not None
                              else cfg.speculation_outlier_sigma)
        self.min_samples = (min_samples if min_samples is not None
                            else (cfg.speculation_min_samples
                                  if cfg.speculation_enabled else 10**9))
        self.rel_margin = (rel_margin if rel_margin is not None
                           else cfg.speculation_rel_margin)
        self.abs_margin_s = (abs_margin_s if abs_margin_s is not None
                             else cfg.speculation_abs_margin_s)
        self.task_timeout_s = cfg.farm_task_timeout_s
        # test hook: delay_hook(task_idx, worker_id) -> seconds the worker
        # should sleep before executing (simulates a slow machine)
        self.delay_hook = delay_hook
        # worker pid -> machine name, for resolving block->host locality
        # hints (source spec ``preferred_hosts``) to dispatchable workers;
        # defaults to the cluster's own map (LocalCluster: every worker on
        # this machine; SshCluster: the per-worker remote host)
        self.worker_hosts = worker_hosts
        self.events: List[dict] = []

    def _emit(self, e: dict) -> None:
        self.events.append(e)
        if self.cluster.event_log is not None:
            self.cluster.event_log(dict(e))

    def _persist_forensics(self, reply: dict):
        """Persist a failing reply's flight-recorder bundle and emit
        the task_forensics breadcrumb (obs/flight.py); returns the
        bundle path (None when the reply carries no bundle)."""
        from dryad_tpu.obs import flight
        return flight.persist_reply_forensics(
            reply, self.config, self.cluster.event_log, self._emit)

    # -- scheduling --------------------------------------------------------

    def _threshold(self, durations: List[float]) -> Optional[float]:
        if len(durations) < self.min_samples:
            return None
        med = statistics.median(durations)
        mad = statistics.median([abs(d - med) for d in durations])
        margin = max(self.outlier_sigma * 1.4826 * mad,
                     self.rel_margin * med, self.abs_margin_s)
        return med + margin

    def run(self, plan_json: str,
            per_task_sources: List[Dict[str, Dict[str, Any]]],
            timeout: Optional[float] = None,
            task_timeout_s: Optional[float] = None
            ) -> List[Dict[str, Any]]:
        """``timeout`` bounds the whole farm run (None = unbounded);
        ``task_timeout_s`` overrides JobConfig.farm_task_timeout_s for
        legitimately slow tasks."""
        from dryad_tpu.obs import trace
        from dryad_tpu.obs.metrics import REGISTRY, family_gauge

        cl = self.cluster
        if not cl.alive():
            cl.restart()
        job = cl.next_job_id()
        # the farm span roots every per-dispatch sched span; its context
        # rides each task envelope to the workers (runtime/protocol
        # TRACE_CTX), so worker task/stage/io spans link back here.  It
        # must finish on EVERY exit path or the sched spans it parents
        # would dangle in the stream.  The sink inherits the attached
        # EventLog's level — and with NO log attached, level 0: no
        # consumer means zero span work, and no trace_ctx means the
        # workers skip theirs too.
        tsink = trace.leveled(self._emit,
                              getattr(cl.event_log, "level", None)
                              if cl.event_log is not None else 0)
        queue_gauge = family_gauge(REGISTRY, "queue_depth",
                                   **self._job_labels)
        farm_span = trace.start("farm", "farm", sink=tsink,
                                job=job, tasks=len(per_task_sources))
        # driver-side resource sampler for the farm's duration (workers
        # run their own per-task samplers); gated by the same sink level
        # as the spans, so an untraced farm starts no thread
        from dryad_tpu.obs import profile as _profile
        sampler = _profile.start(
            tsink, getattr(self.config, "resource_sample_s", 0.0) or 0.0,
            role="driver", job=job)
        try:
            out = self._run(plan_json, per_task_sources, timeout,
                            task_timeout_s, job, farm_span, tsink,
                            queue_gauge)
        except BaseException as e:
            trace.finish(farm_span, error=type(e).__name__)
            raise
        finally:
            _profile.stop(sampler)
            # an idle farm has no queue — a stale depth would misfire
            # any dashboard alerting on it
            queue_gauge.set(0)
        trace.finish(farm_span, done=len(out))
        return out

    def _run(self, plan_json: str,
             per_task_sources: List[Dict[str, Dict[str, Any]]],
             timeout: Optional[float], task_timeout_s: Optional[float],
             job: int, farm_span, tsink, queue_gauge
             ) -> List[Dict[str, Any]]:
        from dryad_tpu.obs import trace
        from dryad_tpu.obs.metrics import REGISTRY, family_histogram

        cl = self.cluster
        task_hist = family_histogram(REGISTRY, "task_seconds",
                                     **self._job_labels)
        hosts = (self.worker_hosts if self.worker_hosts is not None
                 else (cl.worker_hosts()
                       if hasattr(cl, "worker_hosts") else {}))
        host_pids: Dict[str, set] = {}
        for pid, h in hosts.items():
            host_pids.setdefault(_norm_host(h), set()).add(pid)
        tasks = [_Task(i, s, host_pids)
                 for i, s in enumerate(per_task_sources)]
        todo: List[_Task] = list(tasks)
        n_done = 0
        durations: List[float] = []
        # 0 budget = speculation off; otherwise floor at one duplicate so
        # small farms can still speculate (the fraction cap is the
        # reference's 20% rule, DrStageStatistics.cpp)
        dup_cap = (0 if self.duplication_budget <= 0
                   else max(1, int(self.duplication_budget * len(tasks))))
        dups_used = 0
        # a worker is idle only once it answers THIS job's ping: a pong
        # proves it drained any still-running losing duplicate from a
        # previous farm run, so per-task timers never include stale queue
        # time (which would falsely retire a healthy worker)
        idle: set = set()
        ping_t: Dict[int, float] = {}
        for pid in list(cl.sockets):
            sock = cl.sockets[pid]
            try:
                sock.setblocking(True)
                protocol.send_msg(sock, {"cmd": "ping", "job": job})
                sock.setblocking(False)
                ping_t[pid] = time.time()
            except OSError:
                pass   # handled as dead below
        dead: set = set()
        running: Dict[int, _Task] = {}   # worker -> task
        # overall farm deadline only when the caller passes one explicitly;
        # the config knob is PER-TASK (reference per-vertex semantics) and
        # is enforced against each dispatched run below
        deadline = None if timeout is None else time.time() + timeout
        task_timeout = (task_timeout_s if task_timeout_s is not None
                        else self.task_timeout_s)

        def dispatch(task: _Task, pid: int) -> bool:
            delay = (self.delay_hook(task.idx, pid)
                     if self.delay_hook else 0.0)
            sock = cl.sockets[pid]
            # driver-side dispatch span: covers queue + wire + worker
            # execution; the worker's own task span (child) subtracts to
            # the queue/transit share (obs/critical_path.py)
            sp = trace.start(f"task {task.idx}", "sched",
                             parent=farm_span, sink=tsink,
                             task=task.idx, worker=pid)
            try:
                sock.setblocking(True)
                protocol.send_msg(sock, protocol.attach_trace(
                    protocol.attach_job(
                        {"cmd": "run_task", "plan": plan_json,
                         "sources": task.sources,
                         "task": task.idx,
                         "config": self.config, "delay_s": delay}, job),
                    trace.ctx_of(sp if sp is not None else farm_span)))
                sock.setblocking(False)
            except OSError:
                trace.finish(sp, error="dispatch_failed")
                worker_lost(pid)
                return False
            task.runs[pid] = time.time()
            task.delays[pid] = delay
            if sp is not None:
                task.spans[pid] = sp
            running[pid] = task
            idle.discard(pid)
            return True

        n_workers_total = len(cl.sockets)   # gang + elastic at farm start

        def worker_lost(pid: int) -> None:
            dead.add(pid)
            idle.discard(pid)
            task = running.pop(pid, None)
            if task is not None:
                trace.finish(task.spans.pop(pid, None), error="worker_lost")
            if (task is not None and task.result is None
                    and task not in todo):
                task.runs.pop(pid, None)
                todo.insert(0, task)
                self._emit({"event": "task_reassigned", "task": task.idx,
                            "worker": pid})
            if len(dead) >= n_workers_total:
                raise WorkerFailure(
                    "all workers died during task farm" + cl.log_tails())

        while n_done < len(tasks):
            queue_gauge.set(len(todo))
            if deadline is not None and time.time() > deadline:
                raise FarmError(
                    f"task farm timed out; {len(tasks) - n_done} tasks "
                    f"unfinished")
            # per-task timeout: a run stuck past the task timeout means its
            # worker is wedged — retire that worker (the reference abandons
            # the vertex's process, ReactToFailedVertex) so the task
            # reassigns elsewhere and a half-written reply can't wedge the
            # next job's blocking send.  A pid still in `running` has not
            # replied, so this applies even when a duplicate already won the
            # task.  Commanded test delays (delay_hook) extend the budget —
            # they simulate slowness, not a wedge.
            now = time.time()
            for pid, t in list(running.items()):
                budget = task_timeout + t.delays.get(pid, 0.0)
                if now - t.runs.get(pid, now) > budget:
                    self._emit({"event": "task_timeout", "task": t.idx,
                                "worker": pid, "timeout_s": task_timeout})
                    cl.retire_worker(pid)
                    worker_lost(pid)
            # a worker that never answered the idle-gate ping within the
            # task budget is wedged on prior work — retire it too
            for pid, t0 in list(ping_t.items()):
                if pid not in dead and now - t0 > task_timeout:
                    self._emit({"event": "worker_ping_timeout",
                                "worker": pid, "timeout_s": task_timeout})
                    ping_t.pop(pid, None)
                    cl.retire_worker(pid)
                    worker_lost(pid)
            # fill idle workers: fresh tasks first, then speculate.  A task
            # reassigned by worker-loss/timeout may since have finished via
            # a surviving duplicate — skip those.  Locality-aware matching:
            # an idle worker takes a task that PREFERS it when one exists
            # (an explicit worker hint, or a block->host hint resolving to
            # that worker's machine), but preference never blocks — an
            # idle worker with no preferring task takes the queue head
            # (fall back freely; reference weighted affinity,
            # Interfaces.cs:98-152)
            while todo and idle:
                pair = next((t for t in todo
                             if t.result is None and t.pref & idle),
                            None)
                if pair is not None:
                    todo.remove(pair)
                    pid = min(pair.pref & idle)
                    if dispatch(pair, pid):
                        self._emit({"event": "task_locality_dispatch",
                                    "task": pair.idx, "worker": pid})
                    else:
                        todo.insert(0, pair)
                    continue
                t = todo.pop(0)
                if t.result is not None:
                    continue
                if not dispatch(t, min(idle)):
                    todo.insert(0, t)
            if not todo and idle and dups_used < dup_cap:
                thr = self._threshold(durations)
                if thr is not None:
                    now = time.time()
                    cands = [t for t in running.values()
                             if t.result is None and not t.duplicated
                             and now - min(t.runs.values()) > thr]
                    if cands:
                        worst = max(cands,
                                    key=lambda t: now - min(t.runs.values()))
                        pid = min(idle)
                        # burn the budget slot only if the clone actually
                        # dispatched — a failed send must leave the
                        # straggler cloneable elsewhere
                        if dispatch(worst, pid):
                            worst.duplicated = True
                            worst.dup_pid = pid
                            dups_used += 1
                            self._emit({"event": "task_duplicated",
                                        "task": worst.idx, "worker": pid,
                                        "elapsed_s": round(
                                            now - min(worst.runs.values()),
                                            3),
                                        "threshold_s": round(thr, 3)})

            # liveness + replies (gang AND elastic workers)
            for pid, proc in cl.worker_procs().items():
                if pid not in dead and proc.poll() is not None:
                    worker_lost(pid)
            live = {cl.sockets[pid]: pid for pid in cl.sockets
                    if pid not in dead}
            if not live:
                raise WorkerFailure("no live workers" + cl.log_tails())
            ready, _, _ = select.select(list(live), [], [], 0.1)
            for sock in ready:
                pid = live[sock]
                frames, ok = cl.recv_frames(pid, job)
                if not ok:
                    worker_lost(pid)
                    continue
                for reply in frames:
                    if "pong" in reply:      # idle-gate ping answered
                        ping_t.pop(pid, None)
                        idle.add(pid)
                        continue
                    running.pop(pid, None)
                    idle.add(pid)
                    t = (tasks[reply["task"]]
                         if reply.get("task") is not None else None)
                    # forward the worker's span/event records (tagged
                    # with the emitting worker) — losing duplicates
                    # included: their spans ARE the straggler evidence
                    for e in reply.get("events") or ():
                        self._emit(dict(e, worker=pid))
                    if not reply.get("ok"):
                        # a losing duplicate's failure costs nothing once
                        # the winner delivered (first-finisher-wins)
                        if t is not None:
                            trace.finish(t.spans.pop(pid, None),
                                         error="task_failed")
                        if t is not None and t.result is not None:
                            self._emit({"event":
                                        "task_duplicate_failed_ignored",
                                        "task": t.idx, "worker": pid})
                            continue
                        # persist the worker's flight-recorder bundle
                        # BEFORE raising: the error message points the
                        # operator at the local reproduction
                        bpath = self._persist_forensics(reply)
                        raise FarmError(
                            f"task {reply.get('task')} failed on worker "
                            f"{pid}:\n{reply.get('error')}"
                            + (f"\nforensics bundle: {bpath}\n"
                               f"  reproduce locally: python -m "
                               f"dryad_tpu.obs replay {bpath}"
                               if bpath else ""))
                    took = time.time() - t.runs.get(pid, time.time())
                    trace.finish(t.spans.pop(pid, None),
                                 won=t.result is None)
                    if t.result is None:
                        t.result = reply["table"]
                        n_done += 1
                        durations.append(took)
                        task_hist.observe(took)
                        done_ev = {"event": "task_done", "task": t.idx,
                                   "worker": pid,
                                   "wall_s": round(took, 3)}
                        if reply.get("rewrites"):
                            # adaptive rewrites the worker applied while
                            # running this task (dryad_tpu/adapt); the
                            # per-rewrite graph_rewrite events were
                            # forwarded worker-tagged above
                            done_ev["rewrites"] = reply["rewrites"]
                        if t.duplicated:
                            # which copy won (straggler metrics —
                            # DrStageStatistics outcome accounting);
                            # keyed on the RECORDED duplicate pid, not
                            # dispatch order: a lost original's runs
                            # entry is popped by worker_lost
                            done_ev["dup_won"] = pid == t.dup_pid
                        self._emit(done_ev)
                    else:
                        self._emit({"event": "task_duplicate_ignored",
                                    "task": t.idx, "worker": pid})
        return [t.result for t in tasks]
