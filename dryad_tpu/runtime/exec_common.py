"""The SPMD job body every worker process runs for one submitted query.

Mirrored determinism is the correctness contract (the reason a Dryad-style
GM can treat vertices as replayable): all processes rebuild the same graph
from the same JSON, execute the same stage programs in the same order, see
the same replicated overflow flags / range bounds, and therefore make the
same capacity-retry decisions — so the only cross-process coupling is XLA
collectives (the data plane) plus the driver's control messages."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

__all__ = ["execute_plan"]

_EXECUTORS: Dict[int, Any] = {}
# cluster-resident intermediates: token -> PData (loop state, cache());
# cleared implicitly by gang restart (fresh processes), explicitly by the
# driver's piggybacked release lists
_RESIDENT: Dict[str, Any] = {}


def _gang_executor(mesh, config=None):
    """One persistent Executor per mesh, so the compiled-stage cache
    survives across submitted jobs (iterative queries re-submit the same
    body plan every iteration — identical fingerprints must hit).  The
    driver's JobConfig (shipped with each job) is applied per job."""
    from dryad_tpu.exec.executor import Executor
    ex = _EXECUTORS.get(id(mesh))
    if ex is None:
        ex = _EXECUTORS[id(mesh)] = Executor(mesh)
    ex.apply_config(config)  # the single config-application point
    return ex


def execute_plan(plan_json: str, fn_table: Dict[str, Callable],
                 source_specs: Dict[str, Dict[str, Any]], mesh,
                 event_log: Optional[Callable[[dict], None]] = None,
                 store_path: Optional[str] = None,
                 store_partitioning: Optional[Dict[str, Any]] = None,
                 collect: Any = True, config=None,
                 keep_token: Optional[str] = None,
                 release: tuple = (),
                 store_compression: Optional[str] = None) -> Any:
    """Build sources, run the graph, replicate the output, and (on process
    0) return the host table / write the store.  ``collect``: True = full
    host table, "count" = total row count only, False = nothing.

    Returns ``(table, extras)``.  ``keep_token`` caches the output PData
    cluster-resident under that token (readable by later plans via a
    "resident" source spec — zero table bytes across the driver socket);
    ``release`` drops tokens no longer referenced."""
    import jax

    from dryad_tpu.exec.data import replicate_tree
    from dryad_tpu.exec.executor import Executor
    from dryad_tpu.plan.serialize import graph_from_json
    from dryad_tpu.runtime.sources import build_source

    import numpy as np

    from dryad_tpu.runtime.stream_plan import (execute_stream_plan,
                                               has_stream_sources)
    if has_stream_sources(source_specs):
        # >HBM sources: the SAME plan runs as chunk waves + per-device
        # bucket streams (runtime/stream_plan.py) — one lowering, two
        # execution regimes (channelinterface.h:212 parity)
        return execute_stream_plan(
            plan_json, fn_table, source_specs, mesh, event_log=event_log,
            store_path=store_path, store_partitioning=store_partitioning,
            collect=collect, config=config, keep_token=keep_token,
            release=release, store_compression=store_compression)

    for tok in release:
        _RESIDENT.pop(tok, None)
    sources = {key: build_source(spec, mesh, resident=_RESIDENT)
               for key, spec in source_specs.items()}
    graph = graph_from_json(plan_json, fn_table=fn_table, sources=sources)
    ex = _gang_executor(mesh, config)
    from dryad_tpu.exec.executor import _no_event
    ex._event = event_log or _no_event
    pd = ex.run(graph)

    extras: Dict[str, Any] = {}
    # adaptive rewrites are mirrored across the gang (replicated stats
    # drive deterministic rules), so every worker reports the same count
    rewrites = getattr(ex, "_last_run_rewrites", 0)
    if rewrites:
        extras["graph_rewrites"] = rewrites
    # runtime salting decisions are mirrored across processes (pmax'd
    # info), so every worker computes the same flag; placement claims
    # persisted from a salted run — or one whose output placement an
    # adaptive broadcast flip changed — must drop
    salted = (any(st._salted for st in graph.stages)
              or getattr(ex, "_last_run_placement_changed", False))
    if salted:
        extras["salted"] = True
        if store_partitioning:
            store_partitioning = {"kind": "none"}
    if keep_token is not None:
        _RESIDENT[keep_token] = pd
        extras["resident_capacity"] = pd.capacity

    table = None
    if collect == "count":
        # scalar terminals don't need the rows — only the replicated
        # per-partition counts (tiny int32[P] all-gather)
        counts = np.asarray(replicate_tree(pd.batch.count, mesh))
        table = int(counts.sum())
    elif collect:
        # PARALLEL collect: each worker returns only ITS addressable
        # shards' rows (driver concatenates parts in pid order = the
        # partition order) — no whole-table replication collective, no
        # single-process unpack funnel (VERDICT r2 weak 3; the reference
        # reads each vertex's output where it is).  The shrink decision
        # stays mirrored (replicated counts) so shapes agree.
        from dryad_tpu.exec.data import (_shrink_knobs, shrink_bucket_cap,
                                         shrink_pdata)
        from dryad_tpu.exec.stream_exec import chunks_to_table
        from dryad_tpu.exec.ooc import ChunkSource
        from dryad_tpu.runtime.stream_cluster import (_read_local_shards,
                                                      local_batch_chunks)
        counts = np.asarray(replicate_tree(pd.batch.count, mesh))
        new_cap = shrink_bucket_cap(counts, pd.capacity,
                                    *_shrink_knobs(config))
        spd = pd if new_cap is None else shrink_pdata(pd, new_cap)
        nprocs = jax.process_count()
        dpp = spd.nparts // nprocs
        start = jax.process_index() * dpp
        local = _read_local_shards(spd.batch, start, dpp)
        schema, chunks = local_batch_chunks(local)
        table = chunks_to_table(ChunkSource(lambda: iter(chunks), schema,
                                            max(spd.capacity, 1)))
    if store_path is not None:
        # PARALLEL output: each process writes ITS OWN partitions from its
        # addressable shards (no replication collective, no single-writer
        # funnel); process 0 merges meta and commits — the reference's
        # per-vertex output writers + job-end commit (DrOutputVertex,
        # DrVertex.h:325-351)
        from dryad_tpu.runtime.stream_cluster import (_read_local_shards,
                                                      _write_partitions,
                                                      local_batch_chunks)
        nprocs = jax.process_count()
        dpp = pd.nparts // nprocs
        start = jax.process_index() * dpp
        local = _read_local_shards(pd.batch, start, dpp)
        schema, chunks = local_batch_chunks(local)
        _write_partitions(store_path, schema, [[c] for c in chunks],
                          list(range(start, start + dpp)), mesh,
                          pd.capacity, partitioning=store_partitioning,
                          compression=store_compression,
                          capacity=pd.capacity)
    return table, extras
