"""ctypes bindings for the native IO engine (native/dryad_io.cpp).

Builds on first use (g++ via make) and degrades gracefully to pure-Python
fallbacks when no toolchain is available — `available()` reports which path
is active.  pybind11 is not in this environment, so the binding layer is
ctypes over a plain C ABI.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_SO = os.path.join(_NATIVE_DIR, "libdryad_io.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.dryad_pack_lines.restype = ctypes.c_int64
        lib.dryad_pack_lines.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        lib.dryad_pack_bytes.restype = ctypes.c_int64
        lib.dryad_pack_bytes.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64]
        lib.dryad_file_jobs.restype = ctypes.c_int64
        lib.dryad_file_jobs.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
        lib.dryad_fingerprint.restype = ctypes.c_uint64
        lib.dryad_fingerprint.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dryad_compact_rows.restype = ctypes.c_int64
        lib.dryad_compact_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
        lib.dryad_fingerprint_seed.restype = ctypes.c_uint64
        lib.dryad_fingerprint_seed.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# record packing


def pack_lines(buf: bytes, max_len: int,
               capacity: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Split a newline-delimited buffer into (data [n, max_len] u8,
    lengths [n] i32).  Native when built; numpy fallback otherwise."""
    lib = _load()
    if lib is not None:
        cap = capacity or (buf.count(b"\n") + 2)
        data = np.zeros((cap, max_len), np.uint8)
        lens = np.zeros((cap,), np.int32)
        src = np.frombuffer(buf, np.uint8)
        n = lib.dryad_pack_lines(
            src.ctypes.data_as(ctypes.c_void_p), len(buf), max_len,
            data.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.c_void_p), cap)
        if n < 0:
            raise ValueError("pack_lines capacity exceeded")
        return data[:n], lens[:n]
    # fallback mirrors dryad_pack_lines exactly: split ONLY on b"\n"
    # (bytes.splitlines also splits on \x0b, \x0c, \x1c-\x1e, lone \r —
    # which would make ingest differ from the native path), trim a
    # trailing \r (CRLF), drop only the final empty piece.
    lines = buf.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    lines = [l[:-1] if l.endswith(b"\r") else l for l in lines]
    n = len(lines)
    data = np.zeros((n, max_len), np.uint8)
    lens = np.zeros((n,), np.int32)
    for i, l in enumerate(lines):
        l = l[:max_len]
        data[i, : len(l)] = np.frombuffer(l, np.uint8)
        lens[i] = len(l)
    return data, lens


def pack_bytes_list(items: Sequence[bytes], max_len: int, capacity: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Pack a list of bytes into padded (data [capacity, max_len], lens)."""
    n = len(items)
    if n > capacity:
        raise ValueError(f"{n} items > capacity {capacity}")
    data = np.zeros((capacity, max_len), np.uint8)
    lens = np.zeros((capacity,), np.int32)
    lib = _load()
    if lib is not None and n > 0:
        ptrs = (ctypes.c_void_p * n)()
        lens64 = np.empty((n,), np.int64)
        # keep refs alive
        bufs = [i if isinstance(i, bytes) else bytes(i) for i in items]
        for i, b in enumerate(bufs):
            ptrs[i] = ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)
            lens64[i] = len(b)
        rc = lib.dryad_pack_bytes(
            ptrs, lens64.ctypes.data_as(ctypes.c_void_p), n, max_len,
            data.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.c_void_p), capacity)
        if rc < 0:
            raise ValueError("pack_bytes capacity exceeded")
        return data, lens
    for i, b in enumerate(items):
        b = (b if isinstance(b, bytes) else bytes(b))[:max_len]
        data[i, : len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
    return data, lens


# ---------------------------------------------------------------------------
# parallel scatter-gather file IO


def _file_jobs(paths: List[str], segments: List[List[np.ndarray]],
               write: bool, nthreads: int = 8,
               compress: bool = False) -> None:
    n = len(paths)
    if n == 0:
        return
    lib = _load()
    if lib is None:
        import gzip as _gz

        opener = (lambda p, m: _gz.open(p, m, compresslevel=1)) \
            if compress else open
        for p, segs in zip(paths, segments):
            if write:
                with opener(p, "wb") as f:
                    for s in segs:
                        f.write(memoryview(np.ascontiguousarray(s)).cast("B"))
            else:
                with opener(p, "rb") as f:
                    for s in segs:
                        mv = memoryview(s).cast("B")
                        if compress:
                            mv[:] = f.read(mv.nbytes)
                        else:
                            f.readinto(mv)
        return
    flat_ptrs, flat_lens, offsets = [], [], [0]
    keep = []
    for segs in segments:
        for s in segs:
            s = np.ascontiguousarray(s)
            keep.append(s)
            flat_ptrs.append(s.ctypes.data)
            flat_lens.append(s.nbytes)
        offsets.append(len(flat_ptrs))
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    nseg = len(flat_ptrs)
    c_ptrs = (ctypes.c_void_p * nseg)(*flat_ptrs)
    lens_arr = np.asarray(flat_lens, np.int64)
    offs_arr = np.asarray(offsets, np.int64)
    mode = (1 if write else 0) + (2 if compress else 0)
    rc = lib.dryad_file_jobs(
        c_paths, n, c_ptrs, lens_arr.ctypes.data_as(ctypes.c_void_p),
        offs_arr.ctypes.data_as(ctypes.c_void_p), mode, nthreads)
    if rc != 0:
        raise IOError(f"native file job failed: {paths[int(rc) - 1]}")


def write_files(paths: List[str], segments: List[List[np.ndarray]],
                nthreads: int = 8, compress: bool = False) -> None:
    _file_jobs(paths, segments, write=True, nthreads=nthreads,
               compress=compress)


def read_files(paths: List[str], segments: List[List[np.ndarray]],
               nthreads: int = 8, compress: bool = False) -> None:
    """Read each file's bytes contiguously into the given (preallocated,
    writable) arrays."""
    _file_jobs(paths, segments, write=False, nthreads=nthreads,
               compress=compress)


def compact_rows(data: np.ndarray, lens: np.ndarray
                 ) -> Tuple[bytes, np.ndarray]:
    """Compact a padded [n, max_len] u8 matrix into (packed bytes,
    offsets[n+1] i64): row i is packed[offs[i]:offs[i+1]].  Native single
    pass when built; numpy mask-gather fallback.  The egress counterpart of
    pack_bytes_list — collect()'s string columns avoid copying padding."""
    n, L = data.shape
    lens = np.ascontiguousarray(lens[:n], np.int32)
    data = np.ascontiguousarray(data)
    lib = _load()
    if lib is not None:
        out = np.empty(int(np.clip(lens, 0, L).sum()), np.uint8)
        offs = np.empty(n + 1, np.int64)
        lib.dryad_compact_rows(
            data.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.c_void_p), n, L,
            out.ctypes.data_as(ctypes.c_void_p),
            offs.ctypes.data_as(ctypes.c_void_p))
        return out.tobytes(), offs
    cl = np.clip(lens, 0, L)
    mask = np.arange(L)[None, :] < cl[:, None]
    packed = data[mask].tobytes()
    offs = np.concatenate([[0], np.cumsum(cl, dtype=np.int64)])
    return packed, offs


def unpack_rows(data: np.ndarray, lens: np.ndarray) -> List[bytes]:
    """Padded byte matrix -> list of per-row bytes (native compaction +
    zero-padding-free slicing)."""
    packed, offs = compact_rows(data, lens)
    return [packed[offs[i]: offs[i + 1]] for i in range(data.shape[0])]


_FNV_BASIS = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def _fnv_py(data: bytes, seed: int = _FNV_BASIS) -> int:
    h = seed
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def fingerprint(buf) -> int:
    """64-bit FNV-1a.  The Python fallback computes the SAME function as
    the native path (a fallback must never change the digest — the store
    records fnv64 checksums that any environment must be able to verify)."""
    lib = _load()
    arr = np.ascontiguousarray(np.frombuffer(buf, np.uint8) if
                               isinstance(buf, (bytes, bytearray)) else buf)
    if lib is None:
        return _fnv_py(arr.tobytes())
    return int(lib.dryad_fingerprint(
        arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes))


def checksum_segments(segments: Sequence[np.ndarray]) -> int:
    """Chained fnv64 over a partition's segment list (no concatenation):
    store integrity checksums (the role of the reference's channel
    fingerprints, classlib fingerprint.cpp)."""
    lib = _load()
    h = _FNV_BASIS
    for s in segments:
        s = np.ascontiguousarray(s)
        view = s.view(np.uint8).reshape(-1)
        if lib is None:
            h = _fnv_py(view.tobytes(), h)
        else:
            h = int(lib.dryad_fingerprint_seed(
                view.ctypes.data_as(ctypes.c_void_p), view.nbytes,
                ctypes.c_uint64(h)))
    return h
