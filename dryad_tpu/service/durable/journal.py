"""Write-ahead journal for the job service's own state.

Append-only JSONL under ``<service_dir>/durable/``: every admission,
queue entry, dispatch, terminal transition, tenant fair-share charge,
and standing-query registration is one record, fsynced before the
daemon acts on it.  Periodic CHECKPOINT COMPACTION folds the journal
into ``checkpoint.json`` (committed with the tree-wide rename-commit
helper, utils/atomic.py) and truncates the log — recovery is always
"load checkpoint, replay the short journal suffix".

Crash tolerance is asymmetric by design:

* a TORN TAIL (the crash landed mid-append) is normal — the partial
  last record is truncated away and replay proceeds;
* garbage anywhere ELSE, an unreadable checkpoint, or a journal format
  version this code does not speak is real corruption — a typed
  :class:`JournalError` (``DTA914``) refusing recovery, never a silent
  partial restore.

Records use the ``"rec"`` key (not ``"event"``) — the journal is
durable state, not an event stream; the observable recovery events
(``journal_replay``/``job_resumed``/...) are emitted by recover.py
into the normal event logs.

Replay is a pure fold (:func:`replay_records` over :class:`ReplayState`);
the live journal keeps its own folded mirror in step with every append,
so compaction writes the exact state a fresh replay would produce.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dryad_tpu.analysis.diagnostics import DiagnosticError
from dryad_tpu.utils.atomic import atomic_write_json

__all__ = ["Journal", "JournalError", "ReplayState", "JOURNAL_VERSION",
           "TERMINAL_STATES"]

# journal FORMAT version: bumped only when the record schema changes
# incompatibly.  Distinct from the package version (which rolls every
# release and MAY differ across a rolling upgrade — that is the point
# of the handoff protocol; plan-cache salting handles stale lowerings).
JOURNAL_VERSION = 1

# a job in one of these phases needs no recovery action
TERMINAL_STATES = ("done", "failed", "cancelled", "rejected")


class JournalError(DiagnosticError):
    """Corrupt journal / unreadable checkpoint / format-version
    mismatch — recovery is REFUSED with the stable DTA914 code rather
    than silently restoring a partial state."""

    def __init__(self, message: str):
        super().__init__(message, code="DTA914")


class ReplayState:
    """The fold target: everything recovery needs to rebuild the
    daemon.  ``jobs`` maps job id -> ``{"spec": .., "phase": ..,
    "error": ..}`` in admission order (dict insertion order; specs
    carry the original ``seq`` so fair-share order survives exactly)."""

    def __init__(self):
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self.tenants: Dict[str, Dict[str, float]] = {}
        self.standing: Dict[str, Dict[str, Any]] = {}
        self.seq = 0                  # high-water job sequence number
        self.counter = 0              # high-water record number
        self.clean = False            # last epoch ended with a close
        self.handoff: Optional[Dict[str, Any]] = None
        self.epochs = 0
        self.dup_terminals: List[str] = []   # exactly-once violations
        self.torn = False             # a torn tail was truncated

    # -- folding -----------------------------------------------------------

    def fold(self, r: Dict[str, Any]) -> None:
        self.counter = max(self.counter, int(r.get("n", 0)))
        k = r.get("rec")
        if k == "open":
            self.epochs += 1
            self.clean = False
            self.handoff = None
        elif k == "close":
            self.clean = True
        elif k == "handoff_ready":
            self.handoff = {"ver": r.get("ver"), "ts": r.get("ts")}
        elif k == "job_admitted":
            spec = r["spec"]
            self.jobs.setdefault(spec["id"],
                                 {"spec": spec, "phase": "admitted",
                                  "error": None})
            self.jobs[spec["id"]]["spec"] = spec
            self.seq = max(self.seq, int(spec.get("seq", 0)))
        elif k in ("job_queued", "job_dispatched"):
            j = self.jobs.setdefault(r["id"], {"spec": None,
                                               "phase": "admitted",
                                               "error": None})
            if j["phase"] not in TERMINAL_STATES:
                j["phase"] = ("queued" if k == "job_queued"
                              else "running")
        elif k == "job_terminal":
            j = self.jobs.setdefault(r["id"], {"spec": None,
                                               "phase": "admitted",
                                               "error": None})
            if j["phase"] in TERMINAL_STATES:
                self.dup_terminals.append(r["id"])
            else:
                j["phase"] = r["state"]
                j["error"] = r.get("error")
                j["wall_s"] = r.get("wall_s")
        elif k == "tenant_charge":
            t = self.tenants.setdefault(r["tenant"],
                                        {"used_slot_s": 0.0,
                                         "failures": 0})
            t["used_slot_s"] += max(0.0, float(r.get("wall_s", 0.0)))
            if not r.get("ok", True):
                t["failures"] += 1
        elif k == "standing_registered":
            self.standing[r["reg"]["id"]] = r["reg"]
        elif k == "standing_cancelled":
            self.standing.pop(r["id"], None)
        # unknown record kinds are skipped: a NEWER minor writer may add
        # informational records; incompatible changes bump the version

    def live_jobs(self) -> List[Dict[str, Any]]:
        """Non-terminal jobs in original admission (seq) order."""
        live = [dict(j, id=jid) for jid, j in self.jobs.items()
                if j["phase"] not in TERMINAL_STATES]
        live.sort(key=lambda j: (j["spec"] or {}).get("seq", 0))
        return live

    # -- checkpoint serialization ------------------------------------------

    def to_checkpoint(self, max_terminal: int = 4096) -> Dict[str, Any]:
        jobs = dict(self.jobs)
        term = [jid for jid, j in jobs.items()
                if j["phase"] in TERMINAL_STATES]
        # bound checkpoint growth: drop the OLDEST terminal rows beyond
        # the cap (their job dirs/history archives remain on disk)
        for jid in term[:max(0, len(term) - max_terminal)]:
            del jobs[jid]
        return {"journal_version": JOURNAL_VERSION,
                "counter": self.counter, "seq": self.seq,
                "jobs": jobs, "tenants": self.tenants,
                "standing": self.standing}

    @classmethod
    def from_checkpoint(cls, obj: Dict[str, Any]) -> "ReplayState":
        if obj.get("journal_version") != JOURNAL_VERSION:
            raise JournalError(
                f"service journal checkpoint has format version "
                f"{obj.get('journal_version')!r}, this daemon speaks "
                f"{JOURNAL_VERSION} — refusing recovery")
        st = cls()
        st.counter = int(obj.get("counter", 0))
        st.seq = int(obj.get("seq", 0))
        st.jobs = dict(obj.get("jobs") or {})
        st.tenants = dict(obj.get("tenants") or {})
        st.standing = dict(obj.get("standing") or {})
        return st


def _read_records(path: str) -> Tuple[List[Dict[str, Any]], bool]:
    """Parse the journal JSONL tolerantly: a torn TAIL record (crash
    mid-append) is physically truncated away and flagged; garbage
    before the tail is corruption (JournalError/DTA914)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], False
    records: List[Dict[str, Any]] = []
    torn = False
    off = 0
    while off < len(data):
        nl = data.find(b"\n", off)
        end = nl if nl >= 0 else len(data)
        line = data[off:end]
        try:
            rec = json.loads(line.decode("utf-8"))
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except (ValueError, UnicodeDecodeError):
            if nl >= 0 and data[end + 1:].strip():
                raise JournalError(
                    f"service journal {path} is corrupt at byte {off} "
                    f"(garbage before the tail) — refusing recovery")
            # torn tail: truncate it so later appends start clean
            with open(path, "r+b") as f:
                f.truncate(off)
            torn = True
            break
        records.append(rec)
        if nl < 0:
            break
        off = nl + 1
    return records, torn


class Journal:
    """The live write-ahead journal (see module docstring).

    Opening a journal REPLAYS what is on disk first: the folded
    :class:`ReplayState` is exposed as ``self.recovered`` for
    recover.py, and the journal continues appending from the recovered
    record counter.  Every append also folds into the live mirror so
    :meth:`compact` can checkpoint without re-reading the file."""

    def __init__(self, dirpath: str, fsync: bool = True,
                 compact_every: int = 512, version: Optional[str] = None):
        import dryad_tpu
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.path = os.path.join(dirpath, "journal.jsonl")
        self.ckpt_path = os.path.join(dirpath, "checkpoint.json")
        self.lock_path = os.path.join(dirpath, "LOCK")
        self.fsync = fsync
        self.compact_every = max(8, int(compact_every))
        self.version = (version if version is not None
                        else getattr(dryad_tpu, "__version__", "dev"))
        self._lock = threading.Lock()
        self._since_compact = 0
        self.closed = False
        # advisory ownership: last writer wins (a rolling upgrade has
        # BOTH daemons alive during adoption); the previous owner is
        # surfaced so recovery can log it, never a hard refusal
        self.prior_owner = self._take_lock()
        self.recovered = self._replay()
        self._state = self.recovered
        # the "open" append below folds into the live mirror (which
        # ALIASES ``recovered``) and resets the epoch flags — snapshot
        # what recovery needs to see about the PREVIOUS epoch first
        self.was_clean = self.recovered.clean
        self.was_handoff = self.recovered.handoff
        self.was_torn = self.recovered.torn
        self._f = open(self.path, "a")
        self._n = self.recovered.counter
        self._append("open", journal_version=JOURNAL_VERSION,
                     ver=self.version, pid=os.getpid())

    # -- ownership ---------------------------------------------------------

    def _take_lock(self) -> Optional[Dict[str, Any]]:
        prior = None
        try:
            with open(self.lock_path) as f:
                prior = json.load(f)
        except (OSError, ValueError):
            prior = None
        if prior is not None:
            pid = prior.get("pid")
            try:
                alive = (isinstance(pid, int) and pid != os.getpid()
                         and (os.kill(pid, 0) or True))
            except OSError:
                alive = False
            prior = dict(prior, alive=alive)
        atomic_write_json(self.lock_path,
                          {"pid": os.getpid(), "ts": time.time(),
                           "ver": self.version})
        return prior

    def _release_lock(self) -> None:
        try:
            with open(self.lock_path) as f:
                if json.load(f).get("pid") != os.getpid():
                    return           # a successor already took over
        except (OSError, ValueError):
            return
        try:
            os.unlink(self.lock_path)
        except OSError:
            pass

    # -- replay ------------------------------------------------------------

    def _replay(self) -> ReplayState:
        if os.path.exists(self.ckpt_path):
            try:
                with open(self.ckpt_path) as f:
                    obj = json.load(f)
            except (OSError, ValueError) as e:
                raise JournalError(
                    f"service journal checkpoint {self.ckpt_path} is "
                    f"unreadable ({e!r}) — refusing recovery")
            state = ReplayState.from_checkpoint(obj)
        else:
            state = ReplayState()
        records, torn = _read_records(self.path)
        for r in records:
            if r.get("rec") == "open" \
                    and r.get("journal_version") != JOURNAL_VERSION:
                raise JournalError(
                    f"service journal {self.path} was written with "
                    f"format version {r.get('journal_version')!r}, "
                    f"this daemon speaks {JOURNAL_VERSION} — refusing "
                    f"recovery")
            # records folded into the checkpoint already (crash between
            # checkpoint write and journal truncate) must not re-charge
            # tenants — the record counter is globally monotone
            if int(r.get("n", 0)) > state.counter:
                state.fold(r)
        state.torn = torn
        return state

    # -- appends -----------------------------------------------------------

    def _append(self, rec: str, **fields: Any) -> None:
        with self._lock:
            if self.closed:
                return
            self._n += 1
            r = dict(fields, rec=rec, n=self._n,
                     ts=round(time.time(), 4))
            self._f.write(json.dumps(r) + "\n")
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self._state.fold(r)
            self._since_compact += 1
            if self._since_compact >= self.compact_every:
                self._compact_locked()

    def job_admitted(self, spec: Dict[str, Any]) -> None:
        self._append("job_admitted", spec=spec)

    def job_queued(self, jid: str, seq: int) -> None:
        self._append("job_queued", id=jid, seq=seq)

    def job_dispatched(self, jid: str) -> None:
        self._append("job_dispatched", id=jid)

    def job_terminal(self, jid: str, state: str,
                     error: Optional[str] = None,
                     wall_s: Optional[float] = None) -> None:
        self._append("job_terminal", id=jid, state=state,
                     error=(error or None) and str(error)[:2000],
                     wall_s=wall_s)

    def tenant_charge(self, tenant: str, wall_s: float,
                      ok: bool = True) -> None:
        self._append("tenant_charge", tenant=tenant,
                     wall_s=round(float(wall_s), 6), ok=bool(ok))

    def standing_registered(self, reg: Dict[str, Any]) -> None:
        self._append("standing_registered", reg=reg)

    def standing_cancelled(self, sid: str) -> None:
        self._append("standing_cancelled", id=sid)

    def handoff_ready(self, ver: Optional[str] = None) -> None:
        self._append("handoff_ready", ver=ver or self.version)

    # -- compaction --------------------------------------------------------

    def compact(self, max_terminal: int = 4096) -> None:
        with self._lock:
            if not self.closed:
                self._compact_locked(max_terminal)

    def _compact_locked(self, max_terminal: int = 4096) -> None:
        """Checkpoint-then-truncate (holds the lock).  Crash-safe in
        both orders: the checkpoint lands atomically and carries the
        record counter, so replay skips journal records it already
        folded (crash between the two steps double-applies nothing)."""
        atomic_write_json(self.ckpt_path,
                          self._state.to_checkpoint(max_terminal))
        self._f.close()
        self._f = open(self.path, "w")
        self._since_compact = 0
        # re-bookend the fresh epoch so a bare journal still declares
        # its format version
        self._n += 1
        r = {"rec": "open", "n": self._n,
             "journal_version": JOURNAL_VERSION, "ver": self.version,
             "pid": os.getpid(), "ts": round(time.time(), 4),
             "compacted": True}
        self._f.write(json.dumps(r) + "\n")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._state.fold(r)

    # -- lifecycle ---------------------------------------------------------

    def close(self, clean: bool = True, release_lock: bool = True) -> None:
        if self.closed:
            return
        if clean:
            self._append("close")
        with self._lock:
            self.closed = True
            self._f.close()
        if release_lock:
            self._release_lock()
