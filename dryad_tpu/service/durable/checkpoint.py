"""Per-job driver-state snapshots, written at stage boundaries.

The spill dir already makes stage OUTPUTS durable (exec/recovery.Run
``_save_spill`` + restart-stable ``.fp`` fingerprints); what it does
not capture is the DRIVER's view of the run — which stages settled,
how much failure budget remains, which adaptive rewrites fired, and
the last observed-stats box.  ``JobCheckpoint`` snapshots exactly that
into ``<job_dir>/checkpoint.json`` (rename-commit, utils/atomic.py)
every time a stage materializes, so recovery can tell a resumable job
("settled stages 0-3, spill present — re-execute only the rest") from
one whose lineage is gone, and the handoff protocol has a defined
"checkpointed stage boundary" to pause at.

The object is the ``checkpoint=`` hook exec/recovery.Run calls as
``ckpt(run, sid)`` after each stage boundary — it reads only public
run state and must never fail the run.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

from dryad_tpu.utils.atomic import atomic_write_json

__all__ = ["JobCheckpoint"]


class JobCheckpoint:
    """Stage-boundary driver snapshot for one job (see module doc)."""

    def __init__(self, path: str, job: Optional[str] = None):
        self.path = path
        self.job = job

    def __call__(self, run, sid: int) -> None:
        try:
            stats = run._stats_box[0]
            snap = {
                "job": self.job, "ts": round(time.time(), 4),
                "stage": sid,
                "settled": sorted(run._results),
                "failures": run.failures,
                "budget_left": max(0, run.failure_budget - run.failures),
                "rewrites": ([dict(e) for e in run.adapt.applied]
                             if run.adapt is not None else []),
                "stats": (stats.__dict__ if stats is not None
                          and sid == getattr(stats, "stage", None)
                          else None),
                "spill_dir": run.spill_dir,
            }
            atomic_write_json(self.path, snap, default=str)
        except Exception:
            pass      # a snapshot must never fail the run it observes

    @staticmethod
    def load(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None
