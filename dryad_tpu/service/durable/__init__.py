"""Durable job service: write-ahead journal, crash recovery, and
rolling upgrades.

The reference punts job lifetime to YARN Application-Master restarts —
the Graph Manager dies with its job, and Dryad's fault model only ever
re-executes *vertices*, never the manager itself (PAPER.md layer 2).
This package goes beyond that: the daemon journals its OWN state
(admission / queue / tenant / in-flight, ``journal.py``), snapshots
each job's driver state at stage boundaries (``checkpoint.py``), and
on startup replays the journal to re-admit queued jobs fair-share-
order-preserved and RESUME running jobs from lineage + spill instead
of restarting them from scratch (``recover.py``).  A drain-and-handoff
protocol (``JobService.handoff``) lets a new daemon version adopt the
journal mid-flight — the rolling upgrade the one-GM-per-job model
cannot express.  Proven under injected faults by ``dryad_tpu/chaos``.
"""

from dryad_tpu.service.durable.checkpoint import JobCheckpoint
from dryad_tpu.service.durable.journal import (JOURNAL_VERSION, Journal,
                                               JournalError, ReplayState)
from dryad_tpu.service.durable.recover import recover

__all__ = ["Journal", "JournalError", "JobCheckpoint", "ReplayState",
           "JOURNAL_VERSION", "recover"]
